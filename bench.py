"""TPU serving benchmark — driver entry.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "detail": {...}}

Primary metric: aggregate decode throughput (output tokens/s) for the
flagship preset at the canonical multi-round-QA working point (batch =
max_num_seqs, ~2k-token contexts — the reference workload keeps 20k-token
histories alive via KV reuse, run.sh:46-48, so decode dominates steady
state).  ``vs_baseline`` is roofline efficiency: measured tokens/s divided
by the HBM-bandwidth-bound tokens/s for the same model + batch on this
chip (decode is bandwidth-bound; the reference publishes no absolute
numbers in-tree — BASELINE.md — so the honest denominator is the hardware
ceiling, not a GPU we can't measure here).

Timing method: the serving host this runs on reaches the TPU through a
high-RTT tunnel (~70 ms per host sync), so naive wall-clock around a step
measures the tunnel, not the chip.  Every measurement below chains n
iterations inside ONE jitted executable (lax.fori_loop, output feeding
input) and reports (T(n2) - T(n1)) / (n2 - n1): the RTT cancels.

Also reported in detail{}: prefill tokens/s + MFU per bucket, TTFT for a
2k prompt, per-step decode latency, Pallas-vs-gather attention speedup,
and measured peak matmul TF/s + HBM GB/s for context.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time

import numpy as np


_T0 = time.time()

# Wall-clock seconds spent waiting on environment boot (TPU device
# probes, backend-init watchdogs) rather than benchmarking.  Excluded
# from the --budget-s stage accounting: r05 charged 3x420 s of probe
# hang retries against the budget, drove it negative, and silently
# skipped the int8_ab/kv_int8_ab stages.
_BUDGET_EXCLUDED_S = 0.0


def exclude_from_budget(seconds: float) -> None:
    global _BUDGET_EXCLUDED_S
    _BUDGET_EXCLUDED_S += max(0.0, seconds)


def log(msg: str) -> None:
    print(f"[{time.time() - _T0:6.1f}s] {msg}", file=sys.stderr, flush=True)


_FALLBACK_ENV = "PSTPU_BENCH_TPU_UNAVAILABLE"

# Backoff schedule for TPU probe attempts: the r04 tunnel outage outlived
# 2x150s, so wait minutes, not seconds, before concluding the chip is
# gone (~13 min worst case; each attempt is a throwaway subprocess, so a
# hang costs a kill, never the bench process).
_PROBE_SCHEDULE = (120.0, 240.0, 420.0)

_PROBE_CODE = r"""
import sys
def say(stage):
    print("STAGE " + stage, flush=True)
say("import_jax")
import jax
say("enumerate_devices")
devs = jax.devices()
say("tiny_matmul")
import jax.numpy as jnp
x = jnp.ones((128, 128), jnp.bfloat16)
(x @ x).block_until_ready()
print("OK " + jax.default_backend() + " " + devs[0].device_kind, flush=True)
"""


def probe_tpu_subprocess(schedule=_PROBE_SCHEDULE):
    """Stage-attributed TPU liveness probe in throwaway subprocesses.

    Runs import -> device enumerate -> tiny compiled matmul in a child
    process per attempt; a hang is killed at the attempt's timeout and
    recorded with the stage it died in.  The per-attempt log lands in
    the JSON artifact, so an environment fault (tunnel down — r04's
    mode: jax.devices() hangs forever) is provable from the artifact
    alone and distinguishable from a builder regression.  Returns
    {"ok": bool, "backend": str|None, "attempts": [...]}.
    """
    import os
    import subprocess

    attempts = []
    probe_t0 = time.time()
    try:
        return _probe_tpu_attempts(schedule, attempts, os, subprocess)
    finally:
        # Probe/boot wait is environment time, not bench time: keep it
        # out of the --budget-s stage accounting.
        exclude_from_budget(time.time() - probe_t0)


def _probe_tpu_attempts(schedule, attempts, os, subprocess):
    for attempt, timeout_s in enumerate(schedule, 1):
        t0 = time.time()
        stage, outcome, err = "spawn", "hang", ""
        try:
            proc = subprocess.run(
                [sys.executable, "-c", _PROBE_CODE],
                capture_output=True, text=True, timeout=timeout_s,
                env=dict(os.environ),
            )
            stages = [
                ln.split(" ", 1)[1] for ln in proc.stdout.splitlines()
                if ln.startswith("STAGE ")
            ]
            stage = stages[-1] if stages else "spawn"
            ok_line = [
                ln for ln in proc.stdout.splitlines() if ln.startswith("OK ")
            ]
            if proc.returncode == 0 and ok_line:
                backend = ok_line[0].split()[1]
                attempts.append({
                    "attempt": attempt, "outcome": "ok",
                    "waited_s": round(time.time() - t0, 1),
                    "backend": backend,
                    "device": ok_line[0].split(maxsplit=2)[2],
                })
                log(f"probe: {backend} up in {time.time()-t0:.1f}s "
                    f"(attempt {attempt})")
                return {"ok": True, "backend": backend, "attempts": attempts}
            outcome, err = "error", (proc.stderr or "").strip()[-300:]
        except subprocess.TimeoutExpired as e:
            out = e.stdout or b""
            if isinstance(out, bytes):  # TimeoutExpired ignores text=True
                out = out.decode(errors="replace")
            stages = [
                ln.split(" ", 1)[1] for ln in out.splitlines()
                if ln.startswith("STAGE ")
            ]
            stage = stages[-1] if stages else "spawn"
        attempts.append({
            "attempt": attempt, "stage": stage, "outcome": outcome,
            "waited_s": round(time.time() - t0, 1),
            **({"error": err} if err else {}),
        })
        log(f"probe: attempt {attempt} {outcome} at stage={stage} "
            f"after {time.time()-t0:.1f}s")
    return {"ok": False, "backend": None, "attempts": attempts}


def _reexec(extra_env: dict) -> None:
    import os

    env = dict(os.environ)
    env.update(extra_env)
    os.execve(sys.executable, [sys.executable] + sys.argv, env)


def init_backend_or_fallback(timeout_s: float = 180.0) -> str:
    """Initialize jax IN-PROCESS after a successful probe.

    Second line of defense: the probe subprocess said the TPU was up,
    but the tunnel can die between probe and init — a watchdog re-execs
    this script pinned to CPU if in-process init stalls, so the bench
    always emits its one JSON line.
    """
    import os
    import threading

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        return "cpu"
    done = threading.Event()

    def watchdog():
        if done.wait(timeout_s):
            return
        log(f"init: hung >{timeout_s:.0f}s AFTER a successful probe — "
            "re-exec on CPU")
        _reexec({"JAX_PLATFORMS": "cpu", _FALLBACK_ENV: "1"})

    threading.Thread(target=watchdog, daemon=True).start()
    try:
        import jax

        backend = jax.default_backend()
        done.set()
        return backend
    except Exception as e:
        done.set()
        log(f"init: backend init failed after successful probe: {e}")
        _reexec({"JAX_PLATFORMS": "cpu", _FALLBACK_ENV: "1"})
        raise  # unreachable (execve does not return)


class stage_watchdog:
    """Re-exec this script with ``extra_env`` if the enclosed stage doesn't
    finish within ``timeout_s`` (a hung TPU compile/execute can't be
    interrupted in-process; the driver's own timeout would record nothing).
    Same re-exec strategy as init_backend_or_fallback."""

    def __init__(self, stage: str, timeout_s: float, extra_env: dict):
        self.stage = stage
        self.timeout_s = timeout_s
        self.extra_env = extra_env

    def __enter__(self):
        import threading

        self._done = threading.Event()

        def watch():
            if self._done.wait(self.timeout_s):
                return
            log(f"{self.stage}: stalled >{self.timeout_s:.0f}s; "
                f"re-exec with {self.extra_env}")
            _reexec(self.extra_env)

        threading.Thread(target=watch, daemon=True).start()
        return self

    def __exit__(self, *exc):
        self._done.set()
        return False


def timed(fn, *args, repeats=3):
    """Wall time of fn(*args) fully synced via scalar host readback."""
    float(np.asarray(fn(*args)))  # warmup + compile
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        float(np.asarray(fn(*args)))
        best = min(best, time.perf_counter() - t0)
    return best


def diff_time(make_fn, n1, n2, *args, repeats=3):
    """Per-iteration device time via two chained executables (RTT cancels)."""
    t1 = timed(make_fn(n1), *args, repeats=repeats)
    t2 = timed(make_fn(n2), *args, repeats=repeats)
    return max((t2 - t1) / (n2 - n1), 1e-9)


def fit_time(make_fn, ns, *args, repeats=3):
    """Per-iteration time via a least-squares fit of T(n) over several
    chain lengths, plus an absolute estimate from the longest chain.

    The 2-point diff (r03's method) is exposed to tunnel-RTT noise in
    BOTH endpoints; with a per-step time of ~10 ms a 30 ms swing between
    best-of-3 samples moves the diff by ~2 ms/step — enough to "beat the
    roofline" (r03: measured 7.48 ms vs a 10.1 ms bandwidth bound).  The
    fit averages the noise over len(ns) points; T(max_n)/max_n bounds the
    answer from above (dispatch+RTT amortized over the longest chain can
    only over-estimate the per-step time).  Disagreement between the two
    beyond the RTT budget marks the measurement suspect in the artifact.
    """
    ts = {n: timed(make_fn(n), *args, repeats=repeats) for n in ns}
    xs = np.asarray(sorted(ts), np.float64)
    ys = np.asarray([ts[n] for n in sorted(ts)], np.float64)
    slope, intercept = np.polyfit(xs, ys, 1)
    pred = slope * xs + intercept
    ss_res = float(((ys - pred) ** 2).sum())
    ss_tot = float(((ys - ys.mean()) ** 2).sum())
    n_max = int(xs[-1])
    return {
        "per_iter_s": max(float(slope), 1e-9),
        "intercept_ms": round(float(intercept) * 1e3, 2),
        "r2": round(1.0 - ss_res / ss_tot, 5) if ss_tot > 0 else 1.0,
        "abs_per_iter_s": ts[n_max] / n_max,
        "points": {int(n): round(ts[n] * 1e3, 2) for n in sorted(ts)},
    }


# -- microbenches ----------------------------------------------------------


def bench_matmul_tfs(jax, jnp, on_tpu=True):
    # Off-TPU (CI / tunnel-down fallback) the TPU-sized problem takes
    # minutes on a CPU; a small probe keeps the fallback inside the
    # driver's window (the number is only a roofline anchor on TPU).
    n_dim = 8192 if on_tpu else 1024
    a = jax.random.normal(jax.random.PRNGKey(0), (n_dim, n_dim), jnp.bfloat16)

    def mk(n):
        @jax.jit
        def f(a):
            return jax.lax.fori_loop(0, n, lambda i, c: (c @ a) / 90.0, a).sum()

        return f

    dt = diff_time(mk, 4, 24, a)
    return 2 * n_dim**3 / dt / 1e12


def bench_hbm_gbs(jax, jnp, on_tpu=True):
    size = (128 if on_tpu else 16) * 2**20
    x = jax.random.normal(jax.random.PRNGKey(1), (size,), jnp.bfloat16)
    y = jax.random.normal(jax.random.PRNGKey(2), (size,), jnp.bfloat16)

    def mk(n):
        @jax.jit
        def f(x, y):
            # c = c*s + y: reads c,y writes c each iter (unfoldable).
            def body(i, c):
                return c * 0.999 + y
            return jax.lax.fori_loop(0, n, body, x).sum()

        return f

    dt = diff_time(mk, 4, 24, x, y)
    nbytes = 3 * x.size * 2  # read c, read y, write c
    return nbytes / dt / 1e9


def bench_hbm_read_gbs(jax, jnp, on_tpu=True):
    """Achievable WEIGHT-STREAMING read bandwidth: a small activation
    [8, N] times a large loop-invariant matrix [N, N], output feeding
    input.  This is decode's dominant memory pattern (read N^2 weight
    bytes per step, negligible writes), so it is the honest ceiling for
    the decode roofline — the triad bench above pays write traffic that
    decode does not, and read-only streaming usually runs faster.  The
    carried activation defeats loop-invariant hoisting; tanh blocks any
    algebraic refactor of the chain."""
    n_dim = 8192 if on_tpu else 1024
    m = jax.random.normal(jax.random.PRNGKey(3), (n_dim, n_dim), jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(4), (8, n_dim), jnp.bfloat16)

    def mk(n):
        @jax.jit
        def f(v, m):
            def body(i, c):
                return jnp.tanh(c @ m)
            return jax.lax.fori_loop(0, n, body, v).sum()

        return f

    dt = diff_time(mk, 4, 24, v, m)
    return m.size * 2 / dt / 1e9


# -- model-level benches ---------------------------------------------------


def build_state(jax, jnp, cfg, num_blocks, block_size):
    from production_stack_tpu.engine.models import llama

    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    dtype = jnp.dtype(cfg.dtype)
    shape = (num_blocks, block_size, cfg.num_kv_heads, cfg.head_dim)
    kv = [
        (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
        for _ in range(cfg.num_layers)
    ]
    return params, kv


def bench_prefill(jax, jnp, cfg, params, kv_caches, bucket, block_size):
    """Per-call prefill time for one `bucket`-token sequence, fresh cache."""
    from production_stack_tpu.engine.models import llama

    tokens = jnp.zeros((bucket,), jnp.int32)
    nb = bucket // block_size
    new_ids = jnp.arange(1, 1 + nb, dtype=jnp.int32)
    prefix_ids = jnp.zeros((8,), jnp.int32)

    def mk(n):
        @jax.jit
        def f(params, tokens, kv_caches):
            def body(i, carry):
                kv, toks, acc = carry
                logits, kv = llama.prefill(
                    params, cfg, toks, jnp.int32(0), prefix_ids, new_ids,
                    jnp.int32(bucket), kv,
                )
                # Serial dependency: next iteration's tokens derive from
                # these logits, and the sum consumes every logit — XLA can
                # neither hoist the invariant first layer nor dead-code the
                # lm_head columns (round-3 audit: consuming only logits[0]
                # let the measurement beat its own roofline).
                toks = (toks + jnp.argmax(logits).astype(jnp.int32)) % 101
                return kv, toks, acc + logits.sum()
            _, _, acc = jax.lax.fori_loop(0, n, body, (kv_caches, tokens, 0.0))
            return acc

        return f

    return diff_time(mk, 1, 5, params, tokens, kv_caches)


def make_decode_bench(jax, jnp, cfg, S, ctx_len, bmax, block_size, total_blocks):
    """Build the chained decode executable factory (see bench_decode)."""
    from production_stack_tpu.engine.models import llama

    bs = block_size
    nb = -(-ctx_len // bs)
    tables = np.zeros((S, bmax), np.int32)
    nf = 1
    total = total_blocks
    for s in range(S):
        ids = (np.arange(nf, nf + nb) - 1) % (total - 1) + 1
        tables[s, :nb] = ids
        nf += nb
    tokens = jnp.zeros((S,), jnp.int32)
    positions = jnp.full((S,), ctx_len - 1, jnp.int32)
    block_tables = jnp.asarray(tables)
    ctx_lens = jnp.full((S,), ctx_len, jnp.int32)
    slot_blocks = jnp.asarray(tables[:, (ctx_len - 1) // bs], jnp.int32)
    slot_offsets = jnp.full((S,), (ctx_len - 1) % bs, jnp.int32)

    def mk(n):
        @jax.jit
        def f(params, kv_caches):
            def body(i, carry):
                kv, toks, acc = carry
                logits, kv = llama.decode(
                    params, cfg, toks, positions, block_tables, ctx_lens,
                    slot_blocks, slot_offsets, kv,
                )
                # Greedy-decode feedback: every sequence's next token
                # depends on its full logits row, so no per-sequence slice
                # of the batch is dead code (round-3 audit: consuming only
                # logits[0, 0] made sequences 1..S-1 eligible for DCE and
                # the measurement beat its own roofline).
                toks = jnp.argmax(logits, axis=-1).astype(jnp.int32) % 101
                return kv, toks, acc + logits.sum()
            _, _, acc = jax.lax.fori_loop(0, n, body, (kv_caches, tokens, 0.0))
            return acc

        return f

    return mk


def bench_decode(jax, jnp, cfg, params, kv_caches, S, ctx_len, bmax, block_size):
    """Per-step decode time, batch S, every sequence at ctx_len context."""
    mk = make_decode_bench(
        jax, jnp, cfg, S, ctx_len, bmax, block_size, kv_caches[0][0].shape[0]
    )
    return diff_time(mk, 4, 20, params, kv_caches)




def bench_engine_pipeline_ab(args, preset: str) -> dict:
    """Pipelined vs synchronous decode A/B through the REAL engine
    (LLMEngine.step with pipeline_decode on/off), not a raw model loop:
    the async one-step-lookahead pipeline is an engine-level
    restructuring, so only engine-level stepping can show its win.
    Reports per-step wall time for both modes plus each run's
    decode_host_gap_ms — the host serialization the pipeline hides.
    Engines are built serially with explicit small KV pools so two boots
    fit beside each other's freed memory."""
    import dataclasses as _dc
    import gc

    from production_stack_tpu.engine.config import (
        CacheConfig,
        EngineConfig,
        PRESETS,
        SchedulerConfig,
    )
    from production_stack_tpu.engine.core.engine import LLMEngine
    from production_stack_tpu.engine.core.sequence import SamplingParams

    S = args.batch
    warm, measured = 8, 48
    ctx_tokens = 128

    def run(pipeline: bool):
        cfg = EngineConfig(
            model=_dc.replace(PRESETS[preset]),
            cache=CacheConfig(num_blocks=S * 32 + 16),
            scheduler=SchedulerConfig(
                max_num_seqs=S,
                prefill_buckets=(128, 256),
                max_model_len=512,
                pipeline_decode=pipeline,
            ),
        )
        eng = LLMEngine(cfg)
        for i in range(S):
            eng.add_request(
                f"r{i}",
                prompt_token_ids=[(7 * i + j) % 101 for j in range(ctx_tokens)],
                sampling_params=SamplingParams(
                    max_tokens=warm + measured + 8, ignore_eos=True
                ),
            )
        produced = 0
        while produced < warm * S:  # prefills + compile + pipeline fill
            produced += len(eng.step())
        t0 = time.perf_counter()
        produced = 0
        while produced < measured * S:
            produced += len(eng.step())
        dt = time.perf_counter() - t0
        steps = max(1, round(produced / S))
        out = {
            "step_ms": round(dt / steps * 1e3, 3),
            "tokens_per_s": round(produced / dt, 1),
            "host_gap_ms": round(eng.stats()["decode_host_gap_ms"], 3),
        }
        del eng
        gc.collect()
        return out

    sync = run(False)
    piped = run(True)
    return {
        "sync": sync,
        "pipelined": piped,
        "speedup": round(sync["step_ms"] / max(piped["step_ms"], 1e-9), 3),
    }


def bench_engine_mixed_ab(args, preset: str) -> dict:
    """Mixed-batch vs alternating A/B through the REAL engine
    (scheduler.mixed_batch on/off): a Poisson stream of chunk-forcing
    long prompts arrives while a persistent decode batch streams tokens.
    The alternating scheduler stalls every decoder for a full prefill
    bucket per arrival — the head-of-line ITL spike; the fused mixed
    step prefills the same prompts in budgeted chunks beside the
    decodes.  Reports each mode's p95/max decoder ITL, long-prompt mean
    TTFT, aggregate throughput, and the chunk-token counter.  Arrivals
    are a SEEDED step-indexed Poisson process, so both modes replay the
    identical workload."""
    import dataclasses as _dc
    import gc

    from production_stack_tpu.engine.config import (
        CacheConfig,
        EngineConfig,
        PRESETS,
        SchedulerConfig,
    )
    from production_stack_tpu.engine.core.engine import LLMEngine
    from production_stack_tpu.engine.core.sequence import SamplingParams

    S_dec = max(2, min(args.batch, 8) // 2)  # persistent decoders
    n_long = 8
    long_len = 1536  # > largest chunk bucket several times over
    decoder_tokens = 128
    rng = np.random.RandomState(0)
    arrival_steps = sorted(
        (int(s), i)
        for i, s in enumerate(np.cumsum(rng.exponential(8.0, n_long)) + 4)
    )

    def run(mixed: bool) -> dict:
        num_blocks = (
            S_dec * (96 + decoder_tokens) + n_long * (long_len + 64)
        ) // 16 + 64
        eng = LLMEngine(EngineConfig(
            model=_dc.replace(PRESETS[preset]),
            cache=CacheConfig(num_blocks=num_blocks),
            scheduler=SchedulerConfig(
                max_num_seqs=S_dec + 1,
                prefill_buckets=(128, 256, 2048),
                prefill_chunk_buckets=(128, 256),
                max_model_len=2048,
                mixed_batch=mixed,
            ),
        ))
        for i in range(S_dec):
            eng.add_request(
                f"dec{i}",
                prompt_token_ids=[(7 * i + j) % 101 for j in range(96)],
                sampling_params=SamplingParams(
                    max_tokens=decoder_tokens, ignore_eos=True
                ),
            )
        for _ in range(8):  # compile + pipeline fill before measuring
            eng.step()
        arrivals = list(arrival_steps)
        token_times: dict = {}
        ttft: dict = {}
        step = 0
        produced = 0
        t0 = time.perf_counter()
        while eng.has_unfinished() or arrivals:
            while arrivals and arrivals[0][0] <= step:
                _, i = arrivals.pop(0)
                eng.add_request(
                    f"long{i}",
                    prompt_token_ids=[
                        (11 * i + j) % 101 for j in range(long_len)
                    ],
                    sampling_params=SamplingParams(max_tokens=8),
                )
                ttft[f"long{i}"] = [time.perf_counter(), None]
            step += 1
            if step > 5000:
                break
            outs = eng.step()
            now = time.perf_counter()
            for out in outs:
                produced += 1
                if out.seq_id.startswith("dec"):
                    token_times.setdefault(out.seq_id, []).append(now)
                elif out.seq_id in ttft and ttft[out.seq_id][1] is None:
                    ttft[out.seq_id][1] = now
        wall = time.perf_counter() - t0
        gaps = sorted(
            b - a
            for times in token_times.values()
            for a, b in zip(times, times[1:])
        )
        ttfts = [b - a for a, b in ttft.values() if b is not None]
        result = {
            "itl_p95_ms": round(
                gaps[int(0.95 * (len(gaps) - 1))] * 1e3, 3
            ) if gaps else 0.0,
            "itl_max_ms": round(gaps[-1] * 1e3, 3) if gaps else 0.0,
            "long_ttft_mean_ms": round(
                sum(ttfts) / len(ttfts) * 1e3, 2
            ) if ttfts else 0.0,
            "tokens_per_s": round(produced / wall, 1),
            "prefill_chunk_tokens": eng.prefill_chunk_tokens,
        }
        del eng
        gc.collect()
        return result

    alternating = run(False)
    mixed = run(True)
    return {
        "alternating": alternating,
        "mixed": mixed,
        # > 1.0 = the fused path cut the decoder ITL tail.
        "itl_p95_speedup": round(
            alternating["itl_p95_ms"] / max(mixed["itl_p95_ms"], 1e-9), 3
        ),
        "throughput_ratio": round(
            mixed["tokens_per_s"] / max(alternating["tokens_per_s"], 1e-9), 3
        ),
    }


def bench_engine_multistep_ab(args, preset: str) -> dict:
    """K-step decode-window A/B through the REAL engine
    (scheduler.decode_window at K in {1, 4, 8}; K=1 is
    multi_step_window=False, the PR-1 single-token lookahead pipeline).
    A seeded decode-heavy replay measures the per-token HOST cost — the
    schedule+dispatch+sample step-phase histogram sums divided by tokens
    produced, i.e. the host round-trip the window amortizes K-fold —
    then a second stop-mask replay on the same engines stops every
    stream mid-window via a stop_token_id chosen from the greedy
    reference, proving the device stop-mask keeps the wasted-token rate
    ~0 (the pre-mask tax was up to K-1 tokens per stop).  Greedy parity
    across every K is asserted on the stop replay's outputs."""
    import dataclasses as _dc
    import gc

    from production_stack_tpu.engine.config import (
        CacheConfig,
        EngineConfig,
        PRESETS,
        SchedulerConfig,
    )
    from production_stack_tpu.engine.core.engine import LLMEngine
    from production_stack_tpu.engine.core.sequence import SamplingParams

    S = max(2, min(args.batch, 8) // 2)  # decode streams
    ctx_tokens = 96
    T = 96  # decode tokens per stream in the throughput replay
    HOST_PHASES = ("schedule", "dispatch", "sample")

    def run(k: int) -> dict:
        sched = dict(
            max_num_seqs=S,
            prefill_buckets=(128, 256),
            max_model_len=512,
        )
        if k == 1:
            sched["multi_step_window"] = False
        else:
            sched["decode_window"] = k
        eng = LLMEngine(EngineConfig(
            model=_dc.replace(PRESETS[preset]),
            cache=CacheConfig(num_blocks=S * ((ctx_tokens + T) // 16 + 3) + 32),
            scheduler=SchedulerConfig(**sched),
        ))
        prompts = [
            [(7 * i + j) % 101 for j in range(ctx_tokens)] for i in range(S)
        ]
        for i in range(S):
            eng.add_request(
                f"r{i}", prompt_token_ids=prompts[i],
                sampling_params=SamplingParams(max_tokens=T, ignore_eos=True),
            )
        outs: dict = {i: [] for i in range(S)}

        def pump(until_produced: int) -> int:
            produced = 0
            steps = 0
            while eng.has_unfinished() and produced < until_produced:
                steps += 1
                assert steps < 5000, "engine failed to drain"
                for out in eng.step():
                    outs[int(out.seq_id[1:])].append(out.new_token_id)
                    produced += 1
            return produced

        # Warm: prefills + XLA compile + pipeline/window fill.
        warmed = pump(16 * S)
        sums0 = {p: eng.obs.step_hists[p].sum for p in HOST_PHASES}
        collect0 = eng.obs.step_hists["collect"].sum
        t0 = time.perf_counter()
        produced = pump(10**9)
        wall = time.perf_counter() - t0
        host_s = sum(
            eng.obs.step_hists[p].sum - sums0[p] for p in HOST_PHASES
        )
        phase_ms = {
            p: round((eng.obs.step_hists[p].sum - sums0[p]) * 1e3, 2)
            for p in HOST_PHASES
        }
        phase_ms["collect"] = round(
            (eng.obs.step_hists["collect"].sum - collect0) * 1e3, 2
        )

        # Stop-mask replay: per-stream stop token = a token first seen
        # late in the greedy reference, so every stream stops mid-flight
        # (deterministic across K by greedy parity).
        stop_toks = []
        for i in range(S):
            ref = outs[i]
            tok = ref[-1]
            for pos in range(16, len(ref)):
                if ref[pos] not in ref[:pos]:
                    tok = ref[pos]
                    break
            stop_toks.append(tok)
        gen0 = eng.stats()["total_generated_tokens"]
        for i in range(S):
            eng.add_request(
                f"s{i}", prompt_token_ids=prompts[i],
                sampling_params=SamplingParams(
                    max_tokens=T, ignore_eos=True,
                    stop_token_ids=[stop_toks[i]],
                ),
            )
        stop_outs: dict = {}
        steps = 0
        while eng.has_unfinished():
            steps += 1
            assert steps < 5000, "engine failed to drain"
            for out in eng.step():
                stop_outs.setdefault(out.seq_id, []).append(out.new_token_id)
        stats = eng.stats()
        stop_generated = stats["total_generated_tokens"] - gen0
        wasted = stats["multistep_wasted_tokens"]
        result = {
            "per_token_host_ms": round(host_s / max(produced, 1) * 1e3, 4),
            "tokens_per_s": round(produced / max(wall, 1e-9), 1),
            "step_phase_ms": phase_ms,
            "stop_replay_tokens": int(stop_generated),
            "wasted_tokens": int(wasted),
            "wasted_rate": round(wasted / max(stop_generated, 1), 4),
            "fallbacks": dict(stats["multistep_fallback"]),
        }
        del eng
        gc.collect()
        return result, stop_outs

    results = {}
    parity = True
    ref_stop = None
    for k in (1, 4, 8):
        results[f"k{k}"], stop_outs = run(k)
        if ref_stop is None:
            ref_stop = stop_outs
        elif stop_outs != ref_stop:
            parity = False
    return {
        **results,
        # >= 4x is the acceptance bar: the window amortizes the host
        # round-trip K-fold, so K=8 should cut per-token host cost ~8x.
        "host_gap_reduction_k8_vs_k1": round(
            results["k1"]["per_token_host_ms"]
            / max(results["k8"]["per_token_host_ms"], 1e-9), 2
        ),
        "greedy_parity": parity,
    }


def bench_engine_mixed_window_ab(args, preset: str) -> dict:
    """Mixed K-step window A/B through the REAL engine: a seeded
    Poisson continuous-arrival replay (prompts keep arriving while
    resident streams decode — the north-star sustained-traffic regime,
    where the old window-selection rule pinned the engine at K=1) over
    the {K=1 mixed, K=8 mixed} x {ngram 0, 3} grid.  The primary
    metric is the per-token HOST cost expressed as host round-trips
    per produced token — each round-trip is one synchronous
    host<->device cycle (a blocking K=1 mixed step, or one pipelined
    window dispatch+collect pair), costing scheduling, H2D array
    staging, a device sync, and host sampling post-processing; the
    mixed window amortizes exactly this, turning one round-trip per
    TOKEN into one per WINDOW while prompts wait.  On CPU (where host
    and "device" share the same cores) wall-clock cannot isolate that
    serialization, so the round-trip count is the honest structural
    measure; the decode host-gap ms/token and the step-phase sums ride
    along as timing detail, and on TPU the gap becomes the real
    device-idle cost.  Also reports TTFT p50/p95 of the arrivals (the
    admission-boundary guarantee: windows end when a prompt completes,
    so TTFT must stay within 1.10x of the K=1 arm) and decode ITL p95
    of the resident streams (reported honestly: windowed tokens arrive
    in bursts, so token-granular p95 reflects delivery batching, not
    lost throughput).  Arrivals are scheduled in GENERATED-TOKEN time
    (seeded exponential gaps), so the workload is identical across
    arms and greedy byte-identity is assertable across every grid
    cell."""
    import dataclasses as _dc
    import gc
    import random

    from production_stack_tpu.engine.config import (
        CacheConfig,
        EngineConfig,
        PRESETS,
        SchedulerConfig,
    )
    from production_stack_tpu.engine.core.engine import LLMEngine
    from production_stack_tpu.engine.core.sequence import SamplingParams

    S_RES = 2           # resident decode streams
    RES_CTX = 96        # resident prompt length
    CHUNK = 64          # one static chunk bucket: 512-token prompts = 8 chunks
    ARRIVAL_PROMPT = 512
    ARRIVAL_GEN = 4     # tokens generated per admitted arrival
    N_WARM = 3          # arrivals before measurement (XLA compile)
    N_MEAS = 8          # measured arrivals
    HOST_PHASES = ("schedule", "dispatch", "sample")

    rng = random.Random(20260804)
    # Seeded Poisson (exponential inter-arrival gaps) in resident
    # generated-token time: deterministic across arms, and tight enough
    # (mean gap ~9 resident tokens vs 8 prefill chunks + 4 generated
    # tokens per arrival) that a prompt is nearly ALWAYS waiting — the
    # sustained regime the mixed window exists for.
    meas_gaps = [max(6, int(rng.expovariate(1 / 9))) for _ in range(N_MEAS)]
    meas_at = []
    acc = 0
    for g in meas_gaps:
        acc += g
        meas_at.append(acc)
    # Warm arrivals are pinned, not sampled: one lone prompt, then two
    # near-simultaneous ones (a queue-depth-2 moment) so every window
    # variant — full-K and adaptive-clamp scan lengths, both decode
    # buckets — XLA-compiles BEFORE measurement; a first-use compile in
    # the measured segment would charge seconds to one arrival's TTFT.
    # The measured replay only starts once all warm work has drained
    # (its thresholds are relative to the drain point), so warm backlog
    # never queues ahead of a measured arrival.
    warm_at = [8, 26, 26][:N_WARM]
    arrival_prompts = [
        [(7 * i + 13 * n + 1) % 101 for i in range(ARRIVAL_PROMPT)]
        for n in range(N_WARM + N_MEAS)
    ]
    res_prompts = [
        [(5 * i + 3 * r) % 103 for i in range(RES_CTX)] for r in range(S_RES)
    ]

    def run(k: int, ngram: int) -> tuple:
        sched = dict(
            max_num_seqs=4,
            prefill_buckets=(128, 256, 512),
            prefill_chunk_buckets=(CHUNK,),
            max_model_len=768,
            speculative_ngram=ngram,
        )
        if k == 1:
            sched["mixed_window"] = False
        else:
            sched["decode_window"] = k
        eng = LLMEngine(EngineConfig(
            model=_dc.replace(PRESETS[preset]),
            cache=CacheConfig(num_blocks=420),
            scheduler=SchedulerConfig(**sched),
        ))
        res_budget = warm_at[-1] + meas_at[-1] + 96
        for r in range(S_RES):
            eng.add_request(
                f"res{r}", prompt_token_ids=list(res_prompts[r]),
                sampling_params=SamplingParams(
                    max_tokens=res_budget, ignore_eos=True),
            )
        outs: dict = {}
        ttft_s: dict = {}
        added_t: dict = {}
        last_tok_t: dict = {}
        itl_gaps: list = []
        finished: set = set()
        next_arrival = 0
        meas_base = None
        measuring = False
        sums0 = dict.fromkeys(HOST_PHASES, 0.0)
        produced0 = 0
        gap0 = 0.0
        rt0 = 0
        # Host round-trips: synchronous mixed steps (the "mixed" phase
        # histogram observes each _run_mixed) + pipelined
        # dispatch/collect cycles (the "collect" phase observes each).
        rt_count = lambda: (
            eng.obs.step_hists["mixed"].count
            + eng.obs.step_hists["collect"].count
        )
        steps = 0
        while eng.has_unfinished():
            steps += 1
            assert steps < 30000, "engine failed to drain"
            for out in eng.step():
                now = time.perf_counter()
                rid = out.seq_id
                outs.setdefault(rid, []).append(out.new_token_id)
                if out.finished:
                    finished.add(rid)
                if rid in added_t and rid not in ttft_s:
                    ttft_s[rid] = now - added_t.pop(rid)
                if rid.startswith("res") and measuring:
                    if rid in last_tok_t:
                        itl_gaps.append(now - last_tok_t[rid])
                    last_tok_t[rid] = now
            driver = len(outs.get("res0", []))
            if meas_base is None and next_arrival >= N_WARM and all(
                f"arr{n}" in finished for n in range(N_WARM)
            ):
                # All warm work drained: every executable variant is
                # compiled, the queue holds only residents — start the
                # measurement clocks and anchor the measured thresholds.
                measuring = True
                meas_base = driver
                sums0 = {
                    p: eng.obs.step_hists[p].sum for p in HOST_PHASES
                }
                produced0 = eng.stats()["total_generated_tokens"]
                gap0 = eng._gap_total_s
                rt0 = rt_count()
                last_tok_t.clear()
            while True:
                # Admit every due arrival in ONE pass: the pinned warm
                # pair must land as a genuine queue-depth-2 moment (the
                # adaptive clamp's shorter-window variants compile
                # here, not inside the measured segment).
                if next_arrival >= N_WARM + N_MEAS:
                    due = False
                elif next_arrival < N_WARM:
                    due = driver >= warm_at[next_arrival]
                elif meas_base is None:
                    due = False
                else:
                    due = (
                        driver
                        >= meas_base + meas_at[next_arrival - N_WARM]
                    )
                if not due:
                    break
                rid = f"arr{next_arrival}"
                added_t[rid] = time.perf_counter()
                eng.add_request(
                    rid,
                    prompt_token_ids=list(arrival_prompts[next_arrival]),
                    sampling_params=SamplingParams(
                        max_tokens=ARRIVAL_GEN, ignore_eos=True),
                )
                next_arrival += 1
        stats = eng.stats()
        produced = stats["total_generated_tokens"] - produced0
        host_s = sum(
            eng.obs.step_hists[p].sum - sums0[p] for p in HOST_PHASES
        )
        gap_s = eng._gap_total_s - gap0
        meas_ttfts = sorted(
            ttft_s[f"arr{n}"] for n in range(N_WARM, N_WARM + N_MEAS)
        )

        def pct(sorted_vals, q):
            if not sorted_vals:
                return 0.0
            i = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1)))
            return sorted_vals[i]

        round_trips = rt_count() - rt0
        result = {
            "host_round_trips_per_token": round(
                round_trips / max(produced, 1), 4
            ),
            "host_gap_ms_per_token": round(
                gap_s / max(produced, 1) * 1e3, 4
            ),
            "step_phase_host_ms_per_token": round(
                host_s / max(produced, 1) * 1e3, 4
            ),
            "ttft_p50_ms": round(pct(meas_ttfts, 0.50) * 1e3, 1),
            "ttft_p95_ms": round(pct(meas_ttfts, 0.95) * 1e3, 1),
            "decode_itl_p95_ms": round(
                pct(sorted(itl_gaps), 0.95) * 1e3, 1
            ),
            "mixed_window_chunk_tokens": int(
                stats["mixed_window_chunk_tokens"]
            ),
            "prefill_chunk_tokens": int(stats["prefill_chunk_tokens"]),
            "fallbacks": dict(stats["multistep_fallback"]),
            "wasted_tokens": int(stats["multistep_wasted_tokens"]),
        }
        del eng
        gc.collect()
        return result, outs

    results = {}
    parity = True
    ref_outs = None
    for k in (1, 8):
        for ngram in (0, 3):
            cell = f"k{k}_ng{ngram}"
            results[cell], outs = run(k, ngram)
            if ref_outs is None:
                ref_outs = outs
            elif outs != ref_outs:
                parity = False
    k1, k8 = results["k1_ng0"], results["k8_ng0"]
    return {
        **results,
        # The acceptance bars: >= 3x per-token host-cost cut (host
        # round-trips per token) for K=8 mixed vs K=1 mixed under
        # continuous arrivals, with arrival TTFT p95 within 1.10x
        # (windows end at admission boundaries).
        "host_cost_cut_k8_vs_k1": round(
            k1["host_round_trips_per_token"]
            / max(k8["host_round_trips_per_token"], 1e-9), 2
        ),
        "ttft_p95_ratio_k8_vs_k1": round(
            k8["ttft_p95_ms"] / max(k1["ttft_p95_ms"], 1e-9), 3
        ),
        "greedy_parity": parity,
    }


def bench_engine_mixed_window_depth_grid(args, preset: str) -> dict:
    """The ROADMAP grid through the REAL engine: queue-depth {1, 4, 16}
    x drafter {none, ngram, model} on a templated AND an adversarial
    replay — depth scaling of packed multi-prompt mixed windows plus
    the drafter roofline, measured.
    Each cell holds the waiting queue at a target depth d in {1, 4, 16}
    (continuous refill from a fixed 16-arrival pool the moment the queue
    dips below d) while two resident streams decode.  Drafting is
    pure-decode-window-only (mixed windows keep the drafting state warm
    but never draft), so each cell runs TWO timed phases: the admission
    phase (continuous refill — the depth-monotonicity claim; identical
    workload across replays and drafter arms) and a pure-decode TAIL
    after the arrival pool drains — S_TAIL FRESH streams decoding
    through chained spec windows, where the drafter arms separate.
    The model arm loads the TARGET preset as its own drafter (identical
    deterministic init; fresh tail streams keep the draft cache's
    in-graph prime covering the full context, so acceptance is total).
    The replays differ only in the tail text: templated tail streams
    cycle fast (prompt-lookup heaven, n-gram acceptance near-total);
    the adversarial tail adds repetition/frequency penalties so the
    text NEVER cycles — the non-templated regime the ROADMAP claim is
    about — which zeroes prompt-lookup acceptance while the model
    drafter's penalty-aware proposals stay accepted, so its tail
    tokens/s must strictly beat ngram's: acceptance quality measured
    as throughput.
    Arrival prompts are LONGER than the largest
    whole-prefill bucket, so every cell admits through mixed windows —
    the grid isolates PACKING: at depth 1 each window carries one
    prompt's 2 chunks (a short scan, one host dispatch+collect round
    trip per prompt); depth 4 fills 8 of a K=16 window's iterations;
    depth 16 packs all 16 with 8 prompts' chunk cursors back-to-back,
    so deeper queues amortize the same per-window host round-trip over
    more admitted tokens: tokens/s (arrival prompt tokens + generated
    tokens over the measured wall-clock) must be monotone NON-DECREASING
    in depth, within a 2% measurement-noise band per step (CPU timing
    jitter).  A reference cell re-runs depth 16 with
    --no-multi-prompt-window (the single-head planner + adaptive
    deep-queue clamp) to pin the packed path's waiting_head count at
    ZERO against the clamp's nonzero fallbacks.  Greedy parity is a
    sha256 digest over every arrival's full token stream (identical
    prompts + greedy sampling = byte-identical streams across every
    cell, packed or not); resident streams are checked as
    PREFIX-consistent instead (cells stop at different points, so
    lengths differ — a delivery-schedule artifact, not sampling
    divergence).  The warm phase is TWO full dress-rehearsal segments
    of the same refill policy over equal-sized pools, each drained
    completely.  Two, not one: the first segment starts cold (resident
    prefill transient), so its (decode-bucket x window-length) shape
    sequence differs from steady state — but every LATER segment
    starts from the same macro-state (residents decoding, waiting
    queue empty), and arrival dynamics are step-synchronous and
    deterministic, so segment 2 replays segment 3's shape sequence
    exactly and every XLA executable the measured segment needs is
    compiled before the clock starts."""
    import dataclasses as _dc
    import gc
    import hashlib

    from production_stack_tpu.engine.config import (
        CacheConfig,
        EngineConfig,
        PRESETS,
        SchedulerConfig,
    )
    from production_stack_tpu.engine.core.engine import LLMEngine
    from production_stack_tpu.engine.core.sequence import SamplingParams

    S_RES = 2            # resident decode streams
    RES_CTX = 96         # resident prompt length == the one prefill bucket
    CHUNK = 64           # one static chunk bucket: arrivals = 2 chunks
    ARRIVAL_PROMPT = 128  # 2 chunks -> up to 8 prompts pack per K=16 window
    # First token at the admitting window's collect + ONE windowed
    # decode token (exercises the join path), then the slot frees: the
    # grid measures packed ADMISSION throughput, with decode realism
    # carried by the two long-lived residents.  Longer tails would
    # couple depth to drafter row-compute on CPU (verify rows are only
    # free on HBM-bound hardware) and measure that instead.
    ARRIVAL_GEN = 2
    N_WARM = 32          # TWO dress-rehearsal segments (see docstring)
    N_MEAS = 32
    RES_BUDGET = 600     # resident generation cap (never reached)
    S_TAIL = 8           # fresh decode streams for the tail phase
    TAIL_RAMP = 400      # untimed: tail prefills + spec-scan compiles
    TAIL_TOK = 600       # decode tokens timed in the tail phase

    # Admission phase: IDENTICAL across replays and drafter arms (the
    # depth-monotonicity claim is about packing, and mixed windows
    # never draft) — pseudo-random streams, all distinct, no prefix
    # sharing.  The drafter arms separate in the TAIL below.
    arrival_prompts = [
        [(11 * i + 17 * n + 3) % 101 for i in range(ARRIVAL_PROMPT)]
        for n in range(N_WARM + N_MEAS)
    ]
    res_prompts = [
        [(5 * i + 3 * r) % 103 for i in range(RES_CTX)]
        for r in range(S_RES)
    ]

    template = (5, 17, 9, 33, 21, 5, 17, 9)

    def tail_for(replay: str):
        """(prompts, extra SamplingParams kwargs) for the tail streams.

        templated: rotated repetitive prompts, plain greedy — the
        free-running tiny model settles into cycles fast, so
        prompt-lookup acceptance is near-total (n-gram heaven).
        adversarial: distinct pseudo-random prompts PLUS repetition/
        frequency penalties.  The penalties keep the generated text
        from ever cycling — which is exactly the non-templated traffic
        the ROADMAP claim is about, and is what defeats prompt-lookup
        (no bigram ever repeats).  The model drafter's penalty-aware
        proposals (the drafter replays the carried penalty state along
        its chain) keep ITS acceptance total, so the arm separation is
        acceptance quality, not prompt trivia."""
        if replay == "templated":
            prompts = [
                (list(template[r % len(template):])
                 + list(template) * 16)[:RES_CTX]
                for r in range(S_TAIL)
            ]
            return prompts, {}
        prompts = [
            [(7 * i + 5 * r + 11) % 97 for i in range(RES_CTX)]
            for r in range(S_TAIL)
        ]
        return prompts, {"frequency_penalty": 0.6,
                         "repetition_penalty": 1.3}

    def run(depth: int, drafter: str, replay: str,
            packed: bool = True) -> dict:
        sched = dict(
            # 8 arrival slots beside the 2 residents: a K=16 window can
            # pack exactly 8 two-chunk arrivals, so queue DEPTH is what
            # fills the scan — depth 16 packs all 16 iterations, depth
            # 4 fills 8, depth 1 rides 2 — and every window boundary
            # the deep queue saves is measured amortization, not a
            # batch-size ceiling artifact.
            max_num_seqs=10,
            # The largest whole-prefill bucket (96, the residents') is
            # SMALLER than an arrival prompt, so arrivals always admit
            # through mixed windows — depth 1 included.
            prefill_buckets=(RES_CTX,),
            prefill_chunk_buckets=(CHUNK,),
            max_model_len=768,
            decode_window=16,
        )
        if drafter == "ngram":
            sched["speculative_ngram"] = 3
        elif drafter == "model":
            # The target preset as its own drafter: identical
            # deterministic init (same seed) keeps acceptance near
            # total, so the arm measures the fused draft-KV machinery,
            # not a random drafter's (zero) agreement.
            sched["speculative_model"] = preset
            sched["speculative_draft_len"] = 3
        if not packed:
            sched["multi_prompt_window"] = False
        eng = LLMEngine(EngineConfig(
            model=_dc.replace(PRESETS[preset]),
            cache=CacheConfig(num_blocks=420),
            scheduler=SchedulerConfig(**sched),
        ))
        for r in range(S_RES):
            eng.add_request(
                f"res{r}", prompt_token_ids=list(res_prompts[r]),
                sampling_params=SamplingParams(
                    max_tokens=RES_BUDGET, ignore_eos=True),
            )
        outs: dict = {}
        ttft_s: dict = {}
        added_t: dict = {}
        finished: set = set()
        next_arrival = 0

        def refill(pool_end: int) -> None:
            nonlocal next_arrival
            while (next_arrival < pool_end
                   and eng.scheduler.num_waiting < depth):
                rid = f"arr{next_arrival}"
                added_t[rid] = time.perf_counter()
                eng.add_request(
                    rid,
                    prompt_token_ids=list(arrival_prompts[next_arrival]),
                    sampling_params=SamplingParams(
                        max_tokens=ARRIVAL_GEN, ignore_eos=True),
                )
                next_arrival += 1

        def drive(pool_end: int) -> None:
            steps = 0
            while not all(
                f"arr{n}" in finished for n in range(pool_end)
            ):
                steps += 1
                assert steps < 30000, "engine failed to drain"
                refill(pool_end)
                for out in eng.step():
                    rid = out.seq_id
                    outs.setdefault(rid, []).append(out.new_token_id)
                    if out.finished:
                        finished.add(rid)
                    if rid in added_t and rid not in ttft_s:
                        ttft_s[rid] = time.perf_counter() - added_t.pop(rid)

        # Warm: cold-start segment (resident prefill + first arrivals),
        # then one steady-state dress rehearsal that replays the
        # measured segment's exact shape sequence.  Each drains fully.
        drive(N_WARM // 2)
        drive(N_WARM)
        t0 = time.perf_counter()
        s0 = eng.stats()
        gen0 = s0["total_generated_tokens"]
        fb0 = dict(s0["multistep_fallback"]).get("waiting_head", 0)
        hist0 = (eng.mixed_window_prompts_hist.count,
                 eng.mixed_window_prompts_hist.sum)
        drive(N_WARM + N_MEAS)
        elapsed = time.perf_counter() - t0
        s1 = eng.stats()
        for r in range(S_RES):
            eng.abort_request(f"res{r}")
        while eng.has_unfinished():
            for out in eng.step():
                outs.setdefault(out.seq_id, []).append(out.new_token_id)

        # Pure-decode TAIL: S_TAIL FRESH streams decode through
        # chained speculative windows with the queue empty — the phase
        # where the drafter arms separate, since mixed windows never
        # draft.  Fresh streams (not the admission residents) so the
        # model drafter's lazy in-graph prime covers the FULL context
        # (context at the first spec window <= the history window H),
        # keeping identical-weights acceptance total; the untimed ramp
        # absorbs the tail prefills, the spec executables' compiles
        # (both prime variants dispatch within the first chained
        # windows), and the prime itself.
        tail_prompts, tail_kw = tail_for(replay)
        for r in range(S_TAIL):
            eng.add_request(
                f"tail{r}", prompt_token_ids=list(tail_prompts[r]),
                sampling_params=SamplingParams(
                    max_tokens=400, ignore_eos=True, **tail_kw),
            )

        def pump(n_tokens: int) -> None:
            produced = 0
            steps = 0
            while produced < n_tokens:
                steps += 1
                assert steps < 30000, "engine failed to drain"
                for out in eng.step():
                    outs.setdefault(out.seq_id, []).append(
                        out.new_token_id)
                    produced += 1

        pump(TAIL_RAMP)
        st0 = eng.stats()
        t1 = time.perf_counter()
        pump(TAIL_TOK)
        tail_elapsed = time.perf_counter() - t1
        st1 = eng.stats()
        for r in range(S_TAIL):
            eng.abort_request(f"tail{r}")
        while eng.has_unfinished():
            for out in eng.step():
                outs.setdefault(out.seq_id, []).append(out.new_token_id)
        win_n = eng.mixed_window_prompts_hist.count - hist0[0]
        win_sum = eng.mixed_window_prompts_hist.sum - hist0[1]
        gen_delta = s1["total_generated_tokens"] - gen0
        tokens = N_MEAS * ARRIVAL_PROMPT + gen_delta
        meas_ttfts = sorted(
            ttft_s[f"arr{n}"] for n in range(N_WARM, N_WARM + N_MEAS)
        )

        def pct(sorted_vals, q):
            if not sorted_vals:
                return 0.0
            i = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1)))
            return sorted_vals[i]

        digest = hashlib.sha256()
        for n in range(N_WARM + N_MEAS):
            digest.update(
                f"arr{n}:{','.join(map(str, outs[f'arr{n}']))};".encode()
            )
        drafted = st1["spec_tokens_drafted"] - st0["spec_tokens_drafted"]
        accepted = (st1["spec_tokens_accepted"]
                    - st0["spec_tokens_accepted"])
        result = {
            "tokens_per_s": round(tokens / max(elapsed, 1e-9), 1),
            "decode_tokens_per_s": round(
                TAIL_TOK / max(tail_elapsed, 1e-9), 1
            ),
            "acceptance_rate": round(accepted / drafted, 3) if drafted
            else 0.0,
            "ttft_p50_ms": round(pct(meas_ttfts, 0.50) * 1e3, 1),
            "ttft_p95_ms": round(pct(meas_ttfts, 0.95) * 1e3, 1),
            "waiting_head": int(
                dict(s1["multistep_fallback"]).get("waiting_head", 0) - fb0
            ),
            "prompts_per_window_mean": round(win_sum / max(win_n, 1), 2),
            "transfer_overlap_s": round(
                s1["window_transfer_overlap_seconds"], 4
            ),
            "spec_draft_fraction_s": round(
                st1["spec_draft_fraction_seconds"], 4
            ),
            "greedy_digest": digest.hexdigest()[:16],
            "_res_streams": [list(outs.get(f"res{r}", []))
                             for r in range(S_RES)]
            + [list(outs.get(f"tail{r}", []))
               for r in range(S_TAIL)],
        }
        del eng
        gc.collect()
        return result

    DEPTHS = (1, 4, 16)
    DRAFTERS = ("none", "ngram", "model")
    REPLAYS = (("temp", "templated"), ("adv", "adversarial"))
    results = {}
    for rp, replay in REPLAYS:
        for depth in DEPTHS:
            for drafter in DRAFTERS:
                results[f"{rp}_d{depth}_{drafter}"] = run(
                    depth, drafter, replay)
    results["temp_d16_none_nopack"] = run(
        16, "none", "templated", packed=False)

    # Parity is PER REPLAY (the two replays feed different prompts);
    # within a replay every cell — any depth, any drafter, packed or
    # not — must emit byte-identical greedy arrival streams and
    # prefix-consistent resident streams.
    parity = True
    res_parity = True
    for rp, _ in REPLAYS:
        cells = [r for c, r in results.items() if c.startswith(rp + "_")]
        parity &= len({r["greedy_digest"] for r in cells}) == 1
        for r_i in range(S_RES + S_TAIL):
            streams = [c["_res_streams"][r_i] for c in cells]
            shortest = min(streams, key=len)
            res_parity &= all(
                s[: len(shortest)] == shortest for s in streams)
    for cell in results.values():
        del cell["_res_streams"]
    monotone = all(
        results[f"{rp}_d1_{dr}"]["tokens_per_s"]
        <= results[f"{rp}_d4_{dr}"]["tokens_per_s"] * 1.02
        and results[f"{rp}_d4_{dr}"]["tokens_per_s"]
        <= results[f"{rp}_d16_{dr}"]["tokens_per_s"] * 1.02
        for rp, _ in REPLAYS for dr in DRAFTERS
    )
    # The drafter roofline: on the ADVERSARIAL replay prompt-lookup
    # collapses (ngram acceptance ~0 -> one token per scan iteration)
    # while the model drafter keeps proposing the target's own argmax,
    # so its pure-decode tail must be STRICTLY faster.  Depth doesn't
    # matter in the tail (queue empty), so the three depths are three
    # independent samples — compare their sums.
    adv_model = sum(
        results[f"adv_d{d}_model"]["decode_tokens_per_s"] for d in DEPTHS)
    adv_ngram = sum(
        results[f"adv_d{d}_ngram"]["decode_tokens_per_s"] for d in DEPTHS)
    return {
        **results,
        # The acceptance bars: tokens/s monotone non-decreasing in queue
        # depth (2% CPU-noise band per step) in EVERY drafter x replay
        # arm, ZERO waiting_head fallbacks on the packed path at depth
        # 16, model drafter strictly beating ngram on the adversarial
        # decode tail, and greedy streams byte-identical across every
        # cell of a replay including the unpacked reference.
        "tokens_per_s_monotone": monotone,
        "waiting_head_at_depth16": results["temp_d16_none"]["waiting_head"],
        "greedy_parity": parity,
        "resident_prefix_parity": res_parity,
        "model_beats_ngram_adversarial": adv_model > adv_ngram,
        "adv_decode_speedup_model_vs_ngram": round(
            adv_model / max(adv_ngram, 1e-9), 2
        ),
        "depth_speedup_d16_vs_d1": round(
            results["temp_d16_none"]["tokens_per_s"]
            / max(results["temp_d1_none"]["tokens_per_s"], 1e-9), 2
        ),
    }


def bench_engine_spec_window_ab(args, preset: str) -> dict:
    """Speculation x window grid through the REAL engine
    (K in {1, 8} x ngram in {0, 3}): the PR-11 fusion claim, measured.
    K=8/ngram=3 runs the fused draft-and-verify INSIDE the window scan;
    K=8/ngram=0 is the window-only baseline; K=1/ngram=3 the legacy
    host-side speculative path; K=1/ngram=0 classic stepping.  Two
    seeded replays: an acceptance-FRIENDLY one (templated, repetitive
    prompts — prompt-lookup heaven) and an ADVERSARIAL one
    (pseudo-random prompts, wandering outputs).  Reported per cell:
    tokens/s, per-token host cost (schedule+dispatch+sample sums over
    produced tokens), and the acceptance rate.  The bars: the fused
    path beats window-only tokens/s >= 1.3x on the friendly replay and
    stays within 5% on the adversarial one (a rejected draft costs a
    scan iteration, never a host round-trip).  Greedy parity across all
    four cells is asserted per replay.  Measurement stops before the
    drain tail so shrinking-bucket XLA compiles at end-of-stream don't
    pollute the steady-state rate."""
    import dataclasses as _dc
    import gc

    from production_stack_tpu.engine.config import (
        CacheConfig,
        EngineConfig,
        PRESETS,
        SchedulerConfig,
    )
    from production_stack_tpu.engine.core.engine import LLMEngine
    from production_stack_tpu.engine.core.sequence import SamplingParams

    S = max(2, min(args.batch, 8) // 2)
    ctx = 48
    T = 160  # decode tokens per stream
    HOST_PHASES = ("schedule", "dispatch", "sample")
    template = (5, 17, 9, 33, 21, 5, 17, 9)

    def prompts_for(replay: str):
        if replay == "friendly":
            # Templated with a per-stream rotation (identical prompts
            # would collapse into one prefix-cache entry and hide the
            # prefill cost differences between cells).
            return [
                (list(template[i % len(template):])
                 + list(template) * 8)[:ctx]
                for i in range(S)
            ]
        return [
            [(13 * i + 7 * j * j + j) % 311 % 101 for j in range(ctx)]
            for i in range(S)
        ]

    def run(k: int, ngram: int, replay: str):
        sched = dict(
            max_num_seqs=S,
            prefill_buckets=(64, 128),
            max_model_len=512,
            speculative_ngram=ngram,
        )
        if k == 1:
            sched["multi_step_window"] = False
        else:
            sched["decode_window"] = k
        eng = LLMEngine(EngineConfig(
            model=_dc.replace(PRESETS[preset]),
            cache=CacheConfig(
                num_blocks=S * ((ctx + 4 * T) // 16 + 3) + 32
            ),
            scheduler=SchedulerConfig(**sched),
        ))
        prompts = prompts_for(replay)
        for i in range(S):
            eng.add_request(
                f"r{i}", prompt_token_ids=prompts[i],
                sampling_params=SamplingParams(
                    max_tokens=T, ignore_eos=True
                ),
            )
        outs: dict = {i: [] for i in range(S)}

        def pump(until_produced: int) -> int:
            produced = 0
            steps = 0
            while eng.has_unfinished() and produced < until_produced:
                steps += 1
                assert steps < 20000, "engine failed to drain"
                for out in eng.step():
                    outs[int(out.seq_id[1:])].append(out.new_token_id)
                    produced += 1
            return produced

        warmed = pump(24 * S)  # prefills + XLA compile + window fill
        sums0 = {p: eng.obs.step_hists[p].sum for p in HOST_PHASES}
        t0 = time.perf_counter()
        # Stop measuring a margin before the first stream can finish:
        # end-of-stream bucket shrinkage recompiles the scan executable,
        # which is a one-time cost, not a steady-state rate.
        produced = pump(S * T - warmed - 8 * S)
        wall = time.perf_counter() - t0
        host_s = sum(
            eng.obs.step_hists[p].sum - sums0[p] for p in HOST_PHASES
        )
        pump(10**9)  # drain untimed
        stats = eng.stats()
        drafted = stats["spec_tokens_drafted"]
        accepted = stats["spec_tokens_accepted"]
        result = {
            "tokens_per_s": round(produced / max(wall, 1e-9), 1),
            "per_token_host_ms": round(
                host_s / max(produced, 1) * 1e3, 4
            ),
            "spec_tokens_drafted": int(drafted),
            "spec_tokens_accepted": int(accepted),
            "acceptance_rate": round(accepted / max(drafted, 1), 4),
            "spec_window_tokens": dict(stats["spec_window_tokens"]),
        }
        del eng
        gc.collect()
        return result, outs

    out: dict = {"greedy_parity": True}
    for replay in ("friendly", "adversarial"):
        cells = {}
        ref_outs = None
        for k, ngram in ((1, 0), (1, 3), (8, 0), (8, 3)):
            cells[f"k{k}_ng{ngram}"], outs = run(k, ngram, replay)
            if ref_outs is None:
                ref_outs = outs
            elif outs != ref_outs:
                out["greedy_parity"] = False
        fused = cells["k8_ng3"]["tokens_per_s"]
        window_only = cells["k8_ng0"]["tokens_per_s"]
        cells["fused_vs_window_tokens_ratio"] = round(
            fused / max(window_only, 1e-9), 3
        )
        out[replay] = cells
    return out


def bench_engine_overload_ab(args, preset: str) -> dict:
    """Overload shedding A/B through the REAL engine: a seeded Poisson
    workload arriving at ~2x the decode capacity, replayed twice — with
    bounded admission (SchedulerConfig queued_requests_cap, the same
    bound the API server enforces) and without (the unbounded legacy
    queue).  Records the p95 ITL of ADMITTED requests plus goodput
    (completed tokens/s of admitted work) and the shed count: the claim
    is that shedding keeps the admitted requests' latency flat while the
    unbounded queue drags everyone down (docs/robustness.md)."""
    import dataclasses as _dc
    import gc

    from production_stack_tpu.engine.config import (
        CacheConfig,
        EngineConfig,
        PRESETS,
        SchedulerConfig,
    )
    from production_stack_tpu.engine.core.engine import LLMEngine
    from production_stack_tpu.engine.core.sequence import SamplingParams

    S = max(2, min(args.batch, 8))
    n_requests = 8 * S  # ~2x oversubscribed vs the batch over the run
    prompt_len = 96
    gen_tokens = 48
    queue_cap = S  # bounded mode's max_queued_requests
    rng = np.random.RandomState(0)
    arrival_steps = sorted(
        (int(s), i)
        for i, s in enumerate(np.cumsum(rng.exponential(3.0, n_requests)))
    )

    def run(shed: bool) -> dict:
        eng = LLMEngine(EngineConfig(
            model=_dc.replace(PRESETS[preset]),
            cache=CacheConfig(
                num_blocks=(n_requests * (prompt_len + gen_tokens)) // 16 + 64
            ),
            scheduler=SchedulerConfig(
                max_num_seqs=S,
                prefill_buckets=(128, 256),
                max_model_len=512,
                max_queued_requests=queue_cap if shed else None,
                admission_control=shed,
            ),
        ))
        # Warm the compile caches off the clock.
        eng.add_request("warm", prompt_token_ids=[1] * prompt_len,
                        sampling_params=SamplingParams(max_tokens=4))
        while eng.has_unfinished():
            eng.step()
        arrivals = list(arrival_steps)
        token_times: dict = {}
        rejected = 0
        admitted = 0
        step = 0
        completed_tokens = 0
        t0 = time.perf_counter()
        while eng.has_unfinished() or arrivals:
            while arrivals and arrivals[0][0] <= step:
                _, i = arrivals.pop(0)
                cap_hit = (
                    shed and eng.scheduler.num_waiting >= queue_cap
                )
                if cap_hit:
                    rejected += 1  # the server's structured 429
                    continue
                admitted += 1
                eng.add_request(
                    f"r{i}",
                    prompt_token_ids=[(13 * i + j) % 101
                                      for j in range(prompt_len)],
                    sampling_params=SamplingParams(
                        max_tokens=gen_tokens, ignore_eos=True
                    ),
                )
            step += 1
            if step > 20000:
                break
            outs = eng.step()
            now = time.perf_counter()
            for out in outs:
                completed_tokens += 1
                token_times.setdefault(out.seq_id, []).append(now)
        wall = time.perf_counter() - t0
        gaps = sorted(
            b - a
            for times in token_times.values()
            for a, b in zip(times, times[1:])
        )
        result = {
            "admitted": admitted,
            "rejected": rejected,
            "itl_p95_ms": round(
                gaps[int(0.95 * (len(gaps) - 1))] * 1e3, 3
            ) if gaps else 0.0,
            "itl_max_ms": round(gaps[-1] * 1e3, 3) if gaps else 0.0,
            "goodput_tokens_per_s": round(completed_tokens / wall, 1),
        }
        del eng
        gc.collect()
        return result

    unbounded = run(False)
    shedding = run(True)
    return {
        "unbounded": unbounded,
        "shedding": shedding,
        # > 1.0 = shedding cut the admitted requests' ITL tail.
        "itl_p95_ratio": round(
            unbounded["itl_p95_ms"] / max(shedding["itl_p95_ms"], 1e-9), 3
        ),
        "goodput_ratio": round(
            shedding["goodput_tokens_per_s"]
            / max(unbounded["goodput_tokens_per_s"], 1e-9), 3
        ),
    }


def bench_engine_encode_ab(args, preset: str) -> dict:
    """Encode-lane A/B through the REAL engine (ISSUE 19; docs/engine.md
    "The encode lane", docs/router.md "Encode lanes & semantic cache"):

      throughput:  N embed texts through the batched [B, T] encode path
                   vs the serial per-text legacy loop (same forwards,
                   different batching) — claim: batched >= 3x texts/s;
      isolation:   streaming generation p95 ITL with a concurrent embed
                   pump vs embed-free — claim: within 1.10x (the step
                   loop runs at most ONE encode batch per window
                   boundary while generation is live);
      cache:       a repeat-heavy embeddings trace through the router's
                   semantic cache — claim: hit rate >= 0.5 with every
                   hit byte-identical to the first answer;
      parity:      /v1/embeddings and a greedy completion byte-identical
                   between the lane and --no-encode-lane.
    """
    import asyncio
    import dataclasses as _dc
    import gc

    from production_stack_tpu.engine.config import (
        CacheConfig,
        EngineConfig,
        PRESETS,
        SchedulerConfig,
    )
    from production_stack_tpu.engine.core.engine import LLMEngine

    n_texts = 64
    text_words = 24

    def sched(**kw):
        return SchedulerConfig(
            max_num_seqs=4, prefill_buckets=(128, 256), max_model_len=512,
            **kw,
        )

    def make_texts(tag: str):
        return [
            " ".join(f"{tag}{(17 * i + j) % 997}" for j in range(text_words))
            for i in range(n_texts)
        ]

    # -- leg 1: batched vs serial embed throughput (direct engine) -------
    eng = LLMEngine(EngineConfig(
        model=_dc.replace(PRESETS[preset]),
        cache=CacheConfig(num_blocks=256),
        scheduler=sched(),
    ))
    texts = make_texts("doc")
    token_lists = [eng.tokenizer.encode(t) for t in texts]
    bucket = eng.config.scheduler.encode_batch_buckets[-1]
    # Warm both paths' compiles off the clock.
    eng.embed(token_lists[0])
    eng.encode_batch(token_lists[:bucket])

    t0 = time.perf_counter()
    serial_out = [eng.embed(ids) for ids in token_lists]
    serial_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    batched_out = []
    for i in range(0, n_texts, bucket):
        batched_out.extend(eng.encode_batch(token_lists[i:i + bucket]))
    batched_s = time.perf_counter() - t0

    vectors_equal = all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(serial_out, batched_out)
    )
    throughput = {
        "texts": n_texts,
        "serial_texts_per_s": round(n_texts / serial_s, 1),
        "batched_texts_per_s": round(n_texts / batched_s, 1),
        "speedup": round(serial_s / max(batched_s, 1e-9), 2),
        "vectors_bitexact": vectors_equal,
    }
    del eng, serial_out, batched_out
    gc.collect()

    # -- legs 2-4: over HTTP (isolation, cache, parity) ------------------
    async def run_http() -> dict:
        from aiohttp.test_utils import TestClient, TestServer

        from production_stack_tpu.engine.server.api_server import (
            build_engine_app,
        )
        from production_stack_tpu.engine.server.async_engine import AsyncEngine
        from production_stack_tpu.router.app import build_app
        from production_stack_tpu.router.parser import (
            parse_args as parse_router_args,
        )

        def make_async(encode_lane: bool) -> AsyncEngine:
            return AsyncEngine(EngineConfig(
                model=_dc.replace(PRESETS[preset]),
                cache=CacheConfig(num_blocks=512),
                scheduler=sched(encode_lane=encode_lane),
            ))

        lane_eng = make_async(True)
        lane_srv = TestServer(build_engine_app(lane_eng, preset))
        await lane_srv.start_server()
        lane = TestClient(lane_srv)

        async def gen_itl(embed_load: bool) -> float:
            """p95 token gap across 3 concurrent greedy streams, with an
            optional concurrent embed pump riding the same engine."""
            gaps: list = []
            stop = asyncio.Event()

            async def pump():
                docs = make_texts("load")
                i = 0
                while not stop.is_set():
                    resp = await lane.post("/v1/embeddings", json={
                        "model": preset,
                        "input": docs[i % n_texts:][:4] or docs[:4],
                    })
                    await resp.read()
                    i += 4

            async def stream(i: int):
                resp = await lane.post("/v1/completions", json={
                    "model": preset,
                    "prompt": " ".join(f"g{i}w{j}" for j in range(32)),
                    "max_tokens": 24, "ignore_eos": True, "stream": True,
                })
                assert resp.status == 200, await resp.text()
                last = None
                async for chunk in resp.content.iter_any():
                    now = time.perf_counter()
                    if b"data: " not in chunk:
                        continue
                    if last is not None:
                        gaps.append(now - last)
                    last = now

            pump_task = (
                asyncio.ensure_future(pump()) if embed_load else None
            )
            try:
                await asyncio.gather(*(stream(i) for i in range(3)))
            finally:
                stop.set()
                if pump_task is not None:
                    await pump_task
            s = sorted(gaps)
            return s[int(0.95 * (len(s) - 1))] * 1e3 if s else 0.0

        # Warm compiles (prefill bucket + encode batch) off the clock.
        await gen_itl(embed_load=True)
        itl_free_ms = await gen_itl(embed_load=False)
        itl_load_ms = await gen_itl(embed_load=True)
        isolation = {
            "gen_itl_p95_embed_free_ms": round(itl_free_ms, 3),
            "gen_itl_p95_under_embed_ms": round(itl_load_ms, 3),
            "itl_ratio": round(itl_load_ms / max(itl_free_ms, 1e-9), 3),
        }

        # -- cache leg: repeat-heavy trace through the router ------------
        router_srv = TestServer(build_app(parse_router_args([
            "--static-backends", str(lane_srv.make_url("")).rstrip("/"),
            "--static-models", preset,
            "--engine-stats-interval", "1",
            "--encode-cache-max-bytes", "8000000",
        ])))
        await router_srv.start_server()
        router = TestClient(router_srv)
        distinct, total = 8, 32
        rng = np.random.RandomState(3)
        first_bytes: dict = {}
        hits = 0
        identical = True
        try:
            for n in range(total):
                # First pass touches every distinct doc once, then the
                # repeat-heavy tail (RAG re-chunking traffic shape).
                d = n if n < distinct else int(rng.randint(distinct))
                resp = await router.post("/v1/embeddings", json={
                    "model": preset, "input": f"corpus document {d}",
                })
                body = await resp.read()
                assert resp.status == 200, body
                if resp.headers.get("x-encode-cache") == "hit":
                    hits += 1
                    identical = identical and (body == first_bytes[d])
                else:
                    first_bytes.setdefault(d, body)
                # The store is a background task; let it land.
                await asyncio.sleep(0)
            await asyncio.sleep(0.05)
        finally:
            await router.close()
            await router_srv.close()
        cache = {
            "requests": total,
            "distinct": distinct,
            "hits": hits,
            "hit_rate": round(hits / total, 3),
            "hits_byte_identical": identical,
        }

        # -- parity leg: lane vs --no-encode-lane ------------------------
        serial_eng = make_async(False)
        serial_srv = TestServer(build_engine_app(serial_eng, preset))
        await serial_srv.start_server()
        serial = TestClient(serial_srv)
        try:
            embed_body = {"model": preset,
                          "input": ["parity one", "parity two"]}
            comp_body = {"model": preset,
                         "prompt": "the quick brown fox", "max_tokens": 16}
            pair = []
            for client in (lane, serial):
                e = await (await client.post(
                    "/v1/embeddings", json=embed_body)).json()
                c = await (await client.post(
                    "/v1/completions", json=comp_body)).json()
                pair.append((e["data"], c["choices"][0]["text"]))
            parity = {
                "embeddings_identical": pair[0][0] == pair[1][0],
                "greedy_completion_identical": pair[0][1] == pair[1][1],
            }
        finally:
            await serial.close()
            await serial_srv.close()
            await lane.close()
            await lane_srv.close()
        return {"isolation": isolation, "cache": cache, "parity": parity}

    http_legs = asyncio.run(run_http())
    gc.collect()
    result = {"throughput": throughput, **http_legs}
    result["criteria"] = {
        "batched_3x_serial": throughput["speedup"] >= 3.0,
        "gen_itl_within_1_10x": result["isolation"]["itl_ratio"] <= 1.10,
        "cache_hit_rate_ge_0_5": result["cache"]["hit_rate"] >= 0.5,
        "cache_hits_byte_identical": result["cache"]["hits_byte_identical"],
        "no_encode_lane_parity": all(result["parity"].values()),
    }
    return result


def bench_remote_prefix_ab(args, preset: str) -> dict:
    """Remote shared-prefix import A/B through the REAL engine against a
    LATENCY-INJECTED kvserver: a cold replica imports a long warm-store
    prefix while persistent decoders stream tokens.

    The legacy synchronous path (cache.remote_prefetch=False) issues one
    blocking GET per KV block inside Scheduler.schedule(), so the whole
    step loop stalls for a chain of RTTs — the decoder ITL spike.  The
    async plane (prefetch=True) resolves the chain on fetcher threads
    with ONE batched MGET round-trip; decode ITL stays flat.  Round-trip
    counts come from the server's per-op frame counters, so the MGET
    batching claim is measured, not asserted."""
    import asyncio
    import dataclasses as _dc
    import gc
    import threading

    from production_stack_tpu.engine.config import (
        CacheConfig,
        EngineConfig,
        PRESETS,
        SchedulerConfig,
    )
    from production_stack_tpu.engine.core.engine import LLMEngine
    from production_stack_tpu.engine.core.sequence import SamplingParams
    from production_stack_tpu.kvserver.server import KVStore, handle_client

    latency_s = 0.05
    shared_len = 480  # ~29 content-keyed blocks at block_size 16
    S_dec = 2
    decoder_tokens = 48

    # In-process latency-injected store (same asyncio server production
    # runs, daemon thread).
    store = KVStore(256 << 20)
    loop = asyncio.new_event_loop()
    state = {}
    started = threading.Event()

    def serve():
        asyncio.set_event_loop(loop)

        async def boot():
            server = await asyncio.start_server(
                lambda r, w: handle_client(store, r, w, latency_s=latency_s),
                "127.0.0.1", 0,
            )
            state["port"] = server.sockets[0].getsockname()[1]
            started.set()

        loop.run_until_complete(boot())
        loop.run_forever()

    threading.Thread(target=serve, daemon=True).start()
    assert started.wait(10)
    url = f"kv://127.0.0.1:{state['port']}"
    shared_prompt = [(13 * j + 5) % 101 for j in range(shared_len)]

    def make(role, prefetch):
        return LLMEngine(EngineConfig(
            model=_dc.replace(PRESETS[preset]),
            cache=CacheConfig(
                num_blocks=S_dec * 24 + shared_len // 16 + 48,
                remote_kv_url=url,
                disagg_role=role,
                remote_prefetch=prefetch,
            ),
            scheduler=SchedulerConfig(
                max_num_seqs=S_dec + 1,
                prefill_buckets=(128, 256, 512),
                max_model_len=1024,
            ),
        ))

    # Warm the store once through a prefill-role engine.
    producer = make("prefill", True)
    producer.add_request(
        "warm", prompt_token_ids=shared_prompt,
        sampling_params=SamplingParams(max_tokens=4),
    )
    while producer.has_unfinished():
        producer.step()
    producer.flush_prefix_exports(timeout=60.0)
    producer.offload.remote_client.close()
    exported = producer.remote_prefix_blocks_exported
    del producer
    gc.collect()

    def run(prefetch: bool) -> dict:
        ops_before = dict(store.ops)
        eng = make("decode", prefetch)
        for i in range(S_dec):
            eng.add_request(
                f"dec{i}",
                prompt_token_ids=[(7 * i + j) % 101 for j in range(96)],
                sampling_params=SamplingParams(
                    max_tokens=decoder_tokens, ignore_eos=True
                ),
            )
        for _ in range(8):  # compile + pipeline fill before measuring
            eng.step()
        t_arrive = time.perf_counter()
        eng.add_request(
            "shared", prompt_token_ids=shared_prompt,
            sampling_params=SamplingParams(max_tokens=8),
        )
        token_times: dict = {}
        ttft = None
        steps = 0
        while eng.has_unfinished():
            steps += 1
            if steps > 4000:
                break
            outs = eng.step()
            now = time.perf_counter()
            for out in outs:
                if out.seq_id.startswith("dec"):
                    token_times.setdefault(out.seq_id, []).append(now)
                elif out.seq_id == "shared" and ttft is None:
                    ttft = now - t_arrive
        gaps = sorted(
            b - a
            for times in token_times.values()
            for a, b in zip(times, times[1:])
        )
        ops = {
            k: store.ops.get(k, 0) - ops_before.get(k, 0)
            for k in ("get", "mget")
        }
        result = {
            "itl_p95_ms": round(
                gaps[int(0.95 * (len(gaps) - 1))] * 1e3, 3
            ) if gaps else 0.0,
            "itl_max_ms": round(gaps[-1] * 1e3, 3) if gaps else 0.0,
            "shared_ttft_ms": round((ttft or 0.0) * 1e3, 2),
            "blocks_imported": eng.remote_prefix_blocks_fetched,
            "store_round_trips": ops,
            # tpu:kv_wire_bytes_total view: bytes this import pulled
            # over the remote boundary, by wire format.
            "wire_bytes": {
                f"{t}/{f}": b
                for (t, f), b in eng.stats()["kv_wire_bytes"].items()
            },
        }
        eng.offload.remote_client.close()
        del eng
        gc.collect()
        return result

    sync = run(False)
    prefetch = run(True)
    return {
        "store_latency_ms": latency_s * 1e3,
        "chain_blocks_exported": exported,
        "sync": sync,
        "prefetch": prefetch,
        # > 1.0 = the async plane cut the decoder ITL tail during the
        # cold-replica import.
        "itl_max_stall_ratio": round(
            sync["itl_max_ms"] / max(prefetch["itl_max_ms"], 1e-9), 2
        ),
        # MGET batching: round-trips per imported chain, both modes.
        "round_trips_sync": sync["store_round_trips"],
        "round_trips_prefetch": prefetch["store_round_trips"],
    }


def bench_kv_capacity_ab(args, preset: str) -> dict:
    """KV-capacity A/B at an EQUAL HBM block-byte budget: int8 KV vs
    bf16 KV through the real engine.

    The claim (ROADMAP item 2, SURVEY §5 — long-context is KV capacity
    extension + reuse): at the same byte budget an int8 pool holds ~2x
    the resident tokens, which shows up as (a) more admitted concurrency
    under pool pressure, (b) a higher prefix hit rate once the bf16 pool
    starts evicting cached blocks the int8 pool retains, and (c) decode
    throughput that does not regress.  Model shapes use a head_dim-64
    mini-llama (every flagship preset has head_dim >= 64; tiny-llama's
    head_dim 16 is a test artifact that overweights the fp32 scale
    plane).

    Also proves the quantized WIRE end-to-end: one preemption
    offload -> restore cycle on the int8-wire engine must reproduce the
    in-HBM greedy output byte-for-byte (the native (data, scale) wire
    transforms nothing), and the same cycle on the legacy fp32 wire
    must stream ~4x the host-tier bytes — read from the new
    tpu:kv_wire_bytes_total counters."""
    import dataclasses as _dc
    import gc

    from production_stack_tpu.engine.config import (
        CacheConfig,
        EngineConfig,
        ModelConfig,
        SchedulerConfig,
    )
    from production_stack_tpu.engine.core.engine import LLMEngine
    from production_stack_tpu.engine.core.sequence import SamplingParams

    model = ModelConfig(
        name="llama-kv-capacity-ab", vocab_size=384, hidden_size=128,
        intermediate_size=256, num_layers=2, num_heads=2, num_kv_heads=2,
        head_dim=64, max_model_len=2048, dtype="bfloat16",
    )
    bs = 16
    # Per-block bytes by kv dtype (mirrors LLMEngine._kv_bytes): the
    # budget is what a 96-block bf16 pool occupies; each arm gets as
    # many blocks as fit in THAT byte budget.
    dense_block = 2 * model.num_kv_heads * model.head_dim * 2 * model.num_layers * bs
    int8_block = 2 * model.num_kv_heads * (model.head_dim + 4) * model.num_layers * bs
    budget_bytes = 96 * dense_block
    arm_blocks = {
        "bf16": budget_bytes // dense_block,
        "int8": budget_bytes // int8_block,
    }

    n_requests = 12
    gen_tokens = 8
    prompt_blocks = 16  # 256-token prompts: pool-bound admission
    prompts = [
        [(17 * i + 5 + j) % 101 for j in range(prompt_blocks * bs)]
        for i in range(n_requests)
    ]

    def make(kv_dtype, num_blocks, max_seqs=n_requests, **cache_kw):
        return LLMEngine(EngineConfig(
            model=_dc.replace(model),
            cache=CacheConfig(
                block_size=bs, num_blocks=int(num_blocks),
                kv_cache_dtype=kv_dtype, **cache_kw,
            ),
            scheduler=SchedulerConfig(
                max_num_seqs=max_seqs,
                prefill_buckets=(128, 256),
                max_model_len=512,
            ),
        ))

    def run_arm(arm: str) -> dict:
        # Arm label -> CacheConfig.kv_cache_dtype ("auto" = the model
        # dtype, bf16 here).
        kv_dtype = "int8" if arm == "int8" else "auto"

        # Phase 1 — admitted concurrency + decode tok/s: all requests
        # arrive at once; the pool bounds how many run concurrently.
        eng = make(kv_dtype, arm_blocks[arm])
        for i, p in enumerate(prompts):
            eng.add_request(
                f"r{i}", prompt_token_ids=p,
                sampling_params=SamplingParams(
                    max_tokens=gen_tokens, ignore_eos=True
                ),
            )
        max_running = 0
        tokens = 0
        steps = 0
        t0 = time.perf_counter()
        while eng.has_unfinished():
            steps += 1
            if steps > 8000:
                break
            outs = eng.step()
            tokens += sum(1 for o in outs if o.new_token_id >= 0)
            max_running = max(max_running, eng.scheduler.num_running)
        dt = time.perf_counter() - t0
        del eng
        gc.collect()

        # Phase 2 — prefix hit rate under eviction: two sequential
        # rounds of a 10-chain working set (160 blocks).  Round 1
        # registers every chain; the int8 pool (180 blocks) RETAINS the
        # whole set and serves round 2 from cache, while the bf16 pool
        # (96 blocks) LRU-thrashes — the classic cyclic-reuse cliff —
        # and re-prefills everything.  This is the SURVEY §5 mechanism
        # (more resident KV => higher hit rate) measured directly.
        eng = make(kv_dtype, arm_blocks[arm], max_seqs=2)
        for round_tag in ("w", "h"):
            for i, p in enumerate(prompts[:10]):
                eng.add_request(
                    f"{round_tag}{i}", prompt_token_ids=p,
                    sampling_params=SamplingParams(max_tokens=2),
                )
                steps = 0
                while eng.has_unfinished():
                    steps += 1
                    assert steps < 4000
                    eng.step()
        hit_rate = eng.block_pool.prefix_hit_rate
        del eng
        gc.collect()

        return {
            "num_blocks": int(arm_blocks[arm]),
            "resident_tokens": int(arm_blocks[arm]) * bs,
            "admitted_concurrency": max_running,
            "decode_tokens_per_s": round(tokens / max(dt, 1e-9), 1),
            "replay_prefix_hit_rate": round(hit_rate, 3),
        }

    bf16 = run_arm("bf16")
    int8 = run_arm("int8")

    # Offload->restore greedy parity + wire bytes: int8 wire (native
    # (data, scale) tuples) vs the legacy fp32 wire, same workload.
    # remote_prefetch=False pins the deterministic synchronous save
    # path so both wires snapshot identical block sets.
    def offload_cycle(wire: str) -> dict:
        def drain(eng, tag):
            for i, p in enumerate(prompts[:4]):
                eng.add_request(
                    f"{tag}{i}", prompt_token_ids=p,
                    sampling_params=SamplingParams(
                        max_tokens=24, ignore_eos=True
                    ),
                )
            out: dict = {}
            steps = 0
            while eng.has_unfinished():
                steps += 1
                assert steps < 8000
                for o in eng.step():
                    if o.new_token_id >= 0:
                        out.setdefault(o.seq_id, []).append(o.new_token_id)
            return out

        roomy = make("int8", 256, max_seqs=4, kv_wire_format=wire)
        want = drain(roomy, "c")
        del roomy
        gc.collect()
        # Tight pool + host tier: the younger sequences preempt via
        # offload and restore through the wire under test (4 seqs need
        # ~72 blocks incl. generation growth; 52 forces paging).
        tight = make("int8", 52, max_seqs=4, kv_wire_format=wire,
                     host_offload_gb=0.25, remote_prefetch=False)
        got = drain(tight, "c")
        stats = tight.stats()
        cycle = {
            "saves": tight.offload.saves,
            "restores": tight.offload.restores,
            "greedy_parity": got == want,
            "host_wire_bytes": {
                f"{t}/{f}": b
                for (t, f), b in stats["kv_wire_bytes"].items()
            },
        }
        del tight
        gc.collect()
        return cycle

    int8_wire = offload_cycle("auto")
    fp32_wire = offload_cycle("fp32")
    int8_bytes = sum(int8_wire["host_wire_bytes"].values())
    fp32_bytes = sum(fp32_wire["host_wire_bytes"].values())
    return {
        "budget_bytes": int(budget_bytes),
        "bf16": bf16,
        "int8": int8,
        # The headline: resident tokens at the same byte budget.
        "capacity_ratio": round(
            int8["resident_tokens"] / bf16["resident_tokens"], 2
        ),
        "concurrency_ratio": round(
            int8["admitted_concurrency"]
            / max(bf16["admitted_concurrency"], 1), 2
        ),
        "hit_rate_delta": round(
            int8["replay_prefix_hit_rate"] - bf16["replay_prefix_hit_rate"],
            3,
        ),
        "decode_tokens_ratio": round(
            int8["decode_tokens_per_s"]
            / max(bf16["decode_tokens_per_s"], 1e-9), 2
        ),
        "offload_cycle_int8_wire": int8_wire,
        "offload_cycle_fp32_wire": fp32_wire,
        # ~4x: the fp32 wire inflates every offloaded block.
        "wire_bytes_ratio_fp32_over_int8": round(
            fp32_bytes / max(int8_bytes, 1), 2
        ),
    }


def bench_disagg_ab(args, preset: str) -> dict:
    """Disaggregated prefill/decode A/B through the REAL stack: router +
    two CPU engines replaying one seeded Poisson mixed workload both
    ways —

      disagg: 1 prefill-role + 1 decode-role engine over an in-process
              kvserver, routing policy ``disagg`` (two-phase prime ->
              handoff -> decode with admission prefetch import);
      fused:  the same 2 engines role-less, least-loaded routing
              (today's behavior — prompts prefill on whichever backend
              decodes them).

    Claim (DistServe/Splitwise): moving ALL prefill off the decode pool
    removes prompt interference from inter-token latency — decode ITL
    p95 improves — at a bounded TTFT cost (the prime + export + import
    handoff; acceptance bound: p95 TTFT regression <= 10%).  Handoff
    latency comes from the router's own
    ``tpu_router:disagg_handoff_seconds`` histogram, fallback counters
    must stay zero (any nonzero = the fast path silently wasn't
    measured)."""
    import asyncio
    import dataclasses as _dc
    import gc
    import threading

    n_requests = 20
    gen_tokens = 24
    mean_gap_s = 0.25
    rng = np.random.RandomState(7)
    # Mixed prompt mix: short chat heads + long document heads — the
    # long ones are the decode-interference injectors.
    # In WORDS (~3.6 tokens each on tiny-llama's tokenizer): ~115 to
    # ~920 prompt tokens, under max_model_len 2048.
    prompt_lens = rng.choice([32, 80, 160, 256], size=n_requests,
                             p=[0.35, 0.25, 0.25, 0.15])
    gaps = rng.exponential(mean_gap_s, n_requests)

    def make_engine(role, kv_url):
        from production_stack_tpu.engine.config import (
            CacheConfig,
            EngineConfig,
            PRESETS,
            SchedulerConfig,
        )
        from production_stack_tpu.engine.server.async_engine import AsyncEngine

        return AsyncEngine(EngineConfig(
            model=_dc.replace(PRESETS[preset]),
            cache=CacheConfig(
                num_blocks=768,
                remote_kv_url=kv_url,
                disagg_role=role,
            ),
            scheduler=SchedulerConfig(
                max_num_seqs=4,
                prefill_buckets=(128, 256, 512),
                max_model_len=2048,
            ),
        ))

    async def replay(client, model: str) -> dict:
        send_times: list = []
        ttfts: list = []
        gaps_observed: list = []

        async def one(i: int, delay: float):
            await asyncio.sleep(delay)
            prompt = " ".join(
                f"w{(13 * i + j) % 997}" for j in range(int(prompt_lens[i]))
            )
            t0 = time.perf_counter()
            resp = await client.post(
                "/v1/completions",
                json={"model": model, "prompt": prompt,
                      "max_tokens": gen_tokens, "ignore_eos": True,
                      "stream": True},
            )
            assert resp.status == 200, await resp.text()
            last = None
            async for chunk in resp.content.iter_any():
                now = time.perf_counter()
                if b"data: " not in chunk:
                    continue
                if last is None:
                    ttfts.append(now - t0)
                else:
                    gaps_observed.append(now - last)
                last = now

        offsets = np.cumsum(gaps)
        await asyncio.gather(*(one(i, float(offsets[i]))
                               for i in range(n_requests)))

        def p95(xs):
            xs = sorted(xs)
            return xs[int(0.95 * (len(xs) - 1))] * 1e3 if xs else 0.0

        return {
            "ttft_p95_ms": round(p95(ttfts), 2),
            "ttft_p50_ms": round(p95(ttfts[:1]) if not ttfts else
                                 sorted(ttfts)[len(ttfts) // 2] * 1e3, 2),
            "itl_p95_ms": round(p95(gaps_observed), 2),
            "itl_max_ms": round(max(gaps_observed) * 1e3, 2)
            if gaps_observed else 0.0,
        }

    async def run_mode(disagg: bool) -> dict:
        from aiohttp.test_utils import TestClient, TestServer

        from production_stack_tpu.engine.server.api_server import (
            build_engine_app,
        )
        from production_stack_tpu.kvserver.server import KVStore, handle_client
        from production_stack_tpu.router.app import build_app
        from production_stack_tpu.router.parser import (
            parse_args as parse_router_args,
        )

        kv_loop = None
        kv_thread = None
        kv_url = None
        if disagg:
            kv_store = KVStore(capacity_bytes=256 << 20)
            kv_loop = asyncio.new_event_loop()
            started = threading.Event()
            state: dict = {}

            def serve():
                asyncio.set_event_loop(kv_loop)

                async def boot():
                    server = await asyncio.start_server(
                        lambda r, w: handle_client(kv_store, r, w),
                        "127.0.0.1", 0,
                    )
                    state["port"] = server.sockets[0].getsockname()[1]
                    started.set()

                kv_loop.run_until_complete(boot())
                kv_loop.run_forever()

            kv_thread = threading.Thread(target=serve, daemon=True)
            kv_thread.start()
            assert started.wait(10)
            kv_url = f"kv://127.0.0.1:{state['port']}"

        roles = ("prefill", "decode") if disagg else (None, None)
        engines = [make_engine(r, kv_url if disagg else None) for r in roles]
        servers = []
        for eng in engines:
            s = TestServer(build_engine_app(eng, preset))
            await s.start_server()
            servers.append(s)
        urls = [str(s.make_url("")).rstrip("/") for s in servers]
        router_argv = [
            "--static-backends", ",".join(urls),
            "--static-models", ",".join([preset] * 2),
            "--engine-stats-interval", "1",
            "--routing-logic", "disagg" if disagg else "least_loaded",
        ]
        if disagg:
            router_argv += ["--static-backend-roles", "prefill,decode"]
        router_server = TestServer(build_app(parse_router_args(router_argv)))
        await router_server.start_server()
        client = TestClient(router_server)
        try:
            # Warm every engine's compile caches off the clock (each
            # prefill bucket + the decode shapes), through the router so
            # the disagg path warms its prime flow too.
            for _ in range(2):
                for prompt_len in (32, 80, 160, 256):
                    resp = await client.post(
                        "/v1/completions",
                        json={"model": preset,
                              "prompt": " ".join(
                                  f"warm{j}" for j in range(prompt_len)
                              ),
                              "max_tokens": 2, "ignore_eos": True},
                    )
                    await resp.read()
            from prometheus_client import REGISTRY as _REG

            def handoff_stats():
                s = _REG.get_sample_value(
                    "tpu_router:disagg_handoff_seconds_sum"
                ) or 0.0
                c = _REG.get_sample_value(
                    "tpu_router:disagg_handoff_seconds_count"
                ) or 0.0
                fb = {
                    r: _REG.get_sample_value(
                        "tpu_router:disagg_fallback_total", {"reason": r}
                    ) or 0.0
                    for r in ("prime_failed", "prefix_miss",
                              "handoff_unexported", "prefill_pool_empty",
                              "prefill_breaker_open", "decode_pool_empty")
                }
                return s, c, fb

            h_sum0, h_count0, fb0 = handoff_stats()
            result = await replay(client, preset)
            h_sum1, h_count1, fb1 = handoff_stats()
            if disagg:
                handoffs = h_count1 - h_count0
                result["handoffs"] = int(handoffs)
                result["handoff_mean_ms"] = round(
                    (h_sum1 - h_sum0) / handoffs * 1e3, 2
                ) if handoffs else 0.0
                result["fallbacks"] = {
                    r: int(fb1[r] - fb0[r]) for r in fb1
                    if fb1[r] - fb0[r] > 0
                }
                result["decode_engine_prefix_imported"] = int(
                    engines[1].engine.remote_prefix_blocks_fetched
                )
                result["decode_engine_handoff_hits"] = int(
                    engines[1].engine.disagg_handoff_hits
                )
            return result
        finally:
            await client.close()
            await router_server.close()
            for s in servers:
                await s.close()
            if kv_loop is not None:
                kv_loop.call_soon_threadsafe(kv_loop.stop)
            if kv_thread is not None:
                kv_thread.join(timeout=5)

    fused = asyncio.run(run_mode(False))
    gc.collect()
    disagg = asyncio.run(run_mode(True))
    gc.collect()
    return {
        "workload": {
            "requests": n_requests,
            "gen_tokens": gen_tokens,
            "mean_arrival_gap_s": mean_gap_s,
            "prompt_lens": sorted(set(int(x) for x in prompt_lens)),
        },
        "fused": fused,
        "disagg": disagg,
        # > 1.0 = disaggregation cut the decode ITL tail.
        "itl_p95_ratio": round(
            fused["itl_p95_ms"] / max(disagg["itl_p95_ms"], 1e-9), 3
        ),
        # <= 1.10 is the acceptance bound (TTFT tax of the handoff).
        "ttft_p95_ratio": round(
            disagg["ttft_p95_ms"] / max(fused["ttft_p95_ms"], 1e-9), 3
        ),
    }


def bench_fleet_surge_ab(
    args,
    *,
    num_engines: int = 12,
    duration_s: float = 6.0,
    base_qps: float = 6.0,
    peak_qps: float = 60.0,
    seed: int = 7,
) -> dict:
    """Fleet-level admission A/B over the in-process fleet harness
    (testing/fleet.py): the SAME seeded 10x diurnal replay — replicas
    scaled 2→N→2 through drain mid-surge — run twice:

      router_shed: fleet admission ON (router/capacity.py) — the router
        sheds with structured 429s the moment estimated headroom is
        exhausted, before any engine queue grows;
      engine_shed: --no-fleet-admission — overload queues per-engine
        until each backend's own bounded-admission 429 trips (the PR-5
        baseline), oversubscription degrading every admitted stream's
        ITL on the way there.

    The claim (docs/robustness.md "Fleet admission & autoscaling
    contract"): router-level shedding holds admitted p95 ITL flat at
    comparable goodput, and relocates sheds from N engine queues to one
    cheap headroom check.  CPU-only, no jax import — fake engines model
    capacity-degraded ITL deterministically."""
    import asyncio

    from production_stack_tpu.testing.fleet import FleetHarness

    n_mid = max(4, num_engines)

    async def run(fleet_admission: bool) -> dict:
        h = FleetHarness(
            num_engines=n_mid, seed=seed,
            capacity=2, max_queued=8,
            tokens_per_sec=60.0, ttft=0.01, max_tokens=6,
            default_slots=8.0,
            fleet_admission=fleet_admission,
            router_args=("--stream-idle-timeout-s", "2.0"),
        )
        await h.start(active=2)
        try:
            async def scale_up():
                await h.scale_to(n_mid)

            async def scale_down():
                h.scale_to_background(2)

            await h.replay(
                duration_s=duration_s, base_qps=base_qps,
                peak_qps=peak_qps,
                events=[
                    (duration_s * 0.4, scale_up),
                    (duration_s * 0.75, scale_down),
                ],
            )
            await h.wait_background()
            rep = h.report()
            return {
                "total": rep["total"],
                "completed": rep["completed"],
                "shed_router": rep["shed_router"],
                "shed_engine": rep["shed_engine"],
                "dropped": rep["dropped"],
                "errors": rep["error"],
                "admitted_itl_p95_ms": round(
                    rep["admitted_itl_p95_s"] * 1e3, 2
                ),
                "oracle_admitted": round(h.oracle_admitted(), 1),
            }
        finally:
            await h.close()

    router_shed = asyncio.run(run(True))
    engine_shed = asyncio.run(run(False))
    return {
        "router_shed": router_shed,
        "engine_shed": engine_shed,
        # > 1.0 = fleet admission cut the admitted requests' ITL tail.
        "itl_p95_ratio": round(
            engine_shed["admitted_itl_p95_ms"]
            / max(router_shed["admitted_itl_p95_ms"], 1e-9), 3
        ),
        "goodput_ratio": round(
            router_shed["completed"] / max(engine_shed["completed"], 1), 3
        ),
    }


def bench_multi_round_ab(args, preset=None, fake_only: bool = False,
                         small: bool = False) -> dict:
    """The north-star workload (BASELINE.md / SURVEY §6): multi-round QA
    at fleet scale, A/B'd across the full routing ladder — round-robin
    vs session-affinity vs kv_aware vs kv_aware+popularity — on fleet KV
    hit rate, TTFT p50/p95, and output tok/s.

    Two rigs:

      fake_fleet: the PR-10 FleetHarness (12 fake engines behind the
        REAL router, chunk-chain prefix-cache + prefill cost model) runs
        the CI-scaled canonical workload (26 users x 5 rounds, 1000-word
        shared system prompt, heterogeneous answer lengths, 4s join
        ramp) per policy.  Each arm runs TWICE on a fresh fleet and the
        TTFT samples/hit tokens are POOLED — seeded percentile
        comparisons must dominate asyncio loop noise.  A fifth rung runs
        popularity WITH the shared KV store, where replica growth warms
        the hot prefix by import instead of recompute.

      real_engines (skipped with ``fake_only``): 2 CPU tiny-llama
        engines behind the real router, the same ladder at small scale
        with per-arm content salts (fresh-prefix A/B without rebooting
        engines), plus the GREEDY PARITY gate: one replayed conversation
        through every policy must produce byte-identical outputs —
        routing choice must never change generated bytes.

    Acceptance (recorded under ``criteria``): kv_aware+popularity beats
    plain kv_aware on fleet KV hit rate and TTFT p50, and beats
    session-affinity on both."""
    import asyncio
    import dataclasses as _dc

    from production_stack_tpu.testing.multi_round import (
        MultiRoundFleetConfig,
        ROUTING_LADDER,
        run_fleet_multi_round,
    )

    cfg = MultiRoundFleetConfig()
    repeats = 2
    if small:
        cfg = _dc.replace(
            cfg, num_engines=6, num_users=13, num_rounds=3, qps=14.0,
            join_window_s=2.0,
        )
        repeats = 1

    def pooled(rows: list) -> dict:
        samples = sorted(s for r in rows for s in r["ttft_samples"])
        hit = sum(r["hit_tokens"] for r in rows)
        query = sum(r["query_tokens"] for r in rows)

        def pct(p):
            if not samples:
                return 0.0
            return samples[min(len(samples) - 1,
                               round(p / 100 * (len(samples) - 1)))]

        out = {
            "runs": len(rows),
            "requests": sum(r["requests"] for r in rows),
            "failed": sum(r["failed"] for r in rows),
            "kv_hit_rate": round(hit / query, 4) if query else 0.0,
            "ttft_p50_ms": round(pct(50) * 1e3, 1),
            "ttft_p95_ms": round(pct(95) * 1e3, 1),
            "output_tok_s": round(
                sum(r["output_tok_s"] for r in rows) / max(len(rows), 1), 1
            ),
            "shared_prefix_backends": max(
                r["shared_prefix_backends"] for r in rows
            ),
        }
        if any("popularity" in r for r in rows):
            out["popularity"] = rows[-1].get("popularity")
        return out

    table = {}
    for policy in ROUTING_LADDER:
        rows = []
        for rep in range(repeats):
            rows.append(asyncio.run(run_fleet_multi_round(policy, cfg)))
        table[policy] = pooled(rows)
        log(f"multi_round[{policy}]: kv_hit={table[policy]['kv_hit_rate']} "
            f"ttft_p50={table[policy]['ttft_p50_ms']}ms "
            f"tok/s={table[policy]['output_tok_s']}")

    # Store-warming rung: the same popularity policy with the PR-4 shared
    # KV plane simulated — replica growth imports the hot prefix at ~4x
    # the prefill rate instead of recomputing it.
    store_cfg = _dc.replace(cfg, shared_store=True)
    store_row = asyncio.run(
        run_fleet_multi_round("kv_aware_popularity", store_cfg)
    )
    table["kv_aware_popularity_store"] = pooled([store_row])
    log("multi_round[popularity+store]: "
        f"kv_hit={table['kv_aware_popularity_store']['kv_hit_rate']} "
        f"ttft_p50={table['kv_aware_popularity_store']['ttft_p50_ms']}ms")

    pop = table["kv_aware_popularity"]
    kv = table["kv_aware"]
    sess = table["session"]
    criteria = {
        "pop_beats_kv_aware_hit": pop["kv_hit_rate"] > kv["kv_hit_rate"],
        "pop_beats_kv_aware_ttft_p50":
            pop["ttft_p50_ms"] < kv["ttft_p50_ms"],
        "pop_beats_session_hit": pop["kv_hit_rate"] > sess["kv_hit_rate"],
        "pop_beats_session_ttft_p50":
            pop["ttft_p50_ms"] < sess["ttft_p50_ms"],
        "shared_prefix_on_multiple_backends":
            pop["shared_prefix_backends"] > 1,
    }
    detail = {
        "workload": {
            "num_engines": cfg.num_engines, "num_users": cfg.num_users,
            "num_rounds": cfg.num_rounds, "qps": cfg.qps,
            "system_prompt_len": cfg.system_prompt_len,
            "user_info_len": cfg.user_info_len,
            "answer_len": cfg.answer_len,
            "heavy_answer_len": cfg.heavy_answer_len,
            "heavy_every": cfg.heavy_every,
            "seed": cfg.seed, "repeats_pooled": repeats,
        },
        "fake_fleet": table,
        "criteria": criteria,
    }
    if not fake_only:
        try:
            detail["real_engines"] = bench_multi_round_real(args, preset)
        except Exception as e:
            log(f"multi_round real-engine ladder failed: {e}")
            detail["real_engines_error"] = str(e)[:200]
    return detail


def bench_multi_round_real(args, preset: str) -> dict:
    """The multi-round ladder on REAL CPU tiny-llama engines: 2 engines
    boot ONCE; each routing-policy arm gets a fresh router and a SALTED
    system prompt (per-arm content can never hit a previous arm's prefix
    cache, so every arm measures from cold without rebooting/recompiling
    engines).  Fleet KV hit rate is read from the engines' own BlockPool
    token counters (deltas per arm).  Ends with the greedy-parity gate:
    one conversation replayed through every policy must generate
    byte-identical text."""
    import asyncio
    import dataclasses as _dc

    from production_stack_tpu.testing.multi_round import (
        ROUTING_LADDER,
        load_multi_round_module,
    )

    num_users = 4
    num_rounds = 3
    answer_len = 16
    # Big enough that the router's affinity chain resolves several
    # chunks per prompt (with --kv-chunk-chars 256 below), small enough
    # that round-3 histories stay under max_model_len on the byte
    # tokenizer (~3 tok/word).
    sys_words = 250
    info_words = 150

    async def run() -> dict:
        from aiohttp.test_utils import TestClient, TestServer

        from production_stack_tpu.engine.config import (
            CacheConfig,
            EngineConfig,
            PRESETS,
            SchedulerConfig,
        )
        from production_stack_tpu.engine.server.api_server import (
            build_engine_app,
        )
        from production_stack_tpu.engine.server.async_engine import AsyncEngine
        from production_stack_tpu.router.app import build_app
        from production_stack_tpu.router.parser import (
            parse_args as parse_router_args,
        )

        mod = load_multi_round_module()
        engines = [
            AsyncEngine(EngineConfig(
                model=_dc.replace(PRESETS[preset]),
                cache=CacheConfig(num_blocks=1536),
                scheduler=SchedulerConfig(
                    max_num_seqs=4,
                    prefill_buckets=(128, 256, 512, 1024),
                    max_model_len=2048,
                ),
            ))
            for _ in range(2)
        ]
        servers = []
        for eng in engines:
            s = TestServer(build_engine_app(eng, preset))
            await s.start_server()
            servers.append(s)
        urls = [str(s.make_url("")).rstrip("/") for s in servers]

        async def with_router(policy_argv):
            router_server = TestServer(build_app(parse_router_args([
                "--static-backends", ",".join(urls),
                "--static-models", ",".join([preset] * 2),
                "--engine-stats-interval", "1",
                *policy_argv,
            ])))
            await router_server.start_server()
            return router_server

        def pool_counters():
            return (
                sum(e.engine.block_pool.hit_tokens for e in engines),
                sum(e.engine.block_pool.query_tokens for e in engines),
            )

        out: dict = {"engines": 2, "preset": preset}
        try:
            # Warm compile caches off the clock: each engine sees every
            # prefill bucket + the decode shapes once, directly.
            warm_router = await with_router(["--routing-logic", "roundrobin"])
            warm_client = TestClient(warm_router)
            for words in (64, 200, 320):
                for _ in range(2):
                    resp = await warm_client.post(
                        "/v1/completions",
                        json={"model": preset,
                              "prompt": " ".join(
                                  f"warm{j}" for j in range(words)),
                              "max_tokens": 4, "ignore_eos": True},
                    )
                    await resp.read()
            await warm_client.close()

            ladder = {}
            for policy, (logic, extra) in ROUTING_LADDER.items():
                router_server = await with_router(
                    ["--routing-logic", logic, *extra,
                     # CPU-scale prompts are ~1-2k chars; resolve the
                     # affinity chain at finer granularity than the 1k
                     # default or the kv arms see a 1-chunk chain.
                     "--kv-chunk-chars", "256"])
                hit0, query0 = pool_counters()
                wl = mod.WorkloadConfig(
                    base_url=str(router_server.make_url("")).rstrip("/"),
                    model=preset,
                    num_users=num_users, num_rounds=num_rounds, qps=2.0,
                    system_prompt_len=sys_words, user_info_len=info_words,
                    answer_len=answer_len,
                    prompt_salt=f"[arm {policy}] ",
                    request_timeout=300.0,
                )
                result = await mod.run_benchmark(wl)
                hit1, query1 = pool_counters()
                summary = result["summary"]
                ttfts = sorted(
                    r.ttft for r in result["records"] if r.error is None
                )
                p50 = ttfts[len(ttfts) // 2] if ttfts else 0.0
                ladder[policy] = {
                    "requests": summary["requests_finished"],
                    "failed": summary["requests_failed"],
                    "kv_hit_rate": round(
                        (hit1 - hit0) / max(query1 - query0, 1), 4
                    ),
                    "ttft_p50_ms": round(p50 * 1e3, 1),
                    "output_tok_s": summary["output_tokens_per_s"],
                }
                log(f"multi_round real[{policy}]: "
                    f"kv_hit={ladder[policy]['kv_hit_rate']} "
                    f"ttft_p50={ladder[policy]['ttft_p50_ms']}ms")
                await router_server.close()
            out["ladder"] = ladder

            # Greedy-parity gate: ONE conversation replayed through every
            # policy; the generated bytes must not depend on routing.
            parity_outputs = {}
            for policy, (logic, extra) in ROUTING_LADDER.items():
                router_server = await with_router(
                    ["--routing-logic", logic, *extra])
                client = TestClient(router_server)
                history = []
                transcript = []
                for round_id in (1, 2):
                    history.append({
                        "role": "user",
                        "content": (
                            "Replay the fleet parity conversation, round "
                            f"{round_id}: summarize the production stack."
                        ),
                    })
                    resp = await client.post(
                        "/v1/chat/completions",
                        json={"model": preset, "messages": history,
                              "temperature": 0, "max_tokens": 16,
                              "ignore_eos": True},
                        headers={"x-user-id": "parity-user"},
                    )
                    body = await resp.json()
                    assert resp.status == 200, body
                    text = body["choices"][0]["message"]["content"]
                    transcript.append(text)
                    history.append({"role": "assistant", "content": text})
                parity_outputs[policy] = "\n".join(transcript)
                await client.close()
            texts = set(parity_outputs.values())
            out["greedy_parity_ok"] = len(texts) == 1
            out["parity_chars"] = len(next(iter(texts)))
            if len(texts) != 1:
                out["parity_outputs"] = {
                    k: v[:120] for k, v in parity_outputs.items()
                }
            return out
        finally:
            for s in servers:
                await s.close()

    return asyncio.run(run())


# -- trace report ----------------------------------------------------------


def run_trace_report(num_requests: int = 12, max_tokens: int = 16) -> dict:
    """Short serve through the router + fake engine, then pull the
    /debug/requests join and print a per-phase latency attribution table.

    CI-runnable on CPU (no jax import): the point is that every perf
    number this repo reports can come WITH attribution — a regression in
    the primary metric immediately shows which phase grew.  On hardware,
    point the same join at a real engine (docs/observability.md)."""
    import asyncio

    async def run() -> dict:
        from aiohttp.test_utils import TestClient, TestServer

        from production_stack_tpu.router.app import build_app
        from production_stack_tpu.router.parser import parse_args
        from production_stack_tpu.testing.fake_engine import (
            FakeEngineState,
            build_fake_engine_app,
        )

        state = FakeEngineState(
            tokens_per_sec=400.0, ttft=0.02, simulate_compiles=True,
        )
        engine_server = TestServer(build_fake_engine_app(state))
        await engine_server.start_server()
        backend = str(engine_server.make_url("")).rstrip("/")
        args = parse_args([
            "--static-backends", backend,
            "--static-models", state.model,
            "--engine-stats-interval", "1",
        ])
        router_server = TestServer(build_app(args))
        await router_server.start_server()
        client = TestClient(router_server)
        try:
            ids = []
            ttfts = []        # (seconds, compile_tainted) per request
            for i in range(num_requests):
                rid = f"trace-bench-{i}"
                t0 = time.perf_counter()
                resp = await client.post(
                    "/v1/completions",
                    json={"model": state.model, "prompt": f"probe {i}",
                          "max_tokens": max_tokens, "stream": True},
                    headers={"x-request-id": rid},
                )
                first_s = None
                tainted = False
                async for chunk in resp.content.iter_any():
                    if first_s is None:
                        first_s = time.perf_counter() - t0
                        # The engine stamps compile taint into the first
                        # SSE chunk (same sniff the router's stats
                        # monitor uses for its compile-excluded window).
                        tainted = (b'"compile": true' in chunk
                                   or b'"compile":true' in chunk)
                ttfts.append((first_s or 0.0, tainted))
                ids.append(rid)
            phases: dict = {}
            totals = []
            window_rows = []
            for rid in ids:
                resp = await client.get(f"/debug/requests/{rid}")
                if resp.status != 200:
                    continue
                joined = await resp.json()
                totals.append(joined["total_s"])
                for name, dur in joined["phase_s"].items():
                    phases.setdefault(name, []).append(dur)
            resp = await client.session.get(f"{backend}/debug/windows")
            if resp.status == 200:
                window_rows = (await resp.json()).get("windows", [])
            report = {"requests": len(totals)}
            raw = sorted(s for s, _ in ttfts)
            clean = sorted(s for s, tainted in ttfts if not tainted)

            def pct(sorted_vals, q):
                if not sorted_vals:
                    return 0.0
                idx = min(len(sorted_vals) - 1,
                          int(q * (len(sorted_vals) - 1) + 0.5))
                return sorted_vals[idx]

            # Raw vs compile-excluded TTFT: the gap IS the XLA compile
            # cost the first-chunk marker attributed — on the fake, the
            # cold pow2 prompt bucket's first request carries it.
            report["ttft"] = {
                "p50_ms": round(pct(raw, 0.50) * 1e3, 2),
                "p95_ms": round(pct(raw, 0.95) * 1e3, 2),
                "clean_p50_ms": round(pct(clean, 0.50) * 1e3, 2),
                "clean_p95_ms": round(pct(clean, 0.95) * 1e3, 2),
                "compile_tainted": sum(1 for _, t in ttfts if t),
            }
            if window_rows:
                ks = [w.get("k", 1) for w in window_rows]
                delivered = sum(
                    w.get("tokens_delivered", 0) for w in window_rows)
                chunk_tok = sum(
                    w.get("chunk_tokens_delivered", 0) for w in window_rows)
                depth_hist: dict = {}
                for w in window_rows:
                    d = str(w.get("chain_depth", 0))
                    depth_hist[d] = depth_hist.get(d, 0) + 1
                report["windows"] = {
                    "count": len(window_rows),
                    "mean_k": round(sum(ks) / len(ks), 2),
                    "chunk_token_share": round(
                        chunk_tok / max(1, delivered + chunk_tok), 3),
                    "chain_depth_hist": dict(sorted(depth_hist.items())),
                }
            if totals:
                mean_total = sum(totals) / len(totals)
                report["mean_total_ms"] = round(mean_total * 1e3, 2)
                table = {}
                for name, durs in sorted(phases.items()):
                    mean = sum(durs) / len(durs)
                    table[name] = {
                        "mean_ms": round(mean * 1e3, 3),
                        "max_ms": round(max(durs) * 1e3, 3),
                        "share": round(mean / mean_total, 3) if mean_total else 0.0,
                    }
                report["phases"] = table
                log("trace report: per-phase latency attribution "
                    f"({len(totals)} requests, mean e2e "
                    f"{report['mean_total_ms']} ms)")
                log(f"  {'phase':<24} {'mean_ms':>9} {'max_ms':>9} {'share':>6}")
                for name, row in table.items():
                    log(f"  {name:<24} {row['mean_ms']:>9.3f} "
                        f"{row['max_ms']:>9.3f} {row['share']:>6.1%}")
            t = report["ttft"]
            log("trace report: ttft "
                f"p50={t['p50_ms']}ms p95={t['p95_ms']}ms | "
                f"compile-excluded p50={t['clean_p50_ms']}ms "
                f"p95={t['clean_p95_ms']}ms "
                f"({t['compile_tainted']} tainted)")
            if "windows" in report:
                w = report["windows"]
                log("trace report: window composition "
                    f"n={w['count']} mean_k={w['mean_k']} "
                    f"chunk_token_share={w['chunk_token_share']} "
                    f"chain_depth_hist={w['chain_depth_hist']}")
            return report
        finally:
            await client.close()
            await engine_server.close()

    return asyncio.run(run())


# -- main ------------------------------------------------------------------


def approx_param_count(cfg) -> int:
    h, hd = cfg.hidden_size, cfg.head_dim
    H, K, I, V, L = (
        cfg.num_heads, cfg.num_kv_heads, cfg.intermediate_size,
        cfg.vocab_size, cfg.num_layers,
    )
    per_layer = h * H * hd + 2 * h * K * hd + H * hd * h + 3 * h * I + 2 * h
    embed = V * h * (1 if cfg.tie_word_embeddings else 2)
    return L * per_layer + embed + h


def _run_serving_phase(args) -> dict:
    """North-star serving metrics (BASELINE.md): multi-round QA through
    the REAL stack — engine api_server process -> router process -> the
    multi-round-QA harness over HTTP (the actual instrument; round-4
    verdict weak #3).  Runs before this process touches the accelerator
    so the engine subprocess can own it."""
    import importlib.util
    import os as _os

    try:
        spec = importlib.util.spec_from_file_location(
            "serving_bench",
            _os.path.join(_os.path.dirname(_os.path.abspath(__file__)),
                          "benchmarks", "serving_bench.py"),
        )
        serving_bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(serving_bench)
        from production_stack_tpu.engine.config import PRESETS

        on_tpu = _os.environ.get("JAX_PLATFORMS") != "cpu"
        preset = args.preset or ("llama-3.2-3b" if on_tpu else "tiny-llama")
        cfg = PRESETS[preset]
        # Scale the workload's prompt sizes to the serving context: the
        # byte-fallback tokenizer yields ~3 tokens per word, so nominal
        # 600-word prompts reach ~3.7k tokens — fine under the 8k presets
        # (capped 4096) but overflowing a 2048-context fallback preset.
        serving_len = min(cfg.max_model_len, 4096)
        # //10 leaves headroom for chat framing + 3 rounds of history
        # growth at the byte tokenizer's ~3 tokens/word.
        plen = min(600, serving_len // 10)
        log("serving bench: booting engine + router processes ...")
        summary = serving_bench.run_serving_bench_processes_sync(
            preset=preset,
            num_users=6, num_rounds=3, qps=2.0,
            system_prompt_len=plen, user_info_len=plen, answer_len=48,
            max_num_seqs=args.batch,
            max_model_len=serving_len,
            num_scheduler_steps=args.serving_scheduler_steps,
            boot_timeout_s=300.0,
        )
        log(f"serving: ttft_p50={summary.get('ttft_p50_s')}s "
            f"out_tok/s={summary.get('output_tokens_per_s')} "
            f"kv_hit={summary.get('kv_hit_rate')} "
            f"failed={summary.get('requests_failed')}")
        return summary
    except Exception as e:
        # The kernel benches are still valid; record the failure.
        log(f"serving bench failed: {e}")
        return {"error": str(e)[:200]}


# Optional A/B stages in value order (the --stages selector validates
# against this; 'micro' additionally selects the microbench + serving
# phases).
AB_STAGES = (
    # multi_round leads: it is the paper's headline comparison (BASELINE
    # multi-round QA across the routing ladder) and the standing
    # regression gate — it must run before the budget can starve it.
    "multi_round",
    "int8_ab", "kv_int8_ab", "kv_capacity_ab", "gather_ab", "pipeline_ab",
    "mixed_ab", "multistep_ab", "mixed_window_ab", "spec_window_ab",
    "overload_ab", "encode_ab",
    "remote_prefix_ab", "disagg_ab", "fleet_surge_ab",
)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "mode", nargs="?", choices=["multi_round"], default=None,
        help="optional stage shorthand: 'multi_round' == --stages "
        "multi_round (with --fake-fleet: the CI smoke path — fake-fleet "
        "routing-ladder A/B only, no jax, small config)",
    )
    ap.add_argument(
        "--fake-fleet", action="store_true",
        help="with 'multi_round': run ONLY the fake-fleet routing-ladder "
        "A/B at small config and print the JSON line — no jax import, no "
        "TPU probe, CI-runnable in ~1 min (the lint.yml smoke job)",
    )
    ap.add_argument("--preset", default=None, help="model preset (default: by backend)")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--ctx", type=int, default=2048)
    ap.add_argument("--quick", action="store_true", help="skip secondary benches")
    ap.add_argument(
        "--budget-s", type=float, default=480.0,
        help="soft wall-clock budget: optional A/B stages are skipped "
        "when fewer than 120s remain, so the final JSON line always "
        "prints inside the driver's window",
    )
    ap.add_argument(
        "--stages", default=None,
        help="comma-separated A/B stage selector (e.g. "
        "'int8_ab,kv_capacity_ab').  Selected stages run with PRIORITY: "
        "the serving phase and repeat microbenches are skipped to "
        "conserve budget, and a selected stage runs even when the soft "
        "budget is exhausted (r05 silently budget-starved "
        "int8_ab/kv_int8_ab; a requested stage can no longer be).  "
        "Every skipped stage — unselected, quick-mode, or "
        "budget-starved — is recorded loudly in detail.stages_skipped.  "
        "Include 'micro' to keep the microbench + serving phases",
    )
    ap.add_argument(
        "--trace-report", action="store_true",
        help="run only the per-phase latency attribution stage: short "
        "serve through router + fake engine (CPU-safe, no jax), pull "
        "/debug/requests joins, print the phase table and exit",
    )
    ap.add_argument(
        "--serving-scheduler-steps", type=int, default=8,
        help="num_scheduler_steps for the serving bench engine (8 amortizes "
        "dispatch RTT when the TPU sits behind a network tunnel; set 1 for "
        "classic per-token stepping on a directly-attached chip)",
    )
    args = ap.parse_args()

    if args.fake_fleet:
        # CI smoke path (lint.yml multi-round-smoke): fake-fleet ladder
        # only, small config, no jax/TPU anywhere near the process.
        if args.mode != "multi_round":
            raise SystemExit("--fake-fleet requires the 'multi_round' mode")
        report = bench_multi_round_ab(args, fake_only=True, small=True)
        pop = report["fake_fleet"]["kv_aware_popularity"]
        print(json.dumps({
            "metric": "multi_round_fleet_kv_hit_rate",
            "value": pop["kv_hit_rate"],
            "unit": "fraction",
            "vs_baseline": 0.0,
            "detail": {"multi_round": report},
        }), flush=True)
        return
    if args.mode == "multi_round" and not args.stages:
        args.stages = "multi_round"

    if args.trace_report:
        report = run_trace_report()
        print(json.dumps({
            "metric": "trace_report_mean_e2e",
            "value": report.get("mean_total_ms", 0.0),
            "unit": "ms",
            "vs_baseline": 0.0,
            "detail": report,
        }), flush=True)
        return

    import os

    # Phase 0: stage-attributed liveness probe in throwaway subprocesses.
    # A dead tunnel pins the rest of the run (this process AND children)
    # to CPU instead of hanging or exiting rc!=0.
    probe_attempts = []
    if os.environ.get("JAX_PLATFORMS") != "cpu":
        probe = probe_tpu_subprocess()
        probe_attempts = probe["attempts"]
        if not probe["ok"]:
            log("probe: TPU unreachable — pinning run to CPU "
                "(vs_baseline will be 0; no roofline claim)")
            os.environ["JAX_PLATFORMS"] = "cpu"
            os.environ[_FALLBACK_ENV] = "1"

    # Stage selector (--stages): selected A/B stages run with priority —
    # the serving phase and repeat microbenches are skipped so the
    # budget goes to what was asked for, and a selected stage ignores
    # the soft budget entirely (the r05 starvation fix).
    selected = None
    if args.stages:
        selected = {s.strip() for s in args.stages.split(",") if s.strip()}
        unknown = selected - set(AB_STAGES) - {"micro"}
        if unknown:
            raise SystemExit(
                f"--stages: unknown stage(s) {sorted(unknown)}; "
                f"known: {', '.join(AB_STAGES)} (+ 'micro' for the "
                "microbench/serving phases)"
            )

    # Phase 1 (before THIS process claims the chip): the north-star
    # serving bench with REAL process boundaries — engine server process
    # + router process + the multi-round-QA harness over HTTP.  Must run
    # first because the engine subprocess needs the TPU, and a PJRT
    # client in this process would hold it.
    serving_summary = None
    if not args.quick and (selected is None or "micro" in selected):
        serving_summary = _run_serving_phase(args)

    # Initialize the backend with hang/crash protection: the tunnel can
    # die between probe and init; a stall re-execs pinned to CPU.
    init_backend_or_fallback()

    import jax

    # TPU hosts ship a sitecustomize that pins the TPU plugin at interpreter
    # startup; honor an explicit CPU request anyway (same dance as
    # tests/conftest.py).
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp

    from production_stack_tpu.engine.config import PRESETS

    backend = jax.default_backend()
    on_tpu = backend == "tpu"
    preset = args.preset or ("llama-3.2-3b" if on_tpu else "tiny-llama")
    cfg = dataclasses.replace(PRESETS[preset])
    log(f"bench: backend={backend} preset={preset} batch={args.batch} ctx={args.ctx}")
    tpu_unavailable = bool(os.environ.get(_FALLBACK_ENV))

    # v5e nominal: 197 TF/s bf16, 819 GB/s HBM. Non-TPU backends get the
    # measured numbers only (no roofline claim).
    peak_gbs = 819.0 if on_tpu else None

    detail = {"backend": backend, "preset": preset, "batch": args.batch,
              "ctx": args.ctx}
    if tpu_unavailable:
        detail["tpu_unavailable"] = True
    if probe_attempts:
        detail["init_attempts"] = probe_attempts
    if serving_summary is not None:
        detail["serving"] = serving_summary

    if not args.quick and (selected is None or "micro" in selected):
        detail["matmul_tflops"] = round(bench_matmul_tfs(jax, jnp, on_tpu), 1)
        detail["hbm_gbs"] = round(bench_hbm_gbs(jax, jnp, on_tpu), 1)
        detail["hbm_read_gbs"] = round(bench_hbm_read_gbs(jax, jnp, on_tpu), 1)
        log(f"microbench: {detail.get('matmul_tflops')} TF/s, "
            f"triad {detail.get('hbm_gbs')} GB/s, "
            f"weight-stream {detail.get('hbm_read_gbs')} GB/s")

    bs = 16
    S, ctx = args.batch, args.ctx
    # Engine-realistic block-table width: padded to max_model_len, not ctx
    # (engine.py _bmax) — the gather path pays for that padding, the Pallas
    # kernel's dynamic trip count does not.
    bmax = max(min(cfg.max_model_len, 8192) // bs, -(-ctx // bs), 1)
    num_blocks = S * (-(-ctx // bs)) + 1
    params, kv = build_state(jax, jnp, cfg, num_blocks, bs)
    n_params = approx_param_count(cfg)
    log(f"model: ~{n_params/1e9:.2f}B params")

    # Prefill (TTFT component): one 2048-token prompt.
    bucket = min(2048, cfg.max_model_len)
    if os.environ.get("PSTPU_DISABLE_FLASH_PREFILL"):
        detail["flash_prefill_disabled"] = True
    with stage_watchdog("prefill", 300.0, {"PSTPU_DISABLE_FLASH_PREFILL": "1"}):
        t_prefill = bench_prefill(jax, jnp, cfg, params, kv, bucket, bs)
    prefill_tps = bucket / t_prefill
    # Matmul flops only: the embedding is a gather (no flops) and the model
    # applies lm_head to the last token, not the whole bucket
    # (llama.py:184-186) — counting either inflates MFU.
    embed_params = cfg.vocab_size * cfg.hidden_size * (
        1 if cfg.tie_word_embeddings else 2
    )
    prefill_flops = (
        2 * (n_params - embed_params) * bucket
        + 2 * cfg.vocab_size * cfg.hidden_size  # lm_head, last token only
        + 2 * 2 * cfg.num_layers
        * (cfg.num_heads * cfg.head_dim * bucket * bucket / 2)
    )
    detail["prefill_tokens_per_s"] = round(prefill_tps)
    detail["ttft_ms_2k_prompt"] = round(t_prefill * 1e3, 2)
    if on_tpu:
        detail["prefill_mfu"] = round(prefill_flops / t_prefill / 197e12, 3)
    log(f"prefill[{bucket}]: {t_prefill*1e3:.1f} ms "
        f"({prefill_tps:.0f} tok/s, MFU {detail.get('prefill_mfu', '-')})")

    # Decode (the primary metric): least-squares fit over 4 chain
    # lengths, cross-checked against the longest chain's absolute time
    # (r03's 2-point diff produced 7.48 ms/step against its own 10.1 ms
    # bandwidth bound — a physically impossible number that the fit's
    # residuals + the absolute estimate make detectable and correctable).
    mk_decode = make_decode_bench(jax, jnp, cfg, S, ctx, bmax, bs, num_blocks)
    decode_ns = (4, 12, 20, 128) if on_tpu else (4, 12, 20)
    fit = fit_time(mk_decode, decode_ns, params, kv)
    t_decode = fit["per_iter_s"]
    detail["decode_timing"] = {
        "fit_step_ms": round(fit["per_iter_s"] * 1e3, 3),
        "abs_step_ms": round(fit["abs_per_iter_s"] * 1e3, 3),
        "intercept_ms": fit["intercept_ms"],
        "r2": fit["r2"],
        "points_ms": fit["points"],
    }
    # The absolute estimate includes one dispatch+RTT amortized over the
    # longest chain (over-estimates by <1% at n=128): if the fit claims
    # a per-step time more than 10% FASTER than that upper bound, the
    # fit is noise-contaminated — take the conservative estimate.
    if fit["per_iter_s"] < 0.9 * fit["abs_per_iter_s"]:
        detail["decode_timing"]["suspect"] = True
        t_decode = fit["abs_per_iter_s"]
    decode_tps = S / t_decode
    detail["decode_step_ms"] = round(t_decode * 1e3, 3)
    detail["decode_tokens_per_s"] = round(decode_tps, 1)
    log(f"decode[b{S} ctx{ctx}]: {t_decode*1e3:.2f} ms/step "
        f"({decode_tps:.0f} tok/s; fit r2={fit['r2']}, "
        f"abs {fit['abs_per_iter_s']*1e3:.2f} ms)")

    # Roofline: per step, read all params once + each sequence's live KV.
    vs_baseline = 0.0
    if peak_gbs:
        # Weights streamed per step: every matmul weight + lm_head.  With
        # tied embeddings lm_head IS the embedding matrix (read once); with
        # untied, the embedding table is only gathered (S rows, ~0 bytes).
        streamed_params = n_params - (
            0 if cfg.tie_word_embeddings
            else cfg.vocab_size * cfg.hidden_size
        )
        param_bytes = streamed_params * 2
        kv_bytes = S * (-(-ctx // bs)) * bs * cfg.num_kv_heads * cfg.head_dim \
            * 2 * 2 * cfg.num_layers
        roofline_step = (param_bytes + kv_bytes) / (peak_gbs * 1e9)
        vs_baseline = round(decode_tps * roofline_step / S, 3)
        detail["decode_roofline_tokens_per_s"] = round(S / roofline_step)
        # Self-consistency: the effective bandwidth implied by the
        # measurement can't exceed what this chip demonstrably streams
        # (hbm_read_gbs).  If it does, either the timing or the
        # bytes-touched model is wrong — localize with a KV-bytes sweep:
        # step time at 3 context lengths; the slope is the incremental
        # cost of KV bytes, the intercept the parameter-streaming cost.
        eff_gbs = (param_bytes + kv_bytes) / t_decode / 1e9
        detail["decode_effective_gbs"] = round(eff_gbs, 1)
        measured_ceiling = detail.get("hbm_read_gbs") or peak_gbs
        if eff_gbs > 1.05 * max(measured_ceiling, 1e-9) and on_tpu:
            detail["roofline_violation"] = True
            sweep = {}
            for c in (256, 1024, ctx):
                if c > ctx:
                    continue
                mk_c = make_decode_bench(
                    jax, jnp, cfg, S, c, bmax, bs, num_blocks
                )
                sweep[c] = round(
                    diff_time(mk_c, 4, 20, params, kv) * 1e3, 3
                )
            detail["decode_kv_sweep_ms"] = sweep
            log(f"ROOFLINE VIOLATION: effective {eff_gbs:.0f} GB/s > "
                f"measured ceiling {measured_ceiling:.0f} GB/s; "
                f"kv sweep {sweep}")

    # Optional A/B stages, in value order, each gated on selection and
    # the remaining time budget: the driver runs this under a finite
    # window and the JSON line with the core + serving numbers must
    # always print.  EVERY skipped stage is recorded loudly in
    # detail.stages_skipped — r05 silently dropped int8_ab/kv_int8_ab
    # and nobody noticed until the artifact diff.
    def note_skip(stage: str, reason: str) -> None:
        detail.setdefault("stages_skipped", []).append(
            {"stage": stage, "reason": reason}
        )

    def run_stage(stage: str) -> bool:
        if args.quick:
            note_skip(stage, "quick")
            return False
        if selected is not None and stage not in selected:
            note_skip(stage, "unselected")
            return False
        # Probe/boot wait is excluded: a TPU tunnel outage must not eat
        # the stage budget (r05 lost int8_ab/kv_int8_ab to 3x420 s of
        # probe retries billed as bench time).
        spent = time.time() - _T0 - _BUDGET_EXCLUDED_S
        remaining = args.budget_s - spent
        detail["budget_excluded_s"] = round(_BUDGET_EXCLUDED_S, 1)
        if remaining < 120.0:
            if selected is not None and stage in selected:
                # Requested stages preempt the budget: running over the
                # soft window beats silently starving what was asked
                # for.
                log(f"{stage}: {remaining:.0f}s left of --budget-s "
                    f"{args.budget_s}, but the stage was requested via "
                    "--stages — running anyway")
                return True
            log(f"skipping {stage}: {remaining:.0f}s left of "
                f"--budget-s {args.budget_s} "
                f"({_BUDGET_EXCLUDED_S:.0f}s probe/boot wait excluded)")
            detail[f"{stage}_skipped_budget"] = True
            note_skip(stage, "budget")
            return False
        return True

    # The north-star workload: multi-round QA across the routing ladder
    # (fake fleet pooled percentiles + real CPU engines + greedy
    # parity).  Acceptance: kv_aware+popularity beats plain kv_aware AND
    # session-affinity on fleet KV hit rate and TTFT p50
    # (detail.multi_round.criteria).  This stage is the headline
    # comparison and the standing regression gate, so it is exempt from
    # the soft budget: the fake-fleet half always runs (pure asyncio,
    # ~2.5 min); only the real-engine ladder degrades to skipped under
    # budget pressure (recorded, never silent — the r05 lesson).
    if not args.quick and (selected is None or "multi_round" in selected):
        mr_remaining = args.budget_s - (time.time() - _T0 - _BUDGET_EXCLUDED_S)
        mr_fake_only = mr_remaining < 180.0 and (
            selected is None or "multi_round" not in selected
        )
        if mr_fake_only:
            log(f"multi_round: {mr_remaining:.0f}s left of --budget-s "
                f"{args.budget_s} — running the fake-fleet ladder only "
                "(real-engine ladder skipped, recorded)")
            note_skip("multi_round_real_engines", "budget")
        try:
            detail["multi_round"] = bench_multi_round_ab(
                args, preset, fake_only=mr_fake_only)
            mr = detail["multi_round"]
            log(f"multi_round criteria: {mr['criteria']}; "
                f"parity={mr.get('real_engines', {}).get('greedy_parity_ok')}")
        except Exception as e:
            log(f"multi_round bench failed: {e}")
            detail["multi_round_error"] = str(e)[:200]
    else:
        note_skip("multi_round", "quick" if args.quick else "unselected")

    if run_stage("int8_ab"):
        # Int8 weight-only A/B (model.quantization="int8"): decode is
        # HBM-bound, so halving the projection bytes should approach a 2x
        # step-time cut; report the measured ratio next to its own
        # roofline so the claim is falsifiable.
        try:
            from production_stack_tpu.engine.models import llama as _llama
            import dataclasses as _dc

            qcfg = _dc.replace(cfg, quantization="int8")
            qparams = _llama.quantize_params(params, qcfg)
            t_decode_q = bench_decode(
                jax, jnp, qcfg, qparams, kv, S, ctx, bmax, bs
            )
            detail["decode_step_ms_int8"] = round(t_decode_q * 1e3, 3)
            detail["decode_tokens_per_s_int8"] = round(S / t_decode_q, 1)
            detail["int8_decode_speedup"] = round(t_decode / t_decode_q, 2)
            del qparams
            log(f"decode int8: {t_decode_q*1e3:.2f} ms/step "
                f"({S/t_decode_q:.0f} tok/s, "
                f"{detail['int8_decode_speedup']}x vs bf16)")
        except Exception as e:
            log(f"int8 decode bench failed: {e}")
            detail["int8_decode_error"] = str(e)[:200]

    if run_stage("kv_int8_ab"):
        # Int8 KV cache A/B (cache.kv_cache_dtype="int8"): the KV read is
        # the context-scaling term of decode bandwidth; int8 halves it
        # (and the pool bytes — capacity ratio reported alongside).
        try:
            from production_stack_tpu.engine.kv import quant as kv_quant

            kvq = [
                (kv_quant.quantize_vectors(k), kv_quant.quantize_vectors(v))
                for k, v in kv
            ]
            mk_q = make_decode_bench(
                jax, jnp, cfg, S, ctx, bmax, bs, num_blocks
            )
            t_decode_kvq = diff_time(mk_q, 4, 20, params, kvq)
            detail["decode_step_ms_kv_int8"] = round(t_decode_kvq * 1e3, 3)
            detail["kv_int8_decode_speedup"] = round(
                t_decode / t_decode_kvq, 2
            )
            hd = cfg.head_dim
            detail["kv_int8_capacity_ratio"] = round(
                (2 * hd) / (hd + 4), 2
            )
            del kvq
            log(f"decode kv-int8: {t_decode_kvq*1e3:.2f} ms/step "
                f"({detail['kv_int8_decode_speedup']}x vs bf16 KV, "
                f"{detail['kv_int8_capacity_ratio']}x pool capacity)")
        except Exception as e:
            log(f"kv int8 decode bench failed: {e}")
            detail["kv_int8_decode_error"] = str(e)[:200]

    if run_stage("kv_capacity_ab"):
        # KV-capacity A/B (the quantized-tiering headline): same HBM
        # block-byte budget, int8 vs bf16 KV — admitted concurrency,
        # prefix hit rate, decode tok/s — plus offload->restore greedy
        # parity through the native int8 wire and the fp32-vs-int8
        # host-tier byte ratio from tpu:kv_wire_bytes_total.
        try:
            detail["kv_capacity_ab"] = bench_kv_capacity_ab(args, preset)
            ab = detail["kv_capacity_ab"]
            log(f"kv capacity A/B: {ab['capacity_ratio']}x resident "
                f"tokens at equal budget "
                f"({ab['int8']['resident_tokens']} vs "
                f"{ab['bf16']['resident_tokens']}), concurrency "
                f"{ab['concurrency_ratio']}x, hit-rate delta "
                f"{ab['hit_rate_delta']}, wire parity "
                f"{ab['offload_cycle_int8_wire']['greedy_parity']}, "
                f"fp32/int8 wire bytes "
                f"{ab['wire_bytes_ratio_fp32_over_int8']}x")
        except Exception as e:
            log(f"kv capacity A/B failed: {e}")
            detail["kv_capacity_ab_error"] = str(e)[:200]

    if run_stage("gather_ab"):
        if not on_tpu:
            # Recorded, not silent: the gather A/B measures the Pallas
            # kernel delta, which only exists on a TPU backend.
            log("skipping gather_ab: needs a TPU backend")
            note_skip("gather_ab", "needs_tpu")
        else:
            # A/B the full decode step with the gather attention path
            # (the KV cache is loop-carried, so XLA cannot hoist the
            # gather): this is the honest Pallas-kernel delta at engine
            # level.
            os.environ["PSTPU_DISABLE_PALLAS"] = "1"
            try:
                t_gather = bench_decode(
                    jax, jnp, cfg, params, kv, S, ctx, bmax, bs
                )
            finally:
                del os.environ["PSTPU_DISABLE_PALLAS"]
            detail["decode_step_ms_gather"] = round(t_gather * 1e3, 3)
            detail["pallas_decode_speedup"] = round(t_gather / t_decode, 2)
            log(f"decode gather-path: {t_gather*1e3:.2f} ms/step "
                f"(pallas speedup {t_gather/t_decode:.2f}x)")

    if run_stage("pipeline_ab"):
        # Pipelined vs sync decode through the REAL engine — run last so
        # the bench's own params/kv can be freed first (two extra engine
        # boots of the flagship preset must fit in HBM).
        try:
            del params, kv
            import gc as _gc

            _gc.collect()
            detail["pipeline_ab"] = bench_engine_pipeline_ab(args, preset)
            log(f"pipeline A/B: sync "
                f"{detail['pipeline_ab']['sync']['step_ms']} ms/step "
                f"(gap {detail['pipeline_ab']['sync']['host_gap_ms']} ms) "
                f"vs pipelined "
                f"{detail['pipeline_ab']['pipelined']['step_ms']} ms/step "
                f"({detail['pipeline_ab']['speedup']}x)")
        except Exception as e:
            log(f"pipeline A/B failed: {e}")
            detail["pipeline_ab_error"] = str(e)[:200]

    if run_stage("mixed_ab"):
        # Mixed-batch A/B: chunked-prefill-integrated batching vs the
        # alternating scheduler under a Poisson mixed workload — the
        # ITL-under-load claim, measured.  Boots its own engines, so the
        # bench's raw params/kv must be freed (pipeline_ab may already
        # have done so).
        try:
            try:
                del params, kv
            except NameError:
                pass
            import gc as _gc

            _gc.collect()
            detail["mixed_ab"] = bench_engine_mixed_ab(args, preset)
            ab = detail["mixed_ab"]
            log(f"mixed A/B: alternating p95 ITL "
                f"{ab['alternating']['itl_p95_ms']} ms vs mixed "
                f"{ab['mixed']['itl_p95_ms']} ms "
                f"({ab['itl_p95_speedup']}x tail cut, throughput "
                f"{ab['throughput_ratio']}x, "
                f"{ab['mixed']['prefill_chunk_tokens']} chunk tokens)")
        except Exception as e:
            log(f"mixed A/B failed: {e}")
            detail["mixed_ab_error"] = str(e)[:200]

    if run_stage("multistep_ab"):
        # K-step decode-window A/B: per-token host cost at K in {1,4,8}
        # plus the stop-mask wasted-token rate — the host-round-trip
        # amortization claim, measured (docs/engine.md StepPlan).
        try:
            try:
                del params, kv
            except NameError:
                pass
            import gc as _gc

            _gc.collect()
            detail["multistep_ab"] = bench_engine_multistep_ab(args, preset)
            ab = detail["multistep_ab"]
            log(f"multistep A/B: per-token host "
                f"{ab['k1']['per_token_host_ms']} ms @K=1 vs "
                f"{ab['k8']['per_token_host_ms']} ms @K=8 "
                f"({ab['host_gap_reduction_k8_vs_k1']}x cut), wasted rate "
                f"{ab['k8']['wasted_rate']} under the stop-mask, parity "
                f"{ab['greedy_parity']}")
        except Exception as e:
            log(f"multistep A/B failed: {e}")
            detail["multistep_ab_error"] = str(e)[:200]

    if run_stage("mixed_window_ab"):
        # Mixed K-step window grid: {K=1 mixed, K=8 mixed} x {ngram 0,3}
        # under a seeded Poisson continuous-arrival replay — the
        # sustained-arrival host-amortization claim, measured, with the
        # TTFT admission-boundary bound and greedy parity across every
        # cell (docs/engine.md StepPlan, mixed K-step windows).
        try:
            try:
                del params, kv
            except NameError:
                pass
            import gc as _gc

            _gc.collect()
            detail["mixed_window_ab"] = bench_engine_mixed_window_ab(
                args, preset
            )
            ab = detail["mixed_window_ab"]
            log(f"mixed-window A/B: host round-trips/token "
                f"{ab['k1_ng0']['host_round_trips_per_token']} @K=1 vs "
                f"{ab['k8_ng0']['host_round_trips_per_token']} @K=8 "
                f"({ab['host_cost_cut_k8_vs_k1']}x cut), TTFT p95 ratio "
                f"{ab['ttft_p95_ratio_k8_vs_k1']}, "
                f"{ab['k8_ng0']['mixed_window_chunk_tokens']} chunk "
                f"tokens rode windows, fallbacks "
                f"{ab['k8_ng0']['fallbacks']}, parity "
                f"{ab['greedy_parity']}")
        except Exception as e:
            log(f"mixed-window A/B failed: {e}")
            detail["mixed_window_ab_error"] = str(e)[:200]
        # Queue-depth x drafter grid on two replays: tokens/s must be
        # monotone non-decreasing in depth {1, 4, 16} in every
        # {none, ngram, model} arm, packed waiting_head pinned at zero
        # at depth 16, the model drafter strictly beating ngram on the
        # adversarial pure-decode tail, and greedy digests
        # byte-identical across every cell incl. the unpacked reference.
        try:
            import gc as _gc

            _gc.collect()
            detail["mixed_window_depth"] = (
                bench_engine_mixed_window_depth_grid(args, preset)
            )
            dg = detail["mixed_window_depth"]
            log(f"mixed-window depth grid: tokens/s "
                f"{dg['temp_d1_none']['tokens_per_s']} @d1 / "
                f"{dg['temp_d4_none']['tokens_per_s']} @d4 / "
                f"{dg['temp_d16_none']['tokens_per_s']} @d16 "
                f"(monotone {dg['tokens_per_s_monotone']}, "
                f"{dg['depth_speedup_d16_vs_d1']}x d16/d1), "
                f"{dg['temp_d16_none']['prompts_per_window_mean']} prompts/"
                f"window @d16, waiting_head "
                f"{dg['waiting_head_at_depth16']} packed vs "
                f"{dg['temp_d16_none_nopack']['waiting_head']} unpacked, "
                f"adversarial decode tail model vs ngram "
                f"{dg['adv_d16_model']['decode_tokens_per_s']} vs "
                f"{dg['adv_d16_ngram']['decode_tokens_per_s']} tok/s "
                f"({dg['adv_decode_speedup_model_vs_ngram']}x, beats "
                f"{dg['model_beats_ngram_adversarial']}; acceptance "
                f"{dg['adv_d16_model']['acceptance_rate']} vs "
                f"{dg['adv_d16_ngram']['acceptance_rate']}), "
                f"parity {dg['greedy_parity']}")
        except Exception as e:
            log(f"mixed-window depth grid failed: {e}")
            detail["mixed_window_depth_error"] = str(e)[:200]

    if run_stage("spec_window_ab"):
        # Speculation x window grid: the fused in-scan draft-and-verify
        # vs window-only / legacy host speculation, on an
        # acceptance-friendly and an adversarial replay (PR-11,
        # docs/engine.md fused speculative windows).
        try:
            try:
                del params, kv
            except NameError:
                pass
            import gc as _gc

            _gc.collect()
            detail["spec_window_ab"] = bench_engine_spec_window_ab(
                args, preset
            )
            ab = detail["spec_window_ab"]
            fr = ab["friendly"]
            log(f"spec-window A/B: fused {fr['k8_ng3']['tokens_per_s']} "
                f"tok/s vs window-only {fr['k8_ng0']['tokens_per_s']} "
                f"({fr['fused_vs_window_tokens_ratio']}x on the friendly "
                f"replay, acceptance "
                f"{fr['k8_ng3']['acceptance_rate']}); adversarial ratio "
                f"{ab['adversarial']['fused_vs_window_tokens_ratio']}x, "
                f"parity {ab['greedy_parity']}")
        except Exception as e:
            log(f"spec-window A/B failed: {e}")
            detail["spec_window_ab_error"] = str(e)[:200]

    if run_stage("overload_ab"):
        # Overload shedding A/B: bounded admission vs the unbounded
        # legacy queue under a 2x-oversubscribed Poisson replay — the
        # admitted-ITL-stays-flat claim, measured (docs/robustness.md).
        try:
            try:
                del params, kv
            except NameError:
                pass
            import gc as _gc

            _gc.collect()
            detail["overload_ab"] = bench_engine_overload_ab(args, preset)
            ab = detail["overload_ab"]
            log(f"overload A/B: unbounded p95 ITL "
                f"{ab['unbounded']['itl_p95_ms']} ms vs shedding "
                f"{ab['shedding']['itl_p95_ms']} ms "
                f"({ab['itl_p95_ratio']}x tail cut, "
                f"{ab['shedding']['rejected']} shed, goodput "
                f"{ab['goodput_ratio']}x)")
        except Exception as e:
            log(f"overload A/B failed: {e}")
            detail["overload_ab_error"] = str(e)[:200]

    if run_stage("encode_ab"):
        # Encode-lane A/B: batched [B, T] embed throughput vs the serial
        # per-text loop, generation ITL isolation under an embed pump,
        # the router semantic cache on a repeat-heavy trace, and
        # --no-encode-lane parity (docs/engine.md "The encode lane").
        try:
            try:
                del params, kv
            except NameError:
                pass
            import gc as _gc

            _gc.collect()
            detail["encode_ab"] = bench_engine_encode_ab(args, preset)
            ab = detail["encode_ab"]
            log(f"encode A/B: batched {ab['throughput']['speedup']}x "
                f"serial embed throughput "
                f"({ab['throughput']['batched_texts_per_s']} vs "
                f"{ab['throughput']['serial_texts_per_s']} texts/s), "
                f"gen ITL ratio {ab['isolation']['itl_ratio']}x under "
                f"embed load, cache hit rate {ab['cache']['hit_rate']}, "
                f"criteria {ab['criteria']}")
        except Exception as e:
            log(f"encode A/B failed: {e}")
            detail["encode_ab_error"] = str(e)[:200]

    if run_stage("remote_prefix_ab"):
        # Remote shared-prefix import A/B: synchronous per-block GETs
        # inside schedule() vs the async batched transfer plane, against
        # a latency-injected kvserver — the decode-ITL-flatness and
        # MGET-batching claims, measured.
        try:
            try:
                del params, kv
            except NameError:
                pass
            import gc as _gc

            _gc.collect()
            detail["remote_prefix_ab"] = bench_remote_prefix_ab(args, preset)
            ab = detail["remote_prefix_ab"]
            log(f"remote prefix A/B: sync ITL max "
                f"{ab['sync']['itl_max_ms']} ms "
                f"({ab['round_trips_sync']} RTTs) vs prefetch "
                f"{ab['prefetch']['itl_max_ms']} ms "
                f"({ab['round_trips_prefetch']} RTTs), "
                f"{ab['itl_max_stall_ratio']}x stall cut")
        except Exception as e:
            log(f"remote prefix A/B failed: {e}")
            detail["remote_prefix_ab_error"] = str(e)[:200]

    if run_stage("disagg_ab"):
        # Disaggregated prefill/decode A/B: router + 1 prefill + 1 decode
        # engine (two-phase disagg policy over the KV plane) vs the same
        # 2 engines fused, one seeded Poisson mixed replay — the
        # decode-ITL-without-prompt-interference claim, measured, plus
        # the handoff's TTFT tax (docs/engine.md "Disaggregated data
        # path").
        try:
            try:
                del params, kv
            except NameError:
                pass
            import gc as _gc

            _gc.collect()
            detail["disagg_ab"] = bench_disagg_ab(args, preset)
            ab = detail["disagg_ab"]
            log(f"disagg A/B: fused ITL p95 {ab['fused']['itl_p95_ms']} ms "
                f"vs disagg {ab['disagg']['itl_p95_ms']} ms "
                f"({ab['itl_p95_ratio']}x tail cut), TTFT p95 "
                f"{ab['fused']['ttft_p95_ms']} -> "
                f"{ab['disagg']['ttft_p95_ms']} ms "
                f"({ab['ttft_p95_ratio']}x), handoff mean "
                f"{ab['disagg'].get('handoff_mean_ms')} ms, "
                f"{ab['disagg'].get('handoffs')} handoffs, fallbacks "
                f"{ab['disagg'].get('fallbacks')}")
        except Exception as e:
            log(f"disagg A/B failed: {e}")
            detail["disagg_ab_error"] = str(e)[:200]

    if run_stage("fleet_surge_ab"):
        # Fleet admission A/B: router-level shed (capacity model) vs
        # engine-level shed only, same seeded 10x diurnal surge with a
        # 2->N->2 scale cycle through drain — the admitted-ITL-stays-
        # flat-at-the-fleet-level claim, measured (docs/robustness.md
        # "Fleet admission & autoscaling contract").  Fake-engine fleet:
        # no TPU, no jax import.
        try:
            detail["fleet_surge_ab"] = bench_fleet_surge_ab(args)
            ab = detail["fleet_surge_ab"]
            log(f"fleet surge A/B: engine-shed p95 ITL "
                f"{ab['engine_shed']['admitted_itl_p95_ms']} ms vs "
                f"router-shed {ab['router_shed']['admitted_itl_p95_ms']} ms "
                f"({ab['itl_p95_ratio']}x tail cut), goodput ratio "
                f"{ab['goodput_ratio']}, sheds "
                f"{ab['router_shed']['shed_router']} router vs "
                f"{ab['engine_shed']['shed_engine']} engine)")
        except Exception as e:
            log(f"fleet surge A/B failed: {e}")
            detail["fleet_surge_ab_error"] = str(e)[:200]

    result = {
        "metric": f"decode_throughput_{preset}_b{S}_ctx{ctx}",
        "value": round(decode_tps, 1),
        "unit": "tokens/s",
        "vs_baseline": vs_baseline,
        "detail": detail,
    }
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    try:
        main()
    except Exception:
        # The driver records rc + the single JSON line; a crash mid-bench
        # (e.g. the TPU tunnel dying under us) must still produce a parsed
        # artifact rather than rc=1 with nothing.
        import traceback

        traceback.print_exc(file=sys.stderr)
        print(json.dumps({
            "metric": "bench_error",
            "value": 0,
            "unit": "tokens/s",
            "vs_baseline": 0.0,
            "detail": {"error": traceback.format_exc().strip().splitlines()[-1]},
        }), flush=True)
        sys.exit(1)  # parsed artifact + honest failure signal
