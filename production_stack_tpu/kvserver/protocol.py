"""Wire protocol for the shared KV store.

Frame (all little-endian):

    magic   u32  = 0x54505543 ("TPUC")
    op      u8   (1=PUT, 2=GET, 3=DEL, 4=STAT, 5=PING, 6=MGET, 7=MPUT)
    key_len u16
    key     bytes
    val_len u64  (PUT and MPUT only)
    value   bytes

Response:

    magic   u32
    status  u8   (0=OK, 1=NOT_FOUND, 2=ERROR)
    val_len u64
    value   bytes

Batched ops (one framed round-trip for a whole hash chain):

    MGET: the key field carries a packed KEY LIST (u16 count, then per key
    u16 len + bytes) and there is NO value field — a server that predates
    the op parses the frame cleanly and answers ST_ERROR, so clients can
    fall back to serial GETs without desyncing the stream.  The OK
    response value is a packed VALUE LIST (u32 count, then per value
    u64 len + bytes) holding the PRESENT PREFIX of the requested keys:
    the server stops at the first missing key, mirroring how a prefix
    hash chain is consumed (blocks after a miss are useless).

    MPUT: key field = packed key list, value field = packed value list of
    the same count.  Response is a bare ST_OK/ST_ERROR.  Unlike MGET the
    frame has a value field an old server would misparse, so clients must
    reset the connection after any MPUT error reply.

KV snapshot serde is VERSIONED so mixed-precision fleets interop during
a rollout:

    v1 (legacy, untagged): num_tokens u32, num_layers u32, then per
      layer k then v, each a DENSE array:
        ndim u8, shape u32*ndim, dtype_code u8, data
      dtype codes: 0=float32, 1=bfloat16(stored as u16), 2=float16,
      3=int8.

    v2 (tagged, quantized wire): marker u32 = 0xFF000000|2 — the high
      byte can never collide with a v1 ``num_tokens`` (bounded by
      max_model_len, orders of magnitude below 2^24) — then
      num_tokens u32, num_layers u32, and per layer k then v, each a
      SIDE: kind u8 (0=dense -> one array as in v1; 1=int8-quantized ->
      an int8 data array + an fp32 scale array, the cache's native
      (data, scale) representation from engine/kv/quant.py).

Dense snapshots always encode as v1, so fp32-wire configs stay
byte-identical to the legacy format and a v1-only peer keeps reading
them; v2 appears on the wire only for quantized payloads, and only
after the client has probed the store for v2 support (STAT advertises
``snapshot_versions`` — the PR-4 legacy-fallback pattern: probe once,
remember, never corrupt).  Decoding is strict: an unknown version
marker, a truncated frame, or trailing garbage raises ValueError
loudly instead of yielding silently-wrong tensors.
"""

from __future__ import annotations

import struct
import threading
from typing import List, Tuple

import numpy as np

MAGIC = 0x54505543
OP_PUT, OP_GET, OP_DEL, OP_STAT, OP_PING = 1, 2, 3, 4, 5
OP_MGET, OP_MPUT = 6, 7
ST_OK, ST_NOT_FOUND, ST_ERROR = 0, 1, 2

OP_NAMES = {
    OP_PUT: "put", OP_GET: "get", OP_DEL: "del", OP_STAT: "stat",
    OP_PING: "ping", OP_MGET: "mget", OP_MPUT: "mput",
}

# The key field is a u16 length, so a packed key list can never exceed
# 64 KiB — clients chunk longer chains into multiple batches.
MAX_KEYS_PER_BATCH = 512

_DTYPES = {0: np.float32, 2: np.float16, 3: np.int8}
_DTYPE_CODES = {np.dtype(np.float32): 0, np.dtype(np.float16): 2, np.dtype(np.int8): 3}
_BF16_CODE = 1

# -- KV snapshot versioning --------------------------------------------------

SNAPSHOT_V1 = 1  # legacy untagged dense frame
SNAPSHOT_V2 = 2  # tagged frame; sides may carry (int8 data, fp32 scale)
SNAPSHOT_VERSIONS = (SNAPSHOT_V1, SNAPSHOT_V2)
# v2+ frames open with 0xFF000000|version; a v1 frame opens with
# num_tokens, which is bounded by max_model_len and can never reach the
# marker range.
_VERSION_MARKER_BASE = 0xFF000000
_SIDE_DENSE = 0
_SIDE_Q8 = 1


def snapshot_version(blob: bytes) -> int:
    """Peek a snapshot frame's serde version without decoding it."""
    if len(blob) < 4:
        raise ValueError("KV snapshot shorter than its header")
    (head,) = struct.unpack_from("<I", blob, 0)
    if head < _VERSION_MARKER_BASE:
        return SNAPSHOT_V1
    version = head - _VERSION_MARKER_BASE
    if version not in SNAPSHOT_VERSIONS:
        raise ValueError(f"unknown KV snapshot version {version}")
    return version


def is_quantized_side(side) -> bool:
    """A wire-level cache side is a dense ndarray or an (int8 data,
    fp32 scale) tuple — the same convention engine/kv/quant.py uses for
    in-HBM sides."""
    return isinstance(side, tuple)


def dequantize_np(data: np.ndarray, scale: np.ndarray) -> np.ndarray:
    """Host-side (numpy) dequantize of an (int8 [..., D], scale [...])
    pair to fp32 — the v1 dense-wire fallback for quantized payloads.
    Mirrors engine/kv/quant.py dequantize bit-for-bit (fp32 multiply)."""
    return data.astype(np.float32) * np.asarray(scale, np.float32)[..., None]


def quantize_np(x: np.ndarray):
    """Host-side (numpy) per-vector symmetric int8 quantization over the
    trailing axis; mirrors engine/kv/quant.py quantize_vectors (fp32
    math, round-half-to-even) so host- and device-quantized blocks are
    bit-identical."""
    x32 = np.asarray(x, np.float32)
    amax = np.max(np.abs(x32), axis=-1)
    scale = amax / 127.0
    safe = np.where(scale > 0, scale, 1.0)
    data = np.clip(np.round(x32 / safe[..., None]), -127.0, 127.0).astype(
        np.int8
    )
    return data, scale


class KVWireStats:
    """Thread-safe accounting of KV bytes crossing tier boundaries and
    snapshot serde versions (feeds ``tpu:kv_wire_bytes_total{tier,
    format}`` and ``tpu:kv_snapshot_format_total{version}``).  Shared by
    the engine's offload manager (host tier) and its kvserver client
    (remote tier); all writers are off-step worker threads plus the
    legacy sync paths."""

    def __init__(self):
        self._lock = threading.Lock()
        self._wire_bytes: dict = {}  # (tier, format) -> bytes
        self._snapshots: dict = {}  # "v1"/"v2" -> count

    def add_wire(self, tier: str, fmt: str, nbytes: int) -> None:
        with self._lock:
            key = (tier, fmt)
            self._wire_bytes[key] = self._wire_bytes.get(key, 0) + int(nbytes)

    def add_snapshot(self, version: int) -> None:
        with self._lock:
            key = f"v{version}"
            self._snapshots[key] = self._snapshots.get(key, 0) + 1

    def wire_bytes(self) -> dict:
        """{(tier, format): bytes} snapshot."""
        with self._lock:
            return dict(self._wire_bytes)

    def snapshot_formats(self) -> dict:
        """{"v1"/"v2": count} snapshot."""
        with self._lock:
            return dict(self._snapshots)


def _encode_array(arr: np.ndarray) -> bytes:
    if arr.dtype.name == "bfloat16":  # ml_dtypes bfloat16
        code = _BF16_CODE
        raw = arr.view(np.uint16)
    else:
        code = _DTYPE_CODES.get(arr.dtype)
        if code is None:
            arr = arr.astype(np.float32)
            code = 0
        raw = arr
    header = struct.pack("<B", arr.ndim) + struct.pack(f"<{arr.ndim}I", *arr.shape)
    return header + struct.pack("<B", code) + np.ascontiguousarray(raw).tobytes()


def _decode_array(buf: memoryview, offset: int) -> Tuple[np.ndarray, int]:
    ndim = buf[offset]
    offset += 1
    shape = struct.unpack_from(f"<{ndim}I", buf, offset)
    offset += 4 * ndim
    code = buf[offset]
    offset += 1
    count = int(np.prod(shape)) if shape else 1
    if code == _BF16_CODE:
        import ml_dtypes

        raw = np.frombuffer(buf, np.uint16, count, offset)
        arr = raw.view(ml_dtypes.bfloat16).reshape(shape)
        offset += 2 * count
    else:
        dtype = np.dtype(_DTYPES[code])
        arr = np.frombuffer(buf, dtype, count, offset).reshape(shape)
        offset += dtype.itemsize * count
    return arr, offset


def _encode_side(side, version: int) -> bytes:
    """One cache side in the chosen serde version.  Quantized (data,
    scale) sides encode natively under v2; under v1 they dequantize to
    the legacy dense fp32 wire (exactly requantizable — quant.py)."""
    if is_quantized_side(side):
        data, scale = np.asarray(side[0]), np.asarray(side[1])
        if version >= SNAPSHOT_V2:
            return (
                struct.pack("<B", _SIDE_Q8)
                + _encode_array(data)
                + _encode_array(np.asarray(scale, np.float32))
            )
        return _encode_array(dequantize_np(data, scale))
    arr = np.asarray(side)
    if version >= SNAPSHOT_V2:
        return struct.pack("<B", _SIDE_DENSE) + _encode_array(arr)
    return _encode_array(arr)


def encode_kv_snapshot(
    layers: List[Tuple[np.ndarray, np.ndarray]],
    num_tokens: int,
    version: int = None,
) -> bytes:
    """Serialize per-layer (k, v) sides.  A side is a dense ndarray or a
    quantized (int8 data, fp32 scale) tuple.  ``version`` None = auto:
    v2 iff any side is quantized (dense frames stay v1-identical to the
    legacy wire); version=1 forces the dense fp32 legacy frame
    (dequantizing quantized sides — the v1-only-peer fallback)."""
    if version is None:
        quantized = any(
            is_quantized_side(k) or is_quantized_side(v) for k, v in layers
        )
        version = SNAPSHOT_V2 if quantized else SNAPSHOT_V1
    if version not in SNAPSHOT_VERSIONS:
        raise ValueError(f"unknown KV snapshot version {version}")
    parts = []
    if version >= SNAPSHOT_V2:
        parts.append(struct.pack("<I", _VERSION_MARKER_BASE + version))
    parts.append(struct.pack("<II", num_tokens, len(layers)))
    for k, v in layers:
        parts.append(_encode_side(k, version))
        parts.append(_encode_side(v, version))
    return b"".join(parts)


def _decode_side(buf: memoryview, offset: int, version: int):
    if version == SNAPSHOT_V1:
        return _decode_array(buf, offset)
    if offset >= len(buf):
        raise ValueError("truncated KV snapshot (missing side kind)")
    kind = buf[offset]
    offset += 1
    if kind == _SIDE_DENSE:
        return _decode_array(buf, offset)
    if kind == _SIDE_Q8:
        data, offset = _decode_array(buf, offset)
        scale, offset = _decode_array(buf, offset)
        if data.dtype != np.int8 or scale.dtype != np.float32:
            raise ValueError(
                "malformed quantized KV side: expected int8 data + fp32 "
                f"scales, got {data.dtype}/{scale.dtype}"
            )
        if data.shape[:-1] != scale.shape:
            raise ValueError(
                "malformed quantized KV side: scale shape "
                f"{scale.shape} does not match data {data.shape}"
            )
        return (data, scale), offset
    raise ValueError(f"unknown KV snapshot side kind {kind}")


def decode_kv_snapshot(data: bytes) -> Tuple[List[Tuple[np.ndarray, np.ndarray]], int]:
    """Strict decode of either serde version.  Returned sides are dense
    ndarrays (v1, or v2 dense sides) or (int8 data, fp32 scale) tuples
    (v2 quantized sides); truncated or trailing-garbage frames raise
    ValueError instead of yielding silently-wrong tensors."""
    version = snapshot_version(data)
    buf = memoryview(data)
    offset = 4 if version >= SNAPSHOT_V2 else 0
    if len(buf) < offset + 8:
        raise ValueError("truncated KV snapshot header")
    num_tokens, num_layers = struct.unpack_from("<II", buf, offset)
    offset += 8
    layers = []
    try:
        for _ in range(num_layers):
            k, offset = _decode_side(buf, offset, version)
            v, offset = _decode_side(buf, offset, version)
            layers.append((k, v))
    except (struct.error, IndexError, KeyError) as e:
        raise ValueError(f"truncated or malformed KV snapshot: {e}") from e
    if offset != len(buf):
        raise ValueError("trailing bytes after KV snapshot")
    return layers, num_tokens


def pack_request(op: int, key: bytes, value: bytes = b"") -> bytes:
    head = struct.pack("<IBH", MAGIC, op, len(key)) + key
    if op in (OP_PUT, OP_MPUT):
        head += struct.pack("<Q", len(value)) + value
    return head


def pack_response(status: int, value: bytes = b"") -> bytes:
    return struct.pack("<IBQ", MAGIC, status, len(value)) + value


# -- batched-op payloads (MGET/MPUT) ----------------------------------------


def pack_key_list(keys: List[bytes]) -> bytes:
    if len(keys) > 0xFFFF:
        raise ValueError(f"too many keys in one batch: {len(keys)}")
    parts = [struct.pack("<H", len(keys))]
    for key in keys:
        parts.append(struct.pack("<H", len(key)) + key)
    return b"".join(parts)


def unpack_key_list(buf: bytes) -> List[bytes]:
    """Strict parse: truncated or trailing-garbage payloads raise
    ValueError (the server answers ST_ERROR instead of guessing)."""
    view = memoryview(buf)
    if len(view) < 2:
        raise ValueError("key list shorter than its count header")
    (count,) = struct.unpack_from("<H", view, 0)
    offset = 2
    keys: List[bytes] = []
    for _ in range(count):
        if offset + 2 > len(view):
            raise ValueError("truncated key list")
        (klen,) = struct.unpack_from("<H", view, offset)
        offset += 2
        if offset + klen > len(view):
            raise ValueError("truncated key in key list")
        keys.append(bytes(view[offset : offset + klen]))
        offset += klen
    if offset != len(view):
        raise ValueError("trailing bytes after key list")
    return keys


def pack_value_list(values: List[bytes]) -> bytes:
    parts = [struct.pack("<I", len(values))]
    for value in values:
        parts.append(struct.pack("<Q", len(value)) + value)
    return b"".join(parts)


def unpack_value_list(buf: bytes) -> List[bytes]:
    view = memoryview(buf)
    if len(view) < 4:
        raise ValueError("value list shorter than its count header")
    (count,) = struct.unpack_from("<I", view, 0)
    offset = 4
    values: List[bytes] = []
    for _ in range(count):
        if offset + 8 > len(view):
            raise ValueError("truncated value list")
        (vlen,) = struct.unpack_from("<Q", view, offset)
        offset += 8
        if offset + vlen > len(view):
            raise ValueError("truncated value in value list")
        values.append(bytes(view[offset : offset + vlen]))
        offset += vlen
    if offset != len(view):
        raise ValueError("trailing bytes after value list")
    return values
