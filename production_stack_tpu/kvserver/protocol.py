"""Wire protocol for the shared KV store.

Frame (all little-endian):

    magic   u32  = 0x54505543 ("TPUC")
    op      u8   (1=PUT, 2=GET, 3=DEL, 4=STAT, 5=PING)
    key_len u16
    key     bytes
    val_len u64  (PUT only)
    value   bytes

Response:

    magic   u32
    status  u8   (0=OK, 1=NOT_FOUND, 2=ERROR)
    val_len u64
    value   bytes

The ``naive`` serde stores a sequence's KV snapshot as:

    num_tokens u32, num_layers u32, then per layer:
      k: ndim u8, shape u32*ndim, dtype_code u8, data
      v: same

dtype codes: 0=float32, 1=bfloat16(stored as u16), 2=float16, 3=int8.
"""

from __future__ import annotations

import struct
from typing import List, Optional, Tuple

import numpy as np

MAGIC = 0x54505543
OP_PUT, OP_GET, OP_DEL, OP_STAT, OP_PING = 1, 2, 3, 4, 5
ST_OK, ST_NOT_FOUND, ST_ERROR = 0, 1, 2

_DTYPES = {0: np.float32, 2: np.float16, 3: np.int8}
_DTYPE_CODES = {np.dtype(np.float32): 0, np.dtype(np.float16): 2, np.dtype(np.int8): 3}
_BF16_CODE = 1


def _encode_array(arr: np.ndarray) -> bytes:
    if arr.dtype.name == "bfloat16":  # ml_dtypes bfloat16
        code = _BF16_CODE
        raw = arr.view(np.uint16)
    else:
        code = _DTYPE_CODES.get(arr.dtype)
        if code is None:
            arr = arr.astype(np.float32)
            code = 0
        raw = arr
    header = struct.pack("<B", arr.ndim) + struct.pack(f"<{arr.ndim}I", *arr.shape)
    return header + struct.pack("<B", code) + np.ascontiguousarray(raw).tobytes()


def _decode_array(buf: memoryview, offset: int) -> Tuple[np.ndarray, int]:
    ndim = buf[offset]
    offset += 1
    shape = struct.unpack_from(f"<{ndim}I", buf, offset)
    offset += 4 * ndim
    code = buf[offset]
    offset += 1
    count = int(np.prod(shape)) if shape else 1
    if code == _BF16_CODE:
        import ml_dtypes

        raw = np.frombuffer(buf, np.uint16, count, offset)
        arr = raw.view(ml_dtypes.bfloat16).reshape(shape)
        offset += 2 * count
    else:
        dtype = np.dtype(_DTYPES[code])
        arr = np.frombuffer(buf, dtype, count, offset).reshape(shape)
        offset += dtype.itemsize * count
    return arr, offset


def encode_kv_snapshot(
    layers: List[Tuple[np.ndarray, np.ndarray]], num_tokens: int
) -> bytes:
    parts = [struct.pack("<II", num_tokens, len(layers))]
    for k, v in layers:
        parts.append(_encode_array(np.asarray(k)))
        parts.append(_encode_array(np.asarray(v)))
    return b"".join(parts)


def decode_kv_snapshot(data: bytes) -> Tuple[List[Tuple[np.ndarray, np.ndarray]], int]:
    buf = memoryview(data)
    num_tokens, num_layers = struct.unpack_from("<II", buf, 0)
    offset = 8
    layers = []
    for _ in range(num_layers):
        k, offset = _decode_array(buf, offset)
        v, offset = _decode_array(buf, offset)
        layers.append((k, v))
    return layers, num_tokens


def pack_request(op: int, key: bytes, value: bytes = b"") -> bytes:
    head = struct.pack("<IBH", MAGIC, op, len(key)) + key
    if op == OP_PUT:
        head += struct.pack("<Q", len(value)) + value
    return head


def pack_response(status: int, value: bytes = b"") -> bytes:
    return struct.pack("<IBQ", MAGIC, status, len(value)) + value
