"""Wire protocol for the shared KV store.

Frame (all little-endian):

    magic   u32  = 0x54505543 ("TPUC")
    op      u8   (1=PUT, 2=GET, 3=DEL, 4=STAT, 5=PING, 6=MGET, 7=MPUT)
    key_len u16
    key     bytes
    val_len u64  (PUT and MPUT only)
    value   bytes

Response:

    magic   u32
    status  u8   (0=OK, 1=NOT_FOUND, 2=ERROR)
    val_len u64
    value   bytes

Batched ops (one framed round-trip for a whole hash chain):

    MGET: the key field carries a packed KEY LIST (u16 count, then per key
    u16 len + bytes) and there is NO value field — a server that predates
    the op parses the frame cleanly and answers ST_ERROR, so clients can
    fall back to serial GETs without desyncing the stream.  The OK
    response value is a packed VALUE LIST (u32 count, then per value
    u64 len + bytes) holding the PRESENT PREFIX of the requested keys:
    the server stops at the first missing key, mirroring how a prefix
    hash chain is consumed (blocks after a miss are useless).

    MPUT: key field = packed key list, value field = packed value list of
    the same count.  Response is a bare ST_OK/ST_ERROR.  Unlike MGET the
    frame has a value field an old server would misparse, so clients must
    reset the connection after any MPUT error reply.

The ``naive`` serde stores a sequence's KV snapshot as:

    num_tokens u32, num_layers u32, then per layer:
      k: ndim u8, shape u32*ndim, dtype_code u8, data
      v: same

dtype codes: 0=float32, 1=bfloat16(stored as u16), 2=float16, 3=int8.
"""

from __future__ import annotations

import struct
from typing import List, Tuple

import numpy as np

MAGIC = 0x54505543
OP_PUT, OP_GET, OP_DEL, OP_STAT, OP_PING = 1, 2, 3, 4, 5
OP_MGET, OP_MPUT = 6, 7
ST_OK, ST_NOT_FOUND, ST_ERROR = 0, 1, 2

OP_NAMES = {
    OP_PUT: "put", OP_GET: "get", OP_DEL: "del", OP_STAT: "stat",
    OP_PING: "ping", OP_MGET: "mget", OP_MPUT: "mput",
}

# The key field is a u16 length, so a packed key list can never exceed
# 64 KiB — clients chunk longer chains into multiple batches.
MAX_KEYS_PER_BATCH = 512

_DTYPES = {0: np.float32, 2: np.float16, 3: np.int8}
_DTYPE_CODES = {np.dtype(np.float32): 0, np.dtype(np.float16): 2, np.dtype(np.int8): 3}
_BF16_CODE = 1


def _encode_array(arr: np.ndarray) -> bytes:
    if arr.dtype.name == "bfloat16":  # ml_dtypes bfloat16
        code = _BF16_CODE
        raw = arr.view(np.uint16)
    else:
        code = _DTYPE_CODES.get(arr.dtype)
        if code is None:
            arr = arr.astype(np.float32)
            code = 0
        raw = arr
    header = struct.pack("<B", arr.ndim) + struct.pack(f"<{arr.ndim}I", *arr.shape)
    return header + struct.pack("<B", code) + np.ascontiguousarray(raw).tobytes()


def _decode_array(buf: memoryview, offset: int) -> Tuple[np.ndarray, int]:
    ndim = buf[offset]
    offset += 1
    shape = struct.unpack_from(f"<{ndim}I", buf, offset)
    offset += 4 * ndim
    code = buf[offset]
    offset += 1
    count = int(np.prod(shape)) if shape else 1
    if code == _BF16_CODE:
        import ml_dtypes

        raw = np.frombuffer(buf, np.uint16, count, offset)
        arr = raw.view(ml_dtypes.bfloat16).reshape(shape)
        offset += 2 * count
    else:
        dtype = np.dtype(_DTYPES[code])
        arr = np.frombuffer(buf, dtype, count, offset).reshape(shape)
        offset += dtype.itemsize * count
    return arr, offset


def encode_kv_snapshot(
    layers: List[Tuple[np.ndarray, np.ndarray]], num_tokens: int
) -> bytes:
    parts = [struct.pack("<II", num_tokens, len(layers))]
    for k, v in layers:
        parts.append(_encode_array(np.asarray(k)))
        parts.append(_encode_array(np.asarray(v)))
    return b"".join(parts)


def decode_kv_snapshot(data: bytes) -> Tuple[List[Tuple[np.ndarray, np.ndarray]], int]:
    buf = memoryview(data)
    num_tokens, num_layers = struct.unpack_from("<II", buf, 0)
    offset = 8
    layers = []
    for _ in range(num_layers):
        k, offset = _decode_array(buf, offset)
        v, offset = _decode_array(buf, offset)
        layers.append((k, v))
    return layers, num_tokens


def pack_request(op: int, key: bytes, value: bytes = b"") -> bytes:
    head = struct.pack("<IBH", MAGIC, op, len(key)) + key
    if op in (OP_PUT, OP_MPUT):
        head += struct.pack("<Q", len(value)) + value
    return head


def pack_response(status: int, value: bytes = b"") -> bytes:
    return struct.pack("<IBQ", MAGIC, status, len(value)) + value


# -- batched-op payloads (MGET/MPUT) ----------------------------------------


def pack_key_list(keys: List[bytes]) -> bytes:
    if len(keys) > 0xFFFF:
        raise ValueError(f"too many keys in one batch: {len(keys)}")
    parts = [struct.pack("<H", len(keys))]
    for key in keys:
        parts.append(struct.pack("<H", len(key)) + key)
    return b"".join(parts)


def unpack_key_list(buf: bytes) -> List[bytes]:
    """Strict parse: truncated or trailing-garbage payloads raise
    ValueError (the server answers ST_ERROR instead of guessing)."""
    view = memoryview(buf)
    if len(view) < 2:
        raise ValueError("key list shorter than its count header")
    (count,) = struct.unpack_from("<H", view, 0)
    offset = 2
    keys: List[bytes] = []
    for _ in range(count):
        if offset + 2 > len(view):
            raise ValueError("truncated key list")
        (klen,) = struct.unpack_from("<H", view, offset)
        offset += 2
        if offset + klen > len(view):
            raise ValueError("truncated key in key list")
        keys.append(bytes(view[offset : offset + klen]))
        offset += klen
    if offset != len(view):
        raise ValueError("trailing bytes after key list")
    return keys


def pack_value_list(values: List[bytes]) -> bytes:
    parts = [struct.pack("<I", len(values))]
    for value in values:
        parts.append(struct.pack("<Q", len(value)) + value)
    return b"".join(parts)


def unpack_value_list(buf: bytes) -> List[bytes]:
    view = memoryview(buf)
    if len(view) < 4:
        raise ValueError("value list shorter than its count header")
    (count,) = struct.unpack_from("<I", view, 0)
    offset = 4
    values: List[bytes] = []
    for _ in range(count):
        if offset + 8 > len(view):
            raise ValueError("truncated value list")
        (vlen,) = struct.unpack_from("<Q", view, offset)
        offset += 8
        if offset + vlen > len(view):
            raise ValueError("truncated value in value list")
        values.append(bytes(view[offset : offset + vlen]))
        offset += vlen
    if offset != len(view):
        raise ValueError("trailing bytes after value list")
    return values
