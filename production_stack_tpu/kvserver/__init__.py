"""Remote shared KV store (``kv://host:port``).

The cross-replica KV tier: the TPU analogue of the reference's LMCache
cache-server deployment (deployment-cache-server.yaml, remote URL helper
``lm://name:port`` at _helpers.tpl:164-166).  A length-prefixed binary TCP
protocol with a ``naive`` serde (raw little-endian tensors) — see
protocol.py.  Two interchangeable servers: the C++ epoll server under
native/kvserver/ (production) and server.py (pure-python fallback, CI).
"""
