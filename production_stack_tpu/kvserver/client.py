"""Blocking client for the shared KV store (used from the engine thread).

URL form: ``kv://host:port`` (the reference's cacheserver analogue uses
``lm://host:port``, _helpers.tpl:164-166).
"""

from __future__ import annotations

import logging
import socket
import struct
import threading
from typing import List, Optional, Tuple
from urllib.parse import urlparse

import numpy as np

from production_stack_tpu.kvserver import protocol as proto

logger = logging.getLogger(__name__)


class RemoteKVClient:
    def __init__(self, url: str, timeout: float = 10.0):
        parsed = urlparse(url)
        if parsed.scheme not in ("kv", "tcp"):
            raise ValueError(f"Unsupported KV store URL scheme: {url}")
        self.host = parsed.hostname or "127.0.0.1"
        self.port = parsed.port or 9400
        self.timeout = timeout
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None

    # -- socket plumbing ---------------------------------------------------

    def _connect(self) -> socket.socket:
        if self._sock is None:
            sock = socket.create_connection((self.host, self.port), self.timeout)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = sock
        return self._sock

    def _reset(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def _recv_exact(self, sock: socket.socket, n: int) -> bytes:
        chunks = []
        remaining = n
        while remaining:
            chunk = sock.recv(min(remaining, 1 << 20))
            if not chunk:
                raise ConnectionError("KV server closed connection")
            chunks.append(chunk)
            remaining -= len(chunk)
        return b"".join(chunks)

    def _call(self, op: int, key: bytes, value: bytes = b"") -> Tuple[int, bytes]:
        with self._lock:
            try:
                sock = self._connect()
                sock.sendall(proto.pack_request(op, key, value))
                head = self._recv_exact(sock, 13)
                magic, status, val_len = struct.unpack("<IBQ", head)
                if magic != proto.MAGIC:
                    raise ConnectionError("bad magic from KV server")
                payload = self._recv_exact(sock, val_len) if val_len else b""
                return status, payload
            except Exception:
                self._reset()
                raise

    # -- KV snapshot API ---------------------------------------------------

    def put_blocks(
        self,
        seq_id: str,
        layers: List[Tuple[np.ndarray, np.ndarray]],
        num_tokens: int,
    ) -> None:
        blob = proto.encode_kv_snapshot(layers, num_tokens)
        status, _ = self._call(proto.OP_PUT, seq_id.encode(), blob)
        if status != proto.ST_OK:
            raise RuntimeError(f"KV PUT failed with status {status}")

    def get_blocks(
        self, seq_id: str
    ) -> Optional[Tuple[List[Tuple[np.ndarray, np.ndarray]], int]]:
        status, payload = self._call(proto.OP_GET, seq_id.encode())
        if status == proto.ST_NOT_FOUND:
            return None
        if status != proto.ST_OK:
            raise RuntimeError(f"KV GET failed with status {status}")
        return proto.decode_kv_snapshot(payload)

    def delete(self, seq_id: str) -> None:
        self._call(proto.OP_DEL, seq_id.encode())

    def ping(self) -> bool:
        try:
            status, _ = self._call(proto.OP_PING, b"")
            return status == proto.ST_OK
        except Exception:
            return False

    def stat(self) -> dict:
        import json

        status, payload = self._call(proto.OP_STAT, b"")
        if status != proto.ST_OK:
            return {}
        return json.loads(payload)

    def close(self) -> None:
        self._reset()
