"""Blocking client for the shared KV store.

URL form: ``kv://host:port`` (the reference's cacheserver analogue uses
``lm://host:port``, _helpers.tpl:164-166).

Concurrency: a small CONNECTION POOL (``pool_size`` TCP connections,
created on demand) replaces the old single mutex-guarded socket, so the
engine's prefetch/offload worker threads issue RPCs in parallel instead
of serializing on one stream.  Each connection still carries strictly
request->response traffic, so per-connection framing stays trivial.

Batched ops: ``mget_blocks``/``mput_blocks`` move a whole hash chain in
ONE framed round-trip (protocol.py OP_MGET/OP_MPUT).  Against a server
that predates the ops (e.g. an un-rebuilt native/kvserver binary) the
first ST_ERROR reply flips a support flag and the call degrades to the
serial per-key path — same results, just one RTT per key again.

Snapshot serde versioning rides the same probe-once pattern: quantized
(data, scale) payloads want the v2 tagged frame, but a v1-only fleet
(an old store build, or old peer engines behind a store that never
advertised v2) must never receive bytes it would misparse.  Before the
first v2 encode the client asks the server's STAT for
``snapshot_versions``; a store that doesn't list 2 latches the client
to the dense v1 wire (quantized sides dequantize at encode — exactly
requantizable, so nothing corrupts), and a transient STAT failure
degrades THIS call without latching.
"""

from __future__ import annotations

import logging
import random
import socket
import struct
import threading
import time
from typing import List, Optional, Tuple
from urllib.parse import urlparse

import numpy as np

from production_stack_tpu.kvserver import protocol as proto

logger = logging.getLogger(__name__)

Snapshot = Tuple[List[Tuple[np.ndarray, np.ndarray]], int]


class RemoteKVClient:
    def __init__(self, url: str, timeout: float = 10.0, pool_size: int = 4,
                 wire_stats: Optional["proto.KVWireStats"] = None,
                 require_v2: bool = False):
        # require_v2 (cache.kv_wire_format="int8"): the operator asked
        # for the quantized wire explicitly, so a store that fails the
        # v2 probe triggers a WARNING at latch time — the downgrade to
        # dense v1 still happens (degrading beats dying mid-export),
        # but never silently.
        parsed = urlparse(url)
        if parsed.scheme not in ("kv", "tcp"):
            raise ValueError(f"Unsupported KV store URL scheme: {url}")
        self.host = parsed.hostname or "127.0.0.1"
        self.port = parsed.port or 9400
        self.timeout = timeout
        self.pool_size = max(1, int(pool_size))
        self.wire_stats = wire_stats  # tpu:kv_wire_bytes_total feed
        self.require_v2 = bool(require_v2)
        self._cv = threading.Condition()
        self._idle: List[socket.socket] = []
        self._live = 0  # connections checked out + idle
        # Batched-op support, cleared on the first ST_ERROR reply so a
        # legacy server costs exactly one failed probe per process.
        self._batch_ok = True
        # Snapshot serde-v2 support: None = not yet probed; the answer
        # is remembered (probe once) so a legacy fleet costs one STAT.
        self._snapshot_v2: Optional[bool] = None

    # -- socket plumbing ---------------------------------------------------

    # One retry with jittered backoff for transient connect failures (a
    # store pod mid-restart, a momentary accept-queue overflow): the
    # jitter keeps a fleet of engines from re-dialing in lockstep.
    _CONNECT_RETRY_BACKOFF_S = (0.05, 0.15)

    def _connect(self) -> socket.socket:
        try:
            sock = socket.create_connection((self.host, self.port), self.timeout)
        except OSError as e:
            lo, hi = self._CONNECT_RETRY_BACKOFF_S
            delay = random.uniform(lo, hi)
            logger.debug(
                "KV store connect to %s:%d failed (%s); retrying once in "
                "%.0f ms", self.host, self.port, e, delay * 1e3,
            )
            time.sleep(delay)
            sock = socket.create_connection((self.host, self.port), self.timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def _acquire(self) -> socket.socket:
        with self._cv:
            while True:
                if self._idle:
                    return self._idle.pop()
                if self._live < self.pool_size:
                    self._live += 1
                    break
                if not self._cv.wait(self.timeout):
                    raise TimeoutError("KV client pool exhausted")
        try:
            return self._connect()
        except Exception:
            with self._cv:
                self._live -= 1
                self._cv.notify()
            raise

    def _release(self, sock: socket.socket, broken: bool) -> None:
        with self._cv:
            if broken:
                try:
                    sock.close()
                finally:
                    self._live -= 1
            else:
                self._idle.append(sock)
            self._cv.notify()

    def _reset(self) -> None:
        """Close every idle connection (tests; error recovery).  Checked-
        out connections close on their own error path."""
        with self._cv:
            idle, self._idle = self._idle, []
            self._live -= len(idle)
            self._cv.notify_all()
        for sock in idle:
            try:
                sock.close()
            except Exception:
                pass

    def _recv_exact(self, sock: socket.socket, n: int) -> bytes:
        chunks = []
        remaining = n
        while remaining:
            chunk = sock.recv(min(remaining, 1 << 20))
            if not chunk:
                raise ConnectionError("KV server closed connection")
            chunks.append(chunk)
            remaining -= len(chunk)
        return b"".join(chunks)

    def _call(
        self,
        op: int,
        key: bytes,
        value: bytes = b"",
        reset_on_error_status: bool = False,
    ) -> Tuple[int, bytes]:
        """One request->response round-trip on a pooled connection.

        ``reset_on_error_status`` closes the connection when the server
        answers ST_ERROR — required after batched ops, where a legacy
        server may have misparsed the frame and desynced the stream."""
        sock = self._acquire()
        broken = False
        try:
            sock.sendall(proto.pack_request(op, key, value))
            head = self._recv_exact(sock, 13)
            magic, status, val_len = struct.unpack("<IBQ", head)
            if magic != proto.MAGIC:
                raise ConnectionError("bad magic from KV server")
            payload = self._recv_exact(sock, val_len) if val_len else b""
            if reset_on_error_status and status == proto.ST_ERROR:
                broken = True
            return status, payload
        except Exception:
            broken = True
            raise
        finally:
            self._release(sock, broken)

    # -- KV snapshot API ---------------------------------------------------

    def snapshot_wire_version(self, layers) -> int:
        """Serde version the next encode of ``layers`` will use: v2 for
        quantized payloads IF the store advertises it, v1 otherwise.
        The v2 probe (one STAT, answer remembered) only runs when a
        quantized payload first needs it."""
        quantized = any(
            proto.is_quantized_side(k) or proto.is_quantized_side(v)
            for k, v in layers
        )
        if not quantized:
            return proto.SNAPSHOT_V1
        with self._cv:
            known = self._snapshot_v2
        if known is None:
            try:
                versions = self.stat().get("snapshot_versions", [1])
                known = proto.SNAPSHOT_V2 in versions
            except Exception:
                # Transient STAT failure: degrade THIS call to the safe
                # dense wire without latching the answer.
                return proto.SNAPSHOT_V1
            with self._cv:
                self._snapshot_v2 = known
            if not known and self.require_v2:
                logger.warning(
                    "kv_wire_format=int8 requested but the KV store at "
                    "%s:%d does not advertise snapshot serde v2 "
                    "(legacy build, or pinned --max-snapshot-version 1):"
                    " remote snapshots DOWNGRADE to the dense v1 wire "
                    "(~4x the bytes) until the store is upgraded",
                    self.host, self.port,
                )
        return proto.SNAPSHOT_V2 if known else proto.SNAPSHOT_V1

    def _encode_snapshot(self, layers, num_tokens: int) -> bytes:
        return proto.encode_kv_snapshot(
            layers, num_tokens, version=self.snapshot_wire_version(layers)
        )

    def _note_wire(self, blob: bytes, sent: bool) -> None:
        # Called only after a frame actually MOVED (PUT/MPUT accepted,
        # GET/MGET payload received): a refused MPUT batch retried
        # serially must count its snapshots once, not per encode.
        if self.wire_stats is None:
            return
        try:
            version = proto.snapshot_version(blob)
        except ValueError:
            return  # malformed frames are the decoder's error to raise
        fmt = "int8" if version >= proto.SNAPSHOT_V2 else "dense"
        self.wire_stats.add_wire("remote", fmt, len(blob))
        if sent:
            self.wire_stats.add_snapshot(version)

    def _decode_snapshot(self, payload: bytes) -> Snapshot:
        self._note_wire(payload, sent=False)
        return proto.decode_kv_snapshot(payload)

    def put_blocks(
        self,
        seq_id: str,
        layers: List[Tuple[np.ndarray, np.ndarray]],
        num_tokens: int,
    ) -> None:
        blob = self._encode_snapshot(layers, num_tokens)
        status, _ = self._call(proto.OP_PUT, seq_id.encode(), blob)
        if status != proto.ST_OK:
            raise RuntimeError(f"KV PUT failed with status {status}")
        self._note_wire(blob, sent=True)

    def get_blocks(self, seq_id: str) -> Optional[Snapshot]:
        status, payload = self._call(proto.OP_GET, seq_id.encode())
        if status == proto.ST_NOT_FOUND:
            return None
        if status != proto.ST_OK:
            raise RuntimeError(f"KV GET failed with status {status}")
        return self._decode_snapshot(payload)

    def mget_blocks(self, keys: List[str]) -> List[Snapshot]:
        """Fetch the PRESENT PREFIX of a key chain: decoded snapshots for
        the leading keys the store holds, stopping at the first miss.
        One round-trip per MAX_KEYS_PER_BATCH keys when the server speaks
        MGET; serial GETs otherwise."""
        out: List[Snapshot] = []
        if self._batch_ok:
            for start in range(0, len(keys), proto.MAX_KEYS_PER_BATCH):
                chunk = keys[start : start + proto.MAX_KEYS_PER_BATCH]
                status, payload = self._call(
                    proto.OP_MGET,
                    proto.pack_key_list([k.encode() for k in chunk]),
                    reset_on_error_status=True,
                )
                if status == proto.ST_ERROR:
                    logger.info(
                        "KV server does not speak MGET; falling back to "
                        "serial GETs"
                    )
                    # One-way False latch, but written under the pool
                    # lock anyway: prefetch fetchers and the export
                    # writer share this client (SC501).
                    with self._cv:
                        self._batch_ok = False
                    break
                if status != proto.ST_OK:
                    raise RuntimeError(f"KV MGET failed with status {status}")
                values = proto.unpack_value_list(payload)
                out.extend(self._decode_snapshot(v) for v in values)
                if len(values) < len(chunk):
                    return out
            else:
                return out
        for key in keys[len(out):]:
            entry = self.get_blocks(key)
            if entry is None:
                break
            out.append(entry)
        return out

    # Aggregate packed-value bytes per MPUT frame.  Servers guard the
    # frame's value length against their --capacity-gb before buffering
    # it, so an unbounded batch of individually-fine snapshots could trip
    # the guard a single PUT never would.
    _MPUT_BYTE_CAP = 4 << 20

    def _mput_chunks(self, entries):
        """(keys, blobs) frames bounded by count AND aggregate bytes."""
        keys: List[bytes] = []
        blobs: List[bytes] = []
        size = 0
        for key, layers, num_tokens in entries:
            blob = self._encode_snapshot(layers, num_tokens)
            if keys and (
                len(keys) >= proto.MAX_KEYS_PER_BATCH
                or size + len(blob) > self._MPUT_BYTE_CAP
            ):
                yield keys, blobs
                keys, blobs, size = [], [], 0
            keys.append(key.encode())
            blobs.append(blob)
            size += len(blob)
        if keys:
            yield keys, blobs

    def _probe_batch_support(self) -> None:
        """Disambiguate an MPUT ST_ERROR: MGET never trips capacity
        guards, so an MGET error means the server predates the batched
        ops (disable them), while an MGET OK means the MPUT failure was
        about THAT frame (keep batching; the caller retried serially)."""
        try:
            status, _ = self._call(
                proto.OP_MGET,
                proto.pack_key_list([b"\x00batch-support-probe"]),
                reset_on_error_status=True,
            )
            if status == proto.ST_ERROR:
                logger.info(
                    "KV server does not speak MGET/MPUT; using serial ops"
                )
                with self._cv:
                    self._batch_ok = False
        except Exception:
            pass  # transient: keep the current setting

    def mput_blocks(
        self,
        entries: List[Tuple[str, List[Tuple[np.ndarray, np.ndarray]], int]],
    ) -> None:
        """Store many (key, layers, num_tokens) snapshots; one round-trip
        per byte/count-bounded batch when the server speaks MPUT."""
        if self._batch_ok:
            done = 0
            for keys, blobs in self._mput_chunks(entries):
                try:
                    status, _ = self._call(
                        proto.OP_MPUT,
                        proto.pack_key_list(keys),
                        proto.pack_value_list(blobs),
                        reset_on_error_status=True,
                    )
                except (ConnectionError, OSError):
                    # A server refusing the frame mid-upload (capacity
                    # guard closes the connection while our sendall is
                    # still writing the body) surfaces as a reset, not a
                    # readable ST_ERROR.  Same recovery: this call goes
                    # serial, the probe decides whether batching stays.
                    status = proto.ST_ERROR
                if status == proto.ST_ERROR:
                    # Either a legacy server or a frame the store's
                    # capacity guard refused: retry this call serially,
                    # then probe which it was.
                    entries = entries[done:]
                    self._probe_batch_support()
                    break
                if status != proto.ST_OK:
                    raise RuntimeError(f"KV MPUT failed with status {status}")
                for blob in blobs:
                    self._note_wire(blob, sent=True)
                done += len(keys)
            else:
                return
        for key, layers, num_tokens in entries:
            self.put_blocks(key, layers, num_tokens)

    def delete(self, seq_id: str) -> None:
        self._call(proto.OP_DEL, seq_id.encode())

    def ping(self) -> bool:
        try:
            status, _ = self._call(proto.OP_PING, b"")
            return status == proto.ST_OK
        except Exception:
            return False

    def stat(self) -> dict:
        import json

        status, payload = self._call(proto.OP_STAT, b"")
        if status != proto.ST_OK:
            return {}
        return json.loads(payload)

    def close(self) -> None:
        self._reset()
