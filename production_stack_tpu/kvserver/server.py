"""Pure-python shared KV store server (asyncio).

CI/test fallback for the C++ epoll server in native/kvserver/ — same wire
protocol (protocol.py), same CLI shape.  The reference's counterpart is the
LMCache cache-server deployment (deployment-cache-server.yaml:19-42).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import struct
import time
from typing import Dict, Tuple

from production_stack_tpu.kvserver import protocol as proto
from production_stack_tpu.utils.log import init_logger

logger = logging.getLogger(__name__)


class KVStore:
    def __init__(self, capacity_bytes: int, max_snapshot_version: int = 2):
        # The serde-rollout switch (--max-snapshot-version): the store
        # advertises which snapshot versions the DEPLOYMENT accepts, and
        # clients probe it before putting v2 (quantized) frames on the
        # wire.  Hold it at 1 until every engine that READS this store
        # speaks v2 — values are opaque blobs to the store itself; the
        # field protects not-yet-upgraded consumer peers.
        self.max_snapshot_version = int(max_snapshot_version)
        self.capacity_bytes = capacity_bytes
        self.used = 0
        self._data: Dict[bytes, Tuple[bytes, float]] = {}
        self.hits = 0
        self.misses = 0
        # Per-op frame counts: one entry per network round-trip, so a
        # client can prove MGET batching cut its RTTs (bench
        # remote_prefix_ab reads this through STAT).
        self.ops: Dict[str, int] = {}

    def put(self, key: bytes, value: bytes) -> None:
        old = self._data.pop(key, None)
        if old is not None:
            self.used -= len(old[0])
        while self.used + len(value) > self.capacity_bytes and self._data:
            evict_key = min(self._data, key=lambda k: self._data[k][1])
            self.used -= len(self._data.pop(evict_key)[0])
        self._data[key] = (value, time.time())
        self.used += len(value)

    def get(self, key: bytes):
        entry = self._data.get(key)
        if entry is None:
            self.misses += 1
            return None
        self.hits += 1
        self._data[key] = (entry[0], time.time())  # LRU touch
        return entry[0]

    def delete(self, key: bytes) -> None:
        entry = self._data.pop(key, None)
        if entry is not None:
            self.used -= len(entry[0])

    def stats(self) -> dict:
        return {
            "keys": len(self._data),
            "used_bytes": self.used,
            "capacity_bytes": self.capacity_bytes,
            "hits": self.hits,
            "misses": self.misses,
            "ops": dict(self.ops),
            # Snapshot serde versions this deployment accepts: clients
            # probe this before putting v2 (quantized) frames on the
            # wire, so a fleet behind a legacy store — or one pinned to
            # --max-snapshot-version 1 mid-rollout — stays on dense v1
            # (protocol.py versioning).
            "snapshot_versions": [
                v for v in proto.SNAPSHOT_VERSIONS
                if v <= self.max_snapshot_version
            ],
        }


async def _recv_exact(reader: asyncio.StreamReader, n: int) -> bytes:
    return await reader.readexactly(n)


async def handle_client(
    store: KVStore,
    reader: asyncio.StreamReader,
    writer: asyncio.StreamWriter,
    latency_s: float = 0.0,
) -> None:
    """``latency_s`` injects a per-frame service delay (tests and the
    bench's remote_prefix_ab stage emulate a cross-datacenter store with
    it; production serving never sets it)."""
    peer = writer.get_extra_info("peername")
    try:
        while True:
            try:
                head = await _recv_exact(reader, 7)
            except asyncio.IncompleteReadError:
                break
            magic, op, key_len = struct.unpack("<IBH", head)
            if magic != proto.MAGIC:
                writer.write(proto.pack_response(proto.ST_ERROR))
                break
            store.ops[proto.OP_NAMES.get(op, f"op{op}")] = (
                store.ops.get(proto.OP_NAMES.get(op, f"op{op}"), 0) + 1
            )
            key = await _recv_exact(reader, key_len) if key_len else b""
            if latency_s > 0:
                await asyncio.sleep(latency_s)
            if op == proto.OP_PUT:
                (val_len,) = struct.unpack("<Q", await _recv_exact(reader, 8))
                # Reject values the store could never hold before buffering
                # them in DRAM (same guard as the C++ server).
                if val_len > store.capacity_bytes:
                    writer.write(proto.pack_response(proto.ST_ERROR))
                    break
                value = await _recv_exact(reader, val_len)
                store.put(key, value)
                writer.write(proto.pack_response(proto.ST_OK))
            elif op == proto.OP_MGET:
                # Batched chain fetch: answer the PRESENT PREFIX of the
                # requested keys in one reply (a chain consumer cannot
                # use blocks past the first miss anyway).
                try:
                    keys = proto.unpack_key_list(key)
                except ValueError:
                    writer.write(proto.pack_response(proto.ST_ERROR))
                    await writer.drain()
                    continue
                values = []
                for k in keys:
                    value = store.get(k)
                    if value is None:
                        break
                    values.append(value)
                writer.write(
                    proto.pack_response(
                        proto.ST_OK, proto.pack_value_list(values)
                    )
                )
            elif op == proto.OP_MPUT:
                (val_len,) = struct.unpack("<Q", await _recv_exact(reader, 8))
                if val_len > store.capacity_bytes:
                    writer.write(proto.pack_response(proto.ST_ERROR))
                    break
                value = await _recv_exact(reader, val_len)
                try:
                    keys = proto.unpack_key_list(key)
                    values = proto.unpack_value_list(value)
                    if len(keys) != len(values):
                        raise ValueError("key/value count mismatch")
                except ValueError:
                    writer.write(proto.pack_response(proto.ST_ERROR))
                    await writer.drain()
                    continue
                for k, v in zip(keys, values):
                    store.put(k, v)
                writer.write(proto.pack_response(proto.ST_OK))
            elif op == proto.OP_GET:
                value = store.get(key)
                if value is None:
                    writer.write(proto.pack_response(proto.ST_NOT_FOUND))
                else:
                    writer.write(proto.pack_response(proto.ST_OK, value))
            elif op == proto.OP_DEL:
                store.delete(key)
                writer.write(proto.pack_response(proto.ST_OK))
            elif op == proto.OP_STAT:
                writer.write(
                    proto.pack_response(
                        proto.ST_OK, json.dumps(store.stats()).encode()
                    )
                )
            elif op == proto.OP_PING:
                writer.write(proto.pack_response(proto.ST_OK))
            else:
                writer.write(proto.pack_response(proto.ST_ERROR))
            await writer.drain()
    except (ConnectionResetError, BrokenPipeError):
        pass
    finally:
        writer.close()
        logger.debug("client %s disconnected", peer)


async def serve(
    host: str, port: int, capacity_bytes: int, latency_s: float = 0.0,
    max_snapshot_version: int = 2,
) -> None:
    store = KVStore(capacity_bytes, max_snapshot_version=max_snapshot_version)
    server = await asyncio.start_server(
        lambda r, w: handle_client(store, r, w, latency_s=latency_s),
        host, port,
    )
    logger.info("KV store serving on %s:%d (%.1f GiB)", host, port, capacity_bytes / 2**30)
    async with server:
        await server.serve_forever()


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description="Shared KV cache server")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=9400)
    parser.add_argument("--capacity-gb", type=float, default=4.0)
    parser.add_argument(
        "--inject-latency-ms", type=float, default=0.0,
        help="per-frame service delay for latency testing (never set in "
        "production)",
    )
    parser.add_argument(
        "--max-snapshot-version", type=int, default=2, choices=[1, 2],
        help="highest KV snapshot serde version to advertise via STAT "
        "(the mixed-fleet rollout switch: hold at 1 until every engine "
        "that reads this store speaks v2, so quantized writers keep "
        "encoding the dense v1 frames old readers can parse)",
    )
    parser.add_argument("--log-level", default="info")
    args = parser.parse_args(argv)
    init_logger("production_stack_tpu", args.log_level)
    asyncio.run(serve(
        args.host, args.port, int(args.capacity_gb * 2**30),
        latency_s=args.inject_latency_ms / 1e3,
        max_snapshot_version=args.max_snapshot_version,
    ))


if __name__ == "__main__":
    main()
