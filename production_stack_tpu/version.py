"""Single source of the package version (reference: src/vllm_router/version.py)."""

__version__ = "0.1.0"
