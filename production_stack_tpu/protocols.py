"""Pydantic models for the OpenAI-compatible surface.

Reference counterpart: src/vllm_router/protocols.py:7-51.  Extra fields are
tolerated (the router proxies bodies it does not fully model).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from pydantic import BaseModel, ConfigDict, Field


class OpenAIBaseModel(BaseModel):
    model_config = ConfigDict(extra="allow")


class ErrorResponse(OpenAIBaseModel):
    object: str = "error"
    message: str
    type: str
    param: Optional[str] = None
    code: int = 400


class ModelCard(OpenAIBaseModel):
    id: str
    object: str = "model"
    created: int = Field(default_factory=lambda: int(time.time()))
    owned_by: str = "production-stack-tpu"
    root: Optional[str] = None
    parent: Optional[str] = None


class ModelList(OpenAIBaseModel):
    object: str = "list"
    data: List[ModelCard] = Field(default_factory=list)


class ChatMessage(OpenAIBaseModel):
    role: str
    content: Any = None


class ChatCompletionRequest(OpenAIBaseModel):
    model: str
    messages: List[ChatMessage]
    stream: bool = False
    max_tokens: Optional[int] = None
    max_completion_tokens: Optional[int] = None
    temperature: Optional[float] = None
    top_p: Optional[float] = None
    top_k: Optional[int] = None
    n: int = 1
    stop: Optional[Any] = None
    seed: Optional[int] = None
    logprobs: Optional[bool] = None
    top_logprobs: Optional[int] = None
    presence_penalty: Optional[float] = None
    frequency_penalty: Optional[float] = None
    user: Optional[str] = None


class CompletionRequest(OpenAIBaseModel):
    model: str
    prompt: Any
    stream: bool = False
    max_tokens: Optional[int] = None
    temperature: Optional[float] = None
    top_p: Optional[float] = None
    top_k: Optional[int] = None
    n: int = 1
    stop: Optional[Any] = None
    seed: Optional[int] = None
    echo: bool = False
    user: Optional[str] = None


class UsageInfo(OpenAIBaseModel):
    prompt_tokens: int = 0
    completion_tokens: int = 0
    total_tokens: int = 0


class EmbeddingRequest(OpenAIBaseModel):
    model: str
    input: Any
    encoding_format: str = "float"


def error_json(message: str, type_: str = "invalid_request_error", code: int = 400) -> Dict[str, Any]:
    return {"error": {"message": message, "type": type_, "code": code}}
