"""URL/CLI parsing helpers and process limits.

Reference counterpart: src/vllm_router/utils.py:42-95 (validate_url,
parse_static_urls/models, set_ulimit).
"""

from __future__ import annotations

import logging
import re
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)

_URL_RE = re.compile(
    r"^(https?)://"  # scheme
    r"(?:[A-Za-z0-9._~%-]+|\[[0-9A-Fa-f:]+\])"  # host or [ipv6]
    r"(?::\d{1,5})?"  # optional port
    r"(?:/.*)?$"  # optional path
)


def validate_url(url: str) -> bool:
    """True iff *url* looks like an http(s) URL with a host."""
    return bool(_URL_RE.match(url or ""))


def _split_csv(value: Optional[str]) -> List[str]:
    if not value:
        return []
    return [item.strip() for item in value.split(",") if item.strip()]


def parse_static_urls(static_backends: str) -> List[str]:
    urls = _split_csv(static_backends)
    for url in urls:
        if not validate_url(url):
            raise ValueError(f"Invalid backend URL: {url!r}")
    return urls


def parse_static_models(static_models: str) -> List[str]:
    return _split_csv(static_models)


def parse_static_aliases(static_aliases: str) -> Dict[str, str]:
    """Parse ``alias:model,alias2:model2`` into a dict."""
    aliases: Dict[str, str] = {}
    for pair in _split_csv(static_aliases):
        alias, sep, model = pair.partition(":")
        if not sep or not alias or not model:
            raise ValueError(f"Invalid model alias entry: {pair!r}")
        aliases[alias] = model
    return aliases


def parse_static_model_types(static_model_types: str) -> List[str]:
    return _split_csv(static_model_types)


def set_ulimit(target_soft_limit: int = 65535) -> None:
    """Raise RLIMIT_NOFILE so the streaming proxy can hold many sockets
    (reference: src/vllm_router/utils.py:64-79)."""
    try:
        import resource
    except ImportError:  # non-POSIX
        return
    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    if soft >= target_soft_limit:
        return
    try:
        resource.setrlimit(resource.RLIMIT_NOFILE, (min(target_soft_limit, hard), hard))
    except ValueError as e:
        logger.warning(
            "Could not raise RLIMIT_NOFILE from %d to %d: %s", soft, target_soft_limit, e
        )


def parse_deadline(headers, body, now: float) -> Optional[float]:
    """Per-request deadline contract, shared by the router and the engine
    server (docs/robustness.md): an ``X-Request-Deadline`` header carries
    absolute epoch seconds (what the router propagates) and wins over an
    OpenAI ``timeout``-style body field (seconds from now).  Returns an
    absolute epoch float or None; raises ValueError on malformed input.
    One definition on purpose — two copies of this parsing would let the
    router and engine silently diverge on the client-facing contract."""
    hdr = headers.get("x-request-deadline") if headers is not None else None
    if hdr is not None:
        try:
            return float(hdr)
        except (TypeError, ValueError):
            raise ValueError(
                f"X-Request-Deadline must be epoch seconds, got {hdr!r}"
            ) from None
    timeout = (body or {}).get("timeout")
    if timeout is None:
        return None
    if isinstance(timeout, bool) or not isinstance(timeout, (int, float)):
        raise ValueError(
            f"'timeout' must be a number of seconds, got {timeout!r}"
        )
    if timeout <= 0:
        raise ValueError(f"'timeout' must be > 0 seconds, got {timeout}")
    return now + float(timeout)
