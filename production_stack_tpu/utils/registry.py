"""Explicit service registry.

The reference wires its router from module-level metaclass singletons
(src/vllm_router/utils.py:10-39) and tears them down during dynamic
reconfiguration by deleting entries from ``SingletonMeta._instances``
(src/vllm_router/routers/routing_logic.py:189-196), which is racy: a request
thread can observe a half-rebuilt registry.  Here every service lives in one
registry guarded by an RLock, and ``replace()`` swaps atomically.
"""

from __future__ import annotations

import asyncio
import inspect
import logging
import threading
from typing import Any, Callable, Dict, Optional

logger = logging.getLogger(__name__)


class ServiceRegistry:
    """Thread-safe named-service registry with atomic replacement."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._services: Dict[str, Any] = {}

    def set(self, name: str, service: Any) -> Any:
        with self._lock:
            self._services[name] = service
        return service

    def get(self, name: str, default: Any = None) -> Any:
        with self._lock:
            return self._services.get(name, default)

    def require(self, name: str) -> Any:
        with self._lock:
            if name not in self._services:
                raise KeyError(
                    f"Service {name!r} has not been initialized "
                    f"(available: {sorted(self._services)})"
                )
            return self._services[name]

    def contains(self, name: str) -> bool:
        with self._lock:
            return name in self._services

    def replace(
        self,
        name: str,
        factory: Callable[[], Any],
        close_old: Optional[Callable[[Any], None]] = None,
    ) -> Any:
        """Atomically build a new service and swap it in.

        The old service (if any) is closed *after* the swap so readers never
        observe a missing service mid-reconfigure.

        Note on lifetimes: readers hold raw references, so a reader that
        fetched the old service just before the swap may still be using it
        when ``close_old`` runs.  ``close_old`` must therefore be graceful
        for in-flight users — e.g. discovery/scraper closes cancel background
        tasks but leave read methods safe, and long-lived IO objects (client
        sessions) should be drained or closed with a grace period rather
        than hard-closed here.
        """
        new = factory()
        with self._lock:
            old = self._services.get(name)
            self._services[name] = new
        if old is not None and close_old is not None:
            close_old(old)
        return new

    def pop(self, name: str) -> Any:
        with self._lock:
            return self._services.pop(name, None)

    async def close(self, grace_s: float = 5.0) -> None:
        """Close every registered service that exposes a ``close()``,
        each bounded by ``grace_s``.

        This is the grace-period promise the ``replace()`` docstring
        makes, made real: async closes run CONCURRENTLY under
        ``asyncio.wait_for`` (total wall time ~grace_s, and one wedged
        service cannot starve its siblings), sync closes run inline; a
        close that overruns or raises is logged and abandoned instead of
        hanging shutdown.  Closables are popped before closing, so a
        concurrent double-close sweep is a no-op and idempotent services
        may be closed explicitly first without harm."""
        with self._lock:
            names = list(self._services)
        grace_s = max(0.0, float(grace_s))
        pending = []
        for name in names:
            service = self.pop(name)
            close = getattr(service, "close", None)
            if service is None or not callable(close):
                continue
            try:
                result = close()
            except Exception:
                logger.exception("service %r close() failed", name)
                continue
            if inspect.isawaitable(result):
                pending.append((name, result))

        async def _bounded(name: str, awaitable) -> None:
            try:
                await asyncio.wait_for(awaitable, timeout=grace_s)
            except asyncio.TimeoutError:
                logger.warning(
                    "service %r did not close within %.1fs grace",
                    name, grace_s,
                )
            except Exception:
                logger.exception("service %r close() failed", name)

        if pending:
            await asyncio.gather(
                *(_bounded(name, awaitable) for name, awaitable in pending)
            )

    def reset(self) -> None:
        """Drop all services (test isolation; reference counterpart is
        deleting ``SingletonMeta._instances`` entries, src/tests/test_singleton.py:14-60)."""
        with self._lock:
            self._services.clear()


#: Process-global registry used by the router app.  Tests construct their own.
registry = ServiceRegistry()
