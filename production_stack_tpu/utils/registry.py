"""Explicit service registry.

The reference wires its router from module-level metaclass singletons
(src/vllm_router/utils.py:10-39) and tears them down during dynamic
reconfiguration by deleting entries from ``SingletonMeta._instances``
(src/vllm_router/routers/routing_logic.py:189-196), which is racy: a request
thread can observe a half-rebuilt registry.  Here every service lives in one
registry guarded by an RLock, and ``replace()`` swaps atomically.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional


class ServiceRegistry:
    """Thread-safe named-service registry with atomic replacement."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._services: Dict[str, Any] = {}

    def set(self, name: str, service: Any) -> Any:
        with self._lock:
            self._services[name] = service
        return service

    def get(self, name: str, default: Any = None) -> Any:
        with self._lock:
            return self._services.get(name, default)

    def require(self, name: str) -> Any:
        with self._lock:
            if name not in self._services:
                raise KeyError(
                    f"Service {name!r} has not been initialized "
                    f"(available: {sorted(self._services)})"
                )
            return self._services[name]

    def contains(self, name: str) -> bool:
        with self._lock:
            return name in self._services

    def replace(
        self,
        name: str,
        factory: Callable[[], Any],
        close_old: Optional[Callable[[Any], None]] = None,
    ) -> Any:
        """Atomically build a new service and swap it in.

        The old service (if any) is closed *after* the swap so readers never
        observe a missing service mid-reconfigure.

        Note on lifetimes: readers hold raw references, so a reader that
        fetched the old service just before the swap may still be using it
        when ``close_old`` runs.  ``close_old`` must therefore be graceful
        for in-flight users — e.g. discovery/scraper closes cancel background
        tasks but leave read methods safe, and long-lived IO objects (client
        sessions) should be drained or closed with a grace period rather
        than hard-closed here.
        """
        new = factory()
        with self._lock:
            old = self._services.get(name)
            self._services[name] = new
        if old is not None and close_old is not None:
            close_old(old)
        return new

    def pop(self, name: str) -> Any:
        with self._lock:
            return self._services.pop(name, None)

    def reset(self) -> None:
        """Drop all services (test isolation; reference counterpart is
        deleting ``SingletonMeta._instances`` entries, src/tests/test_singleton.py:14-60)."""
        with self._lock:
            self._services.clear()


#: Process-global registry used by the router app.  Tests construct their own.
registry = ServiceRegistry()
