"""Graceful-drain controller shared by the router and the engine server.

Lifecycle contract (docs/robustness.md "Drain sequence"): SIGTERM or
``POST /drain`` flips the process into draining —

1. readiness (``/ready``) starts answering 503, so k8s pulls the pod from
   its Service (and the router's discovery drops a draining engine);
2. new data-plane work is rejected with 503 + ``Connection: close``;
3. in-flight streams run to completion, bounded by ``grace_s``;
4. ``exit_cb`` fires (in production: SIGINT to self, which rides aiohttp's
   graceful-exit path through every cleanup_ctx and exits 0).

``begin()`` is idempotent: the helm preStop hook POSTs /drain and kubelet
then delivers SIGTERM — both paths converge on one watch task.  Liveness
(``/health``) intentionally keeps passing during a drain: a kubelet that
saw liveness fail would kill the pod mid-stream, defeating the point.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Callable, Optional

logger = logging.getLogger(__name__)

#: ServiceRegistry key both processes store their controller under.
DRAIN_CONTROLLER = "drain_controller"


class DrainController:
    def __init__(
        self,
        grace_s: float = 30.0,
        busy_fn: Optional[Callable[[], bool]] = None,
        exit_cb: Optional[Callable[[], None]] = None,
    ):
        self.grace_s = float(grace_s)
        # Extra busy-ness beyond the request counter (the engine reports
        # "streams still attached OR sequences still decoding" here).
        self.busy_fn = busy_fn
        # Fired when the drain ends (cleanly or at grace expiry).  None in
        # tests; the server mains install a SIGINT-to-self here.
        self.exit_cb = exit_cb
        self.draining = False
        self._in_flight = 0
        self._task: Optional[asyncio.Task] = None
        # None while draining (or never drained); True = every stream
        # finished inside the grace; False = grace expired with work live.
        self.completed: Optional[bool] = None

    # -- in-flight tracking (router middleware) ----------------------------

    def inc(self) -> None:
        self._in_flight += 1

    def dec(self) -> None:
        self._in_flight = max(0, self._in_flight - 1)

    @property
    def in_flight(self) -> int:
        return self._in_flight

    def busy(self) -> bool:
        if self._in_flight > 0:
            return True
        return bool(self.busy_fn()) if self.busy_fn is not None else False

    # -- drain -------------------------------------------------------------

    def begin(self) -> None:
        """Start draining (idempotent).  Must run on the event loop —
        signal handlers installed via loop.add_signal_handler qualify."""
        if self.draining:
            return
        self.draining = True
        logger.info(
            "drain started: %d in-flight, grace %.1fs",
            self._in_flight, self.grace_s,
        )
        self._task = asyncio.get_event_loop().create_task(self._watch())

    async def _watch(self) -> None:
        deadline = time.monotonic() + self.grace_s
        while time.monotonic() < deadline and self.busy():
            await asyncio.sleep(0.05)
        self.completed = not self.busy()
        if self.completed:
            logger.info("drain complete: all in-flight work finished")
        else:
            logger.warning(
                "drain grace (%.1fs) expired with work in flight; exiting "
                "anyway", self.grace_s,
            )
        if self.exit_cb is not None:
            self.exit_cb()

    async def wait(self, timeout: Optional[float] = None) -> Optional[bool]:
        """Test helper: await the watch task; returns ``completed``."""
        if self._task is not None:
            await asyncio.wait_for(asyncio.shield(self._task), timeout)
        return self.completed
