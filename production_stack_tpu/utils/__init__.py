"""Shared utilities (reference counterpart: src/vllm_router/utils.py:10-95).

Design deviation from the reference: the reference uses metaclass singletons
(``SingletonMeta``, utils.py:10-39) which made hot-reconfiguration racy
(SURVEY.md section 7, "Hot-reconfig correctness").  We use one explicit,
lock-guarded :class:`ServiceRegistry` instead.
"""

from production_stack_tpu.utils.registry import ServiceRegistry, registry
from production_stack_tpu.utils.net import (
    parse_static_aliases,
    parse_static_model_types,
    parse_static_models,
    parse_static_urls,
    set_ulimit,
    validate_url,
)

__all__ = [
    "ServiceRegistry",
    "registry",
    "validate_url",
    "parse_static_urls",
    "parse_static_models",
    "parse_static_aliases",
    "parse_static_model_types",
    "set_ulimit",
]
