"""Colored logging (reference counterpart: src/vllm_router/log.py:34-43)."""

from __future__ import annotations

import logging
import sys

_COLORS = {
    logging.DEBUG: "\033[36m",  # cyan
    logging.INFO: "\033[32m",  # green
    logging.WARNING: "\033[33m",  # yellow
    logging.ERROR: "\033[31m",  # red
    logging.CRITICAL: "\033[1;31m",  # bold red
}
_RESET = "\033[0m"


class ColorFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        message = super().format(record)
        if sys.stderr.isatty():
            color = _COLORS.get(record.levelno, "")
            if color:
                return f"{color}{message}{_RESET}"
        return message


def init_logger(name: str, level: str = "INFO") -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(
            ColorFormatter(
                "[%(asctime)s] %(levelname)s %(name)s: %(message)s",
                datefmt="%Y-%m-%d %H:%M:%S",
            )
        )
        logger.addHandler(handler)
        logger.propagate = False
    logger.setLevel(level.upper())
    return logger
