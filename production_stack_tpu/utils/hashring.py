"""Consistent hash ring for session-affinity routing.

The reference depends on the third-party ``uhashring`` package
(src/vllm_router/routers/routing_logic.py:10,94-136).  That package is not a
given on TPU images, and the required surface is tiny, so we implement the
ring directly: each node is mapped to ``vnodes`` points on a 2^64 ring via
blake2b; a key routes to the first node clockwise from its hash.  Removing a
node only remaps keys that landed on that node's points (minimal disruption —
the invariant the reference tests in src/tests/test_session_router.py:92-135).
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Dict, Iterable, List, Optional


def _hash(key: str) -> int:
    digest = hashlib.blake2b(key.encode("utf-8"), digest_size=8).digest()
    return int.from_bytes(digest, "big")


class HashRing:
    """Consistent hash ring with virtual nodes."""

    def __init__(self, nodes: Optional[Iterable[str]] = None, vnodes: int = 160):
        self._vnodes = vnodes
        self._ring: List[int] = []  # sorted hash points
        self._points: Dict[int, str] = {}  # hash point -> node
        self._nodes: set = set()
        for node in nodes or ():
            self.add_node(node)

    @property
    def nodes(self) -> set:
        return set(self._nodes)

    def add_node(self, node: str) -> None:
        if node in self._nodes:
            return
        self._nodes.add(node)
        for i in range(self._vnodes):
            point = _hash(f"{node}#{i}")
            if point in self._points:  # vanishingly rare 64-bit collision
                continue
            self._points[point] = node
            bisect.insort(self._ring, point)

    def remove_node(self, node: str) -> None:
        if node not in self._nodes:
            return
        self._nodes.discard(node)
        stale = [p for p, n in self._points.items() if n == node]
        for point in stale:
            del self._points[point]
        stale_set = set(stale)
        self._ring = [p for p in self._ring if p not in stale_set]

    def sync(self, nodes: Iterable[str]) -> None:
        """Make the ring membership equal *nodes* with minimal churn
        (reference ring-sync on endpoint churn: routing_logic.py:117-136)."""
        target = set(nodes)
        for node in self._nodes - target:
            self.remove_node(node)
        for node in target - self._nodes:
            self.add_node(node)

    def get_node(self, key: str) -> Optional[str]:
        if not self._ring:
            return None
        point = _hash(key)
        idx = bisect.bisect_right(self._ring, point)
        if idx == len(self._ring):
            idx = 0
        return self._points[self._ring[idx]]

    def __len__(self) -> int:
        return len(self._nodes)

    def __contains__(self, node: str) -> bool:
        return node in self._nodes
