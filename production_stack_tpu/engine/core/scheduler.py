"""Continuous-batching scheduler (TPU twist: static-shape step plans).

Each call to :meth:`schedule` emits one *step plan*: a single sequence's
prefill (bucketed length), one batched decode over all running sequences
(padded to a batch-size bucket), or — with ``mixed_batch`` on — a fused
MIXED plan packing every running sequence's decode token plus a bounded
prefill chunk of the head waiting sequence under the
``max_num_batched_tokens`` budget (chunked-prefill-integrated batching:
arriving prompts stop stalling the decoders for a full prefill bucket).
Every plan maps to a pre-compiled XLA executable — no shape escapes the
bucket set, so steady-state serving never recompiles.

Preemption: when the block pool cannot back a decode step, the youngest
running sequence is preempted.  With ``preemption_mode="offload"`` its KV
blocks are paged to host DRAM (kv/offload.py) and restored on resume —
cheaper on TPU than recompute because host<->HBM DMA overlaps compute, while
re-prefill burns MXU FLOPs (the reference reaches the same capability with
LMCache CPU offload, deployment-vllm-multi.yaml:161-166).
"""

from __future__ import annotations

import dataclasses
import logging
from collections import deque
from typing import Deque, List, Optional

from production_stack_tpu.engine.config import SchedulerConfig
from production_stack_tpu.engine.core.sequence import (
    Sequence,
    SequenceStatus,
    host_state_flags,
)
from production_stack_tpu.engine.kv.block_pool import BlockPool

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class PrefillPlan:
    seq: Sequence
    bucket_len: int  # padded token count (multiple of block size)
    new_block_ids: List[int]  # blocks receiving the new KV (null-padded)
    prefix_block_ids: List[int]  # cached-prefix blocks (may be empty)
    num_new_tokens: int  # valid tokens to prefill
    cached_len: int
    # False for a non-final chunk of a long prompt (chunked prefill): the
    # engine writes KV but must not sample — the logits are mid-prompt.
    is_final: bool = True


@dataclasses.dataclass
class DecodePlan:
    seqs: List[Sequence]  # <= max_num_seqs running sequences
    # Per-sequence decode TOKEN budget for this plan (aligned with
    # ``seqs``).  All 1s for classic stepping; for K-step windows each
    # entry is capped by the sequence's remaining room (max_model_len,
    # max_tokens) and its blocks are pre-allocated for the whole budget —
    # under the fused speculative window that is the MAX-ACCEPTANCE
    # growth K x (ngram + 1), not the iteration count.
    steps: List[int] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class StepPlan:
    """THE one step-plan type (unifying the former four-way plan
    taxonomy; the PR-8 compat views are retired — callers read the
    fields directly).
    Exactly one execution shape per plan, read off three fields:

      decode only                     pure decode — ``decode_window`` (K)
                                      iterations per row budgeted in
                                      ``decode.steps`` (K > 1 only when
                                      no prompt is waiting)
      prefill_chunk only              one prefill step (bucketed, maybe
                                      chunked)
      decode + prefill_chunk          fused mixed step (always K=1: the
                                      chunk either completes admission
                                      this step or the window machinery
                                      declined)
      decode + chunk_schedule         MIXED K-step window: each of the
                                      K = len(chunk_schedule) scan
                                      iterations runs the packed
                                      [decode + chunk] mixed forward —
                                      decode rows advance one token from
                                      the carried state while the head
                                      prompt's next chunk rides the same
                                      forward, chunk cursor carried
                                      in-graph.  The window always ends
                                      at an admission boundary (the
                                      schedule's last chunk is final, or
                                      the prompt continues next window).

    ``provisional`` marks plans made while the previous window is still
    in flight (optimistic no-finish assumption; the engine rolls back
    at collect).  ``window_fallback`` names the reason a pass that
    WANTED a K>1 window was forced to K=1 (``"waiting_head"`` — the
    head prompt forced per-token admission; ``"bucket_mismatch"`` —
    the final chunk's natural bucket differed from the window's static
    scan shape; ``"pool_pressure"`` — block pool / restore pressure
    ended chunking early); the engine folds it into
    ``tpu:multistep_fallback_total``."""

    decode: Optional[DecodePlan] = None
    prefill_chunk: Optional[PrefillPlan] = None
    decode_window: int = 1
    provisional: bool = False
    # Mixed K-step window: one PrefillPlan per scan iteration, all at
    # ONE chunk bucket (static scan shape).  Packed windows
    # (multi_prompt_window) may carry chunks from SEVERAL prompts: a
    # final chunk mid-schedule admits its prompt and the next iteration
    # starts the next waiting prompt's cursor (later prompts ride
    # padded at the window's established bucket — pf_valid masks
    # identically).  Under --no-multi-prompt-window only the last chunk
    # may be final (the PR-15 single-head shape).
    chunk_schedule: Optional[List[PrefillPlan]] = None
    window_fallback: Optional[str] = None

    @property
    def is_empty(self) -> bool:
        return self.decode is None and self.prefill_chunk is None


class Scheduler:
    def __init__(
        self,
        config: SchedulerConfig,
        block_pool: BlockPool,
        offload_cb=None,
        restore_cb=None,
        remote_prefix_cb=None,
    ):
        self.config = config
        self.block_pool = block_pool
        # offload_cb(seq, block_ids) -> bool: snapshot blocks before they
        # are freed (engine wires offload_seq_blocks).  With the async
        # transfer plane (cache.remote_prefetch) the callback only
        # DISPATCHES a device-side gather and returns — the D2H wait and
        # any remote PUT complete on a writer thread, so schedule() never
        # blocks on DMA or the network here.
        self.offload_cb = offload_cb
        # restore_cb(seq) -> "restored" | "gone" | "retry": page an
        # offloaded sequence's KV back in; on "restored" the engine sets
        # seq.block_table/num_cached_tokens/partial_prefill so the plan
        # below resumes as a held prefix.  "retry" covers transient pool
        # pressure AND an in-flight async remote page-in — schedule again
        # next pass instead of waiting.
        self.restore_cb = restore_cb
        # remote_prefix_cb(seq, prefix_blocks, cached_len) ->
        # (prefix_blocks, cached_len): cross-engine prefix reuse through
        # the shared remote store (engine wires fetch_remote_prefix when
        # cache.disagg_role imports).  Async mode returns the inputs
        # unchanged, only ensuring a background prefetch is in flight —
        # completed fetches were already imported into the prefix cache
        # before schedule() ran, so match_prefix above saw them; legacy
        # mode (remote_prefetch=False) extends in place with blocking
        # GETs.
        self.remote_prefix_cb = remote_prefix_cb
        self.waiting: Deque[Sequence] = deque()
        self.running: List[Sequence] = []
        self.preempted: Deque[Sequence] = deque()
        self.num_preemptions = 0
        # Deterministic admission counter: priority ties break FCFS, and
        # (unlike wall-clock arrival_time) the ordering is identical on
        # every lockstep replica of a multi-host group.
        self._admit_counter = 0
        # Prompt tokens currently held by waiting+preempted sequences,
        # maintained incrementally so bounded admission can read one int
        # cross-thread instead of iterating a deque the step thread
        # mutates (a mid-iteration mutation raises RuntimeError).
        self.queued_prompt_tokens = 0
        # Decode-side chunk-budget computations (_chunk_token_budget
        # calls) — regression-tested O(1) per planning pass: packed
        # window planning over N waiters must not recompute it per
        # chunk.
        self.budget_computations = 0
        # Why the last _extend_chunk_schedule stopped early (None = it
        # ran to a natural end) — window_fallback attribution.
        self._chunk_stop_reason: Optional[str] = None

    # -- admission ---------------------------------------------------------

    def add_seq(self, seq: Sequence) -> None:
        if seq.num_prompt_tokens >= self.config.max_model_len:
            raise ValueError(
                f"Prompt ({seq.num_prompt_tokens} tokens) exceeds max_model_len "
                f"({self.config.max_model_len})"
            )
        bs = self.block_pool.block_size
        worst_tokens = min(
            seq.num_prompt_tokens + seq.sampling_params.max_tokens,
            self.config.max_model_len,
        )
        worst_blocks = (worst_tokens + bs - 1) // bs
        if worst_blocks > self.block_pool.num_blocks - 1:
            raise ValueError(
                f"Request needs up to {worst_blocks} KV blocks but the pool "
                f"only has {self.block_pool.num_blocks - 1}; lower max_tokens "
                "or raise the KV pool size"
            )
        seq._admit_idx = self._admit_counter
        self._admit_counter += 1
        # Priority order (vLLM semantics: LOWER value runs earlier; ties
        # keep admission order).  Admission keys are monotone under FCFS,
        # so the all-default case stays a plain append.
        key = (seq.sampling_params.priority, seq._admit_idx)
        self.queued_prompt_tokens += seq.num_prompt_tokens
        for i, other in enumerate(self.waiting):
            if (other.sampling_params.priority, other._admit_idx) > key:
                self.waiting.insert(i, seq)
                return
        self.waiting.append(seq)

    def abort_seq(self, seq_id: str) -> Optional[Sequence]:
        for queue in (self.waiting, self.preempted):
            for seq in list(queue):
                if seq.seq_id == seq_id:
                    queue.remove(seq)
                    self.queued_prompt_tokens -= seq.num_prompt_tokens
                    self._release(seq)
                    return seq
        for seq in self.running:
            if seq.seq_id == seq_id:
                self.running.remove(seq)
                self._release(seq)
                return seq
        return None

    def has_unfinished(self) -> bool:
        return bool(self.waiting or self.running or self.preempted)

    @property
    def num_waiting(self) -> int:
        return len(self.waiting) + len(self.preempted)

    @property
    def num_running(self) -> int:
        return len(self.running)

    # -- planning ----------------------------------------------------------

    def _bucket_for(self, n_tokens: int) -> Optional[int]:
        for bucket in self.config.prefill_buckets:
            if n_tokens <= bucket:
                return bucket
        return None

    def _window_for_pass(self) -> int:
        """Window-selection rule: K > 1 pure-decode windows only when no
        prompt is waiting to prefill.  A waiting head is first offered a
        MIXED K-step window (its chunks ride the decode scan — see
        ``_try_schedule_mixed_window``); only when that declines does
        the pass drop to K=1 steps so admission — mixed chunk or
        dedicated prefill — is re-evaluated every token, not every K
        tokens (counted as ``window_fallback="waiting_head"``).

        Packed-window exception (multi_prompt_window): when every batch
        slot is occupied, NO admission is possible this pass no matter
        how often it is re-evaluated — dropping to K=1 would burn K
        host round-trips purely on ceremony.  Run a pure-decode window
        clamped to the first step a slot could FREE (the smallest
        remaining output budget across the batch): windows never
        retire rows mid-scan — finish/abort land at collect — so
        iterations past the first exhausted row's budget would decode
        dead rows while admissible prompts wait, and the boundary is
        exactly where packing becomes possible again."""
        window = self.config.window_steps
        if window > 1 and self.num_waiting:
            if (
                self.config.multi_prompt_window_enabled
                and len(self.running) >= self.config.max_num_seqs
            ):
                # Floor 2: still a window (a K=1 pass here would be
                # miscounted as a waiting_head forfeit — it isn't one,
                # no admission fits a full batch either way).
                return min(window, max(
                    2,
                    min(s.remaining_budget for s in self.running),
                ))
            return 1
        return window

    # stackcheck: root=step-thread
    def schedule(self) -> StepPlan:
        """Emit one unified :class:`StepPlan`.  With ``mixed_batch`` on
        and sequences decoding, a fused decode+chunk plan keeps arriving
        prompts from stalling the decoders — as a mixed K-step window
        when the head prompt has several chunks to go (decode keeps its
        host-cost amortization under sustained arrivals), else a K=1
        mixed step; otherwise prefer admitting a prefill when a batch
        slot is open, else decode every running sequence — as a K-step
        window when no prompt waits (the device-resident fast path),
        single-token steps otherwise."""
        window = self._window_for_pass()
        if self.config.mixed_enabled and self.running:
            plan = self._try_schedule_mixed_window()
            if plan is not None:
                return plan
            plan = self._try_schedule_mixed(window)
            if plan is not None:
                if (
                    self.config.window_steps > 1
                    and window == 1
                    and not (
                        plan.prefill_chunk is not None
                        and plan.prefill_chunk.is_final
                    )
                ):
                    # A waiting prompt forced single-stepping and the
                    # pass did NOT complete its admission (a final
                    # chunk IS the optimal full-service step): the
                    # window amortization was forfeited, visibly.
                    plan.window_fallback = "waiting_head"
                return plan
        plan = self._try_schedule_prefill()
        if plan is not None:
            return StepPlan(prefill_chunk=plan)
        decode = self._try_schedule_decode(window)
        if decode is not None:
            return StepPlan(decode=decode, decode_window=window)
        # No step possible.  Two partially-prefilled sequences can coexist
        # (one per queue, or via offload restore) and deadlock each other
        # by jointly holding the pool; roll back the youngest — freeing its
        # blocks for recompute later — until something schedules again.
        while self._rollback_youngest_partial():
            plan = self._try_schedule_prefill()
            if plan is not None:
                return StepPlan(prefill_chunk=plan)
        return StepPlan()

    def _rollback_youngest_partial(self) -> bool:
        """Free a stalled mid-prefill sequence's held blocks (its chunks
        will recompute).  Progress guarantee for the chunked-prefill path:
        admission bounds every single sequence to fit the pool alone."""
        partials = [
            s
            for s in list(self.preempted) + list(self.waiting)
            if s.partial_prefill
        ]
        if not partials:
            return False
        # Victim key mirrors _preempt_youngest: lowest priority loses,
        # youngest ADMISSION among equals.  Never wall-clock arrival_time —
        # clocks diverge across lockstep multi-host replicas, and a
        # replica-dependent victim desyncs every subsequent plan (the same
        # reason admission ordering uses _admit_idx).
        seq = max(
            partials,
            key=lambda s: (s.sampling_params.priority,
                           getattr(s, "_admit_idx", 0)),
        )
        logger.debug("Rolling back partial prefill of %s (pool pressure)", seq.seq_id)
        self.block_pool.free(seq.block_table)
        seq.block_table = []
        seq.num_cached_tokens = 0
        seq.partial_prefill = False
        return True

    def _admission_queue(self) -> Optional[Deque[Sequence]]:
        """Pick which queue admits next.  Preempted sequences normally
        resume first (their progress is largest), but a strictly
        higher-priority waiting head (LOWER value) jumps ahead — without
        this, any preemption would starve later high-priority arrivals
        behind the whole preempted backlog.  Ties keep the preempted
        queue (progress wins).  Residual gap vs vLLM is documented in
        docs/engine.md (no priority-triggered preemption of running
        sequences)."""
        if not self.preempted:
            return self.waiting if self.waiting else None
        if not self.waiting:
            return self.preempted
        if (
            self.waiting[0].sampling_params.priority
            < self.preempted[0].sampling_params.priority
        ):
            return self.waiting
        return self.preempted

    def _try_schedule_mixed(self, window: int = 1) -> Optional[StepPlan]:
        """Fused step: decode every running sequence AND, when the token
        budget and a batch slot allow, a bounded prefill chunk of the
        admission head.  Returns None to fall back to the classic
        alternating path — used when the head needs the full prefill
        machinery (echo+logprobs wants per-position prompt logprobs,
        which only the dedicated prefill executable computes), so such
        requests keep today's prefill-first latency instead of waiting
        behind a decode-forever batch."""
        queue = self._admission_queue()
        head = queue[0] if queue else None
        if (
            head is not None
            and head.sampling_params.echo
            and head.sampling_params.logprobs
            and len(self.running) < self.config.max_num_seqs
        ):
            return None
        decode = self._try_schedule_decode(window)
        if decode is None:
            # Pool pressure emptied the running set: the classic path's
            # prefill-first + rollback machinery handles recovery.
            return None
        chunk = None
        if self.num_waiting and len(self.running) < self.config.max_num_seqs:
            budget = self._chunk_token_budget(len(decode.seqs))
            chunk = self._try_schedule_prefill(chunk_budget=budget)
        if chunk is None:
            return StepPlan(decode=decode, decode_window=window)
        return StepPlan(decode=decode, prefill_chunk=chunk)

    # -- mixed K-step windows ----------------------------------------------

    def _mixed_window_head(self) -> Optional[Sequence]:
        """The admission head a mixed K-step window could chunk, or None
        when the pass must stay on the K=1 machinery: no head / no open
        batch slot, a head needing the prompt-logprobs prefill
        executable, an offloaded head (the restore state machine lives
        on the K=1 path), or any running row using host-sampled
        features the engine would fall back out of the window for."""
        if not self.config.mixed_window_enabled or not self.running:
            return None
        if len(self.running) >= self.config.max_num_seqs:
            return None
        queue = self._admission_queue()
        head = queue[0] if queue else None
        if head is None or head.offloaded:
            return None
        sp = head.sampling_params
        if sp.echo and sp.logprobs:
            return None
        if any(host_state_flags(s)[0] for s in self.running):
            return None
        return head

    def _chunk_token_budget(self, num_decode_rows: int) -> int:
        """Per-iteration chunk token budget beside ``num_decode_rows``
        decode tokens — computed ONCE per planning pass and threaded
        through window planning.  The per-chunk recomputation this
        replaces also drifted on the packed path: a final chunk pops
        its prompt into ``running`` mid-planning, which must not
        shrink later chunks' budget (the window's decode rows are
        fixed at plan time; packed prompts only join the decode batch
        at the next boundary)."""
        self.budget_computations += 1
        return self.config.batched_tokens_budget - num_decode_rows

    def _chunk_buckets_in_budget(self, budget: int) -> List[int]:
        """Chunk buckets admissible beside the current decode batch
        under the per-iteration token budget (each scan iteration is
        one mixed step: decode tokens + one chunk <= the budget, so the
        window's total is K x (decode + chunk))."""
        return [b for b in self.config.prefill_chunk_buckets if b <= budget]

    def _next_packable_head(self) -> Optional[Sequence]:
        """The next waiting prompt a PACKED window may start chunking
        after the previous prompt's final chunk, or None to stop
        packing this window: no open batch slot left (prompts already
        popped by earlier final chunks count), empty queues, an
        offloaded head (the restore state machine lives on the K=1
        path), or a head needing the prompt-logprobs prefill
        executable."""
        if len(self.running) >= self.config.max_num_seqs:
            return None
        queue = self._admission_queue()
        head = queue[0] if queue else None
        if head is None or head.offloaded:
            return None
        sp = head.sampling_params
        if sp.echo and sp.logprobs:
            return None
        return head

    def _extend_chunk_schedule(
        self, head: Sequence, first: PrefillPlan, buckets: List[int],
        k_cap: int, budget: int,
    ) -> List[PrefillPlan]:
        """Grow a window's chunk schedule past its first chunk, one
        ``_try_schedule_prefill`` chunk at a time.

        Single-head mode (--no-multi-prompt-window) iterates the SAME
        bucket rule K=1 mixed stepping uses, so the planned chunk
        shapes (and therefore the compiled forwards, and the streams)
        are identical to the escape-hatch path.  Stops at ``k_cap``, at
        the head's final chunk, at pool pressure (the window ends
        non-final and the next window continues), or when the K=1 rule
        would pick a DIFFERENT bucket for the final chunk (one scan has
        ONE static chunk shape; the mismatched final chunk runs as the
        next pass's K=1 mixed step instead — bit-identical either way).

        Packed mode keeps filling the window across prompts: a final
        chunk admits its prompt, and the next iteration starts the next
        packable head's cursor.  Every chunk after the first is FORCED
        to the window's established bucket T — a chunk smaller than T
        rides padded (pf_valid masks padding out of attention and the
        tail-logit gather reads the last VALID row, so the compute is
        bit-identical to the chunk's natural bucket) — which keeps the
        scan shape static without ever rolling back committed plan
        state when a prefix hit shrinks a chunk at planning time."""
        schedule = [first]
        T = first.bucket_len
        packed = self.config.multi_prompt_window_enabled
        # Why extension stopped EARLY (window_fallback attribution when
        # the schedule collapses to K=1): a final chunk / k_cap exit is a
        # natural end and leaves this None.
        self._chunk_stop_reason = None
        while len(schedule) < k_cap:
            if schedule[-1].is_final:
                if not packed or self._next_packable_head() is None:
                    break
            if packed:
                nxt = self._try_schedule_prefill(
                    chunk_budget=budget, force_bucket=T
                )
            else:
                remaining = head.num_prompt_tokens - head.num_cached_tokens
                fit = [b for b in buckets if b >= remaining]
                if fit and fit[0] != T:
                    # One scan has ONE static chunk shape; the final
                    # chunk's natural bucket differs.
                    self._chunk_stop_reason = "bucket_mismatch"
                    break
                nxt = self._try_schedule_prefill(chunk_budget=budget)
            if nxt is None:
                self._chunk_stop_reason = "pool_pressure"
                break
            schedule.append(nxt)
        return schedule

    def _mixed_window_decode_steps(self, seqs, k_eff, bases=None):
        """Per-row decode token budgets for a mixed K-step window: the
        plain iteration count (the in-window drafter never engages in a
        mixed window — drafting is a pure-decode-window feature), capped
        by each row's max_model_len / max_tokens room.  0 freezes the
        row for the whole window (its stream is length-done; the K=1
        world would have retired it, and collect() does the same)."""
        steps = []
        for i, seq in enumerate(seqs):
            base_tokens, base_gen = (
                bases[i] if bases is not None
                else (seq.num_tokens, seq.num_generated)
            )
            room_len = self.config.max_model_len - base_tokens
            room_out = seq.sampling_params.max_tokens - base_gen
            steps.append(max(0, min(k_eff, room_len, room_out)))
        return steps

    def _try_schedule_mixed_window(self) -> Optional[StepPlan]:
        """Plan a MIXED K-step window: K = min(window_steps, chunks the
        head prompt needs, the adaptive queue-depth clamp) scan
        iterations, each running the packed [decode + chunk] mixed
        forward.  The window always ends at an admission boundary (its
        last chunk is final, or the prompt keeps chunking next window),
        which is what keeps greedy streams byte-identical and seeded
        streams bit-identical to K=1 mixed stepping: iteration t of a
        window dispatched at step counter c IS step c+t of the K=1
        world, chunk shapes included.  Returns None to fall back to the
        K=1 machinery (which owns preemption, restore, and the
        echo+logprobs special cases); a planned single-chunk outcome is
        emitted in the K=1 shape directly (nothing to amortize).

        Packed mode (multi_prompt_window): K is no longer clamped by
        queue depth — the adaptive clamp existed to re-evaluate
        admission often, and a packed window IS the admission: a final
        chunk mid-window admits its prompt and the next iteration
        starts the next waiter's cursor, so deep queues fill the
        window instead of shrinking it."""
        head = self._mixed_window_head()
        if head is None:
            return None
        budget = self._chunk_token_budget(len(self.running))
        buckets = self._chunk_buckets_in_budget(budget)
        if not buckets:
            return None
        packed = self.config.multi_prompt_window_enabled
        if packed:
            k_cap = self.config.window_steps
        else:
            k_cap = min(
                self.config.window_steps,
                self.config.mixed_window_clamp(self.num_waiting),
            )
        if k_cap < 2:
            # Deep waiting queue: the adaptive clamp demands per-token
            # admission re-evaluation — today's K=1 behavior.
            return None
        # Multi-chunk precheck before committing any state: a head that
        # fits one chunk bucket admits completely in one K=1 mixed step
        # (a false positive from an unknown prefix hit just ends the
        # window early at the final chunk).  Packed windows keep going
        # when OTHER waiters could fill the remaining iterations.
        remaining_max = head.num_prompt_tokens - (
            head.num_cached_tokens if head.partial_prefill else 0
        )
        if remaining_max <= buckets[-1] and (
            not packed or self.num_waiting <= 1
        ):
            return None
        decode = self._mixed_window_decode_plan(k_cap)
        if decode is None:
            return None
        first = self._try_schedule_prefill(chunk_budget=budget)
        if first is None or (first.is_final and not packed):
            # Pool pressure / restore retry, or a prefix hit shrank the
            # prompt to one final chunk: emit the exact K=1 mixed shape
            # (decode blocks are over-allocated for the declined window
            # — they sit in the block tables and back later steps).
            self._recap_steps_k1(decode)
            # first can only be None (pool pressure / restore retry) or
            # final here; a final single chunk is a natural K=1 shape,
            # not a decline.
            return StepPlan(
                decode=decode, prefill_chunk=first, decode_window=1,
                window_fallback="pool_pressure" if first is None else None,
            )
        schedule = self._extend_chunk_schedule(
            head, first, buckets, k_cap, budget
        )
        k_eff = len(schedule)
        if k_eff == 1:
            # Couldn't extend (pool pressure / bucket-mismatched final
            # chunk / nothing packable behind a final first chunk): the
            # planned chunk runs as today's K=1 mixed step.
            self._recap_steps_k1(decode)
            # _extend_chunk_schedule says WHY it stopped when it stopped
            # early (pool_pressure / bucket_mismatch); a final first
            # chunk is a natural K=1 shape, not a decline.
            return StepPlan(
                decode=decode, prefill_chunk=first, decode_window=1,
                window_fallback=(
                    None if first.is_final
                    else (self._chunk_stop_reason or "waiting_head")
                ),
            )
        decode.steps = self._mixed_window_decode_steps(decode.seqs, k_eff)
        return StepPlan(
            decode=decode, chunk_schedule=schedule, decode_window=k_eff,
        )

    def _recap_steps_k1(self, decode: DecodePlan) -> None:
        """Re-budget a declined mixed window's decode rows for a K=1
        emission.  The K=1 budget is NOT always 1: with the legacy
        host-side speculative path active, ``_step_budget(seq, 1)`` is
        ngram+1 — which can exceed the k_cap-iteration block allocation
        ``_mixed_window_decode_plan`` made (a deep-queue clamp can push
        k_cap below the draft budget), and the speculative dispatch
        indexes the block table for its whole budget.  Top the
        allocation up; under pool pressure trim the budget to the
        blocks held instead (the drafter derives its draft count from
        the budget, so a trimmed row just drafts less — greedy output
        is unchanged, acceptance merely caps earlier)."""
        bs = self.block_pool.block_size
        steps = []
        for seq in decode.seqs:
            k = self._step_budget(seq, 1)
            slots = seq.num_tokens + k - 1
            need = max(0, -(-slots // bs) - len(seq.block_table))
            if need:
                if self.block_pool.can_allocate(need):
                    seq.block_table.extend(self.block_pool.allocate(need))
                else:
                    k = max(
                        1,
                        len(seq.block_table) * bs - seq.num_tokens + 1,
                    )
            steps.append(k)
        decode.steps = steps

    def _mixed_window_decode_plan(self, k_cap: int) -> Optional[DecodePlan]:
        """Decode rows for a mixed K-step window, blocks pre-allocated
        for the whole k_cap budget.  Declines instead of preempting —
        pool pressure falls back to the K=1 path, which owns the
        preemption/rollback recovery machinery (and whose victim choice
        must not depend on whether a window was attempted)."""
        if not self.running:
            return None
        bs = self.block_pool.block_size
        steps = self._mixed_window_decode_steps(self.running, k_cap)
        needs = []
        for seq, k in zip(self.running, steps):
            slots = seq.num_tokens + max(k, 1) - 1
            needs.append(max(0, -(-slots // bs) - len(seq.block_table)))
        total = sum(needs)
        if total and not self.block_pool.can_allocate(total):
            return None
        for seq, need in zip(self.running, needs):
            if need:
                seq.block_table.extend(self.block_pool.allocate(need))
        return DecodePlan(seqs=list(self.running), steps=steps)

    def _try_schedule_prefill(
        self, chunk_budget: Optional[int] = None,
        force_bucket: Optional[int] = None,
    ) -> Optional[PrefillPlan]:
        """Plan one prefill step.  ``chunk_budget`` switches to mixed-step
        chunk mode: the padded length comes from ``prefill_chunk_buckets``
        (not ``prefill_buckets``) and may not exceed the budget.
        ``force_bucket`` (packed windows) pins the padded chunk shape to
        the window's established bucket — one scan has ONE static chunk
        shape, and a chunk smaller than the bucket rides padded
        (bit-identical: pf_valid masks padding and the tail-logit
        gather reads the last valid row)."""
        if len(self.running) >= self.config.max_num_seqs:
            return None
        queue = self._admission_queue()
        if not queue:
            return None
        seq = queue[0]
        if chunk_budget is not None:
            if force_bucket is not None:
                chunk_buckets = [force_bucket]
            else:
                chunk_buckets = [
                    b for b in self.config.prefill_chunk_buckets
                    if b <= chunk_budget
                ]
            sp = seq.sampling_params
            if not chunk_buckets or (sp.echo and sp.logprobs):
                # No chunk fits the budget, or the head needs the
                # prompt-logprobs prefill executable: no chunk this step
                # (the mixed caller degrades to decode-only; the classic
                # path serves echo+logprobs heads prefill-first).
                return None

        if seq.offloaded:
            # Page the KV snapshot back in; on "restored" the engine has
            # set block_table/num_cached_tokens/partial_prefill and the
            # plan below resumes from that held prefix (no recompute).
            # "retry" (transient pool pressure, snapshot kept) leaves the
            # offloaded flag set and lets decode free blocks first;
            # "gone" falls through to a plain re-prefill.
            result = self.restore_cb(seq) if self.restore_cb is not None else "gone"
            if result == "retry":
                return None
            seq.offloaded = False

        if seq.partial_prefill:
            # Chunks already written: the sequence owns its blocks.
            prefix_blocks = list(seq.block_table)
            cached_len = seq.num_cached_tokens
        elif seq.sampling_params.echo and seq.sampling_params.logprobs:
            # echo+logprobs needs a logprob for EVERY prompt position; a
            # prefix-cache hit would skip those rows' compute, so this
            # sequence prefills from scratch (vLLM's prompt_logprobs makes
            # the same trade).
            prefix_blocks, cached_len = [], 0
        else:
            prefix_blocks, cached_len = self.block_pool.match_prefix(
                seq.prompt_token_ids, namespace=seq.cache_ns
            )
            if self.remote_prefix_cb is not None:
                prefix_blocks, cached_len = self.remote_prefix_cb(
                    seq, prefix_blocks, cached_len
                )
        num_new = seq.num_prompt_tokens - cached_len
        if chunk_budget is not None:
            # Mixed-step chunk: pad to the chunk-bucket set so the fused
            # executable inventory stays |chunk_buckets| x |decode buckets|.
            fit = [b for b in chunk_buckets if b >= num_new]
            is_final = bool(fit)
            bucket = fit[0] if fit else chunk_buckets[-1]
            if not is_final:
                num_new = bucket
        else:
            bucket = self._bucket_for(num_new)
            is_final = bucket is not None
            if bucket is None:
                # Prompt longer than the largest bucket: chunked prefill —
                # run one full-bucket chunk now, keep the sequence at the
                # queue head, and continue next step from the accumulated
                # prefix.
                bucket = self.config.prefill_buckets[-1]
                num_new = bucket
        bs = self.block_pool.block_size
        blocks_needed = (num_new + bs - 1) // bs
        if not self.block_pool.can_allocate(blocks_needed):
            if not seq.partial_prefill:
                self.block_pool.free(prefix_blocks)
            return None
        new_blocks = self.block_pool.allocate(blocks_needed)
        seq.num_cached_tokens = cached_len
        seq.block_table = prefix_blocks + new_blocks
        if is_final:
            queue.popleft()
            self.queued_prompt_tokens -= seq.num_prompt_tokens
            seq.status = SequenceStatus.RUNNING
            seq.partial_prefill = False
            self.running.append(seq)
        else:
            seq.partial_prefill = True
            seq.num_cached_tokens = cached_len + num_new
        return PrefillPlan(
            seq=seq,
            bucket_len=bucket,
            new_block_ids=new_blocks,
            prefix_block_ids=prefix_blocks,
            num_new_tokens=num_new,
            cached_len=cached_len,
            is_final=is_final,
        )

    def _window_token_cap(self, window: int) -> int:
        """Per-row token ceiling for a pure-decode window plan: the
        max-acceptance growth K x (draft_len + 1) — draft_len from
        whichever drafter is configured (n-gram count or the model
        drafter's speculative_draft_len) — only when the fused drafter
        can actually engage: it drafts exclusively for all-greedy
        batches (the same temperature <= 0 predicate the engine
        dispatches on, read from broadcast SamplingParams so lockstep
        replicas agree) — and plain K otherwise, so sampled workloads
        never pre-allocate blocks for drafts that cannot happen.  A
        model-drafter window that declines to plain at dispatch time
        (draft-pool pressure) emits at most K tokens — strictly under
        this ceiling, so the pre-allocation stays sufficient."""
        if (
            window > 1
            and self.config.spec_window_enabled
            and all(
                s.sampling_params.temperature <= 0 for s in self.running
            )
        ):
            return window * (self.config.spec_draft_len + 1)
        return window

    def _step_budget(self, seq: Sequence, window: int = 1) -> int:
        """Decode TOKENS this sequence may emit in one window (or
        speculative) plan: bounded by max_model_len and the request's
        max_tokens (stop/EOS cut shorter — the device stop-mask freezes
        the row; a mismatching host-only condition discards on readback).
        Under the fused speculative window a K-iteration plan can land
        up to K x (ngram + 1) tokens at full acceptance, so the budget —
        and the block pre-allocation derived from it — covers the
        max-acceptance growth (_window_token_cap), never just the
        iteration count."""
        if window > 1:
            n = self._window_token_cap(window)
        else:
            # Legacy host-side speculation (and K=1 passes with
            # speculation on): K drafts + the bonus token per dispatch.
            n = max(1, self.config.speculative_ngram + 1)
        room_len = self.config.max_model_len - seq.num_tokens
        room_out = seq.sampling_params.max_tokens - seq.num_generated
        return max(1, min(n, room_len, room_out))

    def _try_schedule_decode(self, window: int = 1) -> Optional[DecodePlan]:
        if not self.running:
            return None
        bs = self.block_pool.block_size

        def blocks_needed(seq: Sequence) -> int:
            # Iteration i consumes the token at position num_tokens-1+i, so
            # a k-step budget writes KV through slot num_tokens+k-2 — the
            # table must cover num_tokens+k-1 slots (k=1: num_tokens).
            slots = seq.num_tokens + self._step_budget(seq, window) - 1
            return max(0, -(-slots // bs) - len(seq.block_table))

        # Ensure every running sequence has blocks for its whole budget;
        # preempt the youngest until the step fits.
        while self.running:
            need = sum(blocks_needed(seq) for seq in self.running)
            if self.block_pool.can_allocate(need):
                break
            self._preempt_youngest()
        if not self.running:
            return None
        for seq in self.running:
            need = blocks_needed(seq)
            if need:
                seq.block_table.extend(self.block_pool.allocate(need))
        return DecodePlan(
            seqs=list(self.running),
            steps=[self._step_budget(seq, window) for seq in self.running],
        )

    def schedule_provisional_window(
        self, inflight_seqs: List[Sequence], inflight_steps: List[int]
    ) -> Optional[StepPlan]:
        """Plan the NEXT K-step decode window while the previous window
        is still in flight on the device, under the optimistic
        assumption that no in-flight row stops early and every row emits
        its full ``inflight_steps`` budget (the device window carry
        keeps actually-stopped rows frozen; the engine discards their
        overrun on readback).  Declines (None) whenever the pipeline
        must break and replan synchronously: the running set changed, an
        admission is pending that a MIXED window cannot serve (window
        selection must drop to K=1 mixed steps), every row's remaining
        budget is zero, or backing the window would require preemption.
        A waiting head whose chunks CAN ride the scan chains a MIXED
        window off the in-flight carry instead of breaking the pipeline
        — the sustained-arrival case that used to serialize every
        window boundary into K=1 host round-trips."""
        window = self.config.window_steps
        if window <= 1:
            return None
        if len(self.running) < len(inflight_seqs) or any(
            a is not b for a, b in zip(self.running, inflight_seqs)
        ):
            return None
        parked = len(self.running) > len(inflight_seqs)
        if parked:
            # The in-flight window itself admitted prompts (packed
            # final chunks pop into self.running at plan time).  Those
            # rows have NO slot in the device carry yet — a chained
            # MIXED window may keep streaming over the carried rows
            # while the newcomers PARK for one window (their first
            # token is already finalized at the in-flight window's
            # collect; they join the batch at the next synchronous
            # rebuild).  Only the packed planner creates this shape,
            # and only when MORE packing work is waiting — otherwise
            # break the pipeline so the parked rows join immediately
            # (which also keeps the single-head seeded key-ordinal
            # stream bit-identical to the K=1 path).
            if not self.config.multi_prompt_window_enabled:
                return None
            if any(
                seq.num_generated > 0
                for seq in self.running[len(inflight_seqs):]
            ):
                return None  # not a parked admission: replan sync
        if not inflight_seqs:
            return None
        if self.waiting or self.preempted:
            plan = self._provisional_mixed_window(inflight_steps)
            if plan is not None:
                return plan
            if parked or not (
                self.config.multi_prompt_window_enabled
                and len(self.running) >= self.config.max_num_seqs
            ):
                return None
            if any(
                seq.remaining_budget <= prev_k
                for seq, prev_k in zip(inflight_seqs, inflight_steps)
            ):
                # A row exhausts its output budget INSIDE the in-flight
                # window: its slot frees at collect, so a chained pure
                # window would decode a dead row for K steps while this
                # waiting prompt could pack.  Break the pipeline; the
                # synchronous replan sees the freed slot.
                return None
            # Packed mode with a slot-full batch: no admission is
            # possible at this boundary no matter how it replans, so
            # chain a full pure-decode window off the carry instead of
            # breaking the pipeline into K=1 waiting_head steps
            # (mirrors _window_for_pass's slot-full rule).
        elif parked:
            return None  # nothing left to pack: rebuild with the rows
        bs = self.block_pool.block_size
        # Per-window per-row token ceiling: K x (ngram + 1) under the
        # fused speculative window at max acceptance (all-greedy batch),
        # K otherwise.
        max_tok = self._window_token_cap(window)
        rows = self.running[: len(inflight_seqs)]
        steps: List[int] = []
        needs: List[int] = []
        for seq, prev_k in zip(rows, inflight_steps):
            # The in-flight window will (optimistically) land its whole
            # prev_k token budget before this one runs (full acceptance
            # under speculation; the device carry keeps the real count
            # and the engine discards overrun on readback).
            base_tokens = seq.num_tokens + prev_k
            base_gen = seq.num_generated + prev_k
            room_len = self.config.max_model_len - base_tokens
            room_out = seq.sampling_params.max_tokens - base_gen
            k = max(0, min(max_tok, room_len, room_out))
            steps.append(k)
            slots = base_tokens + k - 1
            needs.append(max(0, -(-slots // bs) - len(seq.block_table)))
        if not any(steps):
            return None
        total = sum(needs)
        if total and not self.block_pool.can_allocate(total):
            return None
        for seq, need in zip(rows, needs):
            if need:
                seq.block_table.extend(self.block_pool.allocate(need))
        return StepPlan(
            decode=DecodePlan(seqs=list(rows), steps=steps),
            decode_window=window,
            provisional=True,
        )

    def _provisional_mixed_window(
        self, inflight_steps: List[int]
    ) -> Optional[StepPlan]:
        """Chain a MIXED K-step window off the in-flight carry for a
        waiting head: decode budgets are planned from the optimistic
        post-window base exactly like the pure provisional path, and the
        head's chunk schedule continues from its plan-time cursor (the
        in-flight window's chunks already advanced it).  Unlike the
        synchronous planner this EMITS single-chunk windows too — a
        1-iteration mixed scan is bit-identical to the K=1 mixed step
        and keeps the pipeline streaming through the admission.
        Declines (sync replan at the boundary) when the head cannot
        chunk at all."""
        cfg = self.config
        head = self._mixed_window_head()
        if head is None:
            return None
        # The chained scan's decode batch is the device CARRY's row set
        # (parked admissions from the in-flight window have no slot
        # yet), so the chunk budget and decode planning cover exactly
        # those rows.
        rows = self.running[: len(inflight_steps)]
        budget = self._chunk_token_budget(len(rows))
        buckets = self._chunk_buckets_in_budget(budget)
        if not buckets:
            return None
        packed = cfg.multi_prompt_window_enabled
        if packed:
            k_cap = cfg.window_steps
        else:
            k_cap = min(
                cfg.window_steps, cfg.mixed_window_clamp(self.num_waiting)
            )
        # Single-chunk heads decline (pipeline break -> the sync K=1
        # mixed step admits them whole): a 1-iteration scan would mint
        # a whole executable variant for zero amortization.  A prefix
        # hit discovered at chunk planning can still shrink a
        # multi-chunk head to one final chunk — that rare case emits
        # the 1-iteration window below rather than rolling back
        # committed plan state.  Packed windows keep chaining when
        # OTHER waiters could fill the remaining iterations.
        remaining_max = head.num_prompt_tokens - (
            head.num_cached_tokens if head.partial_prefill else 0
        )
        if remaining_max <= buckets[-1] and (
            not packed or self.num_waiting <= 1
        ):
            return None
        bs = self.block_pool.block_size
        bases = [
            (seq.num_tokens + prev_k, seq.num_generated + prev_k)
            for seq, prev_k in zip(rows, inflight_steps)
        ]
        steps = self._mixed_window_decode_steps(
            rows, k_cap, bases=bases
        )
        needs = []
        for (base_tokens, _), k, seq in zip(bases, steps, rows):
            slots = base_tokens + k - 1
            needs.append(max(0, -(-slots // bs) - len(seq.block_table)))
        total = sum(needs)
        if total and not self.block_pool.can_allocate(total):
            return None
        for seq, need in zip(rows, needs):
            if need:
                seq.block_table.extend(self.block_pool.allocate(need))
        # Snapshot BEFORE chunk planning: a final chunk pops the head
        # into self.running at plan time, and the popped head has no
        # decode row in THIS window (it joins at the next boundary).
        decode_seqs = list(rows)
        first = self._try_schedule_prefill(chunk_budget=budget)
        if first is None:
            # Nothing chunkable (pool pressure / restore retry): break
            # the pipeline so the sync pass re-evaluates at K=1.  The
            # decode blocks above stay in the block tables and back the
            # replanned step.
            return None
        if first.is_final and not packed:
            schedule = [first]
        else:
            schedule = self._extend_chunk_schedule(
                head, first, buckets, k_cap, budget
            )
        k_eff = len(schedule)
        return StepPlan(
            decode=DecodePlan(
                seqs=decode_seqs,
                steps=[min(s, k_eff) for s in steps],
            ),
            chunk_schedule=schedule,
            decode_window=k_eff,
            provisional=True,
        )

    def schedule_provisional(
        self, inflight_seqs: List[Sequence]
    ) -> Optional[DecodePlan]:
        """Plan the NEXT decode step while the previous one is still in
        flight on the device, under the optimistic assumption that no
        in-flight sequence finishes (the engine rolls back appends for
        sequences that did — the same overrun argument multi-step decode
        relies on).  Returns None whenever the pipeline must break and
        replan synchronously:

        * the running set changed under us (an abort landed),
        * an admission is pending (a waiting/preempted sequence could
          prefill into an open slot — ordering must match the
          synchronous scheduler),
        * any in-flight sequence PREDICTABLY finishes this step
          (max_tokens / max_model_len — length finishes are host-known
          before the token is),
        * backing the extra token would require preemption (provisional
          planning never preempts: the victim choice must see collected
          state).

        On success every returned sequence's block table already covers
        the provisional +1 token (at most one new block per sequence)."""
        if len(self.running) != len(inflight_seqs) or any(
            a is not b for a, b in zip(self.running, inflight_seqs)
        ):
            return None
        if not self.running:
            return None
        if (self.waiting or self.preempted) and (
            len(self.running) < self.config.max_num_seqs
        ):
            return None
        for seq in self.running:
            if seq.num_generated + 1 >= seq.sampling_params.max_tokens:
                return None
            if seq.num_tokens + 1 >= self.config.max_model_len:
                return None
        bs = self.block_pool.block_size
        needs = [
            # After the in-flight token lands the sequence holds
            # num_tokens+1 tokens; the next step writes KV at slot index
            # num_tokens, so the table must cover num_tokens+1 slots.
            max(0, -(-(seq.num_tokens + 1) // bs) - len(seq.block_table))
            for seq in self.running
        ]
        total = sum(needs)
        if total and not self.block_pool.can_allocate(total):
            return None
        for seq, need in zip(self.running, needs):
            if need:
                seq.block_table.extend(self.block_pool.allocate(need))
        return DecodePlan(seqs=list(self.running), steps=[1] * len(self.running))

    # -- preemption / release ---------------------------------------------

    def _preempt_youngest(self) -> None:
        # Victim: the lowest-priority running sequence (highest value),
        # youngest among equals — high-priority work survives pool
        # pressure at the expense of low-priority work.
        seq = max(
            self.running,
            key=lambda s: (s.sampling_params.priority,
                           getattr(s, "_admit_idx", 0)),
        )
        self.running.remove(seq)
        seq.status = SequenceStatus.PREEMPTED
        seq.preempt_count += 1
        self.num_preemptions += 1
        if self.config.preemption_mode == "offload" and self.offload_cb is not None:
            # Page the blocks to host DRAM *before* the pool can reuse them.
            seq.offloaded = bool(self.offload_cb(seq, list(seq.block_table)))
        self.block_pool.free(seq.block_table)
        seq.block_table = []
        # Re-prefill path treats all prior tokens as the new prompt.
        seq.outputs_absorbed += len(seq.output_token_ids)
        seq.prompt_token_ids = seq.all_token_ids
        seq.output_token_ids = []
        # Emptying output_token_ids re-arms the min_tokens floor (the
        # host predicate counts post-preemption output tokens); the
        # engine's cached boundary-crossing bit must re-arm with it.
        if getattr(seq, "_min_tok_pending", None) is not None:
            seq._min_tok_pending = (
                seq.sampling_params.min_tokens > 0
            )
        self.queued_prompt_tokens += seq.num_prompt_tokens
        self.preempted.appendleft(seq)
        logger.debug("Preempted %s (mode=%s)", seq.seq_id, self.config.preemption_mode)

    def _release(self, seq: Sequence) -> None:
        if seq.block_table:
            self.block_pool.free(seq.block_table)
            seq.block_table = []

    def finish_seq(self, seq: Sequence) -> None:
        if seq in self.running:
            self.running.remove(seq)
        # Register the sequence's full blocks for prefix reuse BEFORE
        # freeing, so the freed blocks enter the reclaimable LRU tier.
        self.block_pool.register_prefix(
            seq.all_token_ids, seq.block_table, namespace=seq.cache_ns
        )
        self._release(seq)
        seq.status = SequenceStatus.FINISHED
