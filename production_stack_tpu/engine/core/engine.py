"""LLMEngine: the serving engine core.

Owns params + paged KV caches on device, the block pool, the scheduler and
the jitted step functions.  Each step executes exactly one scheduler plan —
a bucketed prefill, a bucket-padded decode batch, or a fused MIXED step
(every running sequence's decode token plus a bounded prefill chunk of the
head waiting sequence in one packed invocation, so arriving prompts no
longer stall the decoders).  Every plan shape maps to a cached XLA
executable, so steady-state serving never recompiles.

Stepping is split into a ``dispatch()``/``collect()`` pair wired as an
async one-step-lookahead pipeline: decode step N+1 is dispatched to the
device (its input tokens chained from step N's still-in-flight sample)
BEFORE step N's result is read back, so host-side scheduling, sampling
post-processing and detokenization overlap device compute instead of
serializing against it.  ``step()`` keeps the classic contract
(one plan's outputs per call) on top of that pipeline.

The engine is the TPU-side counterpart of what the reference runs as an
external ``vllm serve`` container (deployment-vllm-multi.yaml:57-64); the
server wrapper in engine/server/ speaks the same OpenAI + /metrics contract
the router expects.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
import zlib
from collections import OrderedDict, deque
from functools import partial
from typing import Deque, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from production_stack_tpu.engine.config import PRESETS, EngineConfig
from production_stack_tpu.engine.core.scheduler import (
    DecodePlan,
    PrefillPlan,
    Scheduler,
)
from production_stack_tpu.engine.core.sequence import (
    FinishReason,
    SamplingParams,
    Sequence,
    SequenceStatus,
    StepOutput,
    host_state_flags as seq_host_state_flags,
)
from production_stack_tpu.engine.kv.block_pool import (
    BlockPool,
    prefix_block_hashes,
)
from production_stack_tpu.engine.kv import quant as kv_quant
from production_stack_tpu.engine.kv.offload import HostOffloadManager, OffloadStager
from production_stack_tpu.engine.kv.prefetch import PrefetchedChain, PrefetchManager
from production_stack_tpu.engine.models import get_model
from production_stack_tpu.engine.models.weights import load_params
from production_stack_tpu.obs.engine import EngineObs
from production_stack_tpu.obs.histogram import Histogram
from production_stack_tpu.engine.parallel import shardings as shardings_lib
from production_stack_tpu.engine.parallel.mesh import AXES, build_mesh
from production_stack_tpu.engine import sampling as sampling_lib
from production_stack_tpu.engine.sampling import sample_tokens
from production_stack_tpu.engine.tokenizer import get_tokenizer

logger = logging.getLogger(__name__)


def _dtype_size(dtype: str) -> int:
    return jnp.dtype(dtype).itemsize


@dataclasses.dataclass
class _PendingStep:
    """One dispatched-but-not-yet-collected engine step.

    Synchronous steps (prefill, speculative, and decode batches using
    host-state sampling features) carry precomputed ``outputs``;
    pipelined decode steps carry the batch rows and the still-in-flight
    device sample instead — [S] for a single-token step, [K, S] emitted
    tokens for a K-step window (``steps`` holds the per-row iteration
    budgets and ``win_state`` the device-resident window carry the next
    window chains from)."""

    outputs: Optional[List[StepOutput]] = None
    seqs: Optional[List[Sequence]] = None
    sampled: Optional[object] = None  # jax.Array [S] or [K, S], uncollected
    is_decode: bool = False
    host_s: float = 0.0  # host time spent dispatching this step
    steps: Optional[List[int]] = None  # per-row window TOKEN budgets (windows)
    win_state: Optional[dict] = None  # device window carry (windows)
    # Fused speculative windows: ``sampled`` is [K, W, S] (W = draft_len
    # + 1 sub-steps per scan iteration) and ``spec_stats`` the still-in-
    # flight (drafted [K, S], accepted [K, S]) device counters collect()
    # folds into tpu:spec_tokens_* and tpu:spec_window_tokens_total;
    # ``spec_drafter`` names the proposal source that ran ("ngram" /
    # "model") for the per-drafter accounting.
    spec_stats: Optional[tuple] = None
    spec_drafter: Optional[str] = None
    # Mixed K-step windows: the chunk schedule that rode the scan (one
    # PrefillPlan per live iteration — packed windows interleave several
    # prompts' chunks), the still-in-flight per-iteration tail logits
    # [n_scan, V] (None when no chunk in the window was final), and the
    # window's BASE step-counter ordinal — a final chunk at iteration f
    # samples its prompt's first token with ordinal base + f, the PRNG
    # key the K=1 path would burn for that step.
    chunk_sched: Optional[List] = None
    chunk_logits: Optional[object] = None
    chunk_ordinal: int = 0
    # Window flight record (obs/flight_recorder.WindowRecord) stamped at
    # dispatch; collect() completes + publishes it.  None when tracing is
    # off (the recorder is never consulted) or the step completed its
    # record synchronously at dispatch.
    rec: Optional[object] = None


class LLMEngine:
    def __init__(self, config: EngineConfig):
        self.config = config
        cfg = config.model
        self.model = get_model(cfg.name)
        self.tokenizer = get_tokenizer(config.tokenizer)
        if self.tokenizer.vocab_size > cfg.vocab_size:
            raise ValueError(
                f"Tokenizer vocab ({self.tokenizer.vocab_size}) exceeds model "
                f"vocab ({cfg.vocab_size})"
            )

        # SPMD mesh: dp shards the decode batch, tp shards heads/channels,
        # sp is the ring-attention axis for long prefill (parallel/mesh.py).
        # world_size==1 builds a trivial single-device mesh so the code path
        # is identical on one chip and on a slice.
        par = config.parallel
        shardings_lib.validate_tp(cfg, par.tensor_parallel)
        shardings_lib.validate_sp_mode(cfg, par)
        if config.scheduler.max_num_seqs % par.data_parallel:
            raise ValueError(
                f"max_num_seqs={config.scheduler.max_num_seqs} must be "
                f"divisible by data_parallel={par.data_parallel}"
            )
        # Mixed prefill+decode steps pack one [S+T] token batch; that row
        # axis is neither dp- nor sp-shardable (its two segments shard
        # differently), so a dp/sp mesh turns the auto gate off and
        # rejects an explicit request rather than serving a silently
        # different schedule.
        if par.data_parallel > 1 or par.sequence_parallel > 1:
            if config.scheduler.mixed_batch:
                raise ValueError(
                    "mixed_batch=True requires data_parallel == "
                    "sequence_parallel == 1 (the packed mixed token batch "
                    "cannot be dp/sp-sharded); drop the flag or the mesh "
                    "axis"
                )
            config.scheduler.mixed_batch = False
        if config.scheduler.mixed_enabled:
            for bucket in config.scheduler.prefill_chunk_buckets:
                if bucket % config.cache.block_size:
                    raise ValueError(
                        f"prefill chunk bucket {bucket} not divisible by "
                        f"block_size={config.cache.block_size} (non-final "
                        "chunks must leave the cached prefix block-aligned)"
                    )
        if par.sequence_parallel > 1:
            if cfg.sliding_window is not None:
                raise ValueError(
                    "sequence_parallel>1 is not supported with "
                    "sliding_window models (the ring path has no local-"
                    "attention mask); use sp=1"
                )
            span = config.cache.block_size * par.sequence_parallel
            for bucket in config.scheduler.prefill_buckets:
                if bucket % span:
                    raise ValueError(
                        f"prefill bucket {bucket} not divisible by "
                        f"block_size*sp={span}"
                    )
            if config.scheduler.max_model_len % span:
                raise ValueError(
                    f"max_model_len={config.scheduler.max_model_len} not "
                    f"divisible by block_size*sp={span} (the cached-prefix "
                    "ring shards the prefix block table over sp)"
                )
        self.mesh = build_mesh(par)

        logger.info("Loading params for %s ...", cfg.name)
        self.params = load_params(cfg, config.weights_path, seed=config.seed)
        if cfg.quantization is not None:
            logger.info("Quantizing projections to %s ...", cfg.quantization)
            self.params = self.model.quantize_params(self.params, cfg)
        self.params = jax.device_put(
            self.params, shardings_lib.param_shardings(cfg, self.mesh)
        )

        # Draft model for in-scan speculative decoding
        # (scheduler.speculative_model): a second, tiny model loaded
        # through the SAME registry/weights path as the target and
        # sharded on the same mesh.  Compatibility is validated LOUDLY
        # at boot whenever a draft model is configured — a vocab
        # mismatch would silently collapse acceptance (draft argmax over
        # a different token space) or propose out-of-range ids; params
        # are loaded only when the fused window will actually run
        # (spec_window_enabled), so an inert K=1 config stays cheap.
        self.draft_model = None
        self.draft_cfg = None
        self.draft_params = None
        if config.scheduler.speculative_model is not None:
            name = config.scheduler.speculative_model
            if name not in PRESETS:
                raise ValueError(
                    f"Unknown speculative_model preset {name!r}; "
                    f"available: {sorted(PRESETS)}"
                )
            draft_cfg = dataclasses.replace(PRESETS[name])
            if draft_cfg.vocab_size != cfg.vocab_size:
                raise ValueError(
                    f"speculative_model {name!r} vocab "
                    f"({draft_cfg.vocab_size}) != target {cfg.name!r} vocab "
                    f"({cfg.vocab_size}): the drafter must share the "
                    "target's tokenizer/vocab — a mismatched drafter "
                    "proposes tokens the target cannot accept (or ids "
                    "outside its vocab), silently degrading acceptance; "
                    "refusing to boot"
                )
            shardings_lib.validate_tp(draft_cfg, par.tensor_parallel)
            self.draft_cfg = draft_cfg
            if config.scheduler.spec_window_enabled:
                self.draft_model = get_model(draft_cfg.name)
                logger.info("Loading draft params for %s ...", draft_cfg.name)
                self.draft_params = load_params(
                    draft_cfg, config.draft_weights_path, seed=config.seed
                )
                self.draft_params = jax.device_put(
                    self.draft_params,
                    shardings_lib.param_shardings(draft_cfg, self.mesh),
                )

        num_blocks = self._decide_num_blocks()
        self.block_pool = BlockPool(
            num_blocks,
            config.cache.block_size,
            enable_prefix_caching=config.cache.enable_prefix_caching,
        )
        # Cross-engine prefix sharing (cache.disagg_role): content-keyed
        # block export/import through the remote store.
        self._disagg_role = config.cache.disagg_role
        self._exports = self._disagg_role in ("prefill", "both")
        imports = self._disagg_role in ("decode", "both")
        self._imports = imports
        # digest -> export expiry: entries re-export after the TTL so a
        # store-side eviction doesn't silently end sharing forever.
        self._exported_hashes: "OrderedDict[bytes, float]" = OrderedDict()
        self._export_ttl_s = 300.0
        self._export_queue = None
        self._export_thread = None
        # Guards the export thread/queue handles: lazily started from
        # the step thread, retired from the close path (asyncio loop).
        self._export_lock = threading.Lock()
        self.remote_prefix_blocks_fetched = 0
        self.remote_prefix_blocks_exported = 0
        # Disaggregated serving counters (written ONLY by the API
        # server's event loop — the single-writer-per-thread contract
        # the deadline counters follow): prefill-phase primes served,
        # and decode-phase handoff prefetch outcomes.
        self.disagg_prefill_primes = 0
        self.disagg_handoff_hits = 0
        self.disagg_handoff_misses = 0
        self.scheduler = Scheduler(
            config.scheduler,
            self.block_pool,
            offload_cb=self.offload_seq_blocks,
            restore_cb=self.restore_seq_blocks,
            remote_prefix_cb=self.fetch_remote_prefix if imports else None,
        )
        self.kv_caches = self._allocate_kv(num_blocks)
        logger.info(
            "KV pool: %d blocks x %d tokens (%.2f GiB)",
            num_blocks,
            config.cache.block_size,
            self._kv_bytes(num_blocks) / 2**30,
        )

        # Dedicated draft-KV pool (model drafter only): the draft
        # model's device-resident cache lives in its OWN small block
        # pool, so target KV capacity is untouched and a draft-side
        # allocation failure can never preempt serving — it declines the
        # window to plain (tpu:multistep_fallback_total{reason=
        # draft_pool}).  Per-row capacity covers a full causal prime of
        # the carried history window plus _DRAFT_PRIME_CHAIN windows of
        # max-acceptance growth between primes (the skip-prime chain).
        # Dense dtype regardless of cache.kv_cache_dtype: the pool is
        # tiny (a 2-layer drafter at H+chain tokens per row) and the
        # int8 (data, scale) plumbing would buy nothing.
        self.draft_block_pool = None
        self.draft_kv_caches = None
        self._draft_blocks_per_row = 0
        # Host-side draft-cache coherence state (step-thread-only):
        # whether the device draft KV currently extends the batch's
        # committed context (any non-model-spec dispatch breaks it), and
        # how many windows chained since the last in-graph prime (the
        # conservative capacity watermark).
        self._draft_primed = False
        self._draft_windows_since_prime = 0
        self._draft_block_alloc: List[int] = []
        if self.draft_params is not None:
            bs = config.cache.block_size
            cap = (
                self._SPEC_HIST_WINDOW
                + self._DRAFT_PRIME_CHAIN * config.scheduler.window_max_tokens
            )
            self._draft_blocks_per_row = -(-cap // bs)
            pool_blocks = config.scheduler.speculative_draft_pool_blocks
            if pool_blocks is None:
                # Auto: every decode row fits simultaneously (+1 for the
                # reserved null block 0) — exhaustion only under an
                # explicit undersized override.
                pool_blocks = (
                    config.scheduler.max_num_seqs * self._draft_blocks_per_row
                    + 1
                )
            self.draft_block_pool = BlockPool(
                pool_blocks, bs, enable_prefix_caching=False
            )
            self.draft_kv_caches = self._allocate_draft_kv(pool_blocks)
            logger.info(
                "Draft KV pool: %d blocks x %d tokens (%d blocks/row)",
                pool_blocks, bs, self._draft_blocks_per_row,
            )

        offload_bytes = int(config.cache.host_offload_gb * 2**30)
        # Wire representation for offload/remote snapshots
        # (cache.kv_wire_format): with an int8 cache the tiers carry the
        # native (data, scale) tuples end-to-end; bytes crossing each
        # tier boundary and serde versions feed
        # tpu:kv_wire_bytes_total{tier,format} /
        # tpu:kv_snapshot_format_total{version}.
        from production_stack_tpu.kvserver.protocol import KVWireStats

        self._wire_quantized = config.cache.wire_quantized
        self.kv_wire_stats = KVWireStats()
        remote_client = None
        if config.cache.remote_kv_url:
            from production_stack_tpu.kvserver.client import RemoteKVClient

            remote_client = RemoteKVClient(
                config.cache.remote_kv_url, wire_stats=self.kv_wire_stats,
                require_v2=config.cache.kv_wire_format == "int8",
            )
        self.offload = HostOffloadManager(
            offload_bytes, remote_client,
            quantized_wire=self._wire_quantized,
            wire_stats=self.kv_wire_stats,
        )
        # Asynchronous batched KV transfer plane (cache.remote_prefetch):
        # admission-time remote-prefix prefetch on fetcher threads,
        # off-step offload staging, async restore page-in.  None when no
        # remote store (or the legacy synchronous path was requested) —
        # every consumer falls back to today's blocking behavior.
        self.kv_prefetch: Optional[PrefetchManager] = None
        self._offload_stager: Optional[OffloadStager] = None
        # The prefetch plane delivers through the prefix cache
        # (match_prefix over adopted blocks); with caching disabled it
        # could never serve a fetched block, so that config keeps the
        # legacy sync extension, which works per-request without the
        # cache.
        if (
            remote_client is not None
            and config.cache.remote_prefetch_enabled
            and config.cache.enable_prefix_caching
        ):
            self.kv_prefetch = PrefetchManager(
                remote_client,
                restore_sink=self.offload,
                num_threads=config.cache.prefetch_threads,
                observe_fetch=lambda s: self.obs.kv_phase(
                    "remote_kv_fetch", s
                ),
            )
        # The stager also covers host-DRAM-only offload (no remote tier):
        # the D2H snapshot wait is a step-thread stall either way.  Only
        # an explicit remote_prefetch=False keeps the blocking save.
        if offload_bytes > 0 and config.cache.remote_prefetch is not False:
            self._offload_stager = OffloadStager(
                self.offload,
                observe_stage=lambda s: self.obs.kv_phase(
                    "offload_stage", s
                ),
            )
        # Completed prefetches awaiting import into the prefix cache
        # (kept across steps under transient pool pressure).
        self._pending_prefetch_imports: List[PrefetchedChain] = []

        # Fixed shape constants.
        self._bmax = config.scheduler.max_model_len // config.cache.block_size
        self._smax = config.scheduler.max_num_seqs

        # Jitted step functions.  KV caches are donated so updates alias the
        # same HBM; cfg and mesh are closed over (static).
        self._prefill_fn = jax.jit(
            partial(
                self.model.prefill, cfg=cfg, mesh=self.mesh,
                sp_mode=par.sequence_parallel_mode,
            ),
            donate_argnames=("kv_caches",),
            static_argnames=("prompt_topk",),
        )
        self._decode_fn = jax.jit(
            partial(self.model.decode, cfg=cfg, mesh=self.mesh),
            donate_argnames=("kv_caches",),
        )
        # Fused mixed prefill+decode step (StepPlan decode+chunk): one
        # executable per (decode bucket, chunk bucket) pair — jit retraces
        # per shape, and both axes come from small bucket sets.
        self._mixed_fn = None
        if config.scheduler.mixed_enabled and hasattr(self.model, "mixed_step"):
            self._mixed_fn = jax.jit(
                partial(self.model.mixed_step, cfg=cfg, mesh=self.mesh),
                donate_argnames=("kv_caches",),
            )
        elif config.scheduler.mixed_enabled:
            # Model without a fused entry point: fall back to alternating
            # plans rather than failing at the first mixed dispatch.
            config.scheduler.mixed_batch = False
        self._sample_fn = jax.jit(sample_tokens)

        # K-step device-resident decode windows (tentpole of the unified
        # StepPlan path; vLLM --num-scheduler-steps made the default):
        # scan K decode+sample iterations on-device and return all K
        # emitted tokens in one host round-trip.  Slot targeting moves
        # on-device (the block-table lookup per iteration); penalties and
        # the min_tokens EOS floor run INSIDE the scan from device-
        # resident occurrence state, and a per-row stop-token match
        # freezes the row (no further KV writes, position/ctx frozen, -1
        # emitted) so stop conditions no longer waste up to K-1 tokens.
        # The final carry is returned so window N+1 can chain from window
        # N's still-in-flight state (pipelined windows).
        self._window_fn = None
        self._window_steps = config.scheduler.window_steps
        # Per-window per-row token ceiling (max-acceptance growth under
        # the fused speculative window): sizes the chained-window
        # block-table delta and mirrors the scheduler's block budget.
        self._window_max_tokens = config.scheduler.window_max_tokens
        if self._window_steps > 1:
            model_decode = partial(self.model.decode, cfg=cfg, mesh=self.mesh)
            bs = config.cache.block_size
            n_steps = self._window_steps
            vocab = cfg.vocab_size

            def multi_window(
                params, tokens, positions, ctx_lens, done, min_left,
                block_tables, max_steps, kv_caches,
                temps, top_ps, top_ks, min_ps, seq_seeds,
                stop_ids, key_base, counts, seen,
                presence, frequency, repetition,
                use_penalties, use_min_floor,
                lora=None, adapter_idx=None,
            ):
                # Per-row stop set as an [S, V] mask: doubles as the
                # min_tokens ban mask (the banned set IS the stop set —
                # vLLM min_tokens semantics) and the freeze predicate.
                stop_valid = stop_ids >= 0
                stop_mask = None
                if use_min_floor:
                    stop_mask = jax.vmap(
                        lambda ids, v: jnp.zeros(
                            (vocab,), jnp.bool_
                        ).at[jnp.where(v, ids, 0)].max(v)
                    )(stop_ids, stop_valid)

                def body(carry, t):
                    (tokens, positions, ctx_lens, done, min_left,
                     counts, seen, kv_caches) = carry
                    active = jnp.logical_and(~done, t < max_steps)  # [S]
                    blk = jnp.take_along_axis(
                        block_tables, (positions // bs)[:, None], axis=1
                    )[:, 0]
                    extra = (
                        {"lora": lora, "adapter_idx": adapter_idx}
                        if lora is not None else {}
                    )
                    logits, kv_caches = model_decode(
                        params,
                        tokens=tokens,
                        positions=positions,
                        block_tables=block_tables,
                        ctx_lens=ctx_lens,
                        # Frozen/done rows park their KV write on null
                        # block 0 — no cache slot past the stop position
                        # is ever written.
                        slot_block_ids=jnp.where(active, blk, 0),
                        slot_offsets=positions % bs,
                        kv_caches=kv_caches,
                        **extra,
                    )
                    if use_penalties:
                        logits = sampling_lib.apply_penalties_state(
                            logits, counts, seen,
                            presence, frequency, repetition,
                        )
                    if use_min_floor:
                        # Same -1e9 additive bias as the host path's
                        # logit_bias matrix, active while the row's
                        # min_tokens floor is unmet (+0.0 elsewhere is
                        # bit-exact identity).
                        bias = (
                            jnp.logical_and(
                                stop_mask, (min_left > 0)[:, None]
                            ).astype(jnp.float32) * -1e9
                        )
                        logits = logits + bias
                    # Key schedule matches single-token stepping exactly:
                    # iteration t of a window dispatched at step counter
                    # c uses PRNGKey(seed + c + t), the key the classic
                    # path would use for that token — seeded sampling is
                    # bit-identical across window sizes.
                    sampled = sample_tokens(
                        logits, temps, top_ps, top_ks,
                        jax.random.PRNGKey(key_base + t), seq_seeds,
                        min_p=min_ps,
                    )
                    stop_hit = jnp.logical_and(
                        active,
                        jnp.any(
                            jnp.logical_and(
                                sampled[:, None] == stop_ids, stop_valid
                            ),
                            axis=1,
                        ),
                    )
                    emitted = jnp.where(active, sampled, -1)
                    appended = jnp.logical_and(active, ~stop_hit)
                    if use_penalties:
                        rows = jnp.arange(counts.shape[0])
                        counts = counts.at[rows, sampled].add(
                            appended.astype(jnp.int16)
                        )
                        seen = seen.at[rows, sampled].max(appended)
                    step = active.astype(jnp.int32)
                    return (
                        jnp.where(active, sampled, tokens),
                        positions + step,
                        ctx_lens + step,
                        jnp.logical_or(done, stop_hit),
                        jnp.maximum(min_left - step, 0),
                        counts, seen, kv_caches,
                    ), emitted

                carry, emitted = jax.lax.scan(
                    body,
                    (tokens, positions, ctx_lens, done, min_left,
                     counts, seen, kv_caches),
                    jnp.arange(n_steps),
                )
                (tokens, positions, ctx_lens, done, min_left,
                 counts, seen, kv_caches) = carry
                # (No device-side all-finished reduction: every stop is
                # visible in the emitted [K, S] tokens the host reads
                # back anyway, so collect() evaluates the all-finished
                # predicate from host state for free and drops queued
                # successor windows without any extra device sync.)
                state = {
                    "tokens": tokens, "positions": positions,
                    "ctx_lens": ctx_lens, "done": done,
                    "min_left": min_left, "counts": counts, "seen": seen,
                }
                return emitted, state, kv_caches

            self._window_fn = jax.jit(
                multi_window,
                static_argnames=("use_penalties", "use_min_floor"),
                donate_argnames=("kv_caches",),
            )

        # Fused speculation INSIDE the K-step window scan (the ROADMAP
        # item-1 plan fusion): each scan iteration proposes up to
        # `spec_draft_len` draft tokens on-device from ONE of two
        # proposal sources behind a shared drafting interface — the
        # n-gram drafter (prompt lookup: most recent earlier occurrence
        # of the trailing bigram within a carried recent-history buffer)
        # or the draft MODEL (scheduler.speculative_model: a tiny second
        # model run autoregressively from its own compact device-
        # resident KV cache, carried through the scan like the history
        # buffer) — then verifies them in the SAME wide forward by
        # scoring the draft positions alongside the committed token
        # (W = draft_len+1 rows per sequence — the host speculative
        # path's expanded-batch layout, now inside the scan), and folds
        # acceptance into the carried state.  A rejected draft costs a
        # scan iteration, never a host round-trip; accepted tokens
        # advance the row's position/KV cursor inside the window.
        # Greedy-only (acceptance compares the model's own argmax, so
        # greedy streams are byte-identical by construction AND a pure
        # function of weights + carried state — lockstep replicas cannot
        # desync); penalties, the min_tokens floor and stop masking
        # apply to EVERY accepted token sequentially through the same
        # apply_penalties_state / stop-mask code the single-step path
        # uses.
        #
        # Model-drafter cache layout: the draft KV uses COMPACT slots
        # (0-based within the row's dedicated draft blocks) but TRUE
        # sequence positions for RoPE — attention distances stay exact,
        # so draft logits match full-context draft logits whenever the
        # H-token history window covers the whole sequence, and degrade
        # gracefully (history truncation, not corruption) past it.  The
        # cache is (re)built by an in-graph causal PRIME (do_prime
        # static arg): ONE wide draft forward over the S x (H-1) history
        # tokens, write-then-attend + per-row ctx masking making row c
        # attend exactly slots 0..c — the same trick the verify rows
        # use.  Chained windows skip the prime (draft_pos rides the
        # carry); the host re-primes on batch rebuilds, after any
        # non-model-spec dispatch, and every _DRAFT_PRIME_CHAIN windows
        # (the conservative capacity watermark).
        self._spec_window_fn = None
        if self._window_steps > 1 and config.scheduler.spec_window_enabled:
            model_decode = partial(self.model.decode, cfg=cfg, mesh=self.mesh)
            bs = config.cache.block_size
            n_steps = self._window_steps
            vocab = cfg.vocab_size
            drafter = config.scheduler.spec_drafter
            D = config.scheduler.spec_draft_len  # drafts per iteration
            W = D + 1  # verify rows per sequence (committed + drafts)
            H = self._SPEC_HIST_WINDOW
            if drafter == "model":
                draft_decode = partial(
                    self.draft_model.decode, cfg=self.draft_cfg,
                    mesh=self.mesh,
                )

            def spec_window(
                params, tokens, positions, ctx_lens, done, min_left,
                block_tables, max_steps, kv_caches,
                stop_ids, counts, seen, hist,
                presence, frequency, repetition,
                use_penalties, use_min_floor,
                draft_params=None, draft_tables=None, draft_pos=None,
                draft_kv=None, do_prime=False,
                lora=None, adapter_idx=None,
            ):
                stop_valid = stop_ids >= 0
                stop_mask = None
                if use_min_floor:
                    stop_mask = jax.vmap(
                        lambda ids, v: jnp.zeros(
                            (vocab,), jnp.bool_
                        ).at[jnp.where(v, ids, 0)].max(v)
                    )(stop_ids, stop_valid)
                bmax = block_tables.shape[1]
                if lora is not None:
                    wide_adapter = jnp.repeat(adapter_idx, W)
                if drafter == "model":
                    dbmax = draft_tables.shape[1]
                if drafter == "model" and do_prime:
                    # -- in-graph causal prime of the draft cache -------
                    # One wide draft forward over every row's history-
                    # window tokens EXCLUDING the committed last token
                    # (the scan's first draft forward consumes that):
                    # hist col c of a row with `live` valid entries maps
                    # to compact slot c - (H - live) at TRUE position
                    # positions + 1 - H + c; invalid (left-pad) rows
                    # park on draft null block 0 at ctx 0.  Write-then-
                    # attend + ctx = slot+1 masking gives exact causal
                    # attention in the single call.
                    Hm1 = H - 1
                    live = jnp.minimum(positions + 1, H)
                    colsp = jnp.arange(Hm1)[None, :]
                    slots = colsp - (H - live)[:, None]
                    pvalid = slots >= 0
                    safe_slot = jnp.where(pvalid, slots, 0)
                    rope = positions[:, None] + 1 - H + colsp
                    pblk = jnp.take_along_axis(
                        draft_tables,
                        jnp.clip(safe_slot // bs, 0, dbmax - 1),
                        axis=1,
                    )
                    _, draft_kv = draft_decode(
                        draft_params,
                        tokens=jnp.maximum(hist[:, :Hm1], 0).reshape(-1),
                        positions=jnp.where(pvalid, rope, 0).reshape(-1),
                        block_tables=jnp.repeat(draft_tables, Hm1, axis=0),
                        ctx_lens=jnp.where(
                            pvalid, slots + 1, 0
                        ).reshape(-1),
                        slot_block_ids=jnp.where(
                            pvalid, pblk, 0
                        ).reshape(-1),
                        slot_offsets=(safe_slot % bs).reshape(-1),
                        kv_caches=draft_kv,
                    )
                    # Invariant entering the scan: the draft cache holds
                    # all context up to but EXCLUDING the committed
                    # token, and draft_pos counts those compact slots.
                    draft_pos = live - 1

                def body(carry, t):
                    if drafter == "model":
                        (tokens, positions, ctx_lens, done, min_left,
                         emitted_cnt, counts, seen, hist, draft_pos,
                         kv_caches, draft_kv) = carry
                    else:
                        (tokens, positions, ctx_lens, done, min_left,
                         emitted_cnt, counts, seen, hist, kv_caches) = carry
                    # Budget gate is the TOKEN count, not the iteration
                    # index: acceptance advances a row several tokens
                    # per iteration and max_steps budgets the
                    # max-acceptance growth the scheduler allocated
                    # blocks for.
                    active = jnp.logical_and(~done, emitted_cnt < max_steps)

                    if drafter == "model":
                        # -- in-scan draft-model proposal ---------------
                        # D+1 sequential single-row draft forwards: d=0
                        # consumes the committed token (writing its KV
                        # at compact slot draft_pos, TRUE RoPE position
                        # `positions`), each d < D argmaxes the next
                        # proposal and feeds it forward; the final d=D
                        # forward only writes the last draft's KV so the
                        # cache invariant holds even at full acceptance.
                        # The verify's rewind is free: draft_pos
                        # advances by the ACCEPTED count + 1, landing
                        # the next iteration's first write exactly on
                        # the first stale (rejected-draft) slot — stale
                        # slots are overwritten before any row's ctx
                        # mask can attend them.  Inactive rows park
                        # writes on draft null block 0.
                        cur = tokens
                        drafts = []
                        # Penalty-aware proposals: the verifier scores
                        # sub-step j with the carried penalty state plus
                        # the tokens accepted at sub-steps < j, so the
                        # drafter replays the SAME transform on a local
                        # copy along its chain — otherwise every token
                        # where penalties flip the target argmax is a
                        # guaranteed rejection.  Acceptance stays a pure
                        # function of weights + carried state.
                        if use_penalties:
                            dcounts, dseen = counts, seen
                        if use_min_floor:
                            dmin = min_left
                        drows = jnp.arange(tokens.shape[0])
                        for d in range(D + 1):
                            dslot = draft_pos + d
                            dblk = jnp.take_along_axis(
                                draft_tables,
                                jnp.clip(dslot // bs, 0, dbmax - 1)[:, None],
                                axis=1,
                            )[:, 0]
                            dlogits, draft_kv = draft_decode(
                                draft_params,
                                tokens=cur,
                                positions=positions + d,
                                block_tables=draft_tables,
                                ctx_lens=jnp.where(active, dslot + 1, 0),
                                slot_block_ids=jnp.where(active, dblk, 0),
                                slot_offsets=dslot % bs,
                                kv_caches=draft_kv,
                            )
                            if d < D:
                                if use_penalties:
                                    dlogits = (
                                        sampling_lib.apply_penalties_state(
                                            dlogits, dcounts, dseen,
                                            presence, frequency, repetition,
                                        )
                                    )
                                if use_min_floor:
                                    dlogits = dlogits + (
                                        jnp.logical_and(
                                            stop_mask, (dmin > 0)[:, None]
                                        ).astype(jnp.float32) * -1e9
                                    )
                                cur = jnp.argmax(
                                    dlogits, axis=-1
                                ).astype(jnp.int32)
                                drafts.append(cur)
                                if use_penalties:
                                    # Mirror the verifier's append gate:
                                    # a proposed stop token is emitted
                                    # but not counted, and the chain
                                    # past it is dead anyway.
                                    dstop = jnp.any(
                                        jnp.logical_and(
                                            cur[:, None] == stop_ids,
                                            stop_valid,
                                        ),
                                        axis=1,
                                    )
                                    dapp = jnp.logical_and(active, ~dstop)
                                    dcounts = dcounts.at[drows, cur].add(
                                        dapp.astype(jnp.int16)
                                    )
                                    dseen = dseen.at[drows, cur].max(dapp)
                                if use_min_floor:
                                    dmin = jnp.maximum(
                                        dmin - active.astype(jnp.int32), 0
                                    )
                        draft = jnp.stack(drafts, axis=1)  # [S, D]
                        # Room for drafts: the bonus/correction token
                        # always takes one budget slot, drafts fill the
                        # rest (same budget gate as the n-gram source).
                        room = jnp.maximum(max_steps - emitted_cnt - 1, 0)
                        dvalid = jnp.logical_and(
                            jnp.arange(D)[None, :] < room[:, None],
                            active[:, None],
                        )
                    else:
                        # -- on-device prompt-lookup draft --------------
                        # Most recent earlier occurrence of the trailing
                        # bigram within the carried [S, H] history (left
                        # -1-padded, hist[:, -1] == the committed
                        # token); the tokens that followed it are the
                        # draft.  No bigram hit falls back to the most
                        # recent UNIGRAM occurrence of the committed
                        # token: the verify rows are computed either way
                        # (static shapes), so a speculative proposal is
                        # free and a rejected one costs nothing the
                        # empty iteration didn't.
                        key0 = hist[:, H - 2][:, None]
                        key1 = hist[:, H - 1][:, None]
                        starts = jnp.arange(H - 2)
                        match2 = jnp.logical_and(
                            jnp.logical_and(
                                hist[:, : H - 2] == key0,
                                hist[:, 1 : H - 1] == key1,
                            ),
                            hist[:, : H - 2] >= 0,
                        )
                        best2 = jnp.max(
                            jnp.where(match2, starts[None, :], -1), axis=1
                        )
                        match1 = jnp.logical_and(
                            hist[:, 1 : H - 1] == key1,
                            hist[:, 1 : H - 1] >= 0,
                        )
                        best1 = jnp.max(
                            jnp.where(match1, starts[None, :], -1), axis=1
                        )
                        best = jnp.where(best2 >= 0, best2, best1)
                        dpos = best[:, None] + 2 + jnp.arange(D)[None, :]
                        draft = jnp.take_along_axis(
                            hist, jnp.clip(dpos, 0, H - 1), axis=1
                        )
                        # Room for drafts: the bonus/correction token
                        # always takes one budget slot, drafts fill the
                        # rest.
                        room = jnp.maximum(max_steps - emitted_cnt - 1, 0)
                        dvalid = (
                            (best >= 0)[:, None]
                            & (dpos < H)
                            & (draft >= 0)
                            & (jnp.arange(D)[None, :] < room[:, None])
                            & active[:, None]
                        )
                    # Only a contiguous prefix is verifiable (already
                    # contiguous for model proposals; shared so both
                    # sources feed the identical verify machinery).
                    dvalid = jnp.cumsum(
                        jnp.where(dvalid, 0, 1), axis=1
                    ) == 0
                    draft = jnp.where(dvalid, draft, 0)
                    nd = dvalid.sum(axis=1).astype(jnp.int32)

                    # -- one wide verify forward ------------------------
                    # Row j of sequence i consumes chain[j] at position
                    # pos+j with ctx pos+j+1 — exactly the host
                    # speculative layout, so the shared decode kernel's
                    # write-then-attend order makes draft rows see their
                    # predecessors' KV.  Dead rows park KV on null
                    # block 0 (never corrupt a live slot).
                    chain = jnp.concatenate([tokens[:, None], draft], axis=1)
                    row_live = jnp.concatenate(
                        [active[:, None], dvalid], axis=1
                    )
                    offs = jnp.arange(W)[None, :]
                    wpos = positions[:, None] + offs
                    wctx = ctx_lens[:, None] + offs
                    blk = jnp.take_along_axis(
                        block_tables,
                        jnp.clip(wpos // bs, 0, bmax - 1),
                        axis=1,
                    )
                    extra = (
                        {"lora": lora, "adapter_idx": wide_adapter}
                        if lora is not None else {}
                    )
                    logits, kv_caches = model_decode(
                        params,
                        tokens=chain.reshape(-1),
                        positions=jnp.where(row_live, wpos, 0).reshape(-1),
                        block_tables=jnp.repeat(block_tables, W, axis=0),
                        ctx_lens=jnp.where(row_live, wctx, 0).reshape(-1),
                        slot_block_ids=jnp.where(
                            row_live, blk, 0
                        ).reshape(-1),
                        slot_offsets=(wpos % bs).reshape(-1),
                        kv_caches=kv_caches,
                        **extra,
                    )
                    # No dtype cast: the verify rows must see EXACTLY the
                    # logits the single-row path would (lm_head already
                    # emits fp32), or greedy parity could drift.
                    logits = logits.reshape(tokens.shape[0], W, vocab)

                    # -- sequential verify: penalties / min-floor / stop
                    # applied to every accepted token in order, through
                    # the SAME apply_penalties_state call site the
                    # single-step path uses (the PR-8 one-call-site
                    # rule), so streams are byte-identical.
                    rows = jnp.arange(tokens.shape[0])
                    alive = active
                    last_tok = tokens
                    adv = jnp.zeros_like(positions)
                    acc_cnt = jnp.zeros_like(positions)
                    new_done = done
                    emits = []
                    for j in range(W):
                        lj = logits[:, j, :]
                        if use_penalties:
                            lj = sampling_lib.apply_penalties_state(
                                lj, counts, seen,
                                presence, frequency, repetition,
                            )
                        if use_min_floor:
                            bias = (
                                jnp.logical_and(
                                    stop_mask, (min_left > 0)[:, None]
                                ).astype(jnp.float32) * -1e9
                            )
                            lj = lj + bias
                        tok_j = jnp.argmax(lj, axis=-1).astype(jnp.int32)
                        stop_hit = jnp.logical_and(
                            alive,
                            jnp.any(
                                jnp.logical_and(
                                    tok_j[:, None] == stop_ids, stop_valid
                                ),
                                axis=1,
                            ),
                        )
                        emits.append(jnp.where(alive, tok_j, -1))
                        appended = jnp.logical_and(alive, ~stop_hit)
                        if use_penalties:
                            counts = counts.at[rows, tok_j].add(
                                appended.astype(jnp.int16)
                            )
                            seen = seen.at[rows, tok_j].max(appended)
                        step = alive.astype(jnp.int32)
                        adv = adv + step
                        min_left = jnp.maximum(min_left - step, 0)
                        last_tok = jnp.where(alive, tok_j, last_tok)
                        new_done = jnp.logical_or(new_done, stop_hit)
                        if j < W - 1:
                            agree = jnp.logical_and(
                                dvalid[:, j], tok_j == draft[:, j]
                            )
                            acc = jnp.logical_and(appended, agree)
                            acc_cnt = acc_cnt + acc.astype(jnp.int32)
                            alive = acc
                    emitted = jnp.stack(emits, axis=0)  # [W, S]

                    # -- fold acceptance into the carried state ---------
                    # (history shifts by the emitted count so the next
                    # iteration's bigram lookup sees the new tokens).
                    cat = jnp.concatenate(
                        [hist, jnp.maximum(emitted.T, 0)], axis=1
                    )
                    hidx = jnp.arange(H)[None, :] + adv[:, None]
                    hist = jnp.take_along_axis(cat, hidx, axis=1)
                    core = (
                        jnp.where(active, last_tok, tokens),
                        positions + adv,
                        ctx_lens + adv,
                        new_done,
                        min_left,
                        emitted_cnt + adv,
                        counts, seen, hist,
                    )
                    if drafter == "model":
                        # Commit the draft-cache cursor: adv = accepted
                        # + 1 slots now hold exactly the tokens up to
                        # (excluding) the new committed token.
                        return core + (
                            draft_pos + adv, kv_caches, draft_kv,
                        ), (emitted, nd, acc_cnt)
                    return core + (kv_caches,), (emitted, nd, acc_cnt)

                init = (tokens, positions, ctx_lens, done, min_left,
                        jnp.zeros_like(positions), counts, seen, hist)
                if drafter == "model":
                    init = init + (draft_pos, kv_caches, draft_kv)
                else:
                    init = init + (kv_caches,)
                carry, ys = jax.lax.scan(body, init, jnp.arange(n_steps))
                if drafter == "model":
                    (tokens, positions, ctx_lens, done, min_left, _cnt,
                     counts, seen, hist, draft_pos, kv_caches,
                     draft_kv) = carry
                else:
                    (tokens, positions, ctx_lens, done, min_left, _cnt,
                     counts, seen, hist, kv_caches) = carry
                emitted, drafted, accepted = ys  # [K, W, S], [K, S], [K, S]
                state = {
                    "tokens": tokens, "positions": positions,
                    "ctx_lens": ctx_lens, "done": done,
                    "min_left": min_left, "counts": counts, "seen": seen,
                    "hist": hist,
                }
                if drafter == "model":
                    state["draft_pos"] = draft_pos
                    return (
                        emitted, drafted, accepted, state, kv_caches,
                        draft_kv,
                    )
                return emitted, drafted, accepted, state, kv_caches

            self._spec_window_fn = jax.jit(
                spec_window,
                static_argnames=(
                    "use_penalties", "use_min_floor", "do_prime",
                ),
                donate_argnames=(
                    ("kv_caches", "draft_kv") if drafter == "model"
                    else ("kv_caches",)
                ),
            )

        if self._window_steps > 1:

            def win_advance(tables, cols, vals):
                """Chained-window block-table growth: scatter up to C new
                blocks per row into the device-resident table (col -1 =
                no growth), mirroring _pipe_advance's single-column
                form."""
                rows = jnp.arange(tables.shape[0])[:, None]
                valid = cols >= 0
                safe = jnp.where(valid, cols, 0)
                keep = tables[rows, safe]
                return tables.at[rows, safe].set(
                    jnp.where(valid, vals, keep)
                )

            self._win_advance_fn = jax.jit(win_advance)
            self._win_occurrence_fn = jax.jit(
                partial(sampling_lib.occurrence_state, vocab_size=vocab)
            )

        # MIXED K-step windows (the sustained-arrival fusion): a waiting
        # prompt's prefill chunks ride the device-resident decode scan —
        # each scan iteration runs the packed [S_dec + chunk] mixed
        # forward (llama.mixed_step, the SAME executable shape the K=1
        # mixed path compiles), decode rows advancing one token from the
        # carried state exactly like multi_window while the chunk cursor
        # (cached_len, valid_len, new-block row) advances through the
        # precomputed per-iteration schedule carried as scan xs.  The
        # chunk's accumulated-prefix block table is ONE static [P] array
        # whose validity the in-graph cursor masks (a block written by
        # iteration t is attended by iteration t+1 with no host trip).
        # The final chunk's tail-row logits are captured into the carry
        # and sampled ON THE HOST at collect through the identical
        # _finalize_final_prefill path K=1 mixed stepping uses — first
        # tokens are bit-identical by construction.  The drafter never
        # engages here (drafting is a pure-decode-window feature);
        # penalties / min_tokens / stop masks run in-scan as in
        # multi_window.  Scan length is a static arg bucketed to powers
        # of two by the dispatcher, so the inventory stays
        # |chunk buckets| x |decode buckets| x O(log K).
        self._mixed_window_fn = None
        if (
            self._window_steps > 1
            and self._mixed_fn is not None
            and config.scheduler.mixed_window_enabled
        ):
            model_mixed = partial(self.model.mixed_step, cfg=cfg, mesh=self.mesh)
            bs = config.cache.block_size
            vocab = cfg.vocab_size

            def mixed_window(
                params, tokens, positions, ctx_lens, done, min_left,
                block_tables, max_steps, kv_caches,
                temps, top_ps, top_ks, min_ps, seq_seeds,
                stop_ids, key_base, counts, seen,
                presence, frequency, repetition,
                pf_tokens, pf_cached, pf_valid, pf_new_blocks,
                pf_prefix_ids, pf_adapter,
                n_steps, use_penalties, use_min_floor,
                hist=None, lora=None, adapter_idx=None,
            ):
                stop_valid = stop_ids >= 0
                stop_mask = None
                if use_min_floor:
                    stop_mask = jax.vmap(
                        lambda ids, v: jnp.zeros(
                            (vocab,), jnp.bool_
                        ).at[jnp.where(v, ids, 0)].max(v)
                    )(stop_ids, stop_valid)
                S = tokens.shape[0]
                T = pf_tokens.shape[1]

                def body(carry, xs):
                    (tokens, positions, ctx_lens, done, min_left,
                     counts, seen, hist_c, kv_caches) = carry
                    # Packed windows: each iteration carries its OWN
                    # prompt cursor — tokens, block table, and adapter
                    # slot ride the scan xs, so chunks from several
                    # prompts share one static [S + T] shape.
                    t, pft, pfc, pfv, pfnb, pfpid, pfad = xs
                    active = jnp.logical_and(~done, t < max_steps)
                    blk = jnp.take_along_axis(
                        block_tables, (positions // bs)[:, None], axis=1
                    )[:, 0]
                    extra = {}
                    if lora is not None:
                        # Mixed row layout: [S decode rows + T chunk
                        # rows sharing ONE adapter] — the _run_mixed
                        # layout, per iteration.
                        extra = {
                            "lora": lora,
                            "adapter_idx": jnp.concatenate(
                                [adapter_idx,
                                 jnp.full((T,), pfad, jnp.int32)]
                            ),
                        }
                    logits, kv_caches = model_mixed(
                        params,
                        dec_tokens=tokens,
                        dec_positions=positions,
                        dec_block_tables=block_tables,
                        dec_ctx_lens=ctx_lens,
                        # Frozen/done rows park their KV write on null
                        # block 0 — same contract as multi_window.
                        dec_slot_block_ids=jnp.where(active, blk, 0),
                        dec_slot_offsets=positions % bs,
                        pf_tokens=pft,
                        pf_cached_len=pfc,
                        pf_prefix_block_ids=pfpid,
                        pf_new_block_ids=pfnb,
                        pf_valid_len=pfv,
                        kv_caches=kv_caches,
                        **extra,
                    )
                    # logits[-1] is the chunk's tail row (last VALID
                    # token); every iteration's tail rides out as a
                    # scan output so EACH packed prompt's final chunk
                    # can be finalized at collect.
                    tail = logits[-1]
                    dlogits = logits[:S]
                    if use_penalties:
                        dlogits = sampling_lib.apply_penalties_state(
                            dlogits, counts, seen,
                            presence, frequency, repetition,
                        )
                    if use_min_floor:
                        bias = (
                            jnp.logical_and(
                                stop_mask, (min_left > 0)[:, None]
                            ).astype(jnp.float32) * -1e9
                        )
                        dlogits = dlogits + bias
                    # Key schedule: iteration t of a window dispatched
                    # at counter c uses PRNGKey(seed + c + t) — the
                    # ordinal the K=1 mixed step at counter c+t burns.
                    sampled = sample_tokens(
                        dlogits, temps, top_ps, top_ks,
                        jax.random.PRNGKey(key_base + t), seq_seeds,
                        min_p=min_ps,
                    )
                    stop_hit = jnp.logical_and(
                        active,
                        jnp.any(
                            jnp.logical_and(
                                sampled[:, None] == stop_ids, stop_valid
                            ),
                            axis=1,
                        ),
                    )
                    emitted = jnp.where(active, sampled, -1)
                    appended = jnp.logical_and(active, ~stop_hit)
                    if use_penalties:
                        rows = jnp.arange(counts.shape[0])
                        counts = counts.at[rows, sampled].add(
                            appended.astype(jnp.int16)
                        )
                        seen = seen.at[rows, sampled].max(appended)
                    if hist_c is not None:
                        # Keep the speculative drafter's carried history
                        # warm across mixed windows (one committed token
                        # per active row per iteration) so a chained
                        # pure-decode window drafts from fresh context.
                        H = hist_c.shape[1]
                        cat = jnp.concatenate(
                            [hist_c, jnp.maximum(emitted, 0)[:, None]],
                            axis=1,
                        )
                        hidx = (
                            jnp.arange(H)[None, :]
                            + active.astype(jnp.int32)[:, None]
                        )
                        hist_c = jnp.take_along_axis(cat, hidx, axis=1)
                    step = active.astype(jnp.int32)
                    return (
                        jnp.where(active, sampled, tokens),
                        positions + step,
                        ctx_lens + step,
                        jnp.logical_or(done, stop_hit),
                        jnp.maximum(min_left - step, 0),
                        counts, seen, hist_c, kv_caches,
                    ), (emitted, tail)

                init = (
                    tokens, positions, ctx_lens, done, min_left,
                    counts, seen, hist, kv_caches,
                )
                xs = (
                    jnp.arange(n_steps), pf_tokens, pf_cached, pf_valid,
                    pf_new_blocks, pf_prefix_ids, pf_adapter,
                )
                carry, (emitted, tails) = jax.lax.scan(body, init, xs)
                (tokens, positions, ctx_lens, done, min_left,
                 counts, seen, hist, kv_caches) = carry
                state = {
                    "tokens": tokens, "positions": positions,
                    "ctx_lens": ctx_lens, "done": done,
                    "min_left": min_left, "counts": counts, "seen": seen,
                }
                if hist is not None:
                    state["hist"] = hist
                return emitted, tails, state, kv_caches

            self._mixed_window_fn = jax.jit(
                mixed_window,
                static_argnames=(
                    "n_steps", "use_penalties", "use_min_floor",
                ),
                donate_argnames=("kv_caches",),
            )
        self._penalties_fn = jax.jit(sampling_lib.apply_penalties)
        self._argmax_fn = jax.jit(
            lambda logits: jnp.argmax(logits, axis=-1).astype(jnp.int32)
        )
        # Speculative decoding effectiveness counters (fed by the
        # legacy host-side n-gram path and the fused window path, both
        # drafters).
        self.spec_tokens_drafted = 0
        self.spec_tokens_accepted = 0
        # Fused speculative-window outcomes per collected window
        # (tpu:spec_window_tokens_total{outcome,drafter}): draft tokens the
        # verifier accepted / rejected inside windows, and window tokens
        # emitted by the fused path but undeliverable at collect
        # (abort / out-of-band finish mid-window).  Step-thread-only
        # writer, like the multistep counters.
        self.spec_window_tokens: Dict[str, int] = {
            "accepted": 0, "rejected": 0, "wasted": 0,
        }
        # Scan seconds spent in the model drafter's forwards
        # (tpu:spec_draft_fraction_seconds): measured window sync time x
        # a static cost-model split — per scan iteration the drafter
        # runs (D+1) single rows (plus the amortized prime: (H-1) rows
        # every _DRAFT_PRIME_CHAIN x K iterations) through the DRAFT
        # parameter set while the verifier runs W = D+1 rows through the
        # TARGET set; decode is weight-streaming-bound, so row-count x
        # param-count is the honest first-order split.  Step-thread-only
        # writer.
        self.spec_draft_fraction_s = 0.0
        self._draft_cost_fraction = 0.0
        if self.draft_params is not None:
            tgt_n = sum(
                x.size for x in jax.tree_util.tree_leaves(self.params)
            )
            dft_n = sum(
                x.size for x in jax.tree_util.tree_leaves(self.draft_params)
            )
            d_len = config.scheduler.spec_draft_len
            draft_rows = (d_len + 1) + (self._SPEC_HIST_WINDOW - 1) / (
                self._DRAFT_PRIME_CHAIN * self._window_steps
            )
            self._draft_cost_fraction = (draft_rows * dft_n) / (
                draft_rows * dft_n + (d_len + 1) * tgt_n
            )
        self._logprobs_fn = jax.jit(
            sampling_lib.top_logprobs_of, static_argnames=("k",)
        )

        # Multi-LoRA slot arrays (engine/lora.py); None keeps the model's
        # lora-free code path (zero overhead, separate compiled programs).
        self.lora_registry = None
        if config.lora.enabled:
            from production_stack_tpu.engine.lora import AdapterRegistry

            self.lora_registry = AdapterRegistry(
                cfg, config.lora, jnp.dtype(cfg.dtype)
            )

        # Observability hub: request tracer + step-phase/latency histograms
        # + window flight recorder + compile-event tracker (all hooks
        # no-op when config.obs.tracing is off).
        self.obs = EngineObs(
            enabled=config.obs.tracing,
            ring_size=config.obs.trace_ring_size,
            ring_bytes=config.obs.trace_ring_bytes,
            window_ring_size=config.obs.window_ring_size,
        )
        # Wrap every jit entry point in the compile tracker's cache-size
        # probe so XLA compiles are counted/timed per executable shape key
        # (tpu:compile_seconds_total{executable}, GET /debug/compiles).
        # With tracing off wrap() is the identity, keeping bare jit
        # callables — the untraced fast path is byte-identical.
        for _jit_name in (
            "_prefill_fn", "_decode_fn", "_mixed_fn", "_sample_fn",
            "_window_fn", "_spec_window_fn", "_mixed_window_fn",
            "_win_advance_fn", "_win_occurrence_fn", "_penalties_fn",
            "_argmax_fn", "_logprobs_fn",
        ):
            _jit_fn = getattr(self, _jit_name, None)
            if _jit_fn is not None:
                setattr(
                    self, _jit_name,
                    self.obs.compile_tracker.wrap(
                        _jit_name.lstrip("_"), _jit_fn
                    ),
                )

        self._step_counter = 0
        self._encode_fn = None  # lazily jitted /v1/embeddings path
        # Lazily jitted [B, T]-bucketed encode-lane executable (one per
        # static shape, compile-tracked like every other jit family).
        self._encode_batch_fn = None
        # Encode-lane counters (tpu:encode_* families).  The batch
        # counters/histograms are STEP-THREAD-only writers (the batcher
        # runs encode batches from the step loop); encode_queue_depth is
        # a gauge the AsyncEngine's batcher overwrites from either side
        # (plain int store — racy-but-benign snapshot, never summed).
        self.encode_texts_total = 0
        self.encode_batch_size_hist = Histogram(
            bounds=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0)
        )
        self.encode_seconds_hist = Histogram(
            bounds=(0.005, 0.02, 0.05, 0.1, 0.25, 1.0, 4.0)
        )
        self.encode_queue_depth = 0
        self._token_texts = None  # guided decoding token-text cache
        self._seqs: Dict[str, Sequence] = {}
        # Cumulative counters for /metrics.
        self.total_prompt_tokens = 0
        self.total_generated_tokens = 0
        self.total_finished = 0
        # Prompt tokens prefilled INSIDE mixed steps (the interference-
        # removal signal: nonzero means prompts are chunking alongside
        # live decodes instead of stalling them).
        self.prefill_chunk_tokens = 0
        # The subset of prefill_chunk_tokens that rode a mixed K-STEP
        # window (tpu:mixed_window_chunk_tokens_total): nonzero means
        # sustained arrivals are amortizing the host round-trip instead
        # of forcing K=1 steps.  Step-thread-only writer.
        self.mixed_window_chunk_tokens = 0
        # Distinct prompts whose chunks rode each mixed K-step window
        # (tpu:mixed_window_prompts_per_window): >1 means the packed
        # multi-prompt path is filling windows under queue depth.
        # Lives on the engine (not EngineObs) because the packed-window
        # contract metrics render regardless of tracing.  Step-thread-
        # only writer; Histogram.observe is thread-safe anyway.
        self.mixed_window_prompts_hist = Histogram(
            bounds=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0)
        )
        # Seconds of host<->device transfer work issued WHILE the device
        # was busy with an in-flight window — H2D chunk staging for a
        # chained window plus D2H offload gathers dispatched under the
        # scan (tpu:window_transfer_overlap_seconds_total): stalls the
        # overlap-everything dispatch avoided.  Step-thread-only writer.
        self.window_transfer_overlap_s = 0.0
        # Double-buffered host staging arrays for packed-window chunk
        # payloads, keyed by (n_scan, T): two alternating sets per scan
        # shape so building window N+1's H2D payload never waits on
        # window N's still-draining copy.
        self._mw_stage: Dict[tuple, list] = {}
        # Overload-protection counters (docs/robustness.md): requests the
        # API server shed with a structured 429 (bounded admission), and
        # requests shed or aborted because their client deadline expired.
        # deadline_expired is written by the STEP THREAD (queued-expiry
        # sweep) and deadline_expired_admission by the EVENT LOOP
        # (admission sheds) — one writer each, because a shared `+= 1`
        # across threads silently loses increments; stats() reports the
        # sum.  admission_rejected is event-loop-only.
        self.admission_rejected = 0
        self.deadline_expired = 0
        self.deadline_expired_admission = 0
        # K-step window observability (docs/observability.md): dispatches
        # that fell back to single-step because a co-scheduled request
        # needed host-sampled features (by reason — a single logprobs
        # request silently de-optimized every co-scheduled stream before
        # this counter existed), and emitted-but-undeliverable window
        # tokens (abort / out-of-band finish while the window flew; the
        # device stop-mask keeps ordinary stops at zero waste).  Both are
        # step-thread-only writers.
        self.multistep_fallback: Dict[str, int] = {}
        self.multistep_wasted_tokens = 0
        # Last _can_window decline reason, stamped on the flight record
        # of the K=1 dispatch that replaced the declined window (step-
        # thread-only, overwritten every _can_window call).
        self._last_window_decline: Optional[str] = None
        # Host-side mirror of the device-resident window block tables
        # (how many columns of each row are populated), for the chained
        # windows' delta scatter.
        self._win_table_lens: List[int] = []
        self._step_time_accum = 0.0
        # (end_time, duration) of recent steps; duty_cycle = busy fraction
        # of the trailing window (the HPA/dashboard signal, vocabulary.py).
        self._busy_window: List[tuple] = []
        self._busy_window_s = 10.0

        # -- async one-step-lookahead decode pipeline ----------------------
        # dispatch() launches decode N+1 with tokens chained from step N's
        # still-in-flight device sample; collect() reads N back only when
        # N+1 is already enqueued.  Host-state sampling features drop a
        # batch to the classic synchronous path per step (same fallback
        # rule as the multi-step scan).
        self._pipeline_enabled = config.scheduler.pipeline_enabled
        self._pending: Deque[_PendingStep] = deque()
        # Device-resident decode batch state, valid for the most recently
        # dispatched pipelined step: block tables and sampling-parameter
        # arrays stay on device between steps, so steady-state dispatch
        # sends ONE packed [4, S] delta instead of eight per-array H2D
        # transfers.
        self._pipe_tables = None
        self._pipe_sampling = None  # (temps, top_ps, top_ks, min_ps, seeds)
        self._pipe_adapter = None
        self._pipe_table_lens: List[int] = []
        # decode_host_gap_ms: host time between one decode step retiring
        # and the next decode launch while the device had nothing queued —
        # the serialization the pipeline removes (≈0 when pipelining).
        self._gap_total_s = 0.0
        self._gap_steps = 0
        self._last_decode_end: Optional[float] = None

        bs_const = config.cache.block_size

        def _pipe_unpack(packed, tables):
            """Batch-(re)build path: ONE packed [11, S] int32 transfer
            carries every per-row scalar (float rows bitcast); the block
            tables ride in a second transfer only when the batch
            composition changed."""
            def as_f32(row):
                return jax.lax.bitcast_convert_type(row, jnp.float32)

            return {
                "tokens": packed[0],
                "positions": packed[1],
                "ctx_lens": packed[2],
                "slot_blocks": packed[3],
                "slot_offsets": packed[4],
                "temps": as_f32(packed[5]),
                "top_ps": as_f32(packed[6]),
                "top_ks": packed[7],
                "min_ps": as_f32(packed[8]),
                "seeds": packed[9],
                "adapter": packed[10],
                "tables": tables,
            }

        def _pipe_advance(packed, prev_sampled, tables):
            """Steady path ("same batch, +1 token"): tokens chain from the
            in-flight sample; the packed [4, S] int32 delta carries
            (positions, ctx_lens, upd_col, upd_val) and block-table growth
            is a jitted in-place scatter of at most one new block per row
            (col -1 = no growth)."""
            positions, ctx_lens = packed[0], packed[1]
            cols, vals = packed[2], packed[3]
            rows = jnp.arange(tables.shape[0])
            valid = cols >= 0
            safe_col = jnp.where(valid, cols, 0)
            keep = tables[rows, safe_col]
            tables = tables.at[rows, safe_col].set(
                jnp.where(valid, vals, keep)
            )
            blk = jnp.take_along_axis(
                tables, (positions // bs_const)[:, None], axis=1
            )[:, 0]
            active = ctx_lens > 0
            return {
                "tokens": prev_sampled,
                "positions": positions,
                "ctx_lens": ctx_lens,
                "slot_blocks": jnp.where(active, blk, 0),
                "slot_offsets": positions % bs_const,
                "tables": tables,
            }

        self._pipe_unpack_fn = self.obs.compile_tracker.wrap(
            "pipe_unpack_fn", jax.jit(_pipe_unpack)
        )
        self._pipe_advance_fn = self.obs.compile_tracker.wrap(
            "pipe_advance_fn", jax.jit(_pipe_advance)
        )

    # -- sizing ------------------------------------------------------------

    def _kv_bytes(self, num_blocks: int) -> int:
        cfg = self.config.model
        if self.config.cache.kv_cache_dtype == "int8":
            # int8 data + one fp32 scale per (token, kv head): bytes per
            # token roughly halve vs bf16, so _decide_num_blocks fits
            # roughly 2x the blocks in the same HBM budget.
            per_token = 2 * cfg.num_kv_heads * (cfg.head_dim * 1 + 4)
        else:
            per_token = (
                2 * cfg.num_kv_heads * cfg.head_dim * _dtype_size(cfg.dtype)
            )
        return num_blocks * self.config.cache.block_size * per_token * cfg.num_layers

    def _decide_num_blocks(self) -> int:
        cache = self.config.cache
        if cache.num_blocks is not None:
            return cache.num_blocks
        device = jax.local_devices()[0]
        stats = {}
        try:
            stats = device.memory_stats() or {}
        except Exception:
            pass
        limit = stats.get("bytes_limit")
        in_use = stats.get("bytes_in_use", 0)
        if limit:
            free = (limit - in_use) * cache.hbm_utilization
            # KV heads are sharded over tp, so each device holds 1/tp of a
            # block; size the pool against per-device free HBM.
            per_block = self._kv_bytes(1) / self.config.parallel.tensor_parallel
            blocks = max(int(free // per_block), 16)
        else:
            # CPU / unknown backend: enough for tests and smoke serving.
            blocks = 512
        # Cap the block-table width implied by max_model_len.
        return blocks

    def _allocate_kv(self, num_blocks: int):
        cfg = self.config.model
        bs = self.config.cache.block_size
        shape = (num_blocks, bs, cfg.num_kv_heads, cfg.head_dim)
        dtype = jnp.dtype(cfg.dtype)
        # Allocate directly sharded (jit with out_shardings): materializing
        # the full unsharded layer on one device first would OOM at high tp.
        layer_shardings = shardings_lib.kv_cache_shardings(cfg, self.mesh)
        if self.config.cache.kv_cache_dtype == "int8":
            # (data int8, scale fp32 [N, bs, K]) per side — kv/quant.py.
            scale_sharding = shardings_lib.kv_scale_sharding(self.mesh)
            zeros = jax.jit(
                lambda: (
                    jnp.zeros(shape, jnp.int8),
                    jnp.zeros(shape[:3], jnp.float32),
                ),
                out_shardings=(layer_shardings[0][0], scale_sharding),
            )
            return [(zeros(), zeros()) for _ in range(cfg.num_layers)]
        zeros = jax.jit(
            lambda: jnp.zeros(shape, dtype),
            out_shardings=layer_shardings[0][0],
        )
        return [(zeros(), zeros()) for _ in range(cfg.num_layers)]

    def _allocate_draft_kv(self, num_blocks: int):
        """Draft model's paged KV (model drafter): same block size as
        the target pool (one slot-targeting code path), the DRAFT
        architecture's head shapes, always dense dtype (the pool is tiny
        — see the boot-time sizing comment)."""
        cfg = self.draft_cfg
        bs = self.config.cache.block_size
        shape = (num_blocks, bs, cfg.num_kv_heads, cfg.head_dim)
        layer_shardings = shardings_lib.kv_cache_shardings(cfg, self.mesh)
        zeros = jax.jit(
            lambda: jnp.zeros(shape, jnp.dtype(cfg.dtype)),
            out_shardings=layer_shardings[0][0],
        )
        return [(zeros(), zeros()) for _ in range(cfg.num_layers)]

    def _put(self, arr: np.ndarray, spec: P) -> jax.Array:
        """Host array -> device array with an explicit mesh sharding."""
        return jax.device_put(jnp.asarray(arr), NamedSharding(self.mesh, spec))

    # -- request lifecycle -------------------------------------------------

    def add_request(
        self,
        request_id: str,
        prompt: Optional[str] = None,
        prompt_token_ids: Optional[List[int]] = None,
        sampling_params: Optional[SamplingParams] = None,
        adapter: Optional[str] = None,
    ) -> None:
        if prompt_token_ids is None:
            if prompt is None:
                raise ValueError("need prompt or prompt_token_ids")
            prompt_token_ids = self.tokenizer.encode(prompt)
        if not prompt_token_ids:
            prompt_token_ids = [self.tokenizer.bos_token_id or 0]
        params_obj = sampling_params or SamplingParams()
        guide = None
        if params_obj.response_format == "json_object":
            from production_stack_tpu.engine.guided import JsonGuide

            guide = JsonGuide(require_object=True)
            # Completion forces EOS; ignore_eos would append eos text
            # forever.  Enforced here (not only at the API boundary) so
            # direct engine users get the same behavior.
            params_obj.ignore_eos = False
        elif (
            isinstance(params_obj.response_format, dict)
            and params_obj.response_format.get("type") == "json_schema"
        ):
            from production_stack_tpu.engine.guided_schema import SchemaGuide

            # Raises SchemaCompileError (a ValueError) for schemas
            # outside the supported subset -> 400 at the API boundary.
            guide = SchemaGuide(params_obj.response_format.get("schema") or {})
            params_obj.ignore_eos = False
        elif params_obj.response_format not in (None, "text"):
            raise ValueError(
                f"Unsupported response_format {params_obj.response_format!r}"
            )
        adapter_idx = 0
        cache_ns = 0
        if adapter:
            if self.lora_registry is None:
                raise ValueError(
                    "LoRA adapter requested but the engine was started with "
                    "max_loras=0"
                )
            adapter_idx = self.lora_registry.slot_of(adapter)  # raises if unknown
            cache_ns = self.lora_registry.namespace_of(adapter)
        seq = Sequence(
            seq_id=request_id,
            prompt_token_ids=list(prompt_token_ids),
            sampling_params=params_obj,
            adapter=adapter,
            adapter_idx=adapter_idx,
            cache_ns=cache_ns,
            echo_prompt_len=len(prompt_token_ids),
            guide=guide,
        )
        self._seqs[request_id] = seq
        self.scheduler.add_seq(seq)
        self.total_prompt_tokens += len(prompt_token_ids)
        # Admission-time prefetch: start resolving the local prefix-cache
        # miss tail against the remote store NOW, so by the time the
        # scheduler considers this prompt the blocks are (often) already
        # in host staging — and never fetched inside schedule().
        if self.kv_prefetch is not None and self._imports:
            self._submit_prefix_prefetch(seq)

    def abort_request(self, request_id: str) -> None:
        seq = self.scheduler.abort_seq(request_id)
        if seq is not None:
            seq.status = SequenceStatus.FINISHED
            seq.finish_reason = FinishReason.ABORT
        if self.kv_prefetch is not None:
            self.kv_prefetch.cancel(request_id)
        if self._offload_stager is not None:
            # Tombstone BEFORE offload.discard: a snapshot still staging
            # must never be inserted (or remote-PUT) after the DEL.
            self._offload_stager.discard(request_id)
        self.offload.discard(request_id)
        self._seqs.pop(request_id, None)
        self.obs.on_abort(request_id)

    def has_unfinished(self) -> bool:
        return self.scheduler.has_unfinished()

    def scan_expired_deadlines(self, now: float) -> List[str]:
        """Ids of WAITING/PREEMPTED sequences whose client deadline has
        passed.  Pure scan (no aborts): the step loop folds the result
        into its abort batch so lockstep followers replay the identical
        aborts instead of evaluating wall clocks that diverge per
        replica.  Running sequences are exempt — they are streaming
        tokens, and cutting them is the client's call."""
        expired = []
        for queue in (self.scheduler.waiting, self.scheduler.preempted):
            for seq in queue:
                d = seq.sampling_params.deadline
                if d is not None and now > d:
                    expired.append(seq.seq_id)
        return expired

    # -- stepping ----------------------------------------------------------

    def step(self) -> List[StepOutput]:
        """One engine step: top up the device pipeline, then collect the
        oldest in-flight step.  With pipelining on, the collected outputs
        belong to a step whose successor is already running on the
        device; per-sequence greedy token streams are identical to
        classic synchronous stepping."""
        self.dispatch()
        return self.collect()

    def has_pending(self) -> bool:
        """A dispatched step is awaiting collection."""
        return bool(self._pending)

    # stackcheck: root=step-thread
    def dispatch(self) -> bool:
        """Launch device work without reading anything back, filling the
        pipeline to its depth (2 with lookahead, 1 otherwise).  Returns
        True when at least one step was dispatched."""
        depth = 2 if self._pipeline_enabled else 1
        launched = False
        while len(self._pending) < depth:
            ok = (
                self._dispatch_lookahead()
                if self._pending
                else self._dispatch_front()
            )
            if not ok:
                break
            launched = True
        return launched

    # stackcheck: root=step-thread
    def collect(self) -> List[StepOutput]:
        """Block on the oldest dispatched step and finalize it: append
        sampled tokens, run finish checks, and roll back rows whose
        sequence finished while the step was in flight (their token is a
        discarded overrun — vLLM multi-step semantics)."""
        if not self._pending:
            return []
        t0 = time.time()
        p = self._pending.popleft()
        if p.outputs is not None:
            outputs = p.outputs
        elif p.steps is not None:
            # stackcheck: allow=SC201 reason=t0 only stamps the obs collect-phase histogram inside _collect_window; no plan state reads it
            outputs = self._collect_window(p, t0)
        else:
            arr = np.asarray(p.sampled)  # the ONE device sync point
            if self.obs.enabled:
                self.obs.step_phase("collect", time.time() - t0)
            t_post = time.time()
            live = [
                (i, s) for i, s in enumerate(p.seqs) if not s.is_finished
            ]
            outputs = self._append_and_check(
                [s for _, s in live],
                [int(arr[i]) for i, _ in live],
                first_token=False,
            )
            if self.obs.enabled:
                self.obs.step_phase("sample", time.time() - t_post)
            if p.rec is not None:
                # Sample-side jits (penalties/argmax/logprobs) ran inside
                # _append_and_check: drain any compiles onto this record,
                # then complete it.  Rows whose sequence finished while
                # the step flew sampled a discarded overrun token.
                self._note_compiles([s.seq_id for s in p.seqs], p.rec)
                self.obs.recorder.on_collect(
                    p.rec, host_s=p.host_s,
                    tokens_emitted=len(p.seqs),
                    tokens_delivered=len(live),
                    tokens_wasted=len(p.seqs) - len(live),
                )
        if p.outputs is None:
            # Drop in-flight successors whose every row has now finished:
            # pure overrun steps produce no outputs and must not wedge
            # the pipeline when the engine drains.  (For windows this is
            # the host side of the all-finished predicate: the device
            # carry's rows are all frozen no-ops, so the successor is
            # discarded without a second sync.)  A MIXED window is never
            # droppable this way: its chunk head is not a decode row, so
            # "every row finished" says nothing about the chunk schedule
            # — dropping it would skip the final chunk's first-token
            # finalization (and the chunk/waste accounting) for a prompt
            # whose KV the device already wrote.
            while (
                self._pending
                and self._pending[0].sampled is not None
                and self._pending[0].chunk_sched is None
                and all(s.is_finished for s in self._pending[0].seqs)
            ):
                d = self._pending.popleft()
                if d.rec is not None:
                    # Complete the dropped overrun's record so every
                    # dispatched window appears exactly once: a plain
                    # window's rows are all frozen (the device emitted
                    # nothing), a single step sampled one discarded
                    # token per row.
                    n = 0 if d.steps is not None else len(d.seqs)
                    self.obs.recorder.on_collect(
                        d.rec, host_s=d.host_s,
                        tokens_emitted=n, tokens_wasted=n,
                    )
            if self.obs.enabled:
                # Only pipelined steps have a pure-dispatch host_s: a
                # synchronous step's host_s fuses array build, blocking
                # device compute and sampling, and attributing THAT to
                # "dispatch" would point slow-step debugging at H2D work
                # when the time was device compute.  Sync steps feed only
                # the schedule phase; the dispatch/collect/sample split
                # covers the steady-state pipelined decode path.
                self.obs.step_phase("dispatch", p.host_s)
        now = time.time()
        self._last_decode_end = now if p.is_decode else None
        busy = (now - t0) + p.host_s
        self._step_time_accum += busy
        self._busy_window.append((now, busy))
        cutoff = now - self._busy_window_s
        # stackcheck: allow=SC201 reason=duty-cycle window trim; feeds the tpu:duty_cycle metric only, never a plan (replicas may report different utilization, they may not schedule differently)
        self._busy_window = [(t, d) for (t, d) in self._busy_window if t > cutoff]
        return outputs

    def _dispatch_front(self) -> bool:
        """Dispatch with nothing in flight: full scheduler knowledge
        (admission, preemption, partial-prefill rollback) — the only
        place synchronous plans run."""
        # Land completed remote-prefix prefetches in the prefix cache
        # BEFORE planning, so this very schedule()'s match_prefix can
        # serve them (copy-in is an async device dispatch, not a wait).
        self._drain_prefetched()
        t0 = time.time()
        plan = self.scheduler.schedule()
        if self.obs.enabled:
            self.obs.step_phase("schedule", time.time() - t0)
        if plan.is_empty:
            # Nothing schedulable.  If that is because the async transfer
            # plane is mid-flight (a restore page-in or offload stage the
            # scheduler answered "retry" for), yield a tick so a tight
            # caller loop doesn't busy-spin through its step budget
            # faster than the worker threads can land the bytes.  The
            # device is idle here — this is backoff, not a data wait.
            if self._transfer_inflight():
                # stackcheck: allow=SC101 reason=1ms idle backoff while async transfers land; the device is idle here by definition (nothing scheduled) so this is pacing, not a data wait
                time.sleep(0.001)
            return False
        if plan.window_fallback:
            # A waiting head forced K=1 stepping (the mixed-window path
            # could not serve it): the forfeited amortization is
            # visible, like every other window fallback reason.
            self.multistep_fallback[plan.window_fallback] = (
                self.multistep_fallback.get(plan.window_fallback, 0) + 1
            )
        if plan.decode is None:
            outputs = self._run_prefill(plan.prefill_chunk)
            self._step_counter += 1
            # stackcheck: allow=SC201 reason=host_s is a stats field (host-gap metric); no plan state reads it
            host_s = time.time() - t0
            if self.obs.enabled:
                cp = plan.prefill_chunk
                rec = self.obs.recorder.on_dispatch(
                    "prefill", k=1, rows=0, seq_ids=(cp.seq.seq_id,),
                    chunk_prompts=1,
                    chunk_tokens_planned=cp.num_new_tokens,
                    fallback=plan.window_fallback, now=t0,
                )
                self._note_compiles((cp.seq.seq_id,), rec)
                self.obs.recorder.on_collect(
                    rec, host_s=host_s,
                    tokens_emitted=len(outputs),
                    tokens_delivered=len(outputs),
                    chunk_tokens_delivered=cp.num_new_tokens,
                )
            # stackcheck: allow=SC201 reason=host_s is a stats field (host-gap metric); no plan state reads it
            self._pending.append(_PendingStep(outputs=outputs, host_s=host_s))
            return True
        if plan.chunk_schedule is not None:
            # Mixed K-step window: the head prompt's chunks ride the
            # decode scan (chunk cursor carried in-graph); the final
            # chunk's first token is sampled at collect through the K=1
            # finalize path.
            self._pending.append(
                self._dispatch_mixed_window(plan, chain_from=None)
            )
            return True
        if plan.prefill_chunk is not None:
            # Fused decode+prefill-chunk step: synchronous (the chunk's
            # admission/finalization needs collected state), so the
            # lookahead pipeline pauses for the step and resumes on the
            # next pure-decode plan.
            gap = self._recorder_gap(t0) if self.obs.enabled else 0.0
            outputs = self._run_mixed(plan)
            self._step_counter += 1
            # stackcheck: allow=SC201 reason=host_s is a stats field (host-gap metric); no plan state reads it
            host_s = time.time() - t0
            if self.obs.enabled:
                cp = plan.prefill_chunk
                sids = tuple(s.seq_id for s in plan.decode.seqs) + (
                    cp.seq.seq_id,
                )
                rec = self.obs.recorder.on_dispatch(
                    "mixed", k=1, rows=len(plan.decode.seqs),
                    seq_ids=sids, chunk_prompts=1,
                    chunk_tokens_planned=cp.num_new_tokens,
                    fallback=plan.window_fallback, host_gap_s=gap, now=t0,
                )
                self._note_compiles(sids, rec)
                self.obs.recorder.on_collect(
                    rec, host_s=host_s,
                    tokens_emitted=len(outputs),
                    tokens_delivered=len(outputs),
                    chunk_tokens_delivered=cp.num_new_tokens,
                )
            # stackcheck: allow=SC201 reason=host_s is a stats field (host-gap metric); no plan state reads it
            self._pending.append(_PendingStep(
                outputs=outputs, is_decode=True, host_s=host_s,
            ))
            return True
        seqs = plan.decode.seqs
        if plan.decode_window > 1 and self._can_window(seqs):
            self._pending.append(self._dispatch_window(plan, chain_from=None))
            return True
        # A K>1 plan that fell out of the window path carries the decline
        # reason onto the replacing K=1 dispatch's flight record.
        decline = plan.window_fallback or (
            self._last_window_decline if plan.decode_window > 1 else None
        )
        if self._can_pipeline(seqs):
            p = self._dispatch_decode_async(seqs, False)
            if p.rec is not None and decline:
                p.rec.fallback = decline
            self._pending.append(p)
        else:
            gap = self._recorder_gap(t0) if self.obs.enabled else 0.0
            outputs = self._run_decode(plan.decode)
            self._step_counter += 1
            # stackcheck: allow=SC201 reason=host_s is a stats field (host-gap metric); no plan state reads it
            host_s = time.time() - t0
            if self.obs.enabled:
                sids = tuple(s.seq_id for s in seqs)
                rec = self.obs.recorder.on_dispatch(
                    "decode", k=1, rows=len(seqs), seq_ids=sids,
                    fallback=decline, host_gap_s=gap, now=t0,
                )
                self._note_compiles(sids, rec)
                self.obs.recorder.on_collect(
                    rec, host_s=host_s,
                    tokens_emitted=len(seqs),
                    tokens_delivered=len(outputs),
                )
            # stackcheck: allow=SC201 reason=host_s is a stats field (host-gap metric); no plan state reads it
            self._pending.append(_PendingStep(
                outputs=outputs, is_decode=True, host_s=host_s,
            ))
        return True

    def _dispatch_lookahead(self) -> bool:
        """Provisionally dispatch decode N+1 while N is still in flight.
        The scheduler plans under the optimistic no-finish assumption
        (rolling back at collect when wrong); inputs chain from N's
        device-resident sample — the [S] in-flight token for single
        steps, the whole window carry (tokens/positions/done/penalty
        state) for K-step windows — so no host sync separates them."""
        if not self._pipeline_enabled:
            return False
        prev = self._pending[-1]
        if prev.sampled is None:
            return False  # only pipelined decode steps chain
        if prev.win_state is not None:
            t0 = time.time()
            plan = self.scheduler.schedule_provisional_window(
                prev.seqs, prev.steps
            )
            if self.obs.enabled:
                self.obs.step_phase("schedule", time.time() - t0)
            if plan is None:
                return False
            if plan.chunk_schedule is not None:
                # A waiting head's chunks chain onto the in-flight
                # carry as a mixed window — the pipeline never drains
                # through the admission.
                self._pending.append(
                    self._dispatch_mixed_window(plan, chain_from=prev)
                )
                return True
            self._pending.append(self._dispatch_window(plan, chain_from=prev))
            return True
        if not self._can_pipeline(prev.seqs):
            return False
        t0 = time.time()
        plan = self.scheduler.schedule_provisional(prev.seqs)
        if self.obs.enabled:
            self.obs.step_phase("schedule", time.time() - t0)
        if plan is None:
            return False
        self._pending.append(
            self._dispatch_decode_async(plan.seqs, True, prev.sampled)
        )
        return True

    # Host-state verdicts are cached per-sequence at admission instead of
    # re-reading SamplingParams attribute chains in a Python loop on the
    # step thread every dispatch.  Two static verdicts (they never change
    # over a request's life) plus ONE dynamic bit — the pending
    # min_tokens floor — which _append_and_check clears exactly once at
    # the boundary crossing.
    # (window_fallback, classic_fallback, greedy) cached verdicts — the
    # taxonomy itself moved to sequence.host_state_flags so the
    # scheduler's mixed-window planner reads the SAME verdicts the
    # dispatch gates below do (it must never plan a K-step mixed window
    # the engine would have to fall back out of).
    _host_state_flags = staticmethod(seq_host_state_flags)

    def _batch_uses_host_state(self, seqs: List[Sequence]) -> bool:
        """True when any sequence needs host-visible per-token state the
        K-step window cannot reproduce on-device (logprobs, logit_bias,
        guided decoding).  The ONE fallback gate for the window fast
        path; each reason is counted in tpu:multistep_fallback_total —
        a single such request de-optimizes every co-scheduled stream,
        and that used to be invisible."""
        return any(self._host_state_flags(s)[0] for s in seqs)

    def _can_window(self, seqs: List[Sequence]) -> bool:
        """K-step windows serve everything except host-sampled features;
        a fallback is observable, never silent."""
        self._last_window_decline = None
        if self._window_fn is None:
            return False
        if not self._batch_uses_host_state(seqs):
            return True
        # One increment per DISTINCT reason per dispatch (the registered
        # unit is fallback dispatches, not offending sequences — three
        # co-scheduled logprobs requests are still ONE de-optimized
        # dispatch).
        reasons = set()
        for s in seqs:
            if self._host_state_flags(s)[0]:
                sp = s.sampling_params
                reasons.add(
                    "logprobs" if sp.logprobs
                    else "logit_bias" if sp.logit_bias
                    else "guided"
                )
        for reason in reasons:
            self.multistep_fallback[reason] = (
                self.multistep_fallback.get(reason, 0) + 1
            )
        # Remembered for the flight record of the K=1 dispatch that
        # replaces the declined window (deterministic pick when several
        # reasons coincide).
        self._last_window_decline = min(reasons) if reasons else None
        return False

    def _can_pipeline(self, seqs: List[Sequence]) -> bool:
        """Single-step pipelined decode covers the common fast path
        only: its on-device sampler has no penalty/floor path, so
        penalty batches and pending min_tokens floors ALSO drop to the
        classic synchronous path per step (K-step windows serve those
        on-device)."""
        return self._pipeline_enabled and not any(
            self._host_state_flags(s)[1] or s._min_tok_pending
            for s in seqs
        )

    def _recorder_gap(self, t0: float) -> float:
        """Host gap this dispatch inherited from the previous window
        (device idle since the last decode retired), stamped onto the
        flight record so a stalled window's timeline shows WHERE the
        stall was.  Read before the launch bookkeeping clears it."""
        last = self._last_decode_end
        return max(0.0, t0 - last) if last is not None else 0.0

    def _note_compiles(self, seq_ids, rec=None) -> None:
        """Drain XLA compile events fired inside the jit calls this
        dispatch just made and attribute them: the window flight record
        goes compile-tainted and every co-scheduled request's trace is
        tagged compile=true (the compile-excluded-TTFT separator)."""
        if not self.obs.enabled:
            return
        self.obs.on_compile(
            seq_ids, self.obs.compile_tracker.drain_events(), rec
        )

    def _note_decode_launch(self) -> None:
        """Host-gap bookkeeping: time since the previous decode step
        retired with the device left idle.  Lookahead dispatches count a
        zero gap by construction (the device was still busy)."""
        if self._last_decode_end is not None:
            # stackcheck: allow=SC201 reason=gap bookkeeping feeds tpu:decode_host_gap_ms only; no plan state reads it
            self._gap_total_s += max(0.0, time.time() - self._last_decode_end)
            self._gap_steps += 1
        self._last_decode_end = None

    def _dispatch_decode_async(
        self, seqs: List[Sequence], lookahead: bool, prev_sampled=None
    ) -> _PendingStep:
        """Enqueue one decode+sample step on the device and return
        without any host round-trip.  ``lookahead=False`` (re)builds the
        device-resident batch state from host bookkeeping (one packed
        [11, S] transfer + the block tables); ``lookahead=True`` is the
        steady "same batch, +1 token" path (one packed [4, S] delta,
        tokens chained from the in-flight sample)."""
        t0 = time.time()
        gap = self._recorder_gap(t0) if self.obs.enabled else 0.0
        # Rebuilds pad to the decode batch-size bucket; lookahead steps
        # reuse the device-resident state, whose row count is by
        # construction the same bucket (identical running set).
        S = (
            self._decode_bucket(len(seqs))
            if not lookahead
            else self._pipe_tables.shape[0]
        )

        if not lookahead:
            (tokens, positions, tables, ctx_lens, slot_blocks,
             slot_offsets) = self._decode_batch_arrays(seqs, S)
            adapter = np.zeros((S,), np.int32)
            for i, seq in enumerate(seqs):
                adapter[i] = seq.adapter_idx
            temps, top_ps, top_ks, min_ps, seeds = self._sampling_arrays(
                seqs, S
            )
            packed = np.stack([
                tokens, positions, ctx_lens, slot_blocks, slot_offsets,
                temps.view(np.int32), top_ps.view(np.int32), top_ks,
                min_ps.view(np.int32), seeds, adapter,
            ])
            st = self._pipe_unpack_fn(
                self._put(packed, P(None, AXES.DP)),
                self._put(tables, P(AXES.DP, None)),
            )
            self._pipe_sampling = (
                st["temps"], st["top_ps"], st["top_ks"], st["min_ps"],
                st["seeds"],
            )
            self._pipe_adapter = st["adapter"]
            self._pipe_table_lens = [len(s.block_table) for s in seqs]
        else:
            positions = np.zeros((S,), np.int32)
            ctx_lens = np.zeros((S,), np.int32)
            cols = np.full((S,), -1, np.int32)
            vals = np.zeros((S,), np.int32)
            for i, seq in enumerate(seqs):
                pos = seq.num_tokens  # consumes the in-flight token
                positions[i] = pos
                ctx_lens[i] = pos + 1
                have = self._pipe_table_lens[i]
                if len(seq.block_table) > have:
                    # schedule_provisional grows by at most one block.
                    cols[i] = have
                    vals[i] = seq.block_table[have]
                    self._pipe_table_lens[i] = have + 1
            packed = np.stack([positions, ctx_lens, cols, vals])
            st = self._pipe_advance_fn(
                self._put(packed, P(None, AXES.DP)),
                prev_sampled,
                self._pipe_tables,
            )
        self._pipe_tables = st["tables"]

        lora_kwargs = {}
        if self.lora_registry is not None:
            lora_kwargs = {
                "lora": self.lora_registry.params,
                "adapter_idx": self._pipe_adapter,
            }
        if lookahead:
            self._gap_steps += 1  # device busy: zero gap by construction
            self._last_decode_end = None
        else:
            self._note_decode_launch()
        logits, self.kv_caches = self._decode_fn(
            self.params,
            tokens=st["tokens"],
            positions=st["positions"],
            block_tables=st["tables"],
            ctx_lens=st["ctx_lens"],
            slot_block_ids=st["slot_blocks"],
            slot_offsets=st["slot_offsets"],
            kv_caches=self.kv_caches,
            **lora_kwargs,
        )
        temps, top_ps, top_ks, min_ps, seeds = self._pipe_sampling
        step_key = jax.random.PRNGKey(self.config.seed + self._step_counter)
        sampled = self._sample_fn(
            logits, temps, top_ps, top_ks, step_key, seeds, min_p=min_ps,
        )
        self._step_counter += 1
        rec = None
        if self.obs.enabled:
            sids = tuple(s.seq_id for s in seqs)
            rec = self.obs.recorder.on_dispatch(
                "decode", k=1, rows=len(seqs), seq_ids=sids,
                provisional=lookahead, host_gap_s=gap, now=t0,
            )
            self._note_compiles(sids, rec)
        # stackcheck: allow=SC201 reason=host_s is a stats field (host-gap metric); no plan state reads it
        return _PendingStep(
            seqs=list(seqs), sampled=sampled, is_decode=True,
            host_s=time.time() - t0, rec=rec,
        )

    # -- K-step device-resident decode windows -----------------------------

    @staticmethod
    def _pow2_bucket(n: int, floor: int) -> int:
        """Shared shape-bucketing for the window's token/stop-id arrays:
        XLA compiles O(log) variants, not one per length."""
        b = floor
        while b < n:
            b *= 2
        return b

    def _stop_set_ids(self, seq: Sequence) -> tuple:
        """THE per-sequence stop set: ``stop_token_ids`` plus EOS unless
        ``ignore_eos`` — what ends generation at sampling time, and
        (vLLM min_tokens semantics) exactly the set the unmet min_tokens
        floor suppresses.  Shared by the window's device stop-mask and
        the host path's min_tokens logit ban so the two can never
        diverge.  Out-of-vocab ids can never be sampled and are dropped
        (this also keeps both the device scatter and the host bias
        matrix in bounds)."""
        sp = seq.sampling_params
        V = self.config.model.vocab_size
        ids = [t for t in (sp.stop_token_ids or ()) if 0 <= t < V]
        eos = self.tokenizer.eos_token_id
        if eos is not None and not sp.ignore_eos:
            ids.append(eos)
        return tuple(sorted(set(ids)))

    def _window_host_state(self, seqs: List[Sequence], steps: List[int]):
        """Host arrays + static flags for a window batch (re)build."""
        S = self._decode_bucket(len(seqs))
        (tokens, positions, tables, ctx_lens, _sb, _so) = (
            self._decode_batch_arrays(seqs, S)
        )
        max_steps = np.zeros((S,), np.int32)
        max_steps[: len(seqs)] = steps
        done = np.ones((S,), bool)
        done[: len(seqs)] = False
        pad = S - len(seqs)
        min_left = np.array(
            [
                max(0, s.sampling_params.min_tokens
                    - len(s.output_token_ids))
                for s in seqs
            ] + [0] * pad,
            np.int32,
        )
        presence = np.array(
            [s.sampling_params.presence_penalty for s in seqs] + [0.0] * pad,
            np.float32,
        )
        frequency = np.array(
            [s.sampling_params.frequency_penalty for s in seqs] + [0.0] * pad,
            np.float32,
        )
        repetition = np.array(
            [s.sampling_params.repetition_penalty for s in seqs]
            + [1.0] * pad,
            np.float32,
        )
        stop_lists = [self._stop_set_ids(s) for s in seqs]
        B = self._pow2_bucket(
            max([len(ids) for ids in stop_lists] + [1]), 1
        )
        stop_ids = np.full((S, B), -1, np.int32)
        for i, ids in enumerate(stop_lists):
            stop_ids[i, : len(ids)] = ids
        use_penalties = bool(
            np.any(presence) or np.any(frequency) or np.any(repetition != 1.0)
        )
        use_min_floor = bool(np.any(min_left > 0))
        return {
            "S": S, "tokens": tokens, "positions": positions,
            "tables": tables, "ctx_lens": ctx_lens,
            "max_steps": max_steps, "done": done, "min_left": min_left,
            "presence": presence, "frequency": frequency,
            "repetition": repetition, "stop_ids": stop_ids,
            "use_penalties": use_penalties, "use_min_floor": use_min_floor,
        }

    def _window_build(self, seqs: List[Sequence], steps: List[int]) -> dict:
        """Full batch (re)build: transfer every window input to the
        device and construct the occurrence state the penalty math
        reads.  Runs once per batch composition; steady-state windows
        chain through _window_chain's delta transfer instead."""
        h = self._window_host_state(seqs, steps)
        S = h["S"]
        batch_spec = shardings_lib.decode_batch_spec()
        row_spec = P(AXES.DP, None)
        temps, top_ps, top_ks, min_ps, seeds = self._sampling_arrays(seqs, S)
        state = {
            "tokens": self._put(h["tokens"], batch_spec),
            "positions": self._put(h["positions"], batch_spec),
            "ctx_lens": self._put(h["ctx_lens"], batch_spec),
            "done": self._put(h["done"], batch_spec),
            "min_left": self._put(h["min_left"], batch_spec),
            "tables": self._put(h["tables"], row_spec),
            "max_steps": self._put(h["max_steps"], batch_spec),
            "temps": self._put(temps, batch_spec),
            "top_ps": self._put(top_ps, batch_spec),
            "top_ks": self._put(top_ks, batch_spec),
            "min_ps": self._put(min_ps, batch_spec),
            "seeds": self._put(seeds, batch_spec),
            "stop_ids": self._put(h["stop_ids"], row_spec),
            "presence": self._put(h["presence"], batch_spec),
            "frequency": self._put(h["frequency"], batch_spec),
            "repetition": self._put(h["repetition"], batch_spec),
            "use_penalties": h["use_penalties"],
            "use_min_floor": h["use_min_floor"],
        }
        if h["use_penalties"]:
            # Device-resident occurrence state, built by scatter from
            # the bucketed [S, L] id arrays (same content as the host
            # path's arrays, so penalty values are bit-identical).
            L = self._pow2_bucket(
                max([len(s.output_token_ids) for s in seqs] + [1]), 64
            )
            out_tokens = np.full((S, L), -1, np.int32)
            for i, s in enumerate(seqs):
                ids = s.output_token_ids[-L:]
                out_tokens[i, : len(ids)] = ids
            Lc = self._pow2_bucket(
                max(len(s.all_token_ids) for s in seqs), 64
            )
            ctx_tokens = np.full((S, Lc), -1, np.int32)
            for i, s in enumerate(seqs):
                ids = s.all_token_ids[-Lc:]
                ctx_tokens[i, : len(ids)] = ids
            counts, seen = self._win_occurrence_fn(
                self._put(out_tokens, row_spec),
                self._put(ctx_tokens, row_spec),
            )
        else:
            counts = self._put(np.zeros((S, 1), np.int16), row_spec)
            seen = self._put(np.zeros((S, 1), bool), row_spec)
        state["counts"] = counts
        state["seen"] = seen
        if self._spec_window_fn is not None:
            # Carried drafting history for the fused speculative window:
            # the last H tokens (prompt + generated), left -1-padded so
            # hist[:, -1] is always the committed last token.  The scan
            # appends accepted tokens on-device; only a batch rebuild
            # retransfers it.
            H = self._SPEC_HIST_WINDOW
            hist = np.full((S, H), -1, np.int32)
            for i, s in enumerate(seqs):
                ids = s.all_token_ids[-H:]
                hist[i, H - len(ids):] = ids
            state["hist"] = self._put(hist, row_spec)
        if self.draft_block_pool is not None:
            # Model drafter: per-row draft-KV block tables from the
            # DEDICATED pool (static [S, Bd] width — the draft cache is
            # compact, so the table never grows mid-chain).  A rebuild
            # frees the previous batch's allocation wholesale and
            # re-allocates: any preempted / aborted / restored
            # sequence's draft KV is structurally reset (the draft
            # cache is rebuilt from `hist` by the next in-graph prime —
            # nothing stale can survive a batch change, and draft
            # writes never touch self.kv_caches at all).  Allocation
            # failure (an undersized explicit pool) declines this
            # batch's windows to plain — counted per declined dispatch
            # under tpu:multistep_fallback_total{reason=draft_pool},
            # never a stall.
            self._draft_primed = False
            if self._draft_block_alloc:
                self.draft_block_pool.free(self._draft_block_alloc)
                self._draft_block_alloc = []
            bd = self._draft_blocks_per_row
            need = len(seqs) * bd
            if self.draft_block_pool.can_allocate(need):
                blocks = self.draft_block_pool.allocate(need)
                self._draft_block_alloc = blocks
                dt = np.zeros((S, bd), np.int32)
                for i in range(len(seqs)):
                    dt[i] = blocks[i * bd:(i + 1) * bd]
                state["draft_tables"] = self._put(dt, row_spec)
                state["draft_pos"] = self._put(
                    np.zeros((S,), np.int32), batch_spec
                )
        if self.lora_registry is not None:
            adapter = np.zeros((S,), np.int32)
            for i, seq in enumerate(seqs):
                adapter[i] = seq.adapter_idx
            state["adapter"] = self._put(adapter, batch_spec)
        self._win_table_lens = [len(s.block_table) for s in seqs]
        return state

    def _window_chain(self, prev: _PendingStep, seqs: List[Sequence],
                      steps: List[int]) -> dict:
        """Steady path: window N+1's state IS window N's still-in-flight
        device carry — tokens/positions/done/penalty state never touch
        the host.  Only the per-window budget and up to C new block-table
        columns per row transfer."""
        state = dict(prev.win_state)
        S = state["max_steps"].shape[0]
        batch_spec = shardings_lib.decode_batch_spec()
        max_steps = np.zeros((S,), np.int32)
        max_steps[: len(steps)] = steps
        state["max_steps"] = self._put(max_steps, batch_spec)
        # Fixed delta width: retraces would otherwise key on how many
        # blocks happened to be crossed this window.  Sized for the
        # MAX-ACCEPTANCE growth — a fused speculative window can land
        # K x (ngram + 1) tokens, not K.
        C = self._window_max_tokens // self.block_pool.block_size + 2
        cols = np.full((S, C), -1, np.int32)
        vals = np.zeros((S, C), np.int32)
        for i, seq in enumerate(seqs):
            have = self._win_table_lens[i]
            new = seq.block_table[have:]
            for j, blk in enumerate(new[:C]):
                cols[i, j] = have + j
                vals[i, j] = blk
            self._win_table_lens[i] = have + len(new[:C])
        state["tables"] = self._win_advance_fn(
            state["tables"],
            self._put(cols, P(AXES.DP, None)),
            self._put(vals, P(AXES.DP, None)),
        )
        return state

    # stackcheck: root=step-thread
    def _dispatch_window(self, plan, chain_from: Optional[_PendingStep] = None
                         ) -> _PendingStep:
        """Enqueue one K-step decode window on the device and return
        without any host round-trip.  ``chain_from=None`` (re)builds the
        device-resident window state from host bookkeeping;  otherwise
        the state chains from the previous window's in-flight carry
        (pipelined windows — the device never drains between them)."""
        t0 = time.time()
        decode = plan.decode
        seqs = decode.seqs
        gap = self._recorder_gap(t0) if self.obs.enabled else 0.0
        if chain_from is None:
            state = self._window_build(seqs, decode.steps)
            self._note_decode_launch()
        else:
            state = self._window_chain(chain_from, seqs, decode.steps)
            self._gap_steps += 1  # device busy: zero gap by construction
            self._last_decode_end = None
        lora_kwargs = {}
        if self.lora_registry is not None:
            lora_kwargs = {
                "lora": self.lora_registry.params,
                "adapter_idx": state["adapter"],
            }
        # The fused speculative window drafts only for all-greedy
        # batches (acceptance compares the model's own argmax); a batch
        # with sampled rows runs the PLAIN window below with the classic
        # per-iteration key schedule, so seeded streams stay
        # bit-identical across window sizes with speculation configured.
        spec_stats = None
        spec_drafter = None
        use_spec = self._spec_window_fn is not None and all(
            self._host_state_flags(s)[2] for s in seqs
        )
        if use_spec and self.draft_params is not None and (
            "draft_tables" not in state
        ):
            # Model drafter configured but this batch's build could not
            # allocate draft blocks (undersized explicit pool): decline
            # to the plain window — observable, never a stall.  One
            # increment per declined dispatch, matching the _can_window
            # counting unit.
            use_spec = False
            self.multistep_fallback["draft_pool"] = (
                self.multistep_fallback.get("draft_pool", 0) + 1
            )
        if use_spec:
            spec_kwargs = {}
            if self.draft_params is not None:
                spec_drafter = "model"
                # Skip-prime chaining: re-prime the draft cache in-graph
                # on the first model-spec window after any break in the
                # chain (batch rebuild, plain/mixed dispatch) and every
                # _DRAFT_PRIME_CHAIN windows (capacity watermark: a
                # primed cache holds <= H-1 slots and each window adds
                # <= window_max_tokens; the pool sizes exactly that
                # chain).
                do_prime = (
                    not self._draft_primed
                    or self._draft_windows_since_prime
                    >= self._DRAFT_PRIME_CHAIN
                )
                spec_kwargs = {
                    "draft_params": self.draft_params,
                    "draft_tables": state["draft_tables"],
                    "draft_pos": state["draft_pos"],
                    "draft_kv": self.draft_kv_caches,
                    "do_prime": do_prime,
                }
            else:
                spec_drafter = "ngram"
            out = self._spec_window_fn(
                self.params,
                tokens=state["tokens"],
                positions=state["positions"],
                ctx_lens=state["ctx_lens"],
                done=state["done"],
                min_left=state["min_left"],
                block_tables=state["tables"],
                max_steps=state["max_steps"],
                kv_caches=self.kv_caches,
                stop_ids=state["stop_ids"],
                counts=state["counts"],
                seen=state["seen"],
                hist=state["hist"],
                presence=state["presence"],
                frequency=state["frequency"],
                repetition=state["repetition"],
                use_penalties=state["use_penalties"],
                use_min_floor=state["use_min_floor"],
                **spec_kwargs,
                **lora_kwargs,
            )
            if spec_drafter == "model":
                (emitted, drafted, accepted, out_state, self.kv_caches,
                 self.draft_kv_caches) = out
                self._draft_windows_since_prime = (
                    0 if do_prime else self._draft_windows_since_prime + 1
                )
                self._draft_primed = True
            else:
                emitted, drafted, accepted, out_state, self.kv_caches = out
            spec_stats = (drafted, accepted)
            # Greedy argmax consumes no PRNG ordinals; the counter still
            # advances one per iteration (deterministic on every
            # lockstep replica — acceptance is a pure function of the
            # shared weights and carried state, never of wall clock).
            self._step_counter += self._window_steps
        else:
            # Any non-model-spec dispatch advances positions without
            # extending the draft KV: the chain is broken and the next
            # model-spec window must re-prime from `hist`.
            self._draft_primed = False
            emitted, out_state, self.kv_caches = self._window_fn(
                self.params,
                tokens=state["tokens"],
                positions=state["positions"],
                ctx_lens=state["ctx_lens"],
                done=state["done"],
                min_left=state["min_left"],
                block_tables=state["tables"],
                max_steps=state["max_steps"],
                kv_caches=self.kv_caches,
                temps=state["temps"],
                top_ps=state["top_ps"],
                top_ks=state["top_ks"],
                min_ps=state["min_ps"],
                seq_seeds=state["seeds"],
                stop_ids=state["stop_ids"],
                # Masked to 31 bits: a long-lived engine's monotone step
                # counter would otherwise overflow the host->int32 cast
                # and kill the step thread.  Below 2**31 key ordinals
                # (years of serving) the schedule is bit-identical to
                # single-token stepping; past it, +t wraps in-graph,
                # which PRNGKey treats as bits — still deterministic
                # across lockstep replicas.
                key_base=jnp.int32(
                    (self.config.seed + self._step_counter) & 0x7FFFFFFF
                ),
                counts=state["counts"],
                seen=state["seen"],
                presence=state["presence"],
                frequency=state["frequency"],
                repetition=state["repetition"],
                use_penalties=state["use_penalties"],
                use_min_floor=state["use_min_floor"],
                **lora_kwargs,
            )
            # One key ordinal per iteration: single-token stepping would
            # have burned exactly these counter values for the same
            # tokens.
            self._step_counter += self._window_steps
        state.update(out_state)
        rec = None
        if self.obs.enabled:
            depth = 0
            if chain_from is not None and chain_from.rec is not None:
                depth = chain_from.rec.chain_depth + 1
            sids = tuple(s.seq_id for s in seqs)
            rec = self.obs.recorder.on_dispatch(
                "spec" if spec_stats is not None else "decode",
                k=self._window_steps, rows=len(seqs), seq_ids=sids,
                chain_depth=depth, provisional=chain_from is not None,
                spec_width=(
                    self.config.scheduler.spec_draft_len
                    if spec_stats is not None else 0
                ),
                drafter=spec_drafter or "",
                fallback=plan.window_fallback, host_gap_s=gap, now=t0,
            )
            self._note_compiles(sids, rec)
        # stackcheck: allow=SC201 reason=host_s is a stats field (host-gap metric); no plan state reads it
        return _PendingStep(
            seqs=list(seqs), sampled=emitted, is_decode=True,
            host_s=time.time() - t0, steps=list(decode.steps),
            win_state=state, spec_stats=spec_stats,
            spec_drafter=spec_drafter, rec=rec,
        )

    # stackcheck: root=step-thread
    def _dispatch_mixed_window(
        self, plan, chain_from: Optional[_PendingStep] = None
    ) -> _PendingStep:
        """Enqueue one MIXED K-step window: each of the
        K = len(plan.chunk_schedule) scan iterations runs the packed
        [decode + chunk] mixed forward — decode rows advance from the
        carried state exactly like ``_dispatch_window`` while prompt
        chunks ride the same forward, each iteration's cursor
        (cached_len / valid_len / new-block row / prefix table /
        adapter slot) precomputed per iteration and carried as scan xs.
        Packed windows (multi_prompt_window) interleave cursors from
        SEVERAL prompts: a final chunk's iteration f finalizes its
        prompt at collect with PRNG ordinal base+f, and the next
        iteration's xs switch to the next prompt's tokens and block
        tables — the per-iteration prefix table is what makes the
        ragged hand-off transparent to the model fn.  ``chain_from``
        chains the decode carry from the previous window (pure or
        mixed) with no host round-trip; the chunk arrays are fresh per
        window either way, staged through double-buffered host arrays
        (two alternating sets per scan shape) so building window N+1's
        H2D payload never waits on window N's still-draining copy —
        time spent staging while the device is busy is counted in
        ``tpu:window_transfer_overlap_seconds_total``.  The scan length
        is the next power of two >= K (a static compile bucket —
        trailing iterations are no-ops frozen by ``max_steps`` and a
        zero-valid chunk row)."""
        t0 = time.time()
        decode = plan.decode
        seqs = decode.seqs
        sched = plan.chunk_schedule
        k_eff = len(sched)
        n_scan = self._pow2_bucket(k_eff, 1)
        gap = self._recorder_gap(t0) if self.obs.enabled else 0.0
        if self.obs.enabled:
            for cp in sched:
                if cp.seq.first_scheduled_time is None:
                    cp.seq.first_scheduled_time = t0
                    self.obs.on_first_scheduled(cp.seq, t0)
        if chain_from is None:
            state = self._window_build(seqs, decode.steps)
            self._note_decode_launch()
        else:
            state = self._window_chain(chain_from, seqs, decode.steps)
            self._gap_steps += 1  # device busy: zero gap by construction
            self._last_decode_end = None
        # Mixed windows keep `hist` warm but advance positions without
        # extending the draft KV (drafting is a pure-decode-window
        # feature): the model drafter's skip-prime chain is broken and
        # the next model-spec window re-primes from the warm hist.
        self._draft_primed = False

        # Per-iteration chunk schedule (host-precomputed, rides as scan
        # xs).  All chunks share ONE bucket T (static scan shape); dead
        # pow-2 padding iterations carry valid_len 0, new blocks parked
        # on null block 0, and the last chunk's END cursor as cached_len
        # (their masked rows compute garbage that lands only on the null
        # block, exactly like frozen decode rows).
        t_stage = time.time()
        bs = self.block_pool.block_size
        T = sched[0].bucket_len
        pmax = max(self._bmax, 1)
        stage = self._mw_stage.get((n_scan, T))
        if stage is None:
            mk = lambda: {  # noqa: E731
                "tokens": np.zeros((n_scan, T), np.int32),
                "cached": np.zeros((n_scan,), np.int32),
                "valid": np.zeros((n_scan,), np.int32),
                "new_blocks": np.zeros((n_scan, T // bs), np.int32),
                "prefix": np.zeros((n_scan, pmax), np.int32),
                "adapter": np.zeros((n_scan,), np.int32),
            }
            stage = self._mw_stage[(n_scan, T)] = [mk(), mk(), 0]
        buf = stage[stage[2]]
        stage[2] ^= 1
        for arr in buf.values():
            arr.fill(0)
        any_final = False
        for i, cp in enumerate(sched):
            toks = cp.seq.prompt_token_ids[
                cp.cached_len : cp.cached_len + cp.num_new_tokens
            ]
            buf["tokens"][i, : len(toks)] = toks
            buf["cached"][i] = cp.cached_len
            buf["valid"][i] = cp.num_new_tokens
            buf["new_blocks"][i, : len(cp.new_block_ids)] = cp.new_block_ids
            full = list(cp.prefix_block_ids) + list(cp.new_block_ids)
            buf["prefix"][i, : len(full)] = full
            buf["adapter"][i] = cp.seq.adapter_idx
            if cp.is_final:
                any_final = True
        # Dead pow-2 padding iterations replay the LAST live chunk's
        # cursor/table at valid_len 0 (frozen, null-block writes only).
        end_cursor = sched[-1].cached_len + sched[-1].num_new_tokens
        buf["cached"][k_eff:] = end_cursor
        buf["prefix"][k_eff:] = buf["prefix"][k_eff - 1]

        lora_kwargs = {}
        if self.lora_registry is not None:
            lora_kwargs = {
                "lora": self.lora_registry.params,
                "adapter_idx": state["adapter"],
            }
        pf_device = {
            k: self._put(v, P()) for k, v in buf.items()
        }
        overlap_s = 0.0
        if chain_from is not None:
            # The previous window still occupies the device: every
            # second of this H2D staging ran UNDER its compute instead
            # of serializing after it.
            overlap_s = time.time() - t_stage
            self.window_transfer_overlap_s += overlap_s
        emitted, tails, out_state, self.kv_caches = (
            self._mixed_window_fn(
                self.params,
                tokens=state["tokens"],
                positions=state["positions"],
                ctx_lens=state["ctx_lens"],
                done=state["done"],
                min_left=state["min_left"],
                block_tables=state["tables"],
                max_steps=state["max_steps"],
                kv_caches=self.kv_caches,
                temps=state["temps"],
                top_ps=state["top_ps"],
                top_ks=state["top_ks"],
                min_ps=state["min_ps"],
                seq_seeds=state["seeds"],
                stop_ids=state["stop_ids"],
                # Same 31-bit masking rationale as _dispatch_window.
                key_base=jnp.int32(
                    (self.config.seed + self._step_counter) & 0x7FFFFFFF
                ),
                counts=state["counts"],
                seen=state["seen"],
                presence=state["presence"],
                frequency=state["frequency"],
                repetition=state["repetition"],
                pf_tokens=pf_device["tokens"],
                pf_cached=pf_device["cached"],
                pf_valid=pf_device["valid"],
                pf_new_blocks=pf_device["new_blocks"],
                pf_prefix_ids=pf_device["prefix"],
                pf_adapter=pf_device["adapter"],
                n_steps=n_scan,
                use_penalties=state["use_penalties"],
                use_min_floor=state["use_min_floor"],
                hist=state.get("hist"),
                **lora_kwargs,
            )
        )
        # chunk_ordinal is the window's BASE step counter: a final
        # chunk at iteration f is K=1 step (base + f), and the
        # collect-side first-token sample burns exactly that ordinal —
        # per packed prompt.
        chunk_ordinal = self._step_counter
        # K_eff live iterations = K_eff single-step equivalents (dead
        # pow-2 padding iterations burn no ordinal anywhere).
        self._step_counter += k_eff
        state.update(out_state)
        rec = None
        if self.obs.enabled:
            depth = 0
            if chain_from is not None and chain_from.rec is not None:
                depth = chain_from.rec.chain_depth + 1
            sids = tuple(s.seq_id for s in seqs) + tuple(
                dict.fromkeys(cp.seq.seq_id for cp in sched)
            )
            rec = self.obs.recorder.on_dispatch(
                "mixed", k=k_eff, rows=len(seqs), seq_ids=sids,
                chain_depth=depth, provisional=chain_from is not None,
                chunk_prompts=len({cp.seq.seq_id for cp in sched}),
                chunk_tokens_planned=sum(
                    cp.num_new_tokens for cp in sched
                ),
                fallback=plan.window_fallback, host_gap_s=gap,
                transfer_overlap_s=overlap_s, now=t0,
            )
            self._note_compiles(sids, rec)
        # stackcheck: allow=SC201 reason=host_s is a stats field (host-gap metric); no plan state reads it
        return _PendingStep(
            seqs=list(seqs), sampled=emitted, is_decode=True,
            host_s=time.time() - t0, steps=list(decode.steps),
            win_state=state,
            chunk_sched=list(sched),
            chunk_logits=tails if any_final else None,
            chunk_ordinal=chunk_ordinal,
            rec=rec,
        )

    def _collect_window(self, p: _PendingStep, t0: float) -> List[StepOutput]:
        """Read one window's emitted tokens back ([K, S] plain, or
        [K, W, S] from the fused speculative scan — flattened to the
        chronological [K*W, S] token order) and replay them through the
        single finish protocol, token by token — exactly the per-token
        path single stepping takes, so streams are identical.
        Device-frozen rows emit -1 (their stop already retired) and cost
        nothing; emitted tokens that can no longer be delivered (their
        sequence aborted / finished out-of-band while the window flew)
        are counted as multistep waste.  Fused windows additionally
        account drafted / accepted / wasted speculation per window."""
        arr = np.asarray(p.sampled)  # the ONE device sync point
        sync_s = time.time() - t0
        spec = p.spec_stats is not None
        if arr.ndim == 3:
            arr = arr.reshape(-1, arr.shape[-1])  # [K*W, S], in order
        if self.obs.enabled:
            self.obs.step_phase("collect", sync_s)
        t_post = time.time()
        outputs: List[StepOutput] = []
        delivered = [0] * len(p.seqs)
        alive = [(i, s) for i, s in enumerate(p.seqs) if not s.is_finished]
        for t in range(arr.shape[0]):
            batch = []
            toks = []
            for i, s in alive:
                if delivered[i] >= p.steps[i]:
                    continue  # token budget exhausted (belt and braces)
                tok = int(arr[t, i])
                if tok < 0:
                    continue  # frozen row: stop-mask spent no token here
                batch.append((i, s))
                toks.append(tok)
            if not batch:
                if not spec:
                    # done/budget masks are monotone within a plain
                    # window: no row can re-activate later.
                    break
                # Fused windows interleave -1 gaps per iteration (a row
                # that accepted fewer drafts than a neighbor pads its
                # sub-steps), so an empty slice is NOT terminal.
                continue
            outs = self._append_and_check(
                [s for _, s in batch], toks, first_token=False
            )
            outputs.extend(outs)
            for i, _ in batch:
                delivered[i] += 1
            alive = [(i, s) for i, s in alive if not s.is_finished]
        # Waste = emitted (device-computed, >= 0) minus delivered to the
        # finish protocol: rows finished before the window collected
        # (abort, out-of-band) deliver none, and rows a HOST-side finish
        # (stop string, guided rejection) retires mid-replay skip their
        # tail.  Device-stopped rows emit -1 past the stop, so ordinary
        # stops contribute zero by construction.
        emitted = 0
        for i in range(len(p.seqs)):
            emitted += int((arr[:, i] >= 0).sum())
        wasted = emitted - sum(delivered)
        if wasted:
            self.multistep_wasted_tokens += wasted
        chunk_delivered = 0
        if p.chunk_sched is not None:
            # Mixed window: account the chunk tokens that rode the scan
            # and finalize EACH packed prompt whose final chunk landed —
            # the identical _finalize_final_prefill path (and PRNG
            # ordinal: window base + the final chunk's iteration index)
            # the K=1 mixed step uses, so first tokens are bit-identical
            # by construction.  A prompt aborted / deadline-shed while
            # the window flew skips its finalize — the written chunk KV
            # is unreachable and counted as waste, never silently
            # vanished — and the OTHER packed prompts are unaffected.
            tails = (
                np.asarray(p.chunk_logits)  # [n_scan, V] per-iter tails
                if p.chunk_logits is not None else None
            )
            by_seq = []  # [(seq, [(iteration, chunk), ...])] in order
            for i, cp in enumerate(p.chunk_sched):
                if by_seq and by_seq[-1][0] is cp.seq:
                    by_seq[-1][1].append((i, cp))
                else:
                    by_seq.append((cp.seq, [(i, cp)]))
            for seq, chunks in by_seq:
                chunk_tokens = sum(cp.num_new_tokens for _, cp in chunks)
                if seq.is_finished:
                    self.multistep_wasted_tokens += chunk_tokens
                    continue
                self.prefill_chunk_tokens += chunk_tokens
                self.mixed_window_chunk_tokens += chunk_tokens
                chunk_delivered += chunk_tokens
                if tails is None:
                    continue
                for i, cp in chunks:
                    if cp.is_final:
                        outputs.extend(self._finalize_final_prefill(
                            seq, tails[i],
                            step_ordinal=p.chunk_ordinal + i,
                        ))
            self.mixed_window_prompts_hist.observe(len(by_seq))
        drafted = accepted = 0
        if spec:
            # Per-window speculation accounting: drafted/accepted feed
            # the existing acceptance-rate counters; the outcome split
            # (accepted / rejected / wasted) is the fused family.
            n = len(p.seqs)
            drafted = int(np.asarray(p.spec_stats[0])[:, :n].sum())
            accepted = int(np.asarray(p.spec_stats[1])[:, :n].sum())
            self.spec_tokens_drafted += drafted
            self.spec_tokens_accepted += accepted
            self.spec_window_tokens["accepted"] += accepted
            self.spec_window_tokens["rejected"] += drafted - accepted
            self.spec_window_tokens["wasted"] += wasted
            if p.spec_drafter == "model":
                # Scan seconds attributed to draft forwards
                # (tpu:spec_draft_fraction_seconds): the measured
                # collect sync wait times the static cost-model split
                # computed at boot from real parameter counts (the
                # n-gram drafter's lookup costs no forward, so it
                # accrues nothing).  Pipelined windows under-attribute —
                # the host overlaps part of the scan — which keeps the
                # counter a floor, never an overclaim.
                self.spec_draft_fraction_s += (
                    self._draft_cost_fraction * sync_s
                )
        if self.obs.enabled:
            self.obs.step_phase("sample", time.time() - t_post)
        if p.rec is not None:
            # Sample-side jits ran inside the replay above: drain any
            # compiles onto this record, then complete it.
            self._note_compiles([s.seq_id for s in p.seqs], p.rec)
            self.obs.recorder.on_collect(
                p.rec, host_s=p.host_s,
                tokens_emitted=emitted,
                tokens_delivered=emitted - wasted,
                tokens_wasted=wasted,
                chunk_tokens_delivered=chunk_delivered,
                drafted=drafted, accepted=accepted,
            )
        return outputs

    def restore_seq_blocks(self, seq: Sequence) -> str:
        """Scheduler restore_cb: page an offloaded sequence's KV snapshot
        back into freshly allocated blocks.  Returns "restored" (sequence
        now holds the blocks as a partial-prefill prefix — no recompute),
        "gone" (no snapshot: recompute), or "retry" (transient pool
        pressure: snapshot reinserted, try again next step)."""
        if self.obs.enabled:
            t0 = time.time()
            result = self._restore_seq_blocks(seq)
            if result != "retry":
                # KV paging shows up on the request's timeline: a restore
                # that precedes a slow re-admission is the attribution.
                self.obs.tracer.add_span(
                    seq.seq_id, "engine.kv_restore", t0, time.time(),
                    result=result,
                )
            return result
        return self._restore_seq_blocks(seq)

    # Sentinel: a remote restore page-in is in flight — schedule again
    # next pass instead of blocking (async analogue of pool-pressure
    # "retry").
    _RESTORE_PENDING = object()

    def _restore_entry(self, seq_id: str):
        """Snapshot lookup for restore: local host-DRAM tier first; a
        remote-tier miss triggers an ASYNC page-in (prefetch worker lands
        it in the local tier) and returns the pending sentinel — the
        scheduler re-checks readiness instead of blocking on the RPC.
        Legacy mode (remote_prefetch=False) keeps the blocking fetch."""
        if (
            self._offload_stager is not None
            and self._offload_stager.is_inflight(seq_id)
        ):
            # The snapshot is still between device and host: re-check
            # next pass rather than concluding "gone" and recomputing.
            return self._RESTORE_PENDING
        if self.kv_prefetch is None:
            return self.offload.restore(seq_id)
        entry = self.offload.restore_local(seq_id)
        if entry is not None:
            # Consume a completed page-in job, if one fed this entry.
            self.kv_prefetch.poll_restore(seq_id)
            return entry
        if self.offload.remote_client is None:
            return None
        state = self.kv_prefetch.poll_restore(seq_id)
        if state == "absent":
            self.kv_prefetch.submit_restore(seq_id)
            return self._RESTORE_PENDING
        if state == "inflight":
            return self._RESTORE_PENDING
        if state == "ready":
            return self.offload.restore_local(seq_id)
        return None  # "missing": recompute

    def _restore_seq_blocks(self, seq: Sequence) -> str:
        entry = self._restore_entry(seq.seq_id)
        if entry is self._RESTORE_PENDING:
            return "retry"
        if entry is None:
            return "gone"  # fall back to recompute via normal prefill
        bs = self.block_pool.block_size
        usable_tokens = min(entry.num_tokens, len(seq.prompt_token_ids) - 1)
        usable_blocks = usable_tokens // bs
        if usable_blocks == 0:
            return "gone"
        if not self.block_pool.can_allocate(usable_blocks):
            # Transient pool pressure must not cost the snapshot: put it
            # back so the next scheduling attempt can still use it.
            self.offload.reinsert(entry)
            return "retry"
        restored = self.block_pool.allocate(usable_blocks)
        ids = jnp.asarray(restored, jnp.int32)
        for layer_idx, (k_host, v_host) in enumerate(entry.layers):
            k_cache, v_cache = self.kv_caches[layer_idx]
            # set_blocks handles dense hosts (quantizing into int8
            # pools) and native (data, scale) wire tuples (adopted
            # untransformed — the no-requantize restore path).
            self.kv_caches[layer_idx] = (
                kv_quant.set_blocks(
                    k_cache, ids,
                    kv_quant.slice_host_side(k_host, usable_blocks),
                ),
                kv_quant.set_blocks(
                    v_cache, ids,
                    kv_quant.slice_host_side(v_host, usable_blocks),
                ),
            )
        seq.block_table = restored
        seq.num_cached_tokens = usable_blocks * bs
        seq.partial_prefill = True
        return "restored"

    # -- cross-engine prefix sharing (cache.disagg_role) -------------------

    def _px_key_prefix(self) -> str:
        """Content-key namespace binding blocks to THIS model's identity:
        structural shape AND a weight fingerprint (a sample of the
        embedding row), so two engines only exchange KV when they run the
        same weights — a peer serving a different model (or different
        random init) can never poison this one's cache."""
        if not hasattr(self, "_px_prefix_cache"):
            import hashlib

            cfg = self.config.model
            h = hashlib.blake2b(digest_size=8)
            h.update(
                f"{cfg.name}|{cfg.num_layers}|{cfg.num_kv_heads}|"
                f"{cfg.head_dim}|{cfg.dtype}|{self.block_pool.block_size}"
                .encode()
            )
            h.update(np.asarray(
                self.params["embed_tokens"][0], np.float32
            ).tobytes())
            self._px_prefix_cache = f"px:{h.hexdigest()}:"
        return self._px_prefix_cache

    def _seq_prefix_hashes(self, seq) -> List[bytes]:
        """Per-sequence memo: the chain is O(prompt) blake2b work and the
        scheduler may retry admission many times.  Keyed on the prompt
        length so recompute-preemption (which absorbs generated tokens
        into prompt_token_ids) invalidates the memo and the absorbed
        blocks become export/fetch-able too."""
        key = len(seq.prompt_token_ids)
        if getattr(seq, "_px_hashes_key", None) != key:
            seq._px_hashes = prefix_block_hashes(
                seq.prompt_token_ids,
                self.block_pool.block_size,
                namespace=seq.cache_ns,
            )
            seq._px_hashes_key = key
        return seq._px_hashes

    def _transfer_inflight(self) -> bool:
        """Any async KV transfer the scheduler may be waiting out."""
        if self._offload_stager is not None and self._offload_stager.busy:
            return True
        return self.kv_prefetch is not None and self.kv_prefetch.inflight > 0

    # -- admission-time remote-prefix prefetch (cache.remote_prefetch) -----

    def _submit_prefix_prefetch(self, seq) -> None:
        """Queue a background fetch of the sequence's local prefix-cache
        miss tail (called at admission, and again from the scheduler
        callback after recompute-preemption grows the prompt).  Pure host
        hashing + a queue put — no RPC, no device work."""
        hashes = self._seq_prefix_hashes(seq)
        if not hashes:
            return
        start = self.block_pool.count_cached_prefix(hashes)
        if start >= len(hashes):
            return
        # One fetch per distinct miss tail: without this memo a store-MISS
        # chain (submitted, completed empty) would re-fetch on every
        # scheduling pass the sequence spends waiting.  The key changes
        # when recompute-preemption grows the prompt or the local cache
        # absorbs more of the chain.  Set only on an ACCEPTED submit: a
        # decline (e.g. the same-head dedupe against another request's
        # in-flight job) must stay retryable, or an abort of that other
        # request would strand this one without a fetch forever.
        memo = (len(hashes), start)
        if getattr(seq, "_px_prefetch_memo", None) == memo:
            return
        key_prefix = self._px_key_prefix()
        if self.kv_prefetch.submit_chain(
            seq.seq_id,
            [key_prefix + d.hex() for d in hashes[start:]],
            hashes[start:],
            start,
        ):
            seq._px_prefetch_memo = memo

    # stackcheck: root=step-thread
    def _drain_prefetched(self) -> None:
        """Step-thread landing point for completed prefetches: import the
        staged host blocks into freshly allocated pool blocks (async
        device copy-in via set_blocks) and bind them to their chain
        digests in the prefix cache, then park them in the reclaimable
        cached-free tier — the next match_prefix serves them exactly like
        a local hit.  Transient pool pressure keeps a chain pending for a
        bounded number of retries; anything undeliverable counts as
        prefetch waste."""
        if self.kv_prefetch is None:
            return
        self._pending_prefetch_imports.extend(self.kv_prefetch.pop_completed())
        if not self._pending_prefetch_imports:
            return
        keep: List[PrefetchedChain] = []
        for chain in self._pending_prefetch_imports:
            outcome = self._import_prefetch_to_cache(chain)
            if outcome == "retry":
                chain.attempts += 1
                if chain.attempts < 16:
                    keep.append(chain)
                else:
                    self.kv_prefetch.note_waste(len(chain.blocks))
        self._pending_prefetch_imports = keep

    def _import_prefetch_to_cache(self, chain: PrefetchedChain) -> str:
        """Returns "done" (imported / nothing left to do), "retry"
        (pool pressure), or "drop" (malformed entries — degrade)."""
        # A chain is only usable as a PREFIX: stop at the first digest the
        # cache already holds a block for (earlier digests were local
        # hits at submit time; a digest appearing mid-chain means a
        # concurrent prefill registered it and our copy is redundant from
        # that point on).
        ready = []
        for digest, layers in zip(chain.hashes, chain.blocks):
            if self.block_pool.has_digest(digest):
                if not ready:
                    continue  # leading blocks already cached: skip them
                break
            ready.append((digest, layers))
        dropped = len(chain.blocks) - len(ready)
        if not ready:
            if dropped:
                self.kv_prefetch.note_waste(dropped)
            return "done"
        if not self.block_pool.can_allocate(len(ready)):
            return "retry"
        ids = self.block_pool.allocate(len(ready))
        try:
            idx = jnp.asarray(ids, jnp.int32)
            for layer_idx, (k_cache, v_cache) in enumerate(self.kv_caches):
                # Wire sides may be dense or native int8 tuples (and a
                # mixed fleet can interleave both within one chain):
                # stack_wire_blocks normalizes to THIS pool's format, so
                # int8 chains land in an int8 pool without a quantize
                # pass and bf16 pools dequantize at import.
                pool_q = kv_quant.is_quantized(k_cache)
                k_host = kv_quant.stack_wire_blocks(
                    [b[layer_idx][0] for _, b in ready], pool_q
                )
                v_host = kv_quant.stack_wire_blocks(
                    [b[layer_idx][1] for _, b in ready], pool_q
                )
                self.kv_caches[layer_idx] = (
                    kv_quant.set_blocks(k_cache, idx, k_host),
                    kv_quant.set_blocks(v_cache, idx, v_host),
                )
        except Exception:
            # Malformed store entry (wrong layer count / block shape):
            # free and degrade — unreferenced cache lines are harmless.
            self.block_pool.free(ids)
            self.kv_prefetch.note_waste(len(chain.blocks))
            logger.exception("prefetched block import failed; continuing")
            return "drop"
        for (digest, _), block in zip(ready, ids):
            self.block_pool.adopt_prefix_block(digest, block)
        # Freeing parks the adopted blocks in the reclaimable cached-free
        # tier; match_prefix re-claims them by digest.
        self.block_pool.free(ids)
        self.kv_prefetch.note_hit(len(ids))
        if dropped:
            self.kv_prefetch.note_waste(dropped)
        self.remote_prefix_blocks_fetched += len(ids)
        return "done"

    def flush_prefix_imports(self, timeout: float = 10.0) -> None:
        """Block until in-flight prefetches have resolved (tests;
        graceful drain).  The actual cache import still happens on the
        step thread at the next dispatch."""
        if self.kv_prefetch is not None:
            self.kv_prefetch.wait_idle(timeout)

    def fetch_remote_prefix(self, seq, prefix_blocks, cached_len):
        """Scheduler remote_prefix_cb.  With the async transfer plane
        (cache.remote_prefetch, default): NEVER blocks — completed
        prefetches were already imported into the prefix cache before
        schedule() ran (so the match_prefix result this call receives
        already includes them), and all this does is make sure a fetch is
        in flight for any remaining miss tail (admission covers the
        common case; this covers recompute-preemption prompt growth).
        With remote_prefetch=False: the legacy synchronous per-block GET
        loop, kept as the A/B baseline."""
        client = self.offload.remote_client
        if client is None:
            return prefix_blocks, cached_len
        if self.kv_prefetch is not None:
            if not self.kv_prefetch.has_job(seq.seq_id):
                self._submit_prefix_prefetch(seq)
            return prefix_blocks, cached_len
        return self._fetch_remote_prefix_sync(seq, prefix_blocks, cached_len)

    # stackcheck: boundary=step-thread reason=legacy sync fetch path, only reachable with cache.remote_prefetch=False (--no-remote-prefetch A/B baseline); blocking GETs inside the scheduler callback are its documented contract
    def _fetch_remote_prefix_sync(self, seq, prefix_blocks, cached_len):
        """Legacy synchronous remote-prefix extension: one blocking GET
        per block INSIDE the scheduler callback.  Returns the possibly
        extended (prefix_blocks, cached_len); never raises — a store
        outage (or a malformed entry) degrades to local-only prefill."""
        client = self.offload.remote_client
        bs = self.block_pool.block_size
        hashes = self._seq_prefix_hashes(seq)
        start = cached_len // bs
        if start >= len(hashes):
            return prefix_blocks, cached_len
        # Defense in depth: clamp the extension so >= 1 prompt token is
        # ALWAYS left to prefill.  Today the fetch keys come from
        # prefix_block_hashes, which stops at num_prompt_tokens - 1 like
        # the local match_prefix, so this bound is not reachable through
        # the local chain — but nothing else pins the invariant that a
        # PrefillPlan must have num_new_tokens >= 1 (a full-prompt
        # extension would leave no valid last-token logits to sample),
        # and the hash helper is shared code a refactor could loosen.
        # Enforce it where the extension happens, not by construction
        # three modules away.
        max_ext_blocks = (seq.num_prompt_tokens - 1 - cached_len) // bs
        if max_ext_blocks <= 0:
            return prefix_blocks, cached_len
        # Don't fetch what admission can't hold: the whole remaining
        # prompt (fetched + still-to-prefill blocks) must fit, or the
        # scheduler would free the fetch and re-issue it every step.
        remaining_blocks = -(
            -(seq.num_prompt_tokens - cached_len) // bs
        )
        if not self.block_pool.can_allocate(remaining_blocks):
            return prefix_blocks, cached_len
        key_prefix = self._px_key_prefix()
        try:
            fetched: List = []
            for digest in hashes[start : start + max_ext_blocks]:
                entry = client.get_blocks(key_prefix + digest.hex())
                if entry is None:
                    break
                layers, _ = entry
                fetched.append(layers)
            if not fetched or not self.block_pool.can_allocate(len(fetched)):
                return prefix_blocks, cached_len
        except Exception:
            # Includes a store outage mid-chain: degrade, never kill the
            # step loop.
            logger.exception("remote prefix fetch failed; continuing local")
            return prefix_blocks, cached_len
        ids = self.block_pool.allocate(len(fetched))
        try:
            idx = jnp.asarray(ids, jnp.int32)
            for layer_idx, (k_cache, v_cache) in enumerate(self.kv_caches):
                pool_q = kv_quant.is_quantized(k_cache)
                k_host = kv_quant.stack_wire_blocks(
                    [f[layer_idx][0] for f in fetched], pool_q
                )
                v_host = kv_quant.stack_wire_blocks(
                    [f[layer_idx][1] for f in fetched], pool_q
                )
                self.kv_caches[layer_idx] = (
                    kv_quant.set_blocks(k_cache, idx, k_host),
                    kv_quant.set_blocks(v_cache, idx, v_host),
                )
        except Exception:
            # A malformed entry (wrong layer count / block shape — a store
            # polluted by another binary version) fails here: return the
            # blocks to the pool (partially written cache lines are
            # unreferenced until a block_table points at them, so freeing
            # is safe) and degrade to local-only prefill.
            self.block_pool.free(ids)
            logger.exception("remote prefix copy-in failed; continuing local")
            return prefix_blocks, cached_len
        self.remote_prefix_blocks_fetched += len(ids)
        return prefix_blocks + ids, cached_len + len(ids) * bs

    # stackcheck: thread=px-export
    def _export_worker(self) -> None:
        client = self.offload.remote_client
        while True:
            item = self._export_queue.get()
            if item is None:
                self._export_queue.task_done()
                return
            # Coalesce the queue backlog into ONE batched MPUT: a final
            # prefill enqueues its whole chain at once, so the common
            # case is one round-trip per exported prompt instead of one
            # per block.
            batch = [item]
            while len(batch) < 32:
                try:
                    nxt = self._export_queue.get_nowait()
                except Exception:
                    break
                if nxt is None:
                    self._export_queue.task_done()
                    self._export_queue.put(None)  # re-arm shutdown
                    break
                batch.append(nxt)
            try:
                client.mput_blocks(batch)
                self.remote_prefix_blocks_exported += len(batch)
            except Exception:
                logger.exception("remote prefix export failed; continuing")
            finally:
                for _ in batch:
                    self._export_queue.task_done()

    def close(self, timeout: float = 10.0) -> None:
        """Release every worker thread and socket the engine owns (the
        SC6 lifecycle contract; AsyncEngine.close and the follower loop
        land here).  Producers stop before their sinks: the prefetch
        fetchers and the offload stager both write into the
        HostOffloadManager (`insert_fetched`/`insert_saved`), and the
        export worker reads `offload.remote_client` — so fetchers and
        writers retire first, the manager flushes its deleter queue
        second, and the remote client's sockets close last.

        `timeout` is a shared budget across ALL stages, not per stage:
        with the kvserver hung at drain time, per-stage budgets would
        stack to minutes while helm's drainGraceSeconds is 30 — the
        kubelet would SIGKILL the pod mid-close."""
        deadline = time.monotonic() + timeout

        def left() -> float:
            return max(0.0, deadline - time.monotonic())

        with self._export_lock:
            export_thread, self._export_thread = self._export_thread, None
        if export_thread is not None:
            import queue as _queue

            self.flush_prefix_exports(left())
            try:
                # The queue is bounded and full exactly when the writer
                # is wedged mid-RPC against a hung store — an untimed
                # put would block past the deadline this method promises.
                self._export_queue.put(None, timeout=left())
            except _queue.Full:
                logger.warning(
                    "prefix-export writer still wedged at shutdown; "
                    "abandoning its daemon thread past the close deadline"
                )
            export_thread.join(left())
        if self.kv_prefetch is not None:
            self.kv_prefetch.shutdown(left())
        if self._offload_stager is not None:
            self._offload_stager.shutdown(left())
        self.offload.close(left())
        if self.offload.remote_client is not None:
            self.offload.remote_client.close()

    def flush_prefix_exports(self, timeout: float = 10.0) -> None:
        """Block until queued exports have been written (tests; graceful
        shutdown).  No-op when nothing was ever exported."""
        if self._export_queue is None:
            return
        deadline = time.time() + timeout
        while time.time() < deadline:
            if self._export_queue.unfinished_tasks == 0:
                return
            time.sleep(0.01)

    # -- disaggregated prefill/decode handoff (docs/engine.md) -------------

    def cache_ns_of(self, adapter: Optional[str]) -> int:
        """The prefix-cache namespace a request with this adapter would
        hash under (mirrors add_request; 0 = base model)."""
        if adapter and self.lora_registry is not None:
            return self.lora_registry.namespace_of(adapter)
        return 0

    def handoff_token(
        self, prompt_token_ids: List[int], cache_ns: int = 0
    ) -> dict:
        """The prefill-phase handoff token: the prompt's prefix hash
        chain (store content keys) + length, plus the model-identity key
        prefix so a decode peer can verify it shares weights before
        waiting on imports.  Called off the event loop (the first
        ``_px_key_prefix`` pays a small D2H for the weight fingerprint).

        ``exported`` reports whether this engine CAN have exported the
        chain (store + prefill role) — the router's fused fallback keys
        on it; it is not a per-block store receipt (content-keyed PUTs
        are idempotent and a racing eviction shows up as a decode-side
        miss, which degrades safely)."""
        hashes = prefix_block_hashes(
            prompt_token_ids, self.block_pool.block_size, namespace=cache_ns
        )
        key_prefix = self._px_key_prefix()
        return {
            "chain": [key_prefix + d.hex() for d in hashes],
            "chain_len": len(hashes),
            "chain_tail": hashes[-1].hex() if hashes else "",
            "prompt_tokens": len(prompt_token_ids),
            "block_size": self.block_pool.block_size,
            "px": key_prefix,
            "exported": bool(
                self._exports and self.offload.remote_client is not None
            ),
        }

    def wait_handoff_prefix(
        self,
        prompt_token_ids: List[int],
        cache_ns: int,
        handoff: dict,
        timeout: float,
    ) -> str:
        """Decode-phase handoff consumption: make sure a prefetch of the
        prompt's chain is in flight and wait (bounded) for the FETCH to
        complete into host staging.  A staged chain is imported by the
        step thread at the top of its next dispatch, BEFORE any
        ``schedule()`` runs — so admitting the request after this
        returns "hit" guarantees its first schedule serves the whole
        prompt from the prefix cache and decode never executes prompt
        tokens.  (Waiting for the cache import itself would deadlock an
        idle engine: the import point only runs when there is work.)

        Runs on an asyncio.to_thread worker: the polling sleep below
        never touches the event loop or the step thread.  Returns
        "hit" (chain staged or already cached), "partial", "miss", or
        "disabled" (no prefetch plane / imports off / model-identity
        mismatch).
        """
        if self.kv_prefetch is None or not self._imports:
            return "disabled"
        hashes = prefix_block_hashes(
            prompt_token_ids, self.block_pool.block_size, namespace=cache_ns
        )
        if not hashes:
            return "hit"  # prompt shorter than one block: nothing to import
        peer_px = handoff.get("px")
        if peer_px and peer_px != self._px_key_prefix():
            # Different weights/namespace: the peer's exports can never
            # match our keys — admit local-only immediately.
            return "disabled"
        start = self.block_pool.count_cached_prefix(hashes)
        if start >= len(hashes):
            return "hit"
        key_prefix = self._px_key_prefix()
        sid = f"handoff-{hashes[-1].hex()[:16]}"
        submitted = self.kv_prefetch.submit_chain(
            sid,
            [key_prefix + d.hex() for d in hashes[start:]],
            hashes[start:],
            start,
        )
        if not submitted:
            # A same-head job is already in flight (same-prompt burst,
            # or this handoff raced a sibling): we own no job to watch,
            # so poll coverage on a shortened budget.
            timeout = min(timeout, 0.5)
        deadline = time.time() + max(0.0, timeout)
        grace_until: Optional[float] = None
        while time.time() < deadline:
            covered = self.block_pool.count_cached_prefix(hashes)
            if covered >= len(hashes):
                return "hit"
            status = self.kv_prefetch.chain_status(sid)
            if status == "done":
                # Staged in host buffers: the step thread's dispatch
                # drains it into the prefix cache before the request's
                # first schedule() — that IS the hit.
                return "hit"
            if status == "absent" and submitted:
                # Our own fetch settled without a result (store miss
                # completes empty and pops the job) OR the step thread
                # already consumed it.  One short grace window for the
                # coverage check above to observe a consumed import,
                # then classify instead of burning the budget.  Without
                # `submitted` there never was a job under our sid — the
                # sibling that owns the in-flight twin fetch is what we
                # are waiting on, so poll coverage to the (shortened)
                # budget instead of grace-breaking immediately.
                if grace_until is None:
                    grace_until = time.time() + 0.1
                elif time.time() >= grace_until:
                    break
            time.sleep(0.005)
        covered = self.block_pool.count_cached_prefix(hashes)
        if covered >= len(hashes):
            return "hit"
        return "partial" if covered > start else "miss"

    # stackcheck: allow=SC201 reason=the TTL-keyed export dedupe gates only store-side export traffic; the local plan never reads it, and duplicate exports across replicas are idempotent content-keyed PUTs
    def _export_prefix_blocks(self, seq) -> None:
        """After a final prefill: push every full prompt block to the
        shared store under its chain-hash content key, so peer engines
        (and this one, post-restart) can import instead of recomputing.

        The device->host gather happens here (the step thread owns the
        kv_caches references — they are donated next step); the store RPCs
        happen on a writer thread so server latency never becomes serving
        latency.  Dedupe entries expire after a TTL so a store-side
        eviction doesn't permanently end sharing."""
        client = self.offload.remote_client
        if client is None:
            return
        bs = self.block_pool.block_size
        hashes = self._seq_prefix_hashes(seq)
        now = time.time()
        todo = [
            (i, digest)
            for i, digest in enumerate(hashes)
            if self._exported_hashes.get(digest, 0.0) < now
        ]
        if not todo:
            return
        with self._export_lock:
            if self._export_thread is None:
                import queue as _queue

                self._export_queue = _queue.Queue(maxsize=64)
                self._export_thread = threading.Thread(
                    target=self._export_worker, name="px-export", daemon=True
                )
                self._export_thread.start()
        ids = jnp.asarray(
            [seq.block_table[i] for i, _ in todo], jnp.int32
        )
        try:
            # One device->host gather per layer for all exported blocks.
            # Quantized wire: the int8 cache's (data, scale) tuples go
            # out natively (serde v2; the client's probe falls back to a
            # dense v1 encode against a legacy store).  Dense wire:
            # int8 caches dequantize here so any peer can import.
            host_layers = [
                (kv_quant.to_host_side(kv_quant.gather_blocks_wire(
                    k_cache, ids, self._wire_quantized)),
                 kv_quant.to_host_side(kv_quant.gather_blocks_wire(
                    v_cache, ids, self._wire_quantized)))
                for k_cache, v_cache in self.kv_caches
            ]
        except Exception:
            logger.exception("prefix export gather failed; continuing")
            return

        def _row(side, row):
            if kv_quant.is_quantized(side):
                return (side[0][row : row + 1], side[1][row : row + 1])
            return side[row : row + 1]

        key_prefix = self._px_key_prefix()
        for row, (_, digest) in enumerate(todo):
            layers = [
                (_row(k, row), _row(v, row)) for k, v in host_layers
            ]
            try:
                self._export_queue.put_nowait(
                    (key_prefix + digest.hex(), layers, bs)
                )
            except Exception:
                return  # writer backlogged: drop the rest of this export
            self._exported_hashes[digest] = now + self._export_ttl_s
        while len(self._exported_hashes) > 65536:
            self._exported_hashes.popitem(last=False)

    def _run_prefill(self, plan: PrefillPlan) -> List[StepOutput]:
        seq = plan.seq
        if self.obs.enabled and seq.first_scheduled_time is None:
            seq.first_scheduled_time = time.time()
            self.obs.on_first_scheduled(seq, seq.first_scheduled_time)
        T = plan.bucket_len
        tokens, new_block_ids, prefix_ids = self._prefill_plan_arrays(plan)

        lora_kwargs = {}
        if self.lora_registry is not None:
            lora_kwargs = {
                "lora": self.lora_registry.params,
                "adapter_idx": jnp.int32(seq.adapter_idx),
            }

        sp = seq.sampling_params
        want_plp = sp.echo and sp.logprobs
        plp_kwargs = {}
        if want_plp:
            # Target of row t (absolute position cached_len+t) is the NEXT
            # prompt token; rows at/past the prompt tail target 0 (their
            # entries are discarded below).
            targets = np.zeros((T,), np.int32)
            m = min(
                plan.num_new_tokens,
                len(seq.prompt_token_ids) - plan.cached_len - 1,
            )
            if m > 0:
                targets[:m] = seq.prompt_token_ids[
                    plan.cached_len + 1 : plan.cached_len + 1 + m
                ]
            plp_kwargs = {
                "prompt_targets": self._put(targets, P(AXES.SP)),
                # Fixed k: prompt_topk is a STATIC jit arg, and a
                # per-request value would compile a fresh prefill variant
                # per (bucket, k) pair; _collect_prompt_logprobs slices to
                # the request's k host-side.
                "prompt_topk": 20,
            }

        out = self._prefill_fn(
            self.params,
            tokens=self._put(tokens, P(AXES.SP)),
            cached_len=jnp.int32(plan.cached_len),
            prefix_block_ids=self._put(prefix_ids, P(AXES.SP)),
            new_block_ids=self._put(new_block_ids, P(AXES.SP)),
            valid_len=jnp.int32(plan.num_new_tokens),
            kv_caches=self.kv_caches,
            **plp_kwargs,
            **lora_kwargs,
        )
        if want_plp:
            logits, self.kv_caches, plp = out
            self._collect_prompt_logprobs(seq, plan, plp)
        else:
            logits, self.kv_caches = out
        if not plan.is_final:
            # Non-final chunk of a long prompt: KV is written, but the
            # logits are mid-prompt — nothing to sample yet.
            return []
        outputs = self._finalize_final_prefill(seq, logits)
        if want_plp and outputs and seq.prompt_lp is not None:
            # Attach the assembled per-position entries to the request's
            # FIRST token event (position 0 has no predictor -> None).
            n = seq.echo_prompt_len
            entries: List = [(None, None)]
            for pos in range(1, n):
                entries.append(seq.prompt_lp.get(pos, (None, None)))
            outputs[0].prompt_logprobs = entries
        return outputs

    def _collect_prompt_logprobs(self, seq, plan, plp) -> None:
        """Stitch one chunk's (target_lp, top_ids, top_lps) into the
        sequence's absolute-position map (chunked prefill delivers the
        prompt in pieces)."""
        tlp = np.asarray(plp[0])
        top_ids = np.asarray(plp[1])
        top_lps = np.asarray(plp[2])
        if seq.prompt_lp is None:
            seq.prompt_lp = {}
        k = min(seq.sampling_params.top_logprobs or 0, top_ids.shape[1])
        for t in range(plan.num_new_tokens):
            pos = plan.cached_len + t + 1  # entry FOR the predicted token
            if pos >= seq.echo_prompt_len:
                break
            pairs = (
                [(int(top_ids[t, j]), float(top_lps[t, j])) for j in range(k)]
                if k else None
            )
            seq.prompt_lp[pos] = (float(tlp[t]), pairs)

    def _prefill_plan_arrays(self, plan: PrefillPlan):
        """Padded (tokens [T], new_block_ids [T//bs], prefix_ids [pmax])
        host arrays for one PrefillPlan — shared by the dedicated prefill
        executable and the mixed step's chunk segment, so the plan->array
        layout can never diverge between them."""
        seq = plan.seq
        bs = self.block_pool.block_size
        T = plan.bucket_len
        new_tokens = seq.prompt_token_ids[
            plan.cached_len : plan.cached_len + plan.num_new_tokens
        ]
        tokens = np.zeros((T,), np.int32)
        tokens[: len(new_tokens)] = new_tokens
        new_block_ids = np.zeros((T // bs,), np.int32)
        new_block_ids[: len(plan.new_block_ids)] = plan.new_block_ids
        pmax = max(self._bmax, 1)
        prefix_ids = np.zeros((pmax,), np.int32)
        prefix_ids[: len(plan.prefix_block_ids)] = plan.prefix_block_ids
        return tokens, new_block_ids, prefix_ids

    def _finalize_final_prefill(
        self, seq: Sequence, last_logits, step_ordinal: Optional[int] = None
    ) -> List[StepOutput]:
        """Shared tail of every FINAL prefill — dedicated executable,
        mixed-step chunk, or a mixed WINDOW's final chunk (which passes
        ``step_ordinal``: the first token must burn the PRNG ordinal of
        the K=1 step its iteration corresponds to, not the post-window
        counter): prefix export, the max_tokens==0 scoring sentinel, or
        sampling the request's first token from the last valid row's
        logits [V]."""
        if self._exports:
            self._export_prefix_blocks(seq)
        if seq.sampling_params.max_tokens == 0:
            # Scoring-only request (echo+logprobs with max_tokens=0):
            # nothing to sample — finish at prefill with the text-free
            # sentinel the server already understands.
            seq.first_token_time = time.time()
            self._finish_seq_now(seq, FinishReason.LENGTH)
            return [StepOutput(
                seq_id=seq.seq_id,
                new_token_id=-1,
                finished=True,
                finish_reason=FinishReason.LENGTH,
                num_prompt_tokens=seq.num_prompt_tokens,
                num_output_tokens=0,
            )]
        token_ids, logprob_info = self._sample_batch(
            last_logits[None, :], [seq], step_ordinal=step_ordinal
        )
        return self._append_and_check(
            [seq], token_ids, first_token=True, logprob_info=logprob_info
        )

    def _decode_batch_arrays(self, seqs: List[Sequence], S: int):
        """Padded decode-row host arrays ([S] x5 + [S, bmax] tables) for
        one single-token step — shared by the synchronous decode path,
        the pipeline's batch rebuild, and the mixed step's decode
        segment.  Padding rows keep null block 0 / ctx 0 (masked)."""
        bs = self.block_pool.block_size
        tokens = np.zeros((S,), np.int32)
        positions = np.zeros((S,), np.int32)
        block_tables = np.zeros((S, self._bmax), np.int32)
        ctx_lens = np.zeros((S,), np.int32)
        slot_blocks = np.zeros((S,), np.int32)
        slot_offsets = np.zeros((S,), np.int32)
        for i, seq in enumerate(seqs):
            pos = seq.num_tokens - 1
            tokens[i] = seq.all_token_ids[-1]
            positions[i] = pos
            table = seq.block_table[: self._bmax]
            block_tables[i, : len(table)] = table
            ctx_lens[i] = seq.num_tokens
            slot_blocks[i] = seq.block_table[pos // bs]
            slot_offsets[i] = pos % bs
        return tokens, positions, block_tables, ctx_lens, slot_blocks, slot_offsets

    def _decode_bucket(self, n: int) -> int:
        """Static decode batch sizes: the smallest bucket of the
        (dp, 2dp, 4dp, ...) set holding ``n`` rows, capped at
        max_num_seqs.  Replaces the old unconditional max_num_seqs
        padding — a single-sequence stream stops paying full-batch
        attention, KV scatter and sampling; the executable inventory
        grows by one decode variant per power of two."""
        b = max(1, self.config.parallel.data_parallel)
        while b < n:
            b *= 2
        return min(b, self._smax)

    # stackcheck: root=step-thread
    def _run_mixed(self, step_plan) -> List[StepOutput]:
        """One fused step over the packed [decode bucket + chunk bucket]
        token batch (a StepPlan with both ``decode`` and
        ``prefill_chunk`` set): every running sequence decodes exactly
        as in _run_decode (paged attention, then the full host sampling
        surface), and the head waiting sequence's prefill chunk rides
        along paying only its attention/KV-write cost — the projection
        and MLP weight streaming is shared.  Only a FINAL chunk samples
        the prefill tail row (mid-prompt logits have no consumer),
        mirroring _run_prefill's chunked contract."""
        t_start = time.time()
        plan = step_plan.prefill_chunk
        seq = plan.seq
        seqs = step_plan.decode.seqs
        if self.obs.enabled and seq.first_scheduled_time is None:
            seq.first_scheduled_time = t_start
            self.obs.on_first_scheduled(seq, t_start)
        S = self._decode_bucket(len(seqs))
        T = plan.bucket_len
        (tokens, positions, block_tables, ctx_lens, slot_blocks,
         slot_offsets) = self._decode_batch_arrays(seqs, S)
        pf_tokens, pf_new_blocks, pf_prefix = self._prefill_plan_arrays(plan)

        batch_spec = shardings_lib.decode_batch_spec()
        lora_kwargs = {}
        if self.lora_registry is not None:
            # Not _lora_kwargs: the mixed row layout is [S decode rows +
            # T chunk rows sharing ONE adapter], not a per-seq width
            # repeat, and the packed axis is replicated (dp/sp are gated
            # to 1 for mixed), so P() is the right spec.
            adapter_idx = np.zeros((S + T,), np.int32)
            for i, s in enumerate(seqs):
                adapter_idx[i] = s.adapter_idx
            adapter_idx[S:] = seq.adapter_idx
            lora_kwargs = {
                "lora": self.lora_registry.params,
                "adapter_idx": self._put(adapter_idx, P()),
            }

        self._note_decode_launch()
        logits, self.kv_caches = self._mixed_fn(
            self.params,
            dec_tokens=self._put(tokens, batch_spec),
            dec_positions=self._put(positions, batch_spec),
            dec_block_tables=self._put(block_tables, P(AXES.DP, None)),
            dec_ctx_lens=self._put(ctx_lens, batch_spec),
            dec_slot_block_ids=self._put(slot_blocks, batch_spec),
            dec_slot_offsets=self._put(slot_offsets, batch_spec),
            pf_tokens=self._put(pf_tokens, P(AXES.SP)),
            pf_cached_len=jnp.int32(plan.cached_len),
            pf_prefix_block_ids=self._put(pf_prefix, P(AXES.SP)),
            pf_new_block_ids=self._put(pf_new_blocks, P(AXES.SP)),
            pf_valid_len=jnp.int32(plan.num_new_tokens),
            kv_caches=self.kv_caches,
            **lora_kwargs,
        )
        self.prefill_chunk_tokens += plan.num_new_tokens
        # Decode rows first (logits rows 0..len(seqs)-1).
        token_ids, logprob_info = self._sample_batch(logits[: len(seqs)], seqs)
        outputs = self._append_and_check(
            seqs, token_ids, first_token=False, logprob_info=logprob_info
        )
        if plan.is_final:
            # Row -1 is the chunk's last valid token: the request's
            # first sampled token (same finalize contract as the
            # dedicated prefill executable).
            outputs.extend(self._finalize_final_prefill(seq, logits[-1]))
        if self.obs.enabled:
            self.obs.step_phase("mixed", time.time() - t_start)
        return outputs

    def _run_decode(self, plan: DecodePlan) -> List[StepOutput]:
        seqs = plan.seqs
        S = self._decode_bucket(len(seqs))

        # Speculative path first — it builds its own (wider) batch, so
        # deciding here avoids assembling the S-sized arrays only to
        # discard them.  Greedy-only (acceptance compares argmax), and
        # every host-state feature falls back like multi-step.
        spec_k = self.config.scheduler.speculative_ngram
        if spec_k > 0 and all(
            s.sampling_params.temperature <= 0
            and not s.sampling_params.presence_penalty
            and not s.sampling_params.frequency_penalty
            and s.sampling_params.repetition_penalty == 1.0
            and not s.sampling_params.min_tokens
            and not s.sampling_params.logprobs
            and not s.sampling_params.logit_bias
            and s.guide is None
            for s in seqs
        ):
            return self._run_decode_speculative(plan, spec_k)

        (tokens, positions, block_tables, ctx_lens, slot_blocks,
         slot_offsets) = self._decode_batch_arrays(seqs, S)

        batch_spec = shardings_lib.decode_batch_spec()
        lora_kwargs = self._lora_kwargs(seqs, S, 1, batch_spec)

        self._note_decode_launch()
        logits, self.kv_caches = self._decode_fn(
            self.params,
            tokens=self._put(tokens, batch_spec),
            positions=self._put(positions, batch_spec),
            block_tables=self._put(block_tables, P(AXES.DP, None)),
            ctx_lens=self._put(ctx_lens, batch_spec),
            slot_block_ids=self._put(slot_blocks, batch_spec),
            slot_offsets=self._put(slot_offsets, batch_spec),
            kv_caches=self.kv_caches,
            **lora_kwargs,
        )
        token_ids, logprob_info = self._sample_batch(logits[: len(seqs)], seqs)
        return self._append_and_check(
            seqs, token_ids, first_token=False, logprob_info=logprob_info
        )

    def _lora_kwargs(self, seqs: List[Sequence], S: int, width: int,
                     batch_spec) -> Dict:
        """Decode-call LoRA kwargs with each sequence's adapter repeated
        across its `width` batch rows (1 for classic decode, K+1 for the
        speculative chain) — the ONE place the adapter row layout lives."""
        if self.lora_registry is None:
            return {}
        adapter_idx = np.zeros((S * width,), np.int32)
        for i, seq in enumerate(seqs):
            adapter_idx[i * width:(i + 1) * width] = seq.adapter_idx
        return {
            "lora": self.lora_registry.params,
            "adapter_idx": self._put(adapter_idx, batch_spec),
        }

    # Backward-scan bound for drafting: repetition useful to speculation
    # is overwhelmingly recent (chat history, code loops), and an
    # unbounded scan would cost O(context) of host time per sequence per
    # step at long contexts.
    _DRAFT_SCAN_WINDOW = 1024

    # Device-resident history window the FUSED drafter matches against
    # (a fixed [S, H] carry in the window scan — compile-time constant so
    # the executable inventory never keys on context length).  Smaller
    # than the host path's scan bound: the lookup is O(S*H) per scan
    # iteration and recent repetition dominates prompt-lookup hits.
    _SPEC_HIST_WINDOW = 128

    # Model-drafter skip-prime chain length: windows that may chain off
    # one in-graph causal prime of the draft cache before the next prime
    # (the prime costs S x (H-1) draft rows; chained windows extend the
    # compact draft cache in place, so amortizing it over a chain keeps
    # the drafter's per-token overhead near the (D+1)-row floor).  Also
    # sizes the per-row draft-pool capacity: H + chain x
    # window_max_tokens compact slots, rounded up to whole blocks.
    _DRAFT_PRIME_CHAIN = 8

    @classmethod
    def _draft_ngram(cls, seq: Sequence, k: int, n: int = 2) -> List[int]:
        """Prompt-lookup drafting: find the most recent earlier occurrence
        of the trailing n-gram within the scan window of the sequence's
        own history and propose the k tokens that followed it.  Empty when
        no match — the step degenerates to a normal decode."""
        hist = seq.all_token_ids
        if len(hist) < n + 1:
            return []
        key = tuple(hist[-n:])
        lo = max(0, len(hist) - n - 1 - cls._DRAFT_SCAN_WINDOW)
        for start in range(len(hist) - n - 1, lo - 1, -1):
            if tuple(hist[start:start + n]) == key:
                return list(hist[start + n:start + n + k])
        return []

    def _run_decode_speculative(
        self, plan: DecodePlan, k: int
    ) -> List[StepOutput]:
        """Verify K n-gram-drafted tokens + sample one bonus token in ONE
        forward: each sequence occupies K+1 rows of an expanded decode
        batch.  Row j consumes the token at position pos0+j (the last real
        token, then the drafts), writes its KV, and attends with
        ctx_len = num_tokens + j — exactly the single-token decode
        semantics, so the EXISTING decode executable verifies the chain.
        Accepted drafts' KV is already correct (the written K/V came from
        the very tokens that were accepted); rejected rows' KV occupies
        positions that are overwritten when real tokens later reach them
        (the same argument as multi-step overruns, and the same
        full-block prefix-registration boundary protects the cache)."""
        seqs = plan.seqs
        S = self._decode_bucket(len(seqs))
        W = k + 1  # rows per sequence
        R = S * W
        bs = self.block_pool.block_size

        tokens = np.zeros((R,), np.int32)
        positions = np.zeros((R,), np.int32)
        block_tables = np.zeros((R, self._bmax), np.int32)
        ctx_lens = np.zeros((R,), np.int32)
        slot_blocks = np.zeros((R,), np.int32)
        slot_offsets = np.zeros((R,), np.int32)
        drafts: List[List[int]] = []
        for i, seq in enumerate(seqs):
            # Usable draft rows: bounded by the plan's per-seq budget
            # (blocks were allocated for `steps[i]` appended tokens).
            nd = min(k, plan.steps[i] - 1)
            draft = self._draft_ngram(seq, nd) if nd > 0 else []
            drafts.append(draft)
            pos0 = seq.num_tokens - 1
            table = seq.block_table[: self._bmax]
            chain = [seq.all_token_ids[-1]] + draft
            for j, tok in enumerate(chain):
                r = i * W + j
                tokens[r] = tok
                positions[r] = pos0 + j
                block_tables[r, : len(table)] = table
                ctx_lens[r] = seq.num_tokens + j
                slot_blocks[r] = seq.block_table[(pos0 + j) // bs]
                slot_offsets[r] = (pos0 + j) % bs
            # Rows past the chain stay inactive: null block 0, ctx 0.

        batch_spec = shardings_lib.decode_batch_spec()
        lora_kwargs = self._lora_kwargs(seqs, S, W, batch_spec)
        self._note_decode_launch()
        logits, self.kv_caches = self._decode_fn(
            self.params,
            tokens=self._put(tokens, batch_spec),
            positions=self._put(positions, batch_spec),
            block_tables=self._put(block_tables, P(AXES.DP, None)),
            ctx_lens=self._put(ctx_lens, batch_spec),
            slot_block_ids=self._put(slot_blocks, batch_spec),
            slot_offsets=self._put(slot_offsets, batch_spec),
            kv_caches=self.kv_caches,
            **lora_kwargs,
        )
        greedy = np.asarray(self._argmax_fn(logits))  # [R] — one sync

        # Greedy verification: accept the longest draft prefix the model
        # agrees with, then take the model's own token from the first
        # disagreeing (or final) row as the bonus.
        outputs: List[StepOutput] = []
        for i, seq in enumerate(seqs):
            base = i * W
            draft = drafts[i]
            m = 0
            while m < len(draft) and int(greedy[base + m]) == draft[m]:
                m += 1
            accepted = draft[:m] + [int(greedy[base + m])]
            self.spec_tokens_drafted += len(draft)
            self.spec_tokens_accepted += m
            for tok in accepted:
                outs = self._append_and_check([seq], [tok], first_token=False)
                outputs.extend(outs)
                if outs and outs[0].finished:
                    break
        return outputs

    def _sampling_arrays(self, seqs: List[Sequence], S: int):
        """Padded per-sequence sampling parameter arrays [S]."""
        pad = S - len(seqs)
        temps = np.array(
            [s.sampling_params.temperature for s in seqs] + [0.0] * pad,
            np.float32,
        )
        top_ps = np.array(
            [s.sampling_params.top_p for s in seqs] + [1.0] * pad,
            np.float32,
        )
        top_ks = np.array(
            [s.sampling_params.top_k for s in seqs] + [0] * pad, np.int32
        )
        min_ps = np.array(
            [s.sampling_params.min_p for s in seqs] + [0.0] * pad,
            np.float32,
        )
        seeds = np.array(
            [
                (s.sampling_params.seed if s.sampling_params.seed is not None else idx)
                for idx, s in enumerate(seqs)
            ]
            + [0] * pad,
            np.int32,
        )
        return temps, top_ps, top_ks, min_ps, seeds

    def _sample_batch(
        self, logits: jax.Array, seqs: List[Sequence],
        step_ordinal: Optional[int] = None,
    ):
        """Returns (token_ids, logprob_info) where logprob_info is a list of
        None or (chosen_logprob, [(token_id, logprob), ...]) per sequence.
        ``step_ordinal`` overrides the live step counter for the PRNG key
        (a mixed window's final-chunk first token samples with the
        ordinal of the iteration it landed in — the counter has already
        advanced past the whole window by collect time)."""
        S = logits.shape[0]
        pad = S - len(seqs)

        # Presence/frequency/repetition penalties (OpenAI + vLLM surface):
        # only pay the scatter-adds when some live sequence uses them.
        use_rep = any(
            s.sampling_params.repetition_penalty != 1.0 for s in seqs
        )
        if use_rep or any(
            (s.sampling_params.presence_penalty
             or s.sampling_params.frequency_penalty)
            and s.output_token_ids
            for s in seqs
        ):
            max_len = max(
                max((len(s.output_token_ids) for s in seqs), default=1), 1
            )
            # Bucket L so XLA compiles O(log) penalty variants, not one per
            # generated length.
            L = 64
            while L < max_len:
                L *= 2
            out_tokens = np.full((S, L), -1, np.int32)
            for i, s in enumerate(seqs):
                ids = s.output_token_ids[-L:]
                out_tokens[i, : len(ids)] = ids
            presence = np.array(
                [s.sampling_params.presence_penalty for s in seqs] + [0.0] * pad,
                np.float32,
            )
            frequency = np.array(
                [s.sampling_params.frequency_penalty for s in seqs] + [0.0] * pad,
                np.float32,
            )
            kwargs = {}
            if use_rep:
                # repetition_penalty covers prompt AND generated tokens
                # (HF/vLLM semantics) — needs the full context ids.
                max_ctx = max(len(s.all_token_ids) for s in seqs)
                Lc = 64
                while Lc < max_ctx:
                    Lc *= 2
                ctx_tokens = np.full((S, Lc), -1, np.int32)
                for i, s in enumerate(seqs):
                    ids = s.all_token_ids[-Lc:]
                    ctx_tokens[i, : len(ids)] = ids
                kwargs = {
                    "repetition": jnp.asarray(np.array(
                        [s.sampling_params.repetition_penalty
                         for s in seqs] + [1.0] * pad, np.float32,
                    )),
                    "ctx_tokens": jnp.asarray(ctx_tokens),
                }
            logits = self._penalties_fn(
                logits,
                jnp.asarray(out_tokens),
                jnp.asarray(presence),
                jnp.asarray(frequency),
                **kwargs,
            )

        # OpenAI logit_bias: sparse per-request token biases, applied to
        # the raw logits (so greedy argmax shifts too).  The dense [S, V]
        # device array is cached across steps keyed on the batch's bias
        # composition — a biased request decodes many tokens against the
        # same bias, and rebuilding/transferring it per token would
        # dominate the step.
        def _min_tokens_banned(s) -> tuple:
            """Token ids suppressed while min_tokens is unmet — the
            sequence's stop set (_stop_set_ids, shared with the window's
            device stop-mask so host and device semantics cannot
            drift)."""
            if s.sampling_params.min_tokens <= len(s.output_token_ids):
                return ()
            return self._stop_set_ids(s)

        min_tok_banned = [_min_tokens_banned(s) for s in seqs]
        if any(s.sampling_params.logit_bias for s in seqs) or any(
            min_tok_banned
        ):
            V = logits.shape[-1]
            # The cache key includes the min_tokens ban set, which flips
            # exactly once per sequence (unmet -> met): two rebuilds per
            # affected batch composition, not one per step.
            key = (S, V) + tuple(
                (i,
                 tuple(sorted((s.sampling_params.logit_bias or {}).items())),
                 min_tok_banned[i])
                for i, s in enumerate(seqs)
            )
            cached = getattr(self, "_bias_cache", None)
            if cached is None or cached[0] != key:
                bias = np.zeros((S, V), np.float32)
                for i, s in enumerate(seqs):
                    for tid, b in (s.sampling_params.logit_bias or {}).items():
                        t = int(tid)
                        if 0 <= t < V:
                            bias[i, t] = float(b)
                    for t in min_tok_banned[i]:
                        if 0 <= t < V:
                            bias[i, t] = -1e9
                self._bias_cache = (key, jnp.asarray(bias))
            logits = logits + self._bias_cache[1]

        temps, top_ps, top_ks, min_ps, seeds = self._sampling_arrays(seqs, S)
        ordinal = (
            self._step_counter if step_ordinal is None else step_ordinal
        )
        step_key = jax.random.PRNGKey(self.config.seed + ordinal)
        out = self._sample_fn(
            logits,
            jnp.asarray(temps),
            jnp.asarray(top_ps),
            jnp.asarray(top_ks),
            step_key,
            jnp.asarray(seeds),
            min_p=jnp.asarray(min_ps),
        )
        token_ids = [int(t) for t in np.asarray(out[: len(seqs)])]
        any_logprobs = any(s.sampling_params.logprobs for s in seqs)
        if any(s.guide is not None for s in seqs):
            token_ids = self._guided_override(logits, seqs, token_ids)
            if any_logprobs:
                # `out` feeds the logprobs gather below; keep it in sync
                # with the constrained choices.
                out = jnp.asarray(np.array(token_ids + [0] * pad, np.int32))

        logprob_info: List = [None] * len(seqs)
        if any_logprobs:
            # Fixed k = the API clamp (20): a per-batch k would compile a
            # fresh XLA variant inside the step thread for every new value,
            # stalling all in-flight sequences; per-sequence counts are
            # sliced on the host below.
            chosen, top_ids, top_logps = self._logprobs_fn(
                logits, out, k=20
            )
            chosen = np.asarray(chosen)
            top_ids = np.asarray(top_ids)
            top_logps = np.asarray(top_logps)
            for i, s in enumerate(seqs):
                if s.sampling_params.logprobs:
                    n = s.sampling_params.top_logprobs
                    logprob_info[i] = (
                        float(chosen[i]),
                        [
                            (int(top_ids[i, j]), float(top_logps[i, j]))
                            for j in range(n)
                        ],
                    )
        return token_ids, logprob_info

    def _guided_override(
        self, logits: jax.Array, seqs: List[Sequence], token_ids: List[int]
    ) -> List[int]:
        """Constrained choice for guided sequences (engine/guided.py):
        the device-sampled token is kept when the automaton accepts it;
        otherwise candidates are validated host-side in logit order and
        the best valid token replaces it.  A completed JSON value forces
        EOS."""
        from production_stack_tpu.engine.guided import TokenTextCache

        if self._token_texts is None:
            self._token_texts = TokenTextCache(self.tokenizer)
        cache = self._token_texts
        eos = self.tokenizer.eos_token_id or 0
        out = list(token_ids)
        for i, seq in enumerate(seqs):
            guide = seq.guide
            if guide is None:
                continue
            if guide.done:
                out[i] = eos
                continue
            # Budget-aware closing: when the remaining token budget nears
            # the bytes needed to close the JSON, admit only
            # closure-reducing tokens so the value completes instead of
            # truncating (tokens are >=1 byte, so cost+margin tokens
            # always suffice).
            sp = seq.sampling_params
            remaining = min(
                sp.max_tokens - seq.num_generated,
                # max_model_len can bind first (long prompts).
                self.config.scheduler.max_model_len - seq.num_tokens,
            )
            guide.closing = remaining <= guide.closure_cost() + 4
            # Fast path: the unconstrained choice is usually valid.  An
            # EOS pick at a may-finish point is a valid CHOICE to end
            # (root-position scalars: "42" may end or grow another digit;
            # finalize collapses the script so done holds).
            if out[i] == eos and guide.may_finish():
                guide.finalize()
                continue
            fast_bytes = cache.text(out[i]).encode()
            st = guide.try_token(fast_bytes)
            if st is not None and out[i] != eos:
                guide.accept(st, fast_bytes)
                continue
            row = np.asarray(logits[i])  # [V] fp32, post bias/penalties
            # Validate candidates in descending-logit order; with
            # temperature, sample among the first few valid candidates.
            # Valid tokens live at the top of the distribution in
            # practice, so scan an argpartitioned top slice first and only
            # pay the full sort if it comes up empty.
            want = 1 if sp.temperature <= 0 else 8
            valid: List = []
            for scope in (64, len(row)):
                if scope >= len(row):
                    order = np.argsort(-row)
                else:
                    top = np.argpartition(-row, scope)[:scope]
                    order = top[np.argsort(-row[top])]
                for tid in order:
                    tid = int(tid)
                    if tid == eos:
                        if guide.may_finish():
                            valid.append((tid, "FINISH"))
                            if len(valid) >= want:
                                break
                        continue
                    st = guide.try_token(cache.text(tid).encode())
                    if st is not None:
                        valid.append((tid, st))
                        if len(valid) >= want:
                            break
                if valid:
                    break
            if not valid:
                # No token makes progress (pathological vocab): end the
                # request rather than loop.
                logger.warning(
                    "guided decoding: no valid continuation for %s",
                    seq.seq_id,
                )
                out[i] = eos
                continue
            if len(valid) == 1:
                tid, st = valid[0]
            else:
                lps = np.array([row[t] for t, _ in valid], np.float64)
                lps = lps / max(sp.temperature, 1e-5)
                p = np.exp(lps - lps.max())
                p /= p.sum()
                rng = np.random.default_rng(
                    # Per-sequence stream: co-batched guided choices (the
                    # n>1 fan-out) must not collapse to the same picks.
                    (sp.seed if sp.seed is not None else 0) * 1000003
                    + self._step_counter * 31
                    + zlib.crc32(seq.seq_id.encode())
                )
                tid, st = valid[int(rng.choice(len(valid), p=p))]
            if st == "FINISH":
                guide.finalize()
            else:
                guide.accept(st, cache.text(tid).encode())
            out[i] = tid
        return out

    def _append_and_check(
        self,
        seqs: List[Sequence],
        token_ids: List[int],
        first_token: bool,
        logprob_info: Optional[List] = None,
    ) -> List[StepOutput]:
        outputs: List[StepOutput] = []
        now = time.time()
        if logprob_info is None:
            logprob_info = [None] * len(seqs)
        for seq, token_id, lp in zip(seqs, token_ids, logprob_info):
            sp = seq.sampling_params
            # vLLM stop_token_ids semantics: the token ends generation
            # like EOS but is never appended/streamed (the server treats
            # the -1 sentinel as text-free).
            stop_hit = bool(sp.stop_token_ids and token_id in sp.stop_token_ids)
            if not stop_hit:
                seq.output_token_ids.append(token_id)
                self.total_generated_tokens += 1
                if getattr(seq, "_min_tok_pending", False) and (
                    len(seq.output_token_ids) >= sp.min_tokens
                ):
                    # The ONE boundary crossing: the cached host-state
                    # verdict never needs re-reading after this.
                    seq._min_tok_pending = False
            if seq.first_token_time is None:
                seq.first_token_time = now
                if self.obs.enabled:
                    self.obs.on_first_token(seq, now)
            elif self.obs.enabled and seq.last_token_time is not None:
                self.obs.on_token_gap(seq, now - seq.last_token_time)
            if self.obs.enabled:
                seq.last_token_time = now
            if stop_hit:
                finish = FinishReason.STOP
                token_id = -1
                lp = None
            else:
                finish = self._check_finish(seq, token_id)
            if finish is not None:
                finish = self._finish_seq_now(seq, finish)
            outputs.append(
                StepOutput(
                    seq_id=seq.seq_id,
                    new_token_id=token_id,
                    finished=finish is not None,
                    finish_reason=finish,
                    num_prompt_tokens=seq.num_prompt_tokens,
                    num_output_tokens=seq.num_generated,
                    logprob=lp[0] if lp else None,
                    top_logprobs=lp[1] if lp else None,
                )
            )
        return outputs

    def _finish_seq_now(
        self, seq: Sequence, reason: FinishReason
    ) -> FinishReason:
        """The single finish protocol: scheduler release + prefix-cache
        registration, offload cleanup, counters, registry removal.
        Returns the final reason (guided re-validation may rewrite it);
        callers must surface the returned value, not their local one."""
        rf = seq.sampling_params.response_format
        if (
            reason == FinishReason.STOP
            and seq.guide is not None
            and (rf == "json_object" or isinstance(rf, dict))
        ):
            # The automaton validated per-token text from decode([id]);
            # re-validate the assembled text, which is the ground truth
            # the client receives (for json_schema, against the schema).
            import json as _json

            try:
                obj = _json.loads(self.tokenizer.decode(seq.output_token_ids))
                if isinstance(rf, dict):
                    from production_stack_tpu.engine.guided_schema import (
                        validate_instance,
                    )

                    if not validate_instance(rf.get("schema") or {}, obj):
                        raise ValueError("schema mismatch")
            except Exception:
                logger.warning(
                    "guided json output failed final parse for %s",
                    seq.seq_id,
                )
                reason = FinishReason.GUIDED_INVALID
        seq.finish_reason = reason
        self.scheduler.finish_seq(seq)
        if self.kv_prefetch is not None:
            # Release any still-staged prefetch for this request (its
            # prefix is registered locally now anyway).
            self.kv_prefetch.cancel(seq.seq_id)
        if self._offload_stager is not None:
            self._offload_stager.discard(seq.seq_id)
        self.offload.discard(seq.seq_id)
        self.total_finished += 1
        self._seqs.pop(seq.seq_id, None)
        self.obs.on_finish(seq)
        return reason

    def _check_finish(self, seq: Sequence, token_id: int) -> Optional[FinishReason]:
        sp = seq.sampling_params
        if (
            not sp.ignore_eos
            and self.tokenizer.eos_token_id is not None
            and token_id == self.tokenizer.eos_token_id
        ):
            return FinishReason.STOP
        if seq.num_generated >= sp.max_tokens:
            return FinishReason.LENGTH
        if seq.num_tokens >= self.config.scheduler.max_model_len:
            return FinishReason.LENGTH
        return None

    # -- preemption hook (called by scheduler via engine wrapper) ----------

    def offload_seq_blocks(self, seq: Sequence, block_ids: List[int]) -> bool:
        """Scheduler offload_cb.  Async plane (default with a remote
        store): dispatch the device-side gather (a fresh buffer — the
        pool reuses the source blocks immediately) and hand the D2H wait
        + host insert + optional remote PUT to the stager's writer
        thread; the step thread never blocks.  A True return only
        promises a BEST-EFFORT snapshot: if staging later fails (host
        pool full), restore finds nothing and falls back to recompute —
        the same contract a failed synchronous save has.  Legacy mode
        blocks through offload.save as before."""
        if self._offload_stager is None or self.offload.capacity_bytes <= 0:
            return self._offload_seq_blocks_sync(seq, block_ids)
        if not block_ids:
            return False
        if not self._offload_stager.reserve(seq.seq_id):
            return False  # slot busy: recompute fallback (double-buffer)
        t0 = time.time()
        try:
            ids = jnp.asarray(block_ids, jnp.int32)
            # Quantized wire: the gather stays int8 (data, scale) — half
            # the D2H bytes, and restore adopts the tuples untransformed.
            device_layers = [
                (kv_quant.gather_blocks_wire(k_cache, ids, self._wire_quantized),
                 kv_quant.gather_blocks_wire(v_cache, ids, self._wire_quantized))
                for k_cache, v_cache in self.kv_caches
            ]
        except Exception:
            self._offload_stager.release(seq.seq_id)
            logger.exception("offload gather dispatch failed; recomputing")
            return False
        self._offload_stager.commit(
            seq.seq_id, device_layers, seq.num_tokens
        )
        if self._pending:
            # A window (or step) is still in flight: this D2H gather
            # dispatch rode the alternate stream UNDER its compute — an
            # avoided stall the overlap metric makes visible.
            self.window_transfer_overlap_s += time.time() - t0
        if self.obs.enabled:
            # Step-thread cost only (gather DISPATCH): the D2H wait lives
            # in tpu:offload_stage_seconds, observed by the writer.
            self.obs.tracer.add_span(
                seq.seq_id, "engine.kv_offload", t0, time.time(),
                blocks=len(block_ids), staged=True,
            )
        return True

    # stackcheck: boundary=step-thread reason=legacy sync offload path, only reachable with cache.remote_prefetch=False; the inline D2H wait + remote PUT is its documented A/B-baseline contract
    def _offload_seq_blocks_sync(
        self, seq: Sequence, block_ids: List[int]
    ) -> bool:
        if not self.obs.enabled:
            return self.offload.save(
                seq.seq_id, self.kv_caches, block_ids,
                num_tokens=seq.num_tokens,
            )
        t0 = time.time()
        saved = self.offload.save(
            seq.seq_id, self.kv_caches, block_ids, num_tokens=seq.num_tokens
        )
        if saved:
            # Preemption paging on the request's timeline: the span names
            # why this request's decode stalled.
            self.obs.tracer.add_span(
                seq.seq_id, "engine.kv_offload", t0, time.time(),
                blocks=len(block_ids),
            )
        return saved

    # -- metrics -----------------------------------------------------------

    def _duty_cycle(self) -> float:
        """Fraction of the trailing window spent inside step()."""
        now = time.time()
        cutoff = now - self._busy_window_s
        busy = sum(
            # Clip a step straddling the window edge to the in-window part.
            min(d, t - cutoff)
            for (t, d) in self._busy_window
            if t > cutoff
        )
        return min(1.0, busy / self._busy_window_s)

    def embed(self, prompt_token_ids: List[int]) -> np.ndarray:
        """Normalized mean-pooled embedding of a prompt (llama.encode).
        Pads to the nearest prefill bucket so repeat calls reuse one XLA
        program per bucket."""
        if not hasattr(self.model, "encode"):
            raise ValueError(
                f"model {self.config.model.name!r} has no encode path"
            )
        if not prompt_token_ids:
            # An embedding of the pad token would be silent garbage.
            raise ValueError("input produced no tokens")
        n = len(prompt_token_ids)
        max_len = min(
            self.config.scheduler.prefill_buckets[-1],
            self.config.scheduler.max_model_len,
        )
        if n > max_len:
            # Silent truncation would return an embedding of a prefix while
            # reporting the full token count; fail like completions does.
            raise ValueError(
                f"input is {n} tokens; the embedding path supports up to "
                f"{max_len}"
            )
        bucket = next(
            b for b in self.config.scheduler.prefill_buckets if b >= n
        )
        ids = (list(prompt_token_ids) + [0] * bucket)[:bucket]
        if self._encode_fn is None:
            self._encode_fn = self.obs.compile_tracker.wrap(
                "encode_fn",
                jax.jit(
                    partial(self.model.encode, cfg=self.config.model,
                            mesh=self.mesh)
                ),
            )
        out = self._encode_fn(
            self.params,
            tokens=jnp.asarray(ids, jnp.int32),
            valid_len=jnp.int32(n),
        )
        return np.asarray(out)

    def encode_max_len(self) -> int:
        """Longest input (tokens) the embedding path accepts — the bound
        both ``embed`` and ``encode_batch`` validate against, exposed so
        the API layer can reject over-long inputs before queueing."""
        return min(
            self.config.scheduler.prefill_buckets[-1],
            self.config.scheduler.max_model_len,
        )

    def encode_batch(self, batch_token_ids: List[List[int]]) -> np.ndarray:
        """Batched embeddings: ONE [B, T]-bucketed llama.encode_batch
        dispatch for up to encode_batch_buckets[-1] texts (B pads to an
        encode-batch bucket, T to a prefill bucket), replacing B serial
        ``embed`` round-trips.  Vectors are identical to per-text
        ``embed`` output up to float addition order.  STEP-THREAD-only
        caller in production (the EncodeBatcher) — this touches the
        device."""
        if not hasattr(self.model, "encode_batch"):
            raise ValueError(
                f"model {self.config.model.name!r} has no batched encode path"
            )
        if not batch_token_ids:
            raise ValueError("encode_batch needs at least one input")
        sched = self.config.scheduler
        if len(batch_token_ids) > sched.encode_batch_buckets[-1]:
            raise ValueError(
                f"encode_batch of {len(batch_token_ids)} texts exceeds the "
                f"largest encode batch bucket "
                f"({sched.encode_batch_buckets[-1]})"
            )
        max_len = self.encode_max_len()
        lens = []
        for ids in batch_token_ids:
            if not ids:
                raise ValueError("input produced no tokens")
            if len(ids) > max_len:
                raise ValueError(
                    f"input is {len(ids)} tokens; the embedding path "
                    f"supports up to {max_len}"
                )
            lens.append(len(ids))
        b_bucket = next(
            b for b in sched.encode_batch_buckets
            if b >= len(batch_token_ids)
        )
        t_bucket = next(b for b in sched.prefill_buckets if b >= max(lens))
        rows = [
            (list(ids) + [0] * t_bucket)[:t_bucket]
            for ids in batch_token_ids
        ]
        # Padding rows carry valid_len 0: the masked mean-pool yields a
        # zero vector we slice away below.
        rows += [[0] * t_bucket] * (b_bucket - len(rows))
        valid = lens + [0] * (b_bucket - len(lens))
        if self._encode_batch_fn is None:
            self._encode_batch_fn = self.obs.compile_tracker.wrap(
                "encode_batch_fn",
                jax.jit(
                    partial(self.model.encode_batch, cfg=self.config.model,
                            mesh=self.mesh)
                ),
            )
        t0 = time.time()
        out = self._encode_batch_fn(
            self.params,
            tokens=jnp.asarray(rows, jnp.int32),
            valid_lens=jnp.asarray(valid, jnp.int32),
        )
        vectors = np.asarray(out)[: len(batch_token_ids)]
        # Step-thread-only writers (see counter init): one batch per
        # observation, wall seconds include the device sync above.
        self.encode_texts_total += len(batch_token_ids)
        self.encode_batch_size_hist.observe(float(len(batch_token_ids)))
        self.encode_seconds_hist.observe(time.time() - t0)
        return vectors

    # -- multi-LoRA admin (engine/lora.py) ---------------------------------

    def _require_lora(self):
        if self.lora_registry is None:
            raise ValueError("engine started with max_loras=0")
        return self.lora_registry

    def load_lora(self, name: str, layer_factors, rank: int,
                  alpha: float = 16.0) -> int:
        return self._require_lora().load(name, layer_factors, rank, alpha)

    def load_lora_from_path(self, name: str, path: str,
                            alpha: float = 16.0) -> int:
        from production_stack_tpu.engine.lora import load_peft_safetensors

        factors, rank = load_peft_safetensors(
            path, self.config.model.num_layers
        )
        return self.load_lora(name, factors, rank, alpha)

    def unload_lora(self, name: str) -> None:
        self._require_lora().unload(name)

    def loaded_adapters(self) -> List[str]:
        return [] if self.lora_registry is None else self.lora_registry.loaded()

    def compile_inventory(self) -> Dict[str, int]:
        """Config-derived expected executable counts per jit family — the
        denominator of /debug/compiles' warmup coverage report.  These are
        upper bounds on steady-state inventory (a deployment that never
        sees a shape never compiles it); the report's point is naming the
        families still cold after boot, not exact equality."""
        sched = self.config.scheduler
        dp = max(1, self.config.parallel.data_parallel)
        decode_buckets = 1
        b = dp
        while b < sched.max_num_seqs:
            b *= 2
            decode_buckets += 1
        inv: Dict[str, int] = {
            "prefill_fn": len(sched.prefill_buckets),
            "decode_fn": decode_buckets,
            "sample_fn": decode_buckets,
        }
        if sched.mixed_enabled:
            # One fused variant per (decode bucket, chunk bucket) pair.
            inv["mixed_fn"] = decode_buckets * len(sched.prefill_chunk_buckets)
        if sched.window_steps > 1:
            inv["window_fn"] = decode_buckets
            if sched.spec_window_enabled:
                # The model drafter's do_prime static arg doubles the
                # spec-window inventory (prime / skip-prime variants
                # per decode bucket).
                inv["spec_window_fn"] = decode_buckets * (
                    2 if sched.spec_drafter == "model" else 1
                )
            if sched.mixed_window:
                # Chunk schedules pad to pow2 scan lengths <= decode_window.
                scan_variants, n = 1, 1
                while n < sched.decode_window:
                    n *= 2
                    scan_variants += 1
                inv["mixed_window_fn"] = decode_buckets * scan_variants
        if sched.encode_lane_enabled and hasattr(self.model, "encode_batch"):
            # One executable per (B bucket, T bucket) encode-batch shape.
            inv["encode_batch_fn"] = (
                len(sched.encode_batch_buckets) * len(sched.prefill_buckets)
            )
        return inv

    def compiles_payload(self) -> Dict:
        """GET /debug/compiles: per-executable compile events (most
        expensive first) + the warmup coverage join — compiled-shape
        counts per jit family against the config-derived inventory."""
        rows = self.obs.compile_tracker.snapshot()
        by_family: Dict[str, int] = {}
        for r in rows:
            fam = r["executable"].split("[", 1)[0]
            by_family[fam] = by_family.get(fam, 0) + 1
        inventory = self.compile_inventory()
        coverage = {
            fam: {"compiled": by_family.get(fam, 0), "expected": exp}
            for fam, exp in inventory.items()
        }
        return {
            "enabled": self.obs.enabled,
            "compiled_shapes": self.obs.compile_tracker.compiled_shapes(),
            "compile_seconds": round(
                self.obs.compile_tracker.compile_seconds(), 6
            ),
            "executables": rows,
            "coverage": coverage,
        }

    def stats(self) -> Dict[str, float]:
        return {
            "num_requests_running": self.scheduler.num_running,
            "num_requests_waiting": self.scheduler.num_waiting,
            "hbm_kv_usage_perc": self.block_pool.usage,
            "prefix_cache_hit_rate": self.block_pool.prefix_hit_rate,
            # Prefix-cache truth counters/size (token granularity): the
            # router's fleet popularity view scrapes these to compute the
            # fleet-wide hit rate and to reconcile its prefix-owner map
            # against reality (a restarted engine's cache is empty no
            # matter what the router's routing history says).
            "prefix_cache_hit_tokens": self.block_pool.hit_tokens,
            "prefix_cache_query_tokens": self.block_pool.query_tokens,
            "prefix_cache_blocks": self.block_pool.num_cached_blocks,
            "host_kv_usage_perc": self.offload.usage,
            "duty_cycle": self._duty_cycle(),
            "total_prompt_tokens": self.total_prompt_tokens,
            "total_generated_tokens": self.total_generated_tokens,
            "total_finished": self.total_finished,
            # Prompt tokens prefilled inside fused mixed steps (decode
            # never stalled for them), and the subset that rode a mixed
            # K-step window.
            "prefill_chunk_tokens": self.prefill_chunk_tokens,
            "mixed_window_chunk_tokens": self.mixed_window_chunk_tokens,
            # Transfer seconds issued while the device was busy (H2D
            # chunk staging for chained windows + D2H offload gathers
            # under an in-flight scan) — stalls overlap dispatch avoided.
            "window_transfer_overlap_seconds": self.window_transfer_overlap_s,
            "num_preemptions": self.scheduler.num_preemptions,
            # Overload protection: structured 429s issued by bounded
            # admission, and requests shed/aborted on an expired client
            # deadline (docs/robustness.md).
            "admission_rejected_total": self.admission_rejected,
            "deadline_expired_total": (
                self.deadline_expired + self.deadline_expired_admission
            ),
            "queued_prompt_tokens": self.scheduler.queued_prompt_tokens,
            # Encode lane (batched embed/rerank/score): texts encoded via
            # the [B, T]-bucketed batch path and the current queue depth
            # the batcher is carrying (docs/engine.md).
            "encode_texts_total": self.encode_texts_total,
            "encode_queue_depth": self.encode_queue_depth,
            # Mean host-side serialization per decode step (ms): time the
            # device sat idle between decode steps.  ≈0 when the lookahead
            # pipeline is feeding the device ahead of collection.
            "decode_host_gap_ms": (
                1000.0 * self._gap_total_s / self._gap_steps
                if self._gap_steps else 0.0
            ),
            "loaded_loras": len(self.loaded_adapters()),
            "remote_prefix_blocks_fetched": self.remote_prefix_blocks_fetched,
            "remote_prefix_blocks_exported": self.remote_prefix_blocks_exported,
            # Disaggregated serving: prefill-phase primes served, and
            # decode-phase handoff prefetch outcomes (docs/engine.md).
            "disagg_prefill_primes": self.disagg_prefill_primes,
            "disagg_handoff_hits": self.disagg_handoff_hits,
            "disagg_handoff_misses": self.disagg_handoff_misses,
            # Async KV transfer plane (kv/prefetch.py): blocks imported /
            # dropped by admission-time prefetch, and fetches in flight.
            "kv_prefetch_hit": (
                self.kv_prefetch.hit_blocks if self.kv_prefetch else 0
            ),
            "kv_prefetch_waste": (
                self.kv_prefetch.waste_blocks if self.kv_prefetch else 0
            ),
            "kv_prefetch_inflight": (
                self.kv_prefetch.inflight if self.kv_prefetch else 0
            ),
            "spec_tokens_drafted": self.spec_tokens_drafted,
            "spec_tokens_accepted": self.spec_tokens_accepted,
            # Fused speculative windows: per-window outcome split
            # (accepted / rejected draft tokens, wasted emissions), the
            # configured proposal source ("" when none — keys the
            # drafter label on tpu:spec_window_tokens_total), and scan
            # seconds attributed to the model drafter's forwards.
            "spec_window_tokens": dict(self.spec_window_tokens),
            "spec_drafter": self.config.scheduler.spec_drafter or "",
            "spec_draft_fraction_seconds": self.spec_draft_fraction_s,
            # K-step decode windows: single-step fallbacks by reason and
            # emitted-but-undeliverable window tokens.
            "multistep_fallback": dict(self.multistep_fallback),
            "multistep_wasted_tokens": self.multistep_wasted_tokens,
            # Quantized KV tiering plane: bytes crossing each tier
            # boundary by wire format, and snapshot serde versions put
            # on the kvserver wire (tpu:kv_wire_bytes_total /
            # tpu:kv_snapshot_format_total).
            "kv_wire_bytes": self.kv_wire_stats.wire_bytes(),
            "kv_snapshot_format": self.kv_wire_stats.snapshot_formats(),
            # XLA compile events (obs/compile_tracker.py): seconds spent
            # compiling, per executable shape key, plus the distinct-shape
            # count (tpu:compile_seconds_total / tpu:compiled_shapes).
            "compile_seconds": self.obs.compile_tracker.seconds_by_executable(),
            "compiled_shapes": self.obs.compile_tracker.compiled_shapes(),
            # Trace-ring byte-bound evictions (tpu:obs_trace_dropped_total).
            "obs_trace_dropped": self.obs.tracer.dropped,
        }
