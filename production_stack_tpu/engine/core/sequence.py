"""Request/sequence state for the serving engine."""

from __future__ import annotations

import dataclasses
import enum
import time
from typing import List, Optional, Union


class SequenceStatus(enum.Enum):
    WAITING = "waiting"
    RUNNING = "running"
    PREEMPTED = "preempted"  # paged out (host DRAM) or dropped for recompute
    FINISHED = "finished"


class FinishReason(enum.Enum):
    STOP = "stop"  # EOS or stop string
    LENGTH = "length"
    ABORT = "abort"
    # response_format json_object whose assembled text failed the final
    # json.loads re-check (single-token decode() need not equal a token's
    # in-context byte contribution for sentencepiece/byte-BPE vocabs, so
    # the automaton can diverge from the emitted text; the finish-time
    # re-validation makes that divergence visible instead of silent).
    GUIDED_INVALID = "guided_invalid"


@dataclasses.dataclass
class SamplingParams:
    max_tokens: int = 128
    temperature: float = 0.0  # 0 -> greedy
    top_p: float = 1.0
    top_k: int = 0  # 0 -> disabled
    min_p: float = 0.0  # 0 -> disabled (vLLM min_p: mass cut vs the max prob)
    stop: Optional[List[str]] = None
    # Token ids that end generation like EOS, but are NOT appended to the
    # output (vLLM stop_token_ids semantics).
    stop_token_ids: Optional[List[int]] = None
    ignore_eos: bool = False
    seed: Optional[int] = None
    logprobs: bool = False
    top_logprobs: int = 0  # alternatives returned per token when logprobs
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    repetition_penalty: float = 1.0  # HF/vLLM semantics; 1.0 = off
    # vLLM min_tokens: EOS + stop_token_ids are suppressed at the logits
    # until this many tokens have been generated.
    min_tokens: int = 0
    # vLLM priority scheduling: LOWER value = scheduled earlier; equal
    # priorities keep FCFS order.  Preemption evicts the
    # highest-value (lowest-priority) running sequence first.
    priority: int = 0
    # OpenAI logit_bias: token id -> additive bias in [-100, 100].
    logit_bias: Optional[dict] = None
    # OpenAI completions echo: return the prompt ahead of the completion;
    # combined with logprobs, per-position prompt logprobs are computed
    # during prefill (the lm-eval-harness loglikelihood pattern).
    echo: bool = False
    # OpenAI response_format: None | "text" | "json_object" (byte-level
    # guided decoding, engine/guided.py) | {"type": "json_schema",
    # "schema": {...}} (schema-constrained script, engine/guided_schema.py).
    response_format: Union[str, dict, None] = None
    # Absolute wall-clock deadline (epoch seconds) propagated from the
    # client (X-Request-Deadline header / `timeout` body field).  The
    # server sheds at admission when the deadline is unmeetable; the
    # engine step loop aborts expired WAITING/PREEMPTED sequences so they
    # stop occupying queue slots and KV blocks (running sequences are
    # already streaming and are left to the client to cancel).  Lives on
    # SamplingParams so it rides the lockstep event broadcast unchanged —
    # only the leader evaluates it, and the resulting aborts are published
    # like any other (replica-deterministic).
    deadline: Optional[float] = None


@dataclasses.dataclass
class Sequence:
    seq_id: str
    prompt_token_ids: List[int]
    sampling_params: SamplingParams
    arrival_time: float = dataclasses.field(default_factory=time.time)

    status: SequenceStatus = SequenceStatus.WAITING
    # Multi-LoRA: adapter name + resolved slot (0 = base model, engine/lora.py).
    adapter: Optional[str] = None
    adapter_idx: int = 0
    # Prefix-cache namespace: a per-load-event id (NOT the slot index), so
    # KV cached by a slot's previous tenant can never be served after a
    # slot is reused or an adapter reloaded.
    cache_ns: int = 0
    output_token_ids: List[int] = dataclasses.field(default_factory=list)
    block_table: List[int] = dataclasses.field(default_factory=list)
    num_cached_tokens: int = 0  # prefix-cache hit length at admission
    finish_reason: Optional[FinishReason] = None
    first_token_time: Optional[float] = None
    # Observability (obs/): first prefill-chunk launch (ends the queue-wait
    # span) and the previous token's emit time (feeds the engine ITL
    # histogram).  Maintained only when obs.tracing is on.
    first_scheduled_time: Optional[float] = None
    last_token_time: Optional[float] = None
    # Host-offload bookkeeping: host buffer ids per paged-out block.
    offloaded: bool = False
    # Mid-chunked-prefill: the sequence sits at its queue's head holding
    # block_table/num_cached_tokens for the chunks already written; the
    # next prefill plan continues from there (scheduler.py).
    partial_prefill: bool = False
    preempt_count: int = 0
    # Generated tokens absorbed into prompt_token_ids by preemption
    # (re-prefill path); keeps max_tokens accounting correct across preempts.
    outputs_absorbed: int = 0
    # echo+logprobs: per-ABSOLUTE-position prompt logprob entries collected
    # during prefill (position -> (logprob|None, [(tid, lp), ...])), and
    # the original prompt length (preemption absorbs outputs into the
    # prompt; echoed positions never grow past this).
    prompt_lp: Optional[dict] = None
    echo_prompt_len: int = 0
    # Guided decoding state (engine/guided.py JsonGuide) when the request
    # set response_format.
    guide: Optional[object] = None
    # Cached host-state sampling verdicts (LLMEngine._host_state_flags):
    # the (window_fallback, classic_fallback, greedy) triple is static
    # over the request's life, so it's computed once instead of
    # re-reading SamplingParams attribute chains on the step thread
    # every dispatch (greedy = temperature <= 0, the fused speculative
    # window's drafting predicate).
    # _min_tok_pending is the ONE dynamic bit — the min_tokens floor is
    # still unmet — cleared by the engine exactly at the boundary
    # crossing and re-armed when preemption empties output_token_ids.
    _hs_flags: Optional[tuple] = None
    _min_tok_pending: Optional[bool] = None

    @property
    def num_prompt_tokens(self) -> int:
        return len(self.prompt_token_ids)

    @property
    def num_tokens(self) -> int:
        return len(self.prompt_token_ids) + len(self.output_token_ids)

    @property
    def all_token_ids(self) -> List[int]:
        return self.prompt_token_ids + self.output_token_ids

    @property
    def num_generated(self) -> int:
        """Total tokens generated for this request, across preemptions."""
        return self.outputs_absorbed + len(self.output_token_ids)

    @property
    def remaining_budget(self) -> int:
        """Output tokens this request may still generate (max_tokens
        minus generated; model-length limits and stop conditions may
        end it sooner).  The window planners use this as the earliest
        step a batch slot could free: a slot-full pure window under
        waiting pressure ends where the first row's budget runs out,
        so admission re-evaluates the moment packing becomes possible
        again."""
        return max(0, self.sampling_params.max_tokens - self.num_generated)

    @property
    def is_finished(self) -> bool:
        return self.status == SequenceStatus.FINISHED

    def blocks_needed(self, block_size: int) -> int:
        """Blocks for the whole sequence (prompt + outputs so far + 1 lookahead)."""
        return (self.num_tokens + block_size) // block_size


def host_state_flags(seq: Sequence) -> tuple:
    """(window_fallback, classic_fallback, greedy) cached verdicts —
    THE one place the host-state taxonomy lives, shared by the engine's
    dispatch gates and the scheduler's mixed-window planner (the
    scheduler must not plan a K-step mixed window the engine would have
    to fall back out of).  window_fallback: features the K-step window
    cannot serve on-device (logprobs, logit_bias, guided — penalties
    and the min_tokens floor run inside the scan).  classic_fallback:
    the stricter single-step-pipeline set (its sampler has no penalty
    path).  greedy: temperature <= 0 — the fused speculative window's
    drafting predicate.  All three are static over a request's life;
    the companion ``_min_tok_pending`` dynamic bit is armed here and
    cleared by the engine at the boundary crossing."""
    flags = seq._hs_flags
    if flags is None:
        sp = seq.sampling_params
        window = bool(
            sp.logprobs or sp.logit_bias or seq.guide is not None
        )
        classic = window or bool(
            sp.presence_penalty
            or sp.frequency_penalty
            or sp.repetition_penalty != 1.0
        )
        seq._hs_flags = flags = (window, classic, sp.temperature <= 0)
        seq._min_tok_pending = (
            sp.min_tokens > len(seq.output_token_ids)
        )
    return flags


@dataclasses.dataclass
class StepOutput:
    """One engine step's result for one sequence."""

    seq_id: str
    new_token_id: int
    finished: bool
    finish_reason: Optional[FinishReason]
    num_prompt_tokens: int
    num_output_tokens: int
    # Set when the request asked for logprobs: log P(chosen) and the top-k
    # alternatives as (token_id, logprob) pairs.
    logprob: Optional[float] = None
    top_logprobs: Optional[List] = None
    # First-token event of an echo+logprobs request: ordered per-prompt-
    # position entries [(logprob|None, top_pairs|None), ...] (index 0 is
    # None — no context predicts the first token).
    prompt_logprobs: Optional[List] = None
