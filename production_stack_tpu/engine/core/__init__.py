"""Engine core: sequences, continuous-batching scheduler, step loop."""
