"""Int8 KV-cache quantization (CacheConfig.kv_cache_dtype="int8").

Decode at long context is KV-bandwidth-bound: every step re-reads every
live KV block (SURVEY §5 — the stack's long-context story is KV capacity
+ reuse).  Storing each cached K/V vector as int8 with a per-(token,
kv-head) fp32 scale halves the bytes the decode kernel streams AND the
bytes a block occupies, so the pool holds ~2x the tokens at equal HBM.

Representation: a quantized cache side is the 2-tuple

    (data int8 [N, bs, K, D], scale fp32 [N, bs, K])

threaded through the engine/model code in place of the plain
``[N, bs, K, D]`` array — an ordinary jax pytree, so jit/donation/
sharding work unchanged (scales shard over tp on the K axis exactly like
the data).  Quantization is DYNAMIC per written vector (scale =
max|x|/127 at write time), so appends never rescale existing entries.

Host offload and the remote store carry the QUANTIZED representation
end-to-end by default (cache.kv_wire_format="auto"): an int8 cache's
(data, scale) tuples serialize natively — no dequant round-trip on the
D2H path, ~4x more resident tokens per byte in the host tier than the
retired fp32 wire, and restore is trivially bit-preserving because
nothing is transformed.  The kvserver snapshot serde is versioned for
this (kvserver/protocol.py: v1 = legacy dense fp32, v2 = int8 data +
fp32 scales); dense caches still write v1 frames, and
cache.kv_wire_format="fp32" pins an int8 cache to the legacy dense
wire too — that fallback stays exactly idempotent (the dequantized
vector's max-abs IS scale*127, so requantization reproduces the
identical int8 data) and remains parity-tested.  Importers adopt
natively or cast/quantize whatever arrives, so engines with different
kv dtypes (and serde versions, via the client's probe-once fallback)
interoperate either way.

The reference has no analogue (KV precision lives inside its external
vLLM engine; its stack-level lever is LMCache offload,
deployment-vllm-multi.yaml:154-178).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

# int8 symmetric range; -128 is unused so the grid is symmetric.
_QMAX = 127.0


def is_quantized(side) -> bool:
    """A cache side is either a plain array or a (data, scale) tuple."""
    return isinstance(side, tuple)


def cache_shape(side) -> Tuple[int, ...]:
    """[N, bs, K, D] of the underlying block data."""
    return side[0].shape if is_quantized(side) else side.shape


def quantize_vectors(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Per-vector symmetric int8 quantization over the trailing (D) axis.

    x: [..., D] -> (int8 [..., D], fp32 scale [...]).  A zero vector gets
    scale 0 and dequantizes back to exact zeros.
    """
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = amax / _QMAX
    safe = jnp.where(scale > 0, scale, 1.0)
    data = jnp.clip(
        jnp.round(x.astype(jnp.float32) / safe[..., None]), -_QMAX, _QMAX
    ).astype(jnp.int8)
    return data, scale


def dequantize(data: jax.Array, scale: jax.Array, dtype=None) -> jax.Array:
    """(int8 [..., D], scale [...]) -> values [..., D] (fp32 by default)."""
    out = data.astype(jnp.float32) * scale.astype(jnp.float32)[..., None]
    return out if dtype is None else out.astype(dtype)


# -- generic cache-side block transfer (engine / offload / disagg) ---------
#
# A host/wire block side is either a DENSE [n, bs, K, D] array (plain
# caches; the fp32 legacy wire for quantized ones) or the quantized
# (data int8 [n, bs, K, D], scale fp32 [n, bs, K]) tuple (the native
# int8 wire).  These helpers are the single conversion boundary.


def gather_blocks_device(side, ids: jax.Array) -> jax.Array:
    """Device gather of whole blocks -> dense DEVICE array [n, bs, K, D].

    Dispatches asynchronously and returns without a host sync: the result
    is a fresh buffer, so the source cache blocks can be freed/reused
    immediately while a writer thread later pays the D2H wait
    (offload.OffloadStager) off the step thread."""
    if is_quantized(side):
        data, scale = side
        return dequantize(data[ids], scale[ids])
    return side[ids]


def gather_blocks_host(side, ids: jax.Array) -> np.ndarray:
    """Device gather of whole blocks -> dense host array [n, bs, K, D]
    (blocks on the D2H transfer)."""
    return np.asarray(gather_blocks_device(side, ids))


def gather_blocks_wire(side, ids: jax.Array, quantized_wire: bool):
    """Device gather of whole blocks in WIRE format: for a quantized
    cache with the int8 wire active this is the native (data, scale)
    tuple — no dequant pass, half the D2H bytes; otherwise the dense
    array gather_blocks_device produces.  Async like
    gather_blocks_device: fresh buffers, no host sync."""
    if quantized_wire and is_quantized(side):
        data, scale = side
        return (data[ids], scale[ids])
    return gather_blocks_device(side, ids)


def to_host_side(side):
    """Device wire side -> host numpy side (blocks on the D2H wait);
    tuple-aware."""
    if is_quantized(side):
        return (np.asarray(side[0]), np.asarray(side[1]))
    return np.asarray(side)


def slice_host_side(side, n: int):
    """First ``n`` blocks of a host wire side; tuple-aware."""
    if is_quantized(side):
        return (side[0][:n], side[1][:n])
    return side[:n]


def stack_wire_blocks(rows, pool_quantized: bool):
    """Stack single-block host wire sides (each [1, bs, K, D] dense or
    ((data [1, bs, K, D], scale [1, bs, K]))) into one [n, ...] side in
    the POOL's preferred host format, normalizing per block — a mixed-
    precision fleet can interleave dense- and int8-wire blocks within
    one prefix chain.  int8 pools get (data, scale) with dense rows
    host-quantized (bit-identical to the device quantizer — protocol
    quantize_np mirrors quantize_vectors); dense pools get fp32 rows
    with quantized blocks host-dequantized."""
    from production_stack_tpu.kvserver import protocol as proto

    if pool_quantized:
        datas, scales = [], []
        for row in rows:
            if is_quantized(row):
                datas.append(np.asarray(row[0][0]))
                scales.append(np.asarray(row[1][0], np.float32))
            else:
                d, s = proto.quantize_np(np.asarray(row[0]))
                datas.append(d)
                scales.append(s)
        return (np.stack(datas), np.stack(scales))
    dense = []
    for row in rows:
        if is_quantized(row):
            dense.append(
                proto.dequantize_np(np.asarray(row[0][0]), np.asarray(row[1][0]))
            )
        else:
            dense.append(np.asarray(row[0]))
    return np.stack(dense)


def set_blocks(side, ids: jax.Array, host_blocks) -> object:
    """Write host blocks into the cache side and return the new side.
    ``host_blocks`` is a dense [n, bs, K, D] array (quantized sides
    quantize it on write) or a native (data, scale) tuple — adopted
    as-is by a quantized side (the no-requantize restore/import path),
    dequantized for a dense side (mixed-precision import)."""
    if isinstance(host_blocks, tuple):
        q_host, s_host = host_blocks
        if is_quantized(side):
            data, scale = side
            return (
                data.at[ids].set(jnp.asarray(q_host, data.dtype)),
                scale.at[ids].set(jnp.asarray(s_host, scale.dtype)),
            )
        dense = dequantize(jnp.asarray(q_host), jnp.asarray(s_host))
        return side.at[ids].set(dense.astype(side.dtype))
    if is_quantized(side):
        data, scale = side
        q, s = quantize_vectors(jnp.asarray(host_blocks))
        return (data.at[ids].set(q), scale.at[ids].set(s.astype(scale.dtype)))
    return side.at[ids].set(jnp.asarray(host_blocks, side.dtype))
