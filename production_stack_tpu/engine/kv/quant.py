"""Int8 KV-cache quantization (CacheConfig.kv_cache_dtype="int8").

Decode at long context is KV-bandwidth-bound: every step re-reads every
live KV block (SURVEY §5 — the stack's long-context story is KV capacity
+ reuse).  Storing each cached K/V vector as int8 with a per-(token,
kv-head) fp32 scale halves the bytes the decode kernel streams AND the
bytes a block occupies, so the pool holds ~2x the tokens at equal HBM.

Representation: a quantized cache side is the 2-tuple

    (data int8 [N, bs, K, D], scale fp32 [N, bs, K])

threaded through the engine/model code in place of the plain
``[N, bs, K, D]`` array — an ordinary jax pytree, so jit/donation/
sharding work unchanged (scales shard over tp on the K axis exactly like
the data).  Quantization is DYNAMIC per written vector (scale =
max|x|/127 at write time), so appends never rescale existing entries.

Host offload and the remote store keep a DENSE FP32 wire format: the
fp32 dequantize/requantize round-trip is exactly idempotent (the
dequantized vector's max-abs IS scale*127, so requantization reproduces
the identical int8 data), which is what makes offload-restore
bit-preserving; a model-dtype (bf16) wire would halve those bytes but
round the values and break that guarantee.  The trade is deliberate:
offload lives in host DRAM and the store on the datacenter network,
where 2x bytes is cheaper than any restore-fidelity wobble.  Importers
cast-or-quantize whatever arrives, so engines with different kv dtypes
interoperate either way.

The reference has no analogue (KV precision lives inside its external
vLLM engine; its stack-level lever is LMCache offload,
deployment-vllm-multi.yaml:154-178).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

# int8 symmetric range; -128 is unused so the grid is symmetric.
_QMAX = 127.0


def is_quantized(side) -> bool:
    """A cache side is either a plain array or a (data, scale) tuple."""
    return isinstance(side, tuple)


def cache_shape(side) -> Tuple[int, ...]:
    """[N, bs, K, D] of the underlying block data."""
    return side[0].shape if is_quantized(side) else side.shape


def quantize_vectors(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Per-vector symmetric int8 quantization over the trailing (D) axis.

    x: [..., D] -> (int8 [..., D], fp32 scale [...]).  A zero vector gets
    scale 0 and dequantizes back to exact zeros.
    """
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = amax / _QMAX
    safe = jnp.where(scale > 0, scale, 1.0)
    data = jnp.clip(
        jnp.round(x.astype(jnp.float32) / safe[..., None]), -_QMAX, _QMAX
    ).astype(jnp.int8)
    return data, scale


def dequantize(data: jax.Array, scale: jax.Array, dtype=None) -> jax.Array:
    """(int8 [..., D], scale [...]) -> values [..., D] (fp32 by default)."""
    out = data.astype(jnp.float32) * scale.astype(jnp.float32)[..., None]
    return out if dtype is None else out.astype(dtype)


# -- generic cache-side block transfer (engine / offload / disagg) ---------
#
# Host/wire blocks are DENSE [n, bs, K, D] arrays — the cache's own dtype
# for plain caches, fp32 for quantized ones (exact requantization; see
# module docstring).  These helpers are the single conversion boundary.


def gather_blocks_device(side, ids: jax.Array) -> jax.Array:
    """Device gather of whole blocks -> dense DEVICE array [n, bs, K, D].

    Dispatches asynchronously and returns without a host sync: the result
    is a fresh buffer, so the source cache blocks can be freed/reused
    immediately while a writer thread later pays the D2H wait
    (offload.OffloadStager) off the step thread."""
    if is_quantized(side):
        data, scale = side
        return dequantize(data[ids], scale[ids])
    return side[ids]


def gather_blocks_host(side, ids: jax.Array) -> np.ndarray:
    """Device gather of whole blocks -> dense host array [n, bs, K, D]
    (blocks on the D2H transfer)."""
    return np.asarray(gather_blocks_device(side, ids))


def set_blocks(side, ids: jax.Array, host_blocks) -> object:
    """Write dense host blocks [n, bs, K, D] into the cache side
    (quantizing when the side is quantized).  Returns the new side."""
    if is_quantized(side):
        data, scale = side
        q, s = quantize_vectors(jnp.asarray(host_blocks))
        return (data.at[ids].set(q), scale.at[ids].set(s.astype(scale.dtype)))
    return side.at[ids].set(jnp.asarray(host_blocks, side.dtype))
