"""Paged KV cache management: block pool, prefix cache, host offload."""
