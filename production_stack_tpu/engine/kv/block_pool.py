"""KV block pool with hash-chain prefix caching.

vLLM-style paged KV management rebuilt for the TPU engine: fixed-size token
blocks, ref-counted sharing of cached prefixes, and LRU eviction of
freed-but-cached blocks.  The prefix-cache hit rate measured here feeds the
``tpu:prefix_cache_hit_rate`` gauge the router's KV-aware routing and the
Grafana dashboard key off (reference scrapes the same concept from vLLM as
``vllm:gpu_prefix_cache_hit_rate``, stats/engine_stats.py:52-53).

Block 0 is the reserved *null block*: padding scatter targets land there and
it is never read or allocated.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple


def _chain_hash(prev: Optional[bytes], tokens: Sequence[int]) -> bytes:
    h = hashlib.blake2b(digest_size=16)
    h.update(prev or b"\x00" * 16)
    h.update(b",".join(str(t).encode() for t in tokens))
    return h.digest()


def prefix_block_hashes(
    token_ids: Sequence[int], block_size: int, namespace: int = 0
) -> List[bytes]:
    """Chain hash of every full block of ``token_ids`` (leaving >= 1 token
    uncached, mirroring match_prefix).  These digests are the content keys
    for cross-engine prefix sharing through the remote KV store — two
    engines hashing the same tokens under the same namespace produce the
    same keys."""
    usable = len(token_ids) - 1
    prev: Optional[bytes] = (
        _chain_hash(None, [namespace]) if namespace else None
    )
    out: List[bytes] = []
    for start in range(0, usable - usable % block_size, block_size):
        prev = _chain_hash(prev, token_ids[start : start + block_size])
        out.append(prev)
    return out


class BlockPool:
    def __init__(self, num_blocks: int, block_size: int, enable_prefix_caching: bool = True):
        if num_blocks < 2:
            raise ValueError("need at least 2 blocks (block 0 is the null block)")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.enable_prefix_caching = enable_prefix_caching
        self._free: List[int] = list(range(1, num_blocks))  # 0 = null block
        self._ref_counts: Dict[int, int] = {}
        # Prefix cache: chain hash -> block id; and reverse map.
        self._hash_to_block: Dict[bytes, int] = {}
        self._block_to_hash: Dict[int, bytes] = {}
        # Freed blocks whose content is still valid, LRU-ordered.
        self._cached_free: "OrderedDict[int, None]" = OrderedDict()
        # Metrics (token-granularity, like vLLM's hit-rate gauge).
        self.query_tokens = 0
        self.hit_tokens = 0

    # -- capacity ----------------------------------------------------------

    @property
    def num_free_blocks(self) -> int:
        return len(self._free) + len(self._cached_free)

    @property
    def usage(self) -> float:
        """Fraction of non-null blocks currently referenced by sequences."""
        total = self.num_blocks - 1
        return (total - self.num_free_blocks) / total if total else 0.0

    @property
    def prefix_hit_rate(self) -> float:
        if not self.query_tokens:
            return 0.0
        return self.hit_tokens / self.query_tokens

    @property
    def num_cached_blocks(self) -> int:
        """Blocks whose content is reusable through the prefix cache
        (referenced or cached-free).  Exported as the
        ``tpu:prefix_cache_blocks`` gauge — the router's popularity view
        reconciles its owner map against this truth: a collapse to ~0
        means the engine restarted (or flushed) and every prefix the
        router believes resident there is gone."""
        return len(self._block_to_hash)

    # -- allocation --------------------------------------------------------

    def can_allocate(self, n: int) -> bool:
        return self.num_free_blocks >= n

    def allocate(self, n: int) -> List[int]:
        """Allocate n blocks, evicting LRU cached-free blocks as needed."""
        if not self.can_allocate(n):
            raise RuntimeError(
                f"KV pool exhausted: need {n} blocks, have {self.num_free_blocks}"
            )
        out: List[int] = []
        for _ in range(n):
            if self._free:
                block = self._free.pop()
            else:
                block, _ = self._cached_free.popitem(last=False)  # LRU evict
                self._evict_hash(block)
            self._ref_counts[block] = 1
            out.append(block)
        return out

    def free(self, blocks: Sequence[int]) -> None:
        for block in blocks:
            if block == 0:
                continue
            refs = self._ref_counts.get(block, 0) - 1
            if refs > 0:
                self._ref_counts[block] = refs
                continue
            self._ref_counts.pop(block, None)
            if block in self._block_to_hash:
                # Content still valid: keep it reclaimable via the prefix
                # cache until LRU eviction.
                self._cached_free[block] = None
                self._cached_free.move_to_end(block)
            else:
                self._free.append(block)

    def _evict_hash(self, block: int) -> None:
        digest = self._block_to_hash.pop(block, None)
        if digest is not None and self._hash_to_block.get(digest) == block:
            del self._hash_to_block[digest]

    # -- prefix caching ----------------------------------------------------

    @staticmethod
    def _namespace_seed(namespace: int) -> Optional[bytes]:
        """Seed the hash chain per namespace (e.g. LoRA adapter slot): KV
        computed under one adapter must never be served to another.
        Namespace 0 keeps the legacy unseeded chain."""
        return _chain_hash(None, [namespace]) if namespace else None

    def match_prefix(
        self, token_ids: Sequence[int], namespace: int = 0
    ) -> Tuple[List[int], int]:
        """Longest cached full-block prefix of token_ids.

        Returns (block_ids, num_cached_tokens); increments the matched
        blocks' refcounts (caller owns them until free()).  At least one
        token is always left uncached so prefill has work to do.
        """
        self.query_tokens += len(token_ids)
        if not self.enable_prefix_caching:
            return [], 0
        bs = self.block_size
        usable = len(token_ids) - 1  # leave >=1 token for prefill
        blocks: List[int] = []
        prev: Optional[bytes] = self._namespace_seed(namespace)
        for start in range(0, usable - usable % bs, bs):
            digest = _chain_hash(prev, token_ids[start : start + bs])
            block = self._hash_to_block.get(digest)
            if block is None:
                break
            blocks.append(block)
            prev = digest
        for block in blocks:
            if block in self._cached_free:
                del self._cached_free[block]
                self._ref_counts[block] = 1
            else:
                self._ref_counts[block] = self._ref_counts.get(block, 0) + 1
        cached = len(blocks) * bs
        self.hit_tokens += cached
        return blocks, cached

    def count_cached_prefix(self, digests: Sequence[bytes]) -> int:
        """How many LEADING chain digests the cache currently holds,
        WITHOUT claiming them (no refcount change) — the admission-time
        prefetch planner uses this to size the remote miss tail."""
        if not self.enable_prefix_caching:
            return 0
        n = 0
        for digest in digests:
            if digest not in self._hash_to_block:
                break
            n += 1
        return n

    def has_digest(self, digest: bytes) -> bool:
        return digest in self._hash_to_block

    def adopt_prefix_block(self, digest: bytes, block: int) -> bool:
        """Bind an imported (remote-prefetched) block's content to its
        chain digest so match_prefix can serve it.  The caller owns the
        block (allocated, refcount 1) and frees it right after adoption,
        parking it in the reclaimable cached-free tier.  False when the
        digest is already mapped (a concurrent local prefill won the
        race): the caller's block frees as plain storage."""
        if not self.enable_prefix_caching or digest in self._hash_to_block:
            return False
        self._evict_hash(block)  # block may have held older content
        self._hash_to_block[digest] = block
        self._block_to_hash[block] = digest
        return True

    def register_prefix(
        self,
        token_ids: Sequence[int],
        block_table: Sequence[int],
        namespace: int = 0,
    ) -> None:
        """Record hash chain for every *full* block of this sequence so later
        requests with the same prefix hit the cache."""
        if not self.enable_prefix_caching:
            return
        bs = self.block_size
        prev: Optional[bytes] = self._namespace_seed(namespace)
        for i in range(len(token_ids) // bs):
            digest = _chain_hash(prev, token_ids[i * bs : (i + 1) * bs])
            block = block_table[i]
            existing = self._hash_to_block.get(digest)
            if existing is None:
                self._evict_hash(block)  # block may have held older content
                self._hash_to_block[digest] = block
                self._block_to_hash[block] = digest
            prev = digest
