"""Admission-time asynchronous KV prefetch plane.

The synchronous remote-prefix path this replaces issued one blocking TCP
round-trip per KV block, serially, INSIDE the scheduler callback — a
2k-token prompt with a warm shared-store prefix stalled every live
decoder for a full chain of network RTTs (the same decode-interference
failure mode mixed-batch scheduling removed for prefill compute).

Here the transfer moves off-step entirely:

* ``submit_chain`` — when a request enters the waiting queue, a fetcher
  thread resolves the local prefix-cache miss tail against the remote
  store (ONE batched MGET round-trip per chain, client.py) into host
  staging buffers.
* ``pop_completed`` — the engine's step thread drains finished chains at
  the top of its dispatch loop and imports the blocks into the paged-KV
  prefix cache; the next scheduling pass's ``match_prefix`` then serves
  them like any local hit.  Nothing in ``Scheduler.schedule()`` ever
  waits on the network: an in-flight prefetch simply isn't there yet and
  admission proceeds local-only.
* ``submit_restore`` / ``poll_restore`` — the preemption-restore
  analogue: a remote snapshot pages in off-step, landing in the
  HostOffloadManager's local tier; the scheduler re-checks readiness
  ("retry") instead of blocking.
* ``cancel`` — a request aborted or finished mid-flight releases its
  staging buffers; a worker completing a cancelled job drops the result
  (counted as waste) and never touches engine state.

Counters feed ``tpu:kv_prefetch_{hit,waste,inflight}``; per-RPC latency
feeds the ``tpu:remote_kv_fetch_seconds`` histogram via ``observe_fetch``.
"""

from __future__ import annotations

import dataclasses
import logging
import queue
import threading
import time
from typing import TYPE_CHECKING, Dict, List, Optional

if TYPE_CHECKING:
    from production_stack_tpu.engine.kv.offload import HostOffloadManager
    from production_stack_tpu.kvserver.client import RemoteKVClient

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class PrefetchedChain:
    """A completed chain fetch: ``blocks[i]`` is the per-layer
    [(k [1, bs, K, D], v [1, bs, K, D]), ...] staging buffers for the
    block whose chain digest is ``hashes[i]`` (chain index
    ``start_block + i``)."""

    seq_id: str
    start_block: int
    hashes: List[bytes]
    blocks: List[list]
    attempts: int = 0  # import retries under transient pool pressure


class PrefetchManager:
    def __init__(
        self,
        client: "RemoteKVClient",
        restore_sink: Optional["HostOffloadManager"] = None,
        num_threads: int = 2,
        observe_fetch=None,  # callable(seconds) or None
    ):
        self._client = client
        self._restore_sink = restore_sink
        self._num_threads = max(1, int(num_threads))
        self._observe = observe_fetch
        self._q: "queue.Queue" = queue.Queue()
        self._lock = threading.Lock()
        self._idle = threading.Condition(self._lock)
        # (kind, seq_id) -> job dict; kinds "chain"/"restore" are keyed
        # separately so a re-admitted preempted sequence can page in its
        # snapshot while an old chain fetch is still settling.  States:
        # inflight -> done | cancelled (done jobs are popped by
        # pop_completed/poll_restore; cancelled jobs are reaped by the
        # worker that owns them).
        self._jobs: Dict[tuple, dict] = {}
        self._threads: List[threading.Thread] = []
        self.hit_blocks = 0  # blocks imported into HBM / the prefix cache
        self.waste_blocks = 0  # blocks fetched then dropped unused

    # -- accounting (engine import paths call these) -----------------------

    def note_hit(self, n: int) -> None:
        with self._lock:
            self.hit_blocks += n

    def note_waste(self, n: int) -> None:
        with self._lock:
            self.waste_blocks += n

    @property
    def inflight(self) -> int:
        with self._lock:
            return sum(
                1 for j in self._jobs.values() if j["state"] == "inflight"
            )

    # -- chain prefetch ----------------------------------------------------

    def submit_chain(
        self,
        seq_id: str,
        keys: List[str],
        hashes: List[bytes],
        start_block: int,
    ) -> bool:
        """Queue a background fetch of ``keys`` (the local prefix-cache
        miss tail of one request's hash chain).  No-op when a job for the
        sequence already exists, or when another live job is fetching the
        same chain head (the same-prompt burst dedupe: the duplicate will
        hit the prefix cache once the first import lands)."""
        if not keys:
            return False
        key = ("chain", seq_id)
        with self._lock:
            if key in self._jobs:
                return False
            for job in self._jobs.values():
                if job.get("head") == keys[0] and job["state"] == "inflight":
                    return False
            self._jobs[key] = {
                "state": "inflight",
                "head": keys[0],
                "keys": keys,
                "hashes": list(hashes),
                "start_block": start_block,
                "result": None,
            }
        self._ensure_threads()
        self._q.put(key)
        return True

    def has_job(self, seq_id: str) -> bool:
        with self._lock:
            return ("chain", seq_id) in self._jobs

    def chain_status(self, seq_id: str) -> str:
        """"absent" (no job — completed empty, already consumed, or
        never submitted), "inflight", "cancelled", or "done" (staged:
        the step thread imports it at its next dispatch, BEFORE any
        schedule() — the disagg handoff wait keys on this)."""
        with self._lock:
            job = self._jobs.get(("chain", seq_id))
            if job is None:
                return "absent"
            return str(job["state"])

    def pop_completed(self) -> List[PrefetchedChain]:
        """Drain every finished chain fetch (step thread).  Ownership of
        the staging buffers transfers to the caller."""
        out: List[PrefetchedChain] = []
        with self._lock:
            done = [
                key
                for key, job in self._jobs.items()
                if key[0] == "chain" and job["state"] == "done"
            ]
            for key in done:
                job = self._jobs.pop(key)
                if job["result"] is not None:
                    out.append(job["result"])
        return out

    def cancel(self, seq_id: str) -> None:
        """Abort/finish hook: release the sequence's staging buffers
        (chain AND restore jobs).  An in-flight worker sees the cancelled
        state when it completes and drops its result — no late copy-in
        ever reaches the engine."""
        with self._lock:
            for key in (("chain", seq_id), ("restore", seq_id)):
                job = self._jobs.get(key)
                if job is None:
                    continue
                if job["state"] == "done":
                    result = self._jobs.pop(key).get("result")
                    if result is not None:
                        self.waste_blocks += len(result.blocks)
                    continue
                job["state"] = "cancelled"

    # -- restore page-in ---------------------------------------------------

    def submit_restore(self, seq_id: str) -> bool:
        """Queue an async remote page-in of a preemption snapshot; on
        success the worker lands it in the HostOffloadManager local tier
        (restore_sink.insert_fetched) for the next restore_local()."""
        key = ("restore", seq_id)
        with self._lock:
            if key in self._jobs:
                return False
            self._jobs[key] = {"state": "inflight", "found": False}
        self._ensure_threads()
        self._q.put(key)
        return True

    def poll_restore(self, seq_id: str) -> str:
        """"absent" (no job — submit one), "inflight" (re-check next
        pass), "ready" (snapshot now in the local tier), or "missing"
        (store had nothing: recompute)."""
        key = ("restore", seq_id)
        with self._lock:
            job = self._jobs.get(key)
            if job is None:
                return "absent"
            if job["state"] == "inflight":
                return "inflight"
            self._jobs.pop(key)
            return "ready" if job["found"] else "missing"

    # -- worker ------------------------------------------------------------

    def _ensure_threads(self) -> None:
        with self._lock:
            if self._threads:
                return
            for i in range(self._num_threads):
                t = threading.Thread(
                    target=self._worker, name=f"kv-prefetch-{i}", daemon=True
                )
                t.start()
                self._threads.append(t)

    # stackcheck: thread=kv-prefetch
    def _worker(self) -> None:
        while True:
            key = self._q.get()
            if key is None:
                return
            with self._lock:
                job = self._jobs.get(key)
                if job is not None and job["state"] != "inflight":
                    # Cancelled before we picked it up: reap it here so
                    # it neither leaks nor holds wait_idle open.
                    self._jobs.pop(key, None)
                    self._idle.notify_all()
                    job = None
            if job is None:
                continue
            if key[0] == "chain":
                self._fetch_chain(key, job)
            else:
                self._fetch_restore(key, job)
            with self._lock:
                self._idle.notify_all()

    def _fetch_chain(self, key: tuple, job: dict) -> None:
        t0 = time.time()
        blocks: List[list] = []
        try:
            entries = self._client.mget_blocks(job["keys"])
            blocks = [layers for layers, _ in entries]
        except Exception:
            # Store outage: complete empty — admission proceeds (or
            # already proceeded) local-only, exactly as with no store.
            logger.debug(
                "remote prefix prefetch failed for %s; local-only",
                key[1], exc_info=True,
            )
        if self._observe is not None:
            self._observe(time.time() - t0)
        with self._lock:
            live = self._jobs.get(key)
            if live is not job or job["state"] == "cancelled":
                # Aborted mid-flight: drop the staging buffers here.
                self._jobs.pop(key, None)
                self.waste_blocks += len(blocks)
                return
            if not blocks:
                self._jobs.pop(key, None)
                return
            job["state"] = "done"
            job["result"] = PrefetchedChain(
                seq_id=key[1],
                start_block=job["start_block"],
                hashes=job["hashes"][: len(blocks)],
                blocks=blocks,
            )

    def _fetch_restore(self, key: tuple, job: dict) -> None:
        seq_id = key[1]
        t0 = time.time()
        fetched = None
        try:
            fetched = self._client.get_blocks(seq_id)
        except Exception:
            logger.debug(
                "remote restore fetch failed for %s", seq_id, exc_info=True
            )
        if self._observe is not None:
            self._observe(time.time() - t0)
        found = False
        if fetched is not None:
            layers, num_tokens = fetched
            with self._lock:
                cancelled = job["state"] == "cancelled"
            if not cancelled and self._restore_sink is not None:
                found = self._restore_sink.insert_fetched(
                    seq_id, layers, num_tokens
                )
                # A cancel landing between the check and the insert found
                # nothing to discard: re-check and undo, so the aborted
                # sequence's snapshot does not linger in the local tier.
                with self._lock:
                    cancelled = job["state"] == "cancelled"
                if cancelled and found:
                    self._restore_sink.discard(seq_id)
                    found = False
        with self._lock:
            if self._jobs.get(key) is not job or job["state"] == "cancelled":
                self._jobs.pop(key, None)
                return
            job["state"] = "done"
            job["found"] = found

    # -- lifecycle ---------------------------------------------------------

    def wait_idle(self, timeout: float = 10.0) -> bool:
        """Block until no job is in flight (tests; graceful shutdown).
        Completed-but-unconsumed results may still be queued."""
        deadline = time.time() + timeout
        with self._lock:
            # "cancelled" jobs are still owned by a worker until reaped —
            # waiting them out makes waste accounting deterministic.
            while any(
                j["state"] in ("inflight", "cancelled")
                for j in self._jobs.values()
            ):
                remaining = deadline - time.time()
                if remaining <= 0:
                    return False
                self._idle.wait(remaining)
        return True

    def shutdown(self, timeout: float = 5.0) -> None:
        # One shared budget across every fetcher join: N hung fetchers
        # must not stack N timeouts into the drain grace.  The handle
        # list is swapped out under the lock (vs the lazy _ensure_threads
        # start); the joins run outside it.
        deadline = time.monotonic() + timeout
        with self._lock:
            threads, self._threads = self._threads, []
        for _ in threads:
            self._q.put(None)
        for t in threads:
            t.join(max(0.0, deadline - time.monotonic()))
