"""KV offload: TPU HBM -> host DRAM (-> remote shared store).

The reference gets this capability from LMCache env plumbing
(deployment-vllm-multi.yaml:154-178: LMCACHE_LOCAL_CPU,
LMCACHE_MAX_LOCAL_CPU_SIZE, LMCACHE_REMOTE_URL); on TPU we own the
mechanism: preempted sequences' KV blocks are gathered on-device and DMA'd
to pinned host memory, and restored by scatter when the sequence resumes —
trading host<->HBM bandwidth (which overlaps TPU compute) for MXU re-prefill
FLOPs.

Tiering: host DRAM first; optional remote shared KV store
(kvserver/, ``kv://host:port``) as the cross-replica tier, mirroring the
reference's cacheserver (`lm://`) layer.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class OffloadEntry:
    seq_id: str
    num_tokens: int
    # Per layer: (k_blocks, v_blocks) as host numpy arrays [nb, bs, K, D].
    layers: List[Tuple[np.ndarray, np.ndarray]]
    nbytes: int
    saved_at: float = dataclasses.field(default_factory=time.time)


class HostOffloadManager:
    """Bounded host-DRAM pool of per-sequence KV block snapshots."""

    def __init__(self, capacity_bytes: int, remote_client=None):
        self.capacity_bytes = int(capacity_bytes)
        self.used_bytes = 0
        self._entries: Dict[str, OffloadEntry] = {}
        self.remote_client = remote_client  # kvserver client (optional tier)
        # seq_ids known to have a snapshot in the remote store (local put
        # or remote fetch): bounds discard() to one DEL for those only —
        # never a blocking RPC for sequences that were never offloaded.
        self._remote_keys: set = set()
        self.saves = 0
        self.restores = 0
        self.evictions = 0

    @property
    def usage(self) -> float:
        if not self.capacity_bytes:
            return 0.0
        return self.used_bytes / self.capacity_bytes

    def save(
        self,
        seq_id: str,
        kv_caches,  # list of (k_cache, v_cache) device arrays
        block_ids: List[int],
        num_tokens: int,
    ) -> bool:
        """Page a sequence's blocks out to host DRAM.  Returns False when it
        does not fit (caller falls back to recompute)."""
        if not block_ids or self.capacity_bytes <= 0:
            return False
        from production_stack_tpu.engine.kv import quant as kv_quant

        ids = np.asarray(block_ids, dtype=np.int32)
        layers: List[Tuple[np.ndarray, np.ndarray]] = []
        nbytes = 0
        for k_cache, v_cache in kv_caches:
            # Device-side gather then one contiguous DMA per layer
            # (int8 caches dequantize to the dense host/wire format —
            # the requantize on restore is exactly idempotent, quant.py).
            k_host = kv_quant.gather_blocks_host(k_cache, ids)
            v_host = kv_quant.gather_blocks_host(v_cache, ids)
            layers.append((k_host, v_host))
            nbytes += k_host.nbytes + v_host.nbytes
        while self.used_bytes + nbytes > self.capacity_bytes and self._entries:
            self._evict_oldest()
        if self.used_bytes + nbytes > self.capacity_bytes:
            return False
        self._entries[seq_id] = OffloadEntry(
            seq_id=seq_id, num_tokens=num_tokens, layers=layers, nbytes=nbytes
        )
        self.used_bytes += nbytes
        self.saves += 1
        if self.remote_client is not None:
            try:
                self.remote_client.put_blocks(seq_id, layers, num_tokens)
                self._remote_keys.add(seq_id)
            except Exception:
                logger.warning("remote KV put failed for %s", seq_id, exc_info=True)
        return True

    def restore(self, seq_id: str) -> Optional[OffloadEntry]:
        entry = self._entries.pop(seq_id, None)
        if entry is not None:
            self.used_bytes -= entry.nbytes
            self.restores += 1
            return entry
        if self.remote_client is not None:
            try:
                fetched = self.remote_client.get_blocks(seq_id)
            except Exception:
                logger.warning("remote KV get failed for %s", seq_id, exc_info=True)
                return None
            if fetched is not None:
                layers, num_tokens = fetched
                self.restores += 1
                self._remote_keys.add(seq_id)
                return OffloadEntry(
                    seq_id=seq_id,
                    num_tokens=num_tokens,
                    layers=layers,
                    nbytes=sum(k.nbytes + v.nbytes for k, v in layers),
                )
        return None

    def reinsert(self, entry: OffloadEntry) -> bool:
        """Put a restore()d-but-unused entry back (e.g. the pool could not
        host it yet); also caches remote fetches locally.  Evicts older
        entries like save() — the reinserted snapshot is the one about to
        be needed, so it outranks stale residents."""
        self.restores -= 1  # the paired restore() did not take effect
        while self.used_bytes + entry.nbytes > self.capacity_bytes and self._entries:
            self._evict_oldest()
        if self.used_bytes + entry.nbytes > self.capacity_bytes:
            return False
        self._entries[entry.seq_id] = entry
        self.used_bytes += entry.nbytes
        return True

    def discard(self, seq_id: str) -> None:
        """Drop a finished/aborted sequence's snapshot from every tier —
        including the remote store, or the shared cache leaks one snapshot
        per finished sequence forever."""
        entry = self._entries.pop(seq_id, None)
        if entry is not None:
            self.used_bytes -= entry.nbytes
        if self.remote_client is not None and seq_id in self._remote_keys:
            self._remote_keys.discard(seq_id)
            try:
                self.remote_client.delete(seq_id)
            except Exception:
                logger.debug("remote KV delete failed for %s", seq_id, exc_info=True)

    def _evict_oldest(self) -> None:
        oldest = min(self._entries.values(), key=lambda e: e.saved_at)
        del self._entries[oldest.seq_id]
        self.used_bytes -= oldest.nbytes
        self.evictions += 1
