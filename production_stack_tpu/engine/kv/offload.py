"""KV offload: TPU HBM -> host DRAM (-> remote shared store).

The reference gets this capability from LMCache env plumbing
(deployment-vllm-multi.yaml:154-178: LMCACHE_LOCAL_CPU,
LMCACHE_MAX_LOCAL_CPU_SIZE, LMCACHE_REMOTE_URL); on TPU we own the
mechanism: preempted sequences' KV blocks are gathered on-device and DMA'd
to pinned host memory, and restored by scatter when the sequence resumes —
trading host<->HBM bandwidth (which overlaps TPU compute) for MXU re-prefill
FLOPs.

Tiering: host DRAM first; optional remote shared KV store
(kvserver/, ``kv://host:port``) as the cross-replica tier, mirroring the
reference's cacheserver (`lm://`) layer.

Threading: the manager is shared between the engine step thread
(save/restore/discard) and the async transfer plane's worker threads
(OffloadStager's writer completing a staged snapshot, the prefetch
manager's restore fetcher inserting a remote hit) — every mutation of
the entry map runs under one lock.  ``OffloadStager`` is the OFF-STEP
half of preemption offload: the step thread only dispatches the
device-side gather (async, a fresh buffer — the pool can reuse the
source blocks immediately) and hands the D2H wait + host bookkeeping +
optional remote PUT to a writer thread, so no host-DMA or network byte
is ever waited on inside the scheduler callback.
"""

from __future__ import annotations

import dataclasses
import logging
import queue
import threading
import time
from typing import TYPE_CHECKING, Dict, List, Optional, Tuple

import numpy as np

if TYPE_CHECKING:
    from production_stack_tpu.kvserver.client import RemoteKVClient
    from production_stack_tpu.kvserver.protocol import KVWireStats

logger = logging.getLogger(__name__)


def _side_nbytes(side) -> int:
    """Bytes of a host wire side: dense ndarray or (data, scale) tuple
    (kept module-local and numpy-only so sizing a snapshot never pulls
    the jax import that kv/quant carries)."""
    if isinstance(side, tuple):
        return side[0].nbytes + side[1].nbytes
    return side.nbytes


def _layers_nbytes(layers) -> int:
    return sum(_side_nbytes(k) + _side_nbytes(v) for k, v in layers)


def _layers_wire_format(layers) -> str:
    """Label for tpu:kv_wire_bytes_total{format}: "int8" when any side
    rides the quantized wire, else "dense"."""
    for k, v in layers:
        if isinstance(k, tuple) or isinstance(v, tuple):
            return "int8"
    return "dense"


@dataclasses.dataclass
class OffloadEntry:
    seq_id: str
    num_tokens: int
    # Per layer: (k_blocks, v_blocks) host wire sides — dense numpy
    # arrays [nb, bs, K, D], or native quantized (data int8 [nb, bs, K,
    # D], scale fp32 [nb, bs, K]) tuples (cache.kv_wire_format).
    layers: List[Tuple[np.ndarray, np.ndarray]]
    nbytes: int
    saved_at: float = dataclasses.field(default_factory=time.time)


class HostOffloadManager:
    """Bounded host-DRAM pool of per-sequence KV block snapshots."""

    def __init__(self, capacity_bytes: int,
                 remote_client: Optional["RemoteKVClient"] = None,
                 quantized_wire: bool = False,
                 wire_stats: Optional["KVWireStats"] = None):
        # Quantized snapshots (cache.wire_quantized): the sync save path
        # gathers the int8 cache's native (data, scale) tuples instead
        # of dequantizing to the dense wire — ~4x the resident tokens
        # per host-DRAM byte.
        self.quantized_wire = bool(quantized_wire)
        self.wire_stats = wire_stats
        self.capacity_bytes = int(capacity_bytes)
        self.used_bytes = 0
        self._entries: Dict[str, OffloadEntry] = {}
        self._lock = threading.RLock()
        self.remote_client = remote_client  # kvserver client (optional tier)
        # seq_ids known to have a snapshot in the remote store (local put
        # or remote fetch): bounds discard() to one DEL for those only —
        # never a blocking RPC for sequences that were never offloaded.
        self._remote_keys: set = set()
        # Remote DELs run on a dedicated deleter thread: discard() is
        # called from the step thread (abort/finish), and a synchronous
        # DEL there pays a full kvserver round-trip while every decoder
        # stalls — the exact PR-4 invariant (no kvserver RPC reachable
        # from the step thread) stackcheck rule SC101 enforces.  At-most-
        # one-DEL-per-seq is preserved: _remote_keys membership is still
        # consumed under the lock before the enqueue.
        self._del_queue: Optional[queue.Queue] = None
        self._del_thread: Optional[threading.Thread] = None
        self._del_pending = 0
        self._del_cv = threading.Condition(self._lock)
        self.saves = 0
        self.restores = 0
        self.evictions = 0

    @property
    def usage(self) -> float:
        if not self.capacity_bytes:
            return 0.0
        return self.used_bytes / self.capacity_bytes

    def save(
        self,
        seq_id: str,
        kv_caches,  # list of (k_cache, v_cache) device arrays
        block_ids: List[int],
        num_tokens: int,
    ) -> bool:
        """Page a sequence's blocks out to host DRAM, synchronously (the
        legacy path; the async plane stages through OffloadStager
        instead).  Returns False when it does not fit (caller falls back
        to recompute)."""
        if not block_ids or self.capacity_bytes <= 0:
            return False
        from production_stack_tpu.engine.kv import quant as kv_quant

        ids = np.asarray(block_ids, dtype=np.int32)
        layers: List[Tuple[np.ndarray, np.ndarray]] = []
        for k_cache, v_cache in kv_caches:
            # Device-side gather then one contiguous DMA per layer.  The
            # quantized wire DMAs the int8 cache's native (data, scale)
            # tuples; the dense (fp32) wire dequantizes first — its
            # requantize on restore is exactly idempotent (quant.py).
            k_dev = kv_quant.gather_blocks_wire(
                k_cache, ids, self.quantized_wire
            )
            v_dev = kv_quant.gather_blocks_wire(
                v_cache, ids, self.quantized_wire
            )
            layers.append(
                (kv_quant.to_host_side(k_dev), kv_quant.to_host_side(v_dev))
            )
        return self.insert_saved(seq_id, layers, num_tokens)

    def insert_saved(
        self,
        seq_id: str,
        layers: List[Tuple[np.ndarray, np.ndarray]],
        num_tokens: int,
    ) -> bool:
        """Record an already-gathered host snapshot (step thread via
        save(), or the OffloadStager writer thread) and mirror it to the
        remote tier when configured."""
        nbytes = _layers_nbytes(layers)
        with self._lock:
            while (
                self.used_bytes + nbytes > self.capacity_bytes
                and self._entries
            ):
                self._evict_oldest()
            if self.used_bytes + nbytes > self.capacity_bytes:
                return False
            self._entries[seq_id] = OffloadEntry(
                seq_id=seq_id, num_tokens=num_tokens, layers=layers,
                nbytes=nbytes,
            )
            self.used_bytes += nbytes
            self.saves += 1
        # Counted only once the snapshot LANDED in the tier (an
        # over-capacity rejection moved nothing).
        if self.wire_stats is not None:
            self.wire_stats.add_wire(
                "host", _layers_wire_format(layers), nbytes
            )
        if self.remote_client is not None:
            try:
                self.remote_client.put_blocks(seq_id, layers, num_tokens)
                with self._lock:
                    self._remote_keys.add(seq_id)
            except Exception:
                logger.warning("remote KV put failed for %s", seq_id, exc_info=True)
        return True

    def restore_local(self, seq_id: str) -> Optional[OffloadEntry]:
        """Pop a snapshot from host DRAM only — never a network RPC, so
        it is safe inside the scheduler callback.  The async restore path
        (engine + prefetch.PrefetchManager.submit_restore) fills this
        tier from the remote store off-step and retries."""
        with self._lock:
            entry = self._entries.pop(seq_id, None)
            if entry is not None:
                self.used_bytes -= entry.nbytes
                self.restores += 1
            return entry

    # stackcheck: boundary=step-thread reason=legacy sync restore, only reachable with cache.remote_prefetch=False; the async plane pages in via restore_local + PrefetchManager.submit_restore instead
    def restore(self, seq_id: str) -> Optional[OffloadEntry]:
        """Local tier first, then a BLOCKING remote fetch (legacy path;
        kept for remote_prefetch=False compatibility)."""
        entry = self.restore_local(seq_id)
        if entry is not None:
            return entry
        if self.remote_client is not None:
            try:
                fetched = self.remote_client.get_blocks(seq_id)
            except Exception:
                logger.warning("remote KV get failed for %s", seq_id, exc_info=True)
                return None
            if fetched is not None:
                layers, num_tokens = fetched
                with self._lock:
                    self.restores += 1
                    self._remote_keys.add(seq_id)
                return OffloadEntry(
                    seq_id=seq_id,
                    num_tokens=num_tokens,
                    layers=layers,
                    nbytes=_layers_nbytes(layers),
                )
        return None

    def insert_fetched(
        self,
        seq_id: str,
        layers: List[Tuple[np.ndarray, np.ndarray]],
        num_tokens: int,
    ) -> bool:
        """Cache a remote snapshot locally (the async restore fetcher's
        landing point): the next restore_local() finds it without any
        RPC.  Marks the seq as remote-resident so discard() still DELs."""
        nbytes = _layers_nbytes(layers)
        entry = OffloadEntry(
            seq_id=seq_id, num_tokens=num_tokens, layers=layers, nbytes=nbytes
        )
        with self._lock:
            self._remote_keys.add(seq_id)
            while (
                self.used_bytes + nbytes > self.capacity_bytes
                and self._entries
            ):
                self._evict_oldest()
            if self.used_bytes + nbytes > self.capacity_bytes:
                return False
            self._entries[seq_id] = entry
            self.used_bytes += nbytes
        return True

    def reinsert(self, entry: OffloadEntry) -> bool:
        """Put a restore()d-but-unused entry back (e.g. the pool could not
        host it yet); also caches remote fetches locally.  Evicts older
        entries like save() — the reinserted snapshot is the one about to
        be needed, so it outranks stale residents."""
        with self._lock:
            self.restores -= 1  # the paired restore() did not take effect
            while (
                self.used_bytes + entry.nbytes > self.capacity_bytes
                and self._entries
            ):
                self._evict_oldest()
            if self.used_bytes + entry.nbytes > self.capacity_bytes:
                return False
            self._entries[entry.seq_id] = entry
            self.used_bytes += entry.nbytes
            return True

    def discard(self, seq_id: str) -> None:
        """Drop a finished/aborted sequence's snapshot from every tier —
        including the remote store, or the shared cache leaks one snapshot
        per finished sequence forever.  At most ONE remote DEL per seq:
        _remote_keys membership is consumed under the lock before the
        enqueue.  The DEL itself runs on the deleter thread (discard is
        a step-thread call — see __init__); a DEL lost to process exit
        leaks one store entry, which the store's own eviction reclaims."""
        with self._lock:
            entry = self._entries.pop(seq_id, None)
            if entry is not None:
                self.used_bytes -= entry.nbytes
            known_remote = seq_id in self._remote_keys
            self._remote_keys.discard(seq_id)
        if self.remote_client is not None and known_remote:
            self._enqueue_delete(seq_id)

    def _enqueue_delete(self, seq_id: str) -> None:
        with self._lock:
            if self._del_thread is None:
                self._del_queue = queue.Queue()
                self._del_thread = threading.Thread(
                    target=self._delete_worker, name="kv-remote-del",
                    daemon=True,
                )
                self._del_thread.start()
            self._del_pending += 1
        self._del_queue.put(seq_id)

    # stackcheck: thread=kv-remote-del
    def _delete_worker(self) -> None:
        while True:
            seq_id = self._del_queue.get()
            if seq_id is None:
                return
            try:
                self.remote_client.delete(seq_id)
            except Exception:
                logger.debug(
                    "remote KV delete failed for %s", seq_id, exc_info=True
                )
            finally:
                with self._del_cv:
                    self._del_pending -= 1
                    self._del_cv.notify_all()

    def wait_deletes(self, timeout: float = 10.0) -> bool:
        """Block until queued remote DELs have resolved (tests; drain).
        True when the queue went idle within the timeout."""
        deadline = time.monotonic() + timeout
        with self._del_cv:
            while self._del_pending:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._del_cv.wait(remaining)
            return True

    def close(self, timeout: float = 10.0) -> None:
        """Flush queued remote DELs and retire the deleter thread (the
        engine close path; SC601 lifecycle contract).  A DEL still
        pending past the timeout leaks one store snapshot, which the
        store's own eviction reclaims — warn, don't hang the drain.
        The timeout is shared between the flush and the join so a hung
        store costs at most one budget, not two."""
        deadline = time.monotonic() + timeout
        if not self.wait_deletes(timeout):
            logger.warning(
                "remote KV DELs still pending at shutdown; the store "
                "leaks those snapshots until its own eviction"
            )
        with self._lock:
            thread, self._del_thread = self._del_thread, None
        if thread is not None:
            self._del_queue.put(None)
            thread.join(max(0.0, deadline - time.monotonic()))

    def _evict_oldest(self) -> None:
        oldest = min(self._entries.values(), key=lambda e: e.saved_at)
        del self._entries[oldest.seq_id]
        self.used_bytes -= oldest.nbytes
        self.evictions += 1


class OffloadStager:
    """Off-step completion of preemption snapshots.

    The step thread calls ``reserve()`` -> dispatches the device-side
    gathers (async, fresh buffers) -> ``commit()``s the device arrays;
    a single writer thread then pays the D2H wait, inserts the host
    snapshot into the HostOffloadManager (which mirrors to the remote
    tier), and observes ``tpu:offload_stage_seconds``.  Double-buffered
    by design: at most ONE snapshot is staged at a time — a preemption
    arriving while the slot is busy returns False and the scheduler
    falls back to recompute (preemptions are rare; blocking the step
    thread to queue a second snapshot would reintroduce the stall this
    class removes).

    ``discard(seq_id)`` tombstones an in-flight snapshot (request
    aborted/finished while staging): the writer drops the host copy
    instead of inserting it, so no entry (or remote PUT) outlives the
    sequence."""

    def __init__(self, manager: HostOffloadManager, observe_stage=None):
        self._manager = manager
        self._observe = observe_stage  # callable(seconds) or None
        self._q: "queue.Queue" = queue.Queue(maxsize=1)
        self._lock = threading.Lock()
        self._busy = False
        self._inflight_id: Optional[str] = None
        self._dead = False  # inflight snapshot tombstoned
        self._thread: Optional[threading.Thread] = None
        self.staged = 0
        self.skipped = 0  # slot busy -> recompute fallback

    def reserve(self, seq_id: str) -> bool:
        """Claim the staging slot (step thread).  False = slot busy."""
        with self._lock:
            if self._busy:
                self.skipped += 1
                return False
            self._busy = True
            self._inflight_id = seq_id
            self._dead = False
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._worker, name="kv-offload-stage", daemon=True
                )
                self._thread.start()
        return True

    def release(self, seq_id: str) -> None:
        """Abandon a reservation (gather dispatch failed)."""
        with self._lock:
            if self._inflight_id == seq_id:
                self._busy = False
                self._inflight_id = None

    def commit(self, seq_id: str, device_layers, num_tokens: int) -> None:
        """Hand the dispatched device gathers to the writer thread."""
        self.staged += 1
        # stackcheck: allow=SC201 reason=timestamp rides to the writer thread for the tpu:offload_stage_seconds histogram only
        self._q.put((seq_id, device_layers, num_tokens, time.time()))

    def discard(self, seq_id: str) -> None:
        """Tombstone the in-flight snapshot for ``seq_id`` (no-op for
        sequences that are not currently staging)."""
        with self._lock:
            if self._inflight_id == seq_id:
                self._dead = True

    def is_inflight(self, seq_id: str) -> bool:
        """True while ``seq_id``'s snapshot is staged but not yet landed
        in the manager — restore answers "retry" instead of "gone"."""
        with self._lock:
            return self._inflight_id == seq_id and not self._dead

    @property
    def busy(self) -> bool:
        with self._lock:
            return self._busy

    def wait_idle(self, timeout: float = 10.0) -> bool:
        deadline = time.time() + timeout
        while time.time() < deadline:
            with self._lock:
                if not self._busy:
                    return True
            time.sleep(0.005)
        return False

    def shutdown(self, timeout: float = 10.0) -> None:
        """Drain the in-flight snapshot and retire the writer thread
        (engine close path).  wait_idle first: the writer owns staged
        device buffers until it lands them, so a join-before-drain would
        drop a snapshot mid-write.  The timeout is shared between the
        drain and the join."""
        deadline = time.monotonic() + timeout
        self.wait_idle(timeout)
        with self._lock:
            thread, self._thread = self._thread, None
        if thread is not None:
            self._q.put(None)
            thread.join(max(0.0, deadline - time.monotonic()))

    # stackcheck: thread=kv-offload-stage
    def _worker(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            seq_id, device_layers, num_tokens, t0 = item
            try:
                from production_stack_tpu.engine.kv import quant as kv_quant

                layers = [
                    (kv_quant.to_host_side(k), kv_quant.to_host_side(v))
                    for k, v in device_layers
                ]
                with self._lock:
                    dead = self._dead
                if not dead:
                    self._manager.insert_saved(seq_id, layers, num_tokens)
                    # An abort can land BETWEEN the check above and the
                    # insert (its offload.discard then found nothing):
                    # re-check and undo, so neither a host entry nor a
                    # just-PUT remote snapshot outlives the sequence.
                    with self._lock:
                        dead = self._dead
                    if dead:
                        self._manager.discard(seq_id)
                if self._observe is not None:
                    self._observe(time.time() - t0)
            except Exception:
                logger.exception("offload staging failed for %s", seq_id)
            finally:
                with self._lock:
                    self._busy = False
                    self._inflight_id = None
                    self._dead = False
