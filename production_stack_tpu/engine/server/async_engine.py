"""Async bridge between the aiohttp server and the LLMEngine.

The engine step loop runs in one dedicated thread (device execution releases
the GIL, so the event loop keeps serving HTTP while XLA runs).  Requests and
per-token outputs cross the thread boundary via a lock-guarded submission
list and ``loop.call_soon_threadsafe`` hand-offs into per-request asyncio
queues — one queue per request, one engine, no polling of shared state from
the event loop.

The loop drives the engine's dispatch/collect pipeline directly: each
iteration tops up the device pipeline (with pipeline_decode on, decode
step N+1 is enqueued before step N's tokens are read back), then collects
and fans out step N — so detokenization and SSE emission overlap device
compute of the next step instead of serializing against it.  The lockstep
publish sits at the same dispatch boundary: followers replay the event
batch and run the identical dispatch/collect discipline (engine.step()),
keeping every replica's jitted launch sequence byte-identical.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import threading
import time
import uuid
from typing import AsyncIterator, Dict, List, Optional

from production_stack_tpu.engine.config import EngineConfig
from production_stack_tpu.engine.core.engine import LLMEngine
from production_stack_tpu.engine.core.sequence import FinishReason, SamplingParams

logger = logging.getLogger(__name__)


class DeadlineExceeded(Exception):
    """Raised into a request's event stream when its client deadline
    expired while the sequence was still waiting/preempted (the step
    loop's deadline sweep aborted it before it could occupy a batch
    slot).  The API server maps this to a structured 504."""


@dataclasses.dataclass
class AdmissionRejection:
    """Why bounded admission refused a request (serialized into the 429
    body so clients and the router see queue/KV pressure, not a bare
    status code)."""

    queued_requests: int
    queued_tokens: int
    max_queued_requests: int
    max_queued_tokens: int
    kv_usage_perc: float
    retry_after_s: int


@dataclasses.dataclass
class TokenEvent:
    token_id: int
    finished: bool
    finish_reason: Optional[FinishReason]
    num_prompt_tokens: int
    num_output_tokens: int
    logprob: Optional[float] = None
    top_logprobs: Optional[list] = None  # [(token_id, logprob), ...]
    # First event of an echo+logprobs request: per-prompt-position entries.
    prompt_logprobs: Optional[list] = None


class AsyncEngine:
    def __init__(self, config: EngineConfig, lockstep=None):
        # lockstep: parallel.distributed.LockstepChannel when this is the
        # leader of a multi-host slice group — every event batch is
        # broadcast to follower processes right before stepping, keeping
        # all replicas' jitted launches identical (SPMD requirement).
        self.engine = LLMEngine(config)
        self._lockstep = lockstep
        # Group liveness (docs/robustness.md "Slice lifecycle contract"):
        # a real lockstep channel with a control-plane side channel gets
        # a member-liveness monitor — the slice's health becomes the
        # conjunction of its members' through /health.  Recording stubs
        # in tests carry no denv and stay monitor-free.
        from production_stack_tpu.engine.parallel.distributed import (
            GroupLivenessMonitor,
        )

        self._slice_monitor: Optional[GroupLivenessMonitor] = None
        denv = getattr(lockstep, "denv", None)
        if (
            denv is not None
            and denv.num_processes > 1
            and getattr(lockstep, "ack_store", None) is not None
            and getattr(lockstep, "member_timeout_s", 0) > 0
        ):
            self._slice_monitor = GroupLivenessMonitor(lockstep)
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._queues: Dict[str, asyncio.Queue] = {}
        self._pending: List = []  # (request_id, prompt_ids, sampling_params)
        self._aborts: List[str] = []
        self._lock = threading.Lock()
        self._shutdown = threading.Event()
        self._wakeup = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Prompt tokens submitted but not yet drained into the engine by
        # the step thread (guarded by _lock); bounded admission counts
        # these beside the scheduler's waiting queue so a burst between
        # step-loop iterations cannot slip past the caps.
        self._pending_tokens = 0
        # True once any request carried a deadline: keeps the per-step
        # deadline sweep off the hot path for deadline-free serving.
        self._any_deadlines = False
        # Watchdog: wall clock of the step loop's most recent iteration
        # start.  A hung device dispatch (or a wedged collective) stops
        # the stamp advancing, and /health turns that into a liveness
        # failure instead of serving a green probe (tpu:last_step_age_seconds).
        self._last_step_ts: Optional[float] = None
        # Batched encode lane (encode_batcher.py): the event loop queues
        # embed/rerank/score token lists and THIS object's step thread
        # drains them as [B, T]-bucketed encode batches at window
        # boundaries.  Disabled under multi-host lockstep (a leader-only
        # encode forward would desync the SPMD followers' jitted launch
        # sequence) and for models without a batched encode path — both
        # fall back to the legacy serial embed.
        self.encode_batcher = None
        if (
            config.scheduler.encode_lane_enabled
            and (denv is None or denv.num_processes <= 1)
            and hasattr(self.engine.model, "encode_batch")
        ):
            from production_stack_tpu.engine.server.encode_batcher import (
                EncodeBatcher,
            )

            self.encode_batcher = EncodeBatcher(self.engine)

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._thread = threading.Thread(
            target=self._run_loop, name="engine-step-loop", daemon=True
        )
        self._thread.start()
        if self._slice_monitor is not None:
            self._slice_monitor.start()

    async def close(self) -> None:
        self._shutdown.set()
        self._wakeup.set()
        if self._slice_monitor is not None:
            # Before the step-thread join: a member dying mid-close must
            # not fatal_exit a process already shutting down cleanly.
            await asyncio.to_thread(self._slice_monitor.stop)
        if self._thread is not None:
            await asyncio.to_thread(self._thread.join, 30)
        if self.encode_batcher is not None:
            # The step thread is gone; queued embeds can never run.
            self.encode_batcher.fail_all(
                RuntimeError("engine shutting down")
            )
        # Release the engine's own workers AFTER the step thread is gone
        # (it is their producer): prefetch fetchers, offload stager
        # writer, prefix exporter, the remote-KV deleter (whose queued
        # DELs a drain must flush or the store leaks one snapshot per
        # in-flight discard), and the kvserver sockets.
        await asyncio.to_thread(self.engine.close)

    # -- request API (event-loop side) ------------------------------------

    async def generate(
        self,
        prompt: Optional[str] = None,
        prompt_token_ids: Optional[List[int]] = None,
        sampling_params: Optional[SamplingParams] = None,
        request_id: Optional[str] = None,
        adapter: Optional[str] = None,
    ) -> AsyncIterator[TokenEvent]:
        request_id = request_id or f"req-{uuid.uuid4().hex[:12]}"
        queue: asyncio.Queue = asyncio.Queue()
        self._queues[request_id] = queue
        if prompt_token_ids is None:
            prompt_token_ids = self.engine.tokenizer.encode(prompt or "")
        params = sampling_params or SamplingParams()
        if params.deadline is not None:
            self._any_deadlines = True
        with self._lock:
            self._pending.append(
                (request_id, prompt_token_ids, params, adapter)
            )
            self._pending_tokens += len(prompt_token_ids)
        self._wakeup.set()
        finished = False
        try:
            while True:
                event = await queue.get()
                if isinstance(event, Exception):
                    raise event
                yield event
                if event.finished:
                    finished = True
                    return
        finally:
            self._queues.pop(request_id, None)
            if not finished:
                # Consumer stopped early (client disconnect, pump cancel,
                # error on a sibling choice): abort in-engine so the
                # scheduler doesn't keep decoding for nobody.  Inline sync
                # append — `await` in an async-generator finally runs
                # during aclose and must not block.
                with self._lock:
                    self._aborts.append(request_id)
                self._wakeup.set()

    async def abort(self, request_id: str) -> None:
        with self._lock:
            self._aborts.append(request_id)
        self._wakeup.set()

    async def embed_batch(
        self,
        batch_token_ids: List[List[int]],
        deadline: Optional[float] = None,
    ) -> List:
        """Embed a list of tokenized inputs.  With the encode lane on
        (the default) every text is queued on the EncodeBatcher and the
        STEP THREAD runs the [B, T]-bucketed batch at a window boundary
        — the device is never touched from this coroutine's thread.
        With the lane off (--no-encode-lane / multi-host lockstep) each
        text runs the legacy serial encode off-thread, preserving the
        pre-lane behavior exactly.  Raises ValueError on empty or
        over-long inputs either way."""
        max_len = self.engine.encode_max_len()
        for ids in batch_token_ids:
            if not ids:
                raise ValueError("input produced no tokens")
            if len(ids) > max_len:
                raise ValueError(
                    f"input is {len(ids)} tokens; the embedding path "
                    f"supports up to {max_len}"
                )
        if self.encode_batcher is None:
            return [
                await asyncio.to_thread(self.engine.embed, ids)
                for ids in batch_token_ids
            ]
        futures = self.encode_batcher.submit(
            batch_token_ids, asyncio.get_running_loop(), deadline
        )
        self._wakeup.set()
        return list(await asyncio.gather(*futures))

    def stats(self) -> Dict[str, float]:
        return self.engine.stats()

    # -- overload protection / lifecycle reads -----------------------------

    def check_admission(
        self, n_requests: int, n_tokens: int
    ) -> Optional[AdmissionRejection]:
        """Bounded admission (docs/robustness.md): None = admit; otherwise
        the structured rejection the server turns into a 429.

        Queue depth = scheduler waiting/preempted + submissions the step
        thread has not drained yet.  The read is advisory (concurrent
        handlers may interleave between check and submit), but the
        overshoot is bounded by the handful of requests parsing bodies at
        once — the queue cannot grow without bound either way."""
        cfg = self.engine.config.scheduler
        if not cfg.admission_enabled:
            return None
        with self._lock:
            pending_n = len(self._pending)
            pending_tok = self._pending_tokens
        queued_requests = self.engine.scheduler.num_waiting + pending_n
        queued_tokens = (
            self.engine.scheduler.queued_prompt_tokens + pending_tok
        )
        if (
            queued_requests + n_requests <= cfg.queued_requests_cap
            and queued_tokens + n_tokens <= cfg.queued_tokens_cap
        ):
            return None
        # Crude service-rate estimate: each batch generation drains up to
        # max_num_seqs queued requests; tell the client to come back after
        # roughly that many "turns".
        retry_after = max(
            1, -(-queued_requests // max(1, cfg.max_num_seqs))
        )
        return AdmissionRejection(
            queued_requests=queued_requests,
            queued_tokens=queued_tokens,
            max_queued_requests=cfg.queued_requests_cap,
            max_queued_tokens=cfg.queued_tokens_cap,
            kv_usage_perc=float(self.engine.block_pool.usage),
            retry_after_s=min(retry_after, 60),
        )

    def check_encode_admission(
        self, n_texts: int, n_tokens: int
    ) -> Optional[AdmissionRejection]:
        """Bounded admission for the encode lane: the queue the batcher
        carries is bounded in texts (queued_encode_texts_cap) and tokens
        (the shared queued_tokens_cap), so an embed burst sheds with a
        structured 429 at the edge instead of queueing unboundedly.
        With the lane off, encode requests count against the generation
        caps (one text = one request) — they compete for the same
        device either way."""
        cfg = self.engine.config.scheduler
        if not cfg.admission_enabled:
            return None
        if self.encode_batcher is None:
            return self.check_admission(n_texts, n_tokens)
        depth, queued_tokens = self.encode_batcher.snapshot()
        if (
            depth + n_texts <= cfg.queued_encode_texts_cap
            and queued_tokens + n_tokens <= cfg.queued_tokens_cap
        ):
            return None
        # Service-rate estimate, encode flavor: each window boundary
        # drains up to one full encode batch bucket.
        retry_after = max(
            1, -(-depth // max(1, cfg.encode_batch_buckets[-1]))
        )
        return AdmissionRejection(
            queued_requests=depth,
            queued_tokens=queued_tokens,
            max_queued_requests=cfg.queued_encode_texts_cap,
            max_queued_tokens=cfg.queued_tokens_cap,
            kv_usage_perc=float(self.engine.block_pool.usage),
            retry_after_s=min(retry_after, 60),
        )

    @property
    def last_step_age_s(self) -> float:
        """Seconds since the step loop last started an iteration (0.0
        before the loop boots).  Exported as tpu:last_step_age_seconds;
        /health fails liveness past scheduler.step_watchdog_s."""
        ts = self._last_step_ts
        if ts is None:
            return 0.0
        return max(0.0, time.time() - ts)

    # -- slice-group liveness reads (docs/robustness.md) --------------------

    @property
    def slice_monitor(self):
        return self._slice_monitor

    def slice_problem(self) -> Optional[str]:
        """Non-None when the slice group lost a member (the leader's
        /health conjoins this with the step watchdog, so the WHOLE slice
        fails liveness within --slice-member-timeout-s of the member
        going silent — the router's breaker routes around it in
        seconds).  None on single-host engines."""
        if self._slice_monitor is None:
            return None
        return self._slice_monitor.problem()

    @property
    def slice_epoch(self) -> int:
        """The group epoch (leader boot nonce; 0 single-host) —
        tpu:lockstep_group_epoch."""
        if self._lockstep is None:
            return 0
        return getattr(self._lockstep, "epoch", 0)

    @property
    def step_thread_healthy(self) -> bool:
        """False only when the step thread died unexpectedly (crashed out
        of its loop without a shutdown request)."""
        if self._thread is None or self._shutdown.is_set():
            return True  # not started yet / clean shutdown in progress
        return self._thread.is_alive()

    # -- engine thread -----------------------------------------------------

    # stackcheck: root=step-thread
    # stackcheck: thread=engine-step-loop
    def _run_loop(self) -> None:
        logger.info("engine step loop started")
        last_publish = time.time()
        while not self._shutdown.is_set():
            self._last_step_ts = time.time()
            with self._lock:
                pending, self._pending = self._pending, []
                aborts, self._aborts = self._aborts, []
                self._pending_tokens -= sum(len(p[1]) for p in pending)
            # Deadline sweep (each scheduler pass): expired waiting/
            # preempted sequences fold into this iteration's abort batch —
            # published under lockstep like any client abort, so followers
            # replay the leader's wall-clock decision instead of making
            # their own.  The consumer sees DeadlineExceeded, not silence.
            expired: List[str] = []
            if self._any_deadlines and self.engine.has_unfinished():
                expired = [
                    rid
                    for rid in self.engine.scan_expired_deadlines(
                        self._last_step_ts
                    )
                    if rid not in aborts
                ]
                for rid in expired:
                    aborts.append(rid)
            if self._lockstep is not None and (
                pending or aborts or self.engine.has_unfinished()
                # Idle heartbeat: followers detect a dead leader by event
                # staleness (their /health fails, k8s restarts the group
                # member); without it an idle group is indistinguishable
                # from a dead one.
                or time.time() - last_publish
                > self._lockstep.heartbeat_seconds
            ):
                from production_stack_tpu.engine.parallel.distributed import (
                    StepEvents,
                )

                self._lockstep.publish(StepEvents(
                    requests=[
                        (rid, toks, params, adapter)
                        for rid, toks, params, adapter in pending
                    ],
                    aborts=list(aborts),
                ))
                last_publish = time.time()
            for request_id in expired:
                self.engine.deadline_expired += 1
                self._emit(
                    request_id,
                    DeadlineExceeded(
                        f"request {request_id} missed its deadline while "
                        "queued; shed before occupying a batch slot"
                    ),
                )
            for request_id in aborts:
                self.engine.abort_request(request_id)
            for request_id, token_ids, params, adapter in pending:
                try:
                    self.engine.add_request(
                        request_id,
                        prompt_token_ids=token_ids,
                        sampling_params=params,
                        adapter=adapter,
                    )
                except Exception as e:
                    self._emit(request_id, e)
            if not self.engine.has_unfinished():
                # Device idle: encode batches are the only work there is
                # — drain the queue completely before sleeping.
                if (
                    self.encode_batcher is not None
                    and self.encode_batcher.run_pending(max_batches=0)
                ):
                    continue
                self._wakeup.wait(timeout=0.01)
                self._wakeup.clear()
                continue
            try:
                # Keep the device fed before fanning out results: with
                # pipeline_decode on, dispatch() enqueues decode N+1
                # (chained on N's in-flight sample) and collect() then
                # reads N back — the _emit loop below runs while N+1 is
                # computing.
                self.engine.dispatch()
                outputs = self.engine.collect()
            except Exception:
                if self._lockstep is not None:
                    # Fatal under lockstep: followers have already
                    # launched this iteration's collectives (or will
                    # hang waiting for them).  Retrying against a
                    # desynced SPMD group wedges it in collectives;
                    # exiting lets k8s restart the slice group together.
                    # The shutdown publish is best-effort — if the
                    # collective transport still works, followers exit
                    # cleanly instead of waiting out the staleness
                    # window.
                    logger.exception(
                        "engine step failed under lockstep; exiting so "
                        "the slice group restarts together"
                    )
                    from production_stack_tpu.engine.parallel.distributed import (
                        StepEvents,
                        fatal_exit,
                    )

                    try:
                        self._lockstep.publish(StepEvents(shutdown=True))
                    except Exception:
                        logger.exception("shutdown publish failed")
                    fatal_exit(1)
                    return  # unreachable except under monkeypatched exit
                logger.exception("engine step failed")
                # stackcheck: allow=SC101 reason=error backoff after a failed step; the device produced nothing to wait for and hammering a failing dispatch would spin the log
                time.sleep(0.1)
                continue
            for out in outputs:
                # Drop events for requests whose client vanished.
                if out.seq_id in self._queues:
                    self._emit(
                        out.seq_id,
                        TokenEvent(
                            token_id=out.new_token_id,
                            finished=out.finished,
                            finish_reason=out.finish_reason,
                            num_prompt_tokens=out.num_prompt_tokens,
                            num_output_tokens=out.num_output_tokens,
                            logprob=out.logprob,
                            top_logprobs=out.top_logprobs,
                            prompt_logprobs=out.prompt_logprobs,
                        ),
                    )
            # Window boundary: at most ONE encode batch per iteration
            # while generation is live — an embed burst adds one
            # prefill-chunk-shaped pass between decode windows, never
            # preempts a window mid-scan, and generation ITL stays
            # bounded.  (The batcher is None under lockstep, so
            # followers never see a forward they didn't replay.)
            if self.encode_batcher is not None:
                self.encode_batcher.run_pending(max_batches=1)
        if self._lockstep is not None:
            from production_stack_tpu.engine.parallel.distributed import (
                StepEvents,
            )

            self._lockstep.publish(StepEvents(shutdown=True))
        logger.info("engine step loop exited")

    def _emit(self, request_id: str, event) -> None:
        queue = self._queues.get(request_id)
        if queue is None or self._loop is None:
            return
        self._loop.call_soon_threadsafe(queue.put_nowait, event)
