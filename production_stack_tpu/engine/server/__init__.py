"""OpenAI-compatible serving front-end for the TPU engine."""
