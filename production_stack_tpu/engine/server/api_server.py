"""OpenAI-compatible API server for the TPU engine.

Speaks exactly the contract the router (and the reference's router) expects
from a serving engine: /v1/chat/completions, /v1/completions (SSE streaming
and non-streaming), /v1/models, /health, and Prometheus /metrics in the
``tpu:`` vocabulary (production_stack_tpu/router/stats/vocabulary.py).
This is the process the helm chart runs per engine pod — the TPU analogue of
``vllm serve`` (reference deployment-vllm-multi.yaml:57-64).
"""

from __future__ import annotations

import argparse
import asyncio
import dataclasses
import json
import logging
import os
import time
import uuid
from typing import Optional

import numpy as np
from aiohttp import web

from production_stack_tpu.engine.config import config_from_preset
from production_stack_tpu.engine.core.sequence import FinishReason, SamplingParams
from production_stack_tpu.engine.server.async_engine import (
    AsyncEngine,
    DeadlineExceeded,
)
from production_stack_tpu.obs.histogram import render_histogram
from production_stack_tpu.obs.trace import parse_traceparent
from production_stack_tpu.router.stats import vocabulary as vocab
from production_stack_tpu.utils.drain import DrainController
from production_stack_tpu.utils.log import init_logger
from production_stack_tpu.utils.net import parse_deadline

logger = logging.getLogger(__name__)


def _sampling_from_body(body: dict, chat: bool) -> SamplingParams:
    stop = body.get("stop")
    if isinstance(stop, str):
        stop = [stop]
    # logprobs: chat uses bool `logprobs` + int `top_logprobs`; the legacy
    # completions API uses int-or-null `logprobs` as the top-k count.
    if chat:
        want_logprobs = bool(body.get("logprobs", False))
        top_logprobs = int(body.get("top_logprobs") or 0)
    else:
        raw = body.get("logprobs")
        want_logprobs = raw is not None and raw is not False
        top_logprobs = int(raw or 0) if not isinstance(raw, bool) else 0
    logit_bias = body.get("logit_bias") or None
    if logit_bias is not None:
        if not isinstance(logit_bias, dict):
            raise ValueError("'logit_bias' must be a map of token id -> bias")
        try:
            logit_bias = {int(k): float(v) for k, v in logit_bias.items()}
        except (TypeError, ValueError):
            raise ValueError(
                "'logit_bias' keys must be token ids and values numbers"
            ) from None
    stop_token_ids = body.get("stop_token_ids") or None
    if stop_token_ids is not None:
        if not isinstance(stop_token_ids, list):
            raise ValueError("'stop_token_ids' must be a list of token ids")
        try:
            stop_token_ids = [int(t) for t in stop_token_ids]
        except (TypeError, ValueError):
            raise ValueError("'stop_token_ids' entries must be token ids") from None
    min_p = float(body.get("min_p") or 0.0)
    if not 0.0 <= min_p <= 1.0:
        raise ValueError(f"'min_p' must be in [0, 1], got {min_p}")
    response_format = None
    rf = body.get("response_format")
    if rf is not None:
        rf_type = rf.get("type") if isinstance(rf, dict) else rf
        if rf_type == "json_object":
            response_format = "json_object"
        elif rf_type == "json_schema":
            # OpenAI structured outputs: {"type": "json_schema",
            # "json_schema": {"name":..., "schema": {...}, "strict":...}}.
            spec = rf.get("json_schema") if isinstance(rf, dict) else None
            if not isinstance(spec, dict):
                raise ValueError(
                    "response_format json_schema requires a 'json_schema' "
                    "object"
                )
            schema = spec.get("schema")
            if not isinstance(schema, dict):
                raise ValueError(
                    "response_format json_schema requires "
                    "json_schema.schema (an object)"
                )
            # Compile HERE so unsupported schemas 400 before any stream
            # starts (SchemaCompileError is a ValueError); the cache makes
            # the per-sequence guides reuse this compilation.
            from production_stack_tpu.engine.guided_schema import (
                compile_schema_cached,
            )

            compile_schema_cached(schema)
            response_format = {"type": "json_schema", "schema": schema}
        elif rf_type in ("text", None):
            response_format = None
        else:
            raise ValueError(
                f"Unsupported response_format type {rf_type!r} "
                "(supported: text, json_object, json_schema)"
            )
    raw_max = body.get("max_tokens")
    if raw_max is None:
        raw_max = body.get("max_completion_tokens")
    # Explicit 0 is meaningful (echo+logprobs scoring wants NO generated
    # tokens); only absence falls back to the default.
    max_tokens = 128 if raw_max is None else int(raw_max)
    if max_tokens < 0:
        raise ValueError(f"'max_tokens' must be >= 0, got {max_tokens}")
    rep = body.get("repetition_penalty")
    if rep is not None and not (isinstance(rep, (int, float)) and rep > 0):
        raise ValueError(
            f"'repetition_penalty' must be a positive number, got {rep}"
        )
    min_tokens = int(body.get("min_tokens") or 0)
    if min_tokens < 0 or min_tokens > max_tokens:
        raise ValueError(
            f"'min_tokens' must be in [0, max_tokens], got {min_tokens}"
        )
    try:
        priority = int(body.get("priority") or 0)
    except (TypeError, ValueError):
        raise ValueError("'priority' must be an integer") from None
    return SamplingParams(
        max_tokens=max_tokens,
        temperature=float(body.get("temperature") or 0.0),
        top_p=float(body.get("top_p") or 1.0),
        top_k=int(body.get("top_k") or 0),
        min_p=min_p,
        stop=stop,
        stop_token_ids=stop_token_ids,
        logit_bias=logit_bias,
        echo=bool(body.get("echo")) and not chat,
        # Guided decoding forces EOS when the JSON completes, so
        # ignore_eos would loop forever; response_format wins.
        ignore_eos=bool(body.get("ignore_eos", False)) and response_format is None,
        response_format=response_format,
        seed=body.get("seed"),
        logprobs=want_logprobs,
        top_logprobs=max(0, min(top_logprobs, 20)),
        presence_penalty=float(body.get("presence_penalty") or 0.0),
        frequency_penalty=float(body.get("frequency_penalty") or 0.0),
        repetition_penalty=float(body.get("repetition_penalty") or 1.0),
        min_tokens=min_tokens,
        priority=priority,
    )


class StopChecker:
    """Incremental detokenization with stop-string truncation."""

    def __init__(self, tokenizer, stop: Optional[list]):
        self.tokenizer = tokenizer
        self.stop = stop or []
        self.token_ids: list = []
        self.emitted_text = ""

    def push(self, token_id: int):
        """Returns (delta_text, stopped).  Negative ids are no-text
        sentinels (a stop_token_ids match ends generation without
        contributing text)."""
        if token_id >= 0:
            self.token_ids.append(token_id)
        text = self.tokenizer.decode(self.token_ids)
        for s in self.stop:
            idx = text.find(s)
            if idx != -1:
                delta = text[len(self.emitted_text) : idx]
                self.emitted_text = text[:idx]
                return delta, True
        # Hold back a partial-stop-suffix so we never emit half a stop string.
        hold = 0
        for s in self.stop:
            for k in range(1, len(s)):
                if text.endswith(s[:k]):
                    hold = max(hold, k)
        safe = text[: len(text) - hold] if hold else text
        delta = safe[len(self.emitted_text) :]
        if delta:
            self.emitted_text = safe
        return delta, False

    def flush(self) -> str:
        """Remaining held-back text when generation ends WITHOUT a stop
        match (e.g. max_tokens with output ending in a partial stop
        prefix); without this the tail characters are silently dropped."""
        text = self.tokenizer.decode(self.token_ids)
        delta = text[len(self.emitted_text):]
        self.emitted_text = text
        return delta

    def aligned_token_count(self) -> int:
        """Largest k such that the first k tokens detokenize within the
        emitted (post-stop-trim) text — i.e. how many tokens' logprobs
        entries align with the returned content.  Tokens consumed by a
        multi-token stop string fall outside."""
        emitted = len(self.emitted_text)
        for k in range(len(self.token_ids), -1, -1):
            if len(self.tokenizer.decode(self.token_ids[:k])) <= emitted:
                return k
        return 0


def _is_engine_data_plane(request: web.Request) -> bool:
    """Mutating model-serving work a draining engine must refuse (the
    same contract as the router's drain middleware): completions,
    embeddings/rerank/score, tokenize/detokenize, LoRA admin.  GET
    control-plane surfaces (/health, /ready, /metrics, /debug...) and
    POST /drain itself stay served throughout."""
    if request.method not in ("POST", "DELETE") or request.path == "/drain":
        return False
    return (
        request.path.startswith("/v1/")
        or request.path in ("/rerank", "/score", "/tokenize", "/detokenize")
        or request.path.startswith("/admin/")
    )


def build_engine_app(
    engine: AsyncEngine, served_model: str, drain_grace_s: float = 30.0
) -> web.Application:
    # Graceful lifecycle: /drain (helm preStop) and SIGTERM (main) both
    # converge here.  busy = any stream still attached to the engine OR
    # sequences still decoding.  exit_cb stays None under tests; main()
    # installs a SIGINT-to-self so the process exits 0 after the drain.
    drain = DrainController(
        grace_s=drain_grace_s,
        busy_fn=lambda: bool(engine._queues) or engine.engine.has_unfinished(),
    )

    @web.middleware
    async def drain_gate(request: web.Request, handler):
        """503 + Connection: close for ALL data-plane work during a drain
        — one gate instead of per-handler checks, so new endpoints cannot
        forget it, and the connection is never reused for a pod about to
        exit."""
        if drain.draining and _is_engine_data_plane(request):
            resp = web.json_response(
                {"error": {"message": "server is draining for shutdown",
                           "type": "shutting_down", "code": 503}},
                status=503,
            )
            resp.force_close()
            return resp
        return await handler(request)

    app = web.Application(middlewares=[drain_gate])
    app["engine"] = engine
    app["drain"] = drain

    def _watchdog_problem() -> Optional[str]:
        if not engine.step_thread_healthy:
            return "engine step thread died"
        # Slice-group liveness conjunction (docs/robustness.md "Slice
        # lifecycle contract"): the leader IS the slice's one discovery
        # endpoint, so a silent member fails the WHOLE slice's health
        # here — within --slice-member-timeout-s, well before the step
        # watchdog would notice the wedged collective.
        slice_problem = engine.slice_problem()
        if slice_problem is not None:
            return slice_problem
        wd = engine.engine.config.scheduler.step_watchdog_s
        age = engine.last_step_age_s
        if wd and age > wd:
            return (
                f"step loop stalled: last iteration started {age:.1f}s ago "
                f"(watchdog {wd:.0f}s)"
            )
        return None

    async def models(_req: web.Request) -> web.Response:
        def card(model_id: str) -> dict:
            return {
                "id": model_id,
                "object": "model",
                "created": int(time.time()),
                "owned_by": "production-stack-tpu",
            }

        # Loaded LoRA adapters are addressable as "<base>:<adapter>".
        data = [card(served_model)] + [
            card(f"{served_model}:{name}")
            for name in engine.engine.loaded_adapters()
        ]
        return web.json_response({"object": "list", "data": data})

    async def health(_req: web.Request) -> web.Response:
        """Liveness: fails when the step loop is hung or dead (watchdog),
        NOT during a drain — kubelet killing a draining pod would drop
        the very streams the drain exists to finish."""
        problem = _watchdog_problem()
        if problem is not None:
            return web.json_response(
                {"status": "unhealthy", "problem": problem,
                 "last_step_age_s": engine.last_step_age_s},
                status=503,
            )
        return web.json_response(
            {"status": "ok", "last_step_age_s": engine.last_step_age_s}
        )

    async def ready(_req: web.Request) -> web.Response:
        """Readiness: additionally fails while draining, so k8s pulls the
        pod from its Service (and the router's discovery drops it) while
        in-flight streams finish."""
        if drain.draining:
            return web.json_response(
                {"status": "draining", "in_flight_streams": len(engine._queues)},
                status=503,
            )
        problem = _watchdog_problem()
        if problem is not None:
            return web.json_response(
                {"status": "unhealthy", "problem": problem}, status=503
            )
        return web.json_response({"status": "ready"})

    async def drain_endpoint(_req: web.Request) -> web.Response:
        """POST /drain: flip readiness, stop admission, let in-flight
        streams finish within the grace, then exit (helm preStop hook;
        SIGTERM lands on the same controller)."""
        drain.begin()
        return web.json_response({
            "draining": True,
            "in_flight_streams": len(engine._queues),
            "unfinished_sequences": engine.engine.has_unfinished(),
            "grace_s": drain.grace_s,
        })

    async def metrics(_req: web.Request) -> web.Response:
        s = engine.stats()
        monitor = engine.slice_monitor
        pairs = [
            (vocab.TPU_NUM_REQUESTS_RUNNING, s["num_requests_running"]),
            (vocab.TPU_NUM_REQUESTS_WAITING, s["num_requests_waiting"]),
            (vocab.TPU_HBM_KV_USAGE_PERC, s["hbm_kv_usage_perc"]),
            (vocab.TPU_PREFIX_CACHE_HIT_RATE, s["prefix_cache_hit_rate"]),
            # Prefix-cache truth for the router's fleet popularity view:
            # hit/query token counters + resident content-blocks gauge.
            (vocab.TPU_PREFIX_CACHE_HIT_TOKENS, s["prefix_cache_hit_tokens"]),
            (vocab.TPU_PREFIX_CACHE_QUERY_TOKENS,
             s["prefix_cache_query_tokens"]),
            (vocab.TPU_PREFIX_CACHE_BLOCKS, s["prefix_cache_blocks"]),
            (vocab.TPU_HOST_KV_USAGE_PERC, s["host_kv_usage_perc"]),
            (vocab.TPU_DUTY_CYCLE, s["duty_cycle"]),
            (vocab.TPU_DECODE_HOST_GAP_MS, s["decode_host_gap_ms"]),
            (vocab.TPU_LOADED_LORAS, s["loaded_loras"]),
            (vocab.TPU_TOTAL_PROMPT_TOKENS, s["total_prompt_tokens"]),
            (vocab.TPU_TOTAL_GENERATED_TOKENS, s["total_generated_tokens"]),
            (vocab.TPU_TOTAL_FINISHED_REQUESTS, s["total_finished"]),
            (vocab.TPU_NUM_PREEMPTIONS, s["num_preemptions"]),
            (vocab.TPU_REMOTE_PREFIX_BLOCKS_FETCHED,
             s["remote_prefix_blocks_fetched"]),
            (vocab.TPU_REMOTE_PREFIX_BLOCKS_EXPORTED,
             s["remote_prefix_blocks_exported"]),
            # Disaggregated serving: prime completions served and
            # decode-phase handoff prefetch outcomes (docs/engine.md).
            (vocab.TPU_DISAGG_PREFILL_PRIMES, s["disagg_prefill_primes"]),
            (vocab.TPU_DISAGG_HANDOFF_HITS, s["disagg_handoff_hits"]),
            (vocab.TPU_DISAGG_HANDOFF_MISSES, s["disagg_handoff_misses"]),
            (vocab.TPU_KV_PREFETCH_HIT, s["kv_prefetch_hit"]),
            (vocab.TPU_KV_PREFETCH_WASTE, s["kv_prefetch_waste"]),
            (vocab.TPU_KV_PREFETCH_INFLIGHT, s["kv_prefetch_inflight"]),
            (vocab.TPU_SPEC_TOKENS_DRAFTED, s["spec_tokens_drafted"]),
            (vocab.TPU_SPEC_TOKENS_ACCEPTED, s["spec_tokens_accepted"]),
            (vocab.TPU_PREFILL_CHUNK_TOKENS, s["prefill_chunk_tokens"]),
            (vocab.TPU_MIXED_WINDOW_CHUNK_TOKENS,
             s["mixed_window_chunk_tokens"]),
            # Overlapped window dispatch: transfer seconds issued while
            # the device was busy with an in-flight window (H2D chunk
            # staging for chained windows + D2H offload gathers).
            (vocab.TPU_WINDOW_TRANSFER_OVERLAP_SECONDS,
             s["window_transfer_overlap_seconds"]),
            # Overload protection + step-loop watchdog (docs/robustness.md).
            (vocab.TPU_ADMISSION_REJECTED, s["admission_rejected_total"]),
            (vocab.TPU_DEADLINE_EXPIRED, s["deadline_expired_total"]),
            (vocab.TPU_QUEUED_PROMPT_TOKENS, s["queued_prompt_tokens"]),
            (vocab.TPU_LAST_STEP_AGE, engine.last_step_age_s),
            # K-step decode windows: emitted-but-undeliverable tokens
            # (the labeled fallback family renders below).
            (vocab.TPU_MULTISTEP_WASTED_TOKENS, s["multistep_wasted_tokens"]),
            # Slice-group lifecycle (0 on single-host engines): the group
            # epoch steps on every group restart, and drain relays count
            # follower-initiated slice-wide drains (docs/robustness.md).
            (vocab.TPU_LOCKSTEP_GROUP_EPOCH, engine.slice_epoch),
            (vocab.TPU_SLICE_DRAIN_RELAYS,
             monitor.drain_relays if monitor is not None else 0),
        ]
        # Latency histogram families (TTFT/ITL/e2e + step phases) ride the
        # same exposition; rendered even at zero observations so the
        # router scraper and dashboards see stable names.
        text = (
            vocab.render_prometheus(pairs)
            + vocab.render_labeled_counter(
                vocab.TPU_MULTISTEP_FALLBACK, "reason",
                {
                    **dict.fromkeys(vocab.TPU_MULTISTEP_FALLBACK_REASONS, 0),
                    **s["multistep_fallback"],
                },
            )
            # Fused speculative windows: outcome x drafter (one engine
            # runs at most one proposal source, so the live counts land
            # on the configured drafter's series; all six cells pre-seed
            # at zero so dashboards see a stable label set from boot),
            # plus the draft-forward time the model drafter spent.
            + vocab.render_labeled_counter2(
                vocab.TPU_SPEC_WINDOW_TOKENS, ("outcome", "drafter"),
                {
                    **{
                        (o, d): 0
                        for o in vocab.TPU_SPEC_WINDOW_OUTCOMES
                        for d in vocab.TPU_SPEC_WINDOW_DRAFTERS
                    },
                    **{
                        (o, s["spec_drafter"]): v
                        for o, v in s["spec_window_tokens"].items()
                        if s["spec_drafter"]
                    },
                },
            )
            + vocab.render_prometheus([
                (vocab.TPU_SPEC_DRAFT_FRACTION_SECONDS,
                 s["spec_draft_fraction_seconds"]),
            ])
            # Quantized KV tiering plane: bytes per tier boundary by
            # wire format, and snapshot serde versions on the kvserver
            # wire (pre-seeded with the closed label sets so scrapers
            # see stable series from boot).
            + vocab.render_labeled_counter2(
                vocab.TPU_KV_WIRE_BYTES, ("tier", "format"),
                {
                    **{
                        (t, f): 0
                        for t in vocab.TPU_KV_WIRE_TIERS
                        for f in vocab.TPU_KV_WIRE_FORMATS
                    },
                    **s["kv_wire_bytes"],
                },
            )
            + vocab.render_labeled_counter(
                vocab.TPU_KV_SNAPSHOT_FORMAT, "version",
                {
                    **dict.fromkeys(vocab.TPU_KV_SNAPSHOT_VERSIONS, 0),
                    **s["kv_snapshot_format"],
                },
            )
            # Slice-group member liveness (empty member set single-host;
            # the TYPE headers still render so the scrape contract is
            # stable across single- and multi-host engines).
            + vocab.render_labeled_gauge(
                vocab.TPU_LOCKSTEP_MEMBER_LAST_ACK, "member",
                {} if monitor is None else {
                    str(pid): age
                    for pid, age in monitor.member_ack_ages().items()
                },
            )
            + vocab.render_labeled_counter(
                vocab.TPU_LOCKSTEP_MEMBER_FAILURES, "reason",
                {
                    **dict.fromkeys(vocab.TPU_LOCKSTEP_FAILURE_REASONS, 0),
                    **({} if monitor is None else monitor.member_failures),
                },
            )
            # Packed multi-prompt windows: how many distinct prompts'
            # chunks rode each mixed K-step window (mass above bucket 1
            # is queue depth converted into device utilization).
            + render_histogram(
                vocab.TPU_MIXED_WINDOW_PROMPTS,
                engine.engine.mixed_window_prompts_hist,
            )
            # Encode lane: batched embed/rerank/score texts, the queue
            # the batcher is carrying, and per-batch size/latency
            # (docs/engine.md "The encode lane").
            + vocab.render_prometheus([
                (vocab.TPU_ENCODE_TEXTS, s["encode_texts_total"]),
                (vocab.TPU_ENCODE_QUEUE_DEPTH, s["encode_queue_depth"]),
            ])
            + render_histogram(
                vocab.TPU_ENCODE_BATCH_SIZE,
                engine.engine.encode_batch_size_hist,
            )
            + render_histogram(
                vocab.TPU_ENCODE_SECONDS,
                engine.engine.encode_seconds_hist,
            )
            # XLA compile events per executable shape key + the
            # distinct-shape gauge, and trace-ring byte-bound evictions
            # (obs/compile_tracker.py, obs/trace.py).
            + vocab.render_labeled_counter(
                vocab.TPU_COMPILE_SECONDS, "executable",
                s["compile_seconds"],
            )
            + vocab.render_prometheus([
                (vocab.TPU_COMPILED_SHAPES, s["compiled_shapes"]),
                (vocab.TPU_OBS_TRACE_DROPPED, s["obs_trace_dropped"]),
            ])
            + engine.engine.obs.render_metrics()
        )
        return web.Response(text=text)

    # -- request tracing debug surface (obs/) ------------------------------

    async def debug_requests(_req: web.Request) -> web.Response:
        """Ring buffer of completed request timelines, newest first."""
        return web.json_response(engine.engine.obs.debug_payload())

    async def debug_request(request: web.Request) -> web.Response:
        snap = engine.engine.obs.request_payload(
            request.match_info["request_id"]
        )
        if snap is None:
            return web.json_response(
                {"error": {"message": "unknown request id (expired from the "
                           "trace ring, or tracing is off)"}},
                status=404,
            )
        return web.json_response(snap)

    async def debug_windows(request: web.Request) -> web.Response:
        """Window flight-recorder ring, newest first (?seq= filters to
        windows one sequence rode)."""
        return web.json_response(
            engine.engine.obs.windows_payload(
                seq=request.query.get("seq") or None
            )
        )

    async def debug_compiles(_req: web.Request) -> web.Response:
        """XLA compile events per executable + warmup coverage report."""
        return web.json_response(engine.engine.compiles_payload())

    async def chat_completions(request: web.Request) -> web.StreamResponse:
        return await _serve_completion(request, chat=True)

    async def completions(request: web.Request) -> web.StreamResponse:
        return await _serve_completion(request, chat=False)

    async def _serve_completion(request: web.Request, chat: bool) -> web.StreamResponse:
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return web.json_response(
                {"error": {"message": "invalid JSON", "type": "invalid_request_error"}},
                status=400,
            )
        tokenizer = engine.engine.tokenizer
        tools = body.get("tools") if chat else None
        tool_choice = body.get("tool_choice", "auto") if chat else "auto"
        forced_tool = None
        if chat and tool_choice not in ("auto", "none") and not tools:
            # OpenAI: tool_choice is only allowed when tools are given.
            return web.json_response(
                {"error": {"message": "'tool_choice' requires a non-empty "
                           "'tools' array", "type": "invalid_request_error"}},
                status=400,
            )
        if chat and tools:
            if not isinstance(tools, list) or not all(
                isinstance(t, dict) and t.get("type") == "function"
                and isinstance(t.get("function"), dict)
                and t["function"].get("name")
                for t in tools
            ):
                return web.json_response(
                    {"error": {"message": "'tools' must be a list of "
                               "{type: function, function: {name, ...}}",
                               "type": "invalid_request_error"}},
                    status=400,
                )
            if isinstance(tool_choice, dict):
                wanted = (tool_choice.get("function") or {}).get("name")
                match = [t for t in tools
                         if t["function"]["name"] == wanted]
                if not match:
                    return web.json_response(
                        {"error": {"message": f"tool_choice function "
                                   f"{wanted!r} not in tools",
                                   "type": "invalid_request_error"}},
                        status=400,
                    )
                forced_tool = match[0]
            elif tool_choice == "required":
                if len(tools) > 1:
                    # Model-driven tool selection needs per-family output
                    # parsers (out of scope); with several tools the
                    # caller must force one explicitly rather than get
                    # tools[0] silently.
                    return web.json_response(
                        {"error": {"message": "tool_choice 'required' with "
                                   "multiple tools is not supported; force "
                                   "one with {type: function, function: "
                                   "{name: ...}}",
                                   "type": "invalid_request_error"}},
                        status=400,
                    )
                forced_tool = tools[0]
            elif tool_choice not in ("auto", "none"):
                return web.json_response(
                    {"error": {"message": f"Unsupported tool_choice "
                               f"{tool_choice!r} (auto | none | required | "
                               "{type: function, ...})",
                               "type": "invalid_request_error"}},
                    status=400,
                )
        if chat:
            messages = list(body.get("messages") or [])
            if forced_tool is not None:
                # Steer content quality; the JSON guarantee comes from the
                # guided decoder below.  The instruction rides the LAST
                # USER turn — an appended system message would be rejected
                # by strict templates (gemma; role-alternation checks).
                steer = (
                    f"\n\n(Call the function "
                    f"{forced_tool['function']['name']} by replying with "
                    "ONLY its JSON arguments object.)"
                )
                if messages and messages[-1].get("role") == "user" and \
                        isinstance(messages[-1].get("content"), str):
                    messages[-1] = dict(
                        messages[-1],
                        content=messages[-1]["content"] + steer,
                    )
                else:
                    messages.append({"role": "user", "content": steer.strip()})
            prompt = tokenizer.apply_chat_template(
                messages,
                # 'none' means the model must not call tools: don't prompt
                # it with them.
                tools=tools if tool_choice != "none" else None,
            )
        else:
            prompt = body.get("prompt") or ""
            if isinstance(prompt, list):
                prompt = "\n".join(str(p) for p in prompt)
        try:
            params = _sampling_from_body(body, chat)
        except (TypeError, ValueError) as e:
            return web.json_response(
                {"error": {"message": str(e),
                           "type": "invalid_request_error"}},
                status=400,
            )
        stream = bool(body.get("stream", False))
        stream_options = body.get("stream_options")
        if stream_options is not None:
            # OpenAI: stream_options is only valid with stream=true.
            if not isinstance(stream_options, dict):
                return web.json_response(
                    {"error": {"message": "'stream_options' must be an "
                               "object", "type": "invalid_request_error"}},
                    status=400,
                )
            if not stream:
                return web.json_response(
                    {"error": {"message": "'stream_options' is only "
                               "allowed when 'stream' is true",
                               "type": "invalid_request_error"}},
                    status=400,
                )
        include_usage = bool((stream_options or {}).get("include_usage"))
        if params.echo and stream:
            return web.json_response(
                {"error": {"message": "'echo' is not supported with "
                           "streaming", "type": "invalid_request_error"}},
                status=400,
            )
        if forced_tool is not None:
            if stream:
                return web.json_response(
                    {"error": {"message": "forced tool_choice is not "
                               "supported with streaming",
                               "type": "invalid_request_error"}},
                    status=400,
                )
            # The arguments object is produced under the JSON guarantee —
            # and when the tool's parameters schema compiles under the
            # guided_schema subset, under THAT schema (strict tool calls:
            # correct keys/types by construction, not just valid JSON).
            params.response_format = "json_object"
            tool_schema = (forced_tool.get("function") or {}).get(
                "parameters"
            )
            if isinstance(tool_schema, dict):
                from production_stack_tpu.engine.guided_schema import (
                    SchemaCompileError,
                    compile_schema_cached,
                )

                try:
                    compile_schema_cached(tool_schema)
                    params.response_format = {
                        "type": "json_schema", "schema": tool_schema,
                    }
                except SchemaCompileError:
                    pass  # outside the subset: generic JSON guarantee
            params.ignore_eos = False
        request_id = request.headers.get("x-request-id") or f"cmpl-{uuid.uuid4().hex[:16]}"
        created = int(time.time())
        model_name = body.get("model", served_model)
        # "<base>:<adapter>" selects a loaded LoRA adapter; validate BEFORE
        # any stream starts so unknown adapters 400 cleanly.  Only active
        # on LoRA-enabled engines: otherwise ':' stays an opaque character
        # in the model id (e.g. ollama-style names) as before.
        adapter = None
        if ":" in model_name and engine.engine.lora_registry is not None:
            _, adapter = model_name.split(":", 1)
            try:
                engine.engine.lora_registry.slot_of(adapter)
            except ValueError as e:
                return web.json_response(
                    {"error": {"message": str(e),
                               "type": "invalid_request_error", "code": 404}},
                    status=400,
                )
        object_name = "chat.completion.chunk" if chat else "text_completion"
        prompt_token_ids = tokenizer.encode(prompt)

        # Reject over-long prompts BEFORE the stream starts: once the SSE
        # response is prepared, a scheduler-side ValueError can only
        # truncate the chunked body (clients see ClientPayloadError, not a
        # clean 400).
        max_len = engine.engine.config.scheduler.max_model_len
        if len(prompt_token_ids) >= max_len:
            return web.json_response(
                {
                    "error": {
                        "message": (
                            f"This model's maximum context length is "
                            f"{max_len} tokens, but the prompt is "
                            f"{len(prompt_token_ids)} tokens long"
                        ),
                        "type": "invalid_request_error",
                        "code": "context_length_exceeded",
                    }
                },
                status=400,
            )

        # n > 1: fan out one engine request per choice (OpenAI `n`).  Each
        # choice gets a distinct seed when one was supplied; without one
        # the engine's per-slot seeding already diversifies sampled runs.
        n_choices = body.get("n", 1)
        if n_choices is None:
            n_choices = 1
        if not isinstance(n_choices, int) or isinstance(n_choices, bool):
            # int() would silently truncate 2.9 and accept True.
            return web.json_response(
                {"error": {"message": f"n must be an integer, got "
                           f"{body.get('n')!r}",
                           "type": "invalid_request_error"}},
                status=400,
            )
        if not 1 <= n_choices <= 16:
            return web.json_response(
                {"error": {"message": f"n must be in [1, 16], got {n_choices}",
                           "type": "invalid_request_error"}},
                status=400,
            )

        # -- overload protection (docs/robustness.md) ----------------------
        now = time.time()
        try:
            deadline = parse_deadline(request.headers, body, now)
        except ValueError as e:
            return web.json_response(
                {"error": {"message": str(e),
                           "type": "invalid_request_error"}},
                status=400,
            )
        # Bounded admission: reject early and cheaply at the edge with a
        # structured 429 instead of queueing unboundedly and timing out
        # expensively in the middle.
        rejection = engine.check_admission(
            n_choices, n_choices * len(prompt_token_ids)
        )
        if rejection is not None:
            engine.engine.admission_rejected += 1
            return web.json_response(
                {
                    "error": {
                        "message": (
                            "engine overloaded: "
                            f"{rejection.queued_requests} requests "
                            f"({rejection.queued_tokens} prompt tokens) "
                            "already queued; retry after "
                            f"{rejection.retry_after_s}s"
                        ),
                        "type": "overloaded",
                        "code": 429,
                        "detail": dataclasses.asdict(rejection),
                    }
                },
                status=429,
                headers={"Retry-After": str(rejection.retry_after_s)},
            )

        def _shed_deadline(why: str, type_: str) -> web.Response:
            # Event-loop-side counter: the step thread owns
            # deadline_expired; sharing one attribute across threads
            # would lose increments (non-atomic +=).
            engine.engine.deadline_expired_admission += 1
            return web.json_response(
                {"error": {"message": why, "type": type_, "code": 504}},
                status=504,
            )

        if deadline is not None:
            params.deadline = deadline
            if now >= deadline:
                return _shed_deadline(
                    "request deadline already expired at admission",
                    "deadline_expired",
                )
            # "Would miss the deadline before first token -> shed now":
            # conservative wait estimate from the observed median TTFT
            # scaled by queue depth in batch units.  Only meaningful once
            # the histogram has real observations; tracing-off engines
            # skip the estimate and rely on the queued-expiry sweep.
            ttft_hist = engine.engine.obs.request_hists["ttft"]
            if ttft_hist.count >= 8:
                sched_cfg = engine.engine.config.scheduler
                est_wait = ttft_hist.quantile(0.5) * (
                    1.0
                    + engine.engine.scheduler.num_waiting
                    / max(1, sched_cfg.max_num_seqs)
                )
                if now + est_wait > deadline:
                    return _shed_deadline(
                        f"deadline unmeetable: estimated {est_wait:.2f}s to "
                        "first token exceeds the remaining budget",
                        "deadline_unmeetable",
                    )

        # -- disaggregated prefill phase (docs/engine.md) ------------------
        # The router's disagg policy primes a prefill-pool engine with
        # this marker: run the prefill (admission control and deadlines
        # above already applied), EAGERLY flush the prefix-chain export
        # so the shared store holds it before we answer — the decode
        # side's prefetch must never race the export writer — and return
        # a handoff token instead of generating.
        if request.headers.get("x-disagg-phase") == "prefill":
            prime_params = dataclasses.replace(
                params, max_tokens=0, logprobs=False, top_logprobs=0,
                echo=False,
            )
            gen = engine.generate(
                prompt_token_ids=prompt_token_ids,
                sampling_params=prime_params,
                request_id=request_id,
                adapter=adapter,
            )
            try:
                async for _event in gen:
                    pass
            except DeadlineExceeded as e:
                engine.engine.deadline_expired_admission += 1
                return web.json_response(
                    {"error": {"message": str(e), "type": "deadline_expired",
                               "code": 504}},
                    status=504,
                )
            # Eager (not off-step) export: the gather ran on the step
            # thread at final prefill; this blocks (off the event loop)
            # until the px-export writer has MPUT the chain.
            await asyncio.to_thread(
                engine.engine.flush_prefix_exports, 10.0
            )
            handoff = await asyncio.to_thread(
                engine.engine.handoff_token,
                prompt_token_ids,
                engine.engine.cache_ns_of(adapter),
            )
            engine.engine.disagg_prefill_primes += 1
            return web.json_response(
                {
                    "id": request_id,
                    "object": "disagg.prefill",
                    "created": created,
                    "model": model_name,
                    "disagg": {"handoff": handoff},
                    "usage": {
                        "prompt_tokens": len(prompt_token_ids),
                        "completion_tokens": 0,
                        "total_tokens": len(prompt_token_ids),
                    },
                },
                headers={"X-Request-Id": request_id},
            )

        # -- disaggregated decode phase -------------------------------------
        # A handoff-tagged generation waits (bounded, off the event loop
        # and off the step thread) for the prefetched chain to land in
        # the prefix cache, so its first schedule() serves the whole
        # prompt from cache.  Any other outcome admits normally — the
        # engine recomputes the prefill locally (in-place fused
        # fallback), never fails the request.
        disagg_prefix_outcome: Optional[str] = None
        handoff_hdr = request.headers.get("x-disagg-handoff")
        if handoff_hdr:
            try:
                handoff = json.loads(handoff_hdr)
            except json.JSONDecodeError:
                handoff = None
            disagg_prefix_outcome = "disabled"
            if isinstance(handoff, dict):
                wait_s = engine.engine.config.cache.disagg_handoff_wait_s
                if deadline is not None:
                    # Leave headroom for the generation itself.
                    wait_s = min(
                        wait_s, max(0.0, deadline - time.time() - 0.05)
                    )
                disagg_prefix_outcome = await asyncio.to_thread(
                    engine.engine.wait_handoff_prefix,
                    prompt_token_ids,
                    engine.engine.cache_ns_of(adapter),
                    handoff,
                    wait_s,
                )
            if disagg_prefix_outcome == "hit":
                engine.engine.disagg_handoff_hits += 1
            else:
                engine.engine.disagg_handoff_misses += 1

        obs = engine.engine.obs
        if obs.enabled:
            # Start the trace only AFTER every validation 400 above: a
            # rejected request must not leave a permanently-active trace
            # (the bounded active map would evict legitimate in-flight
            # timelines under a stream of rejects).  The router-propagated
            # W3C context joins this timeline to the router's.  With n>1
            # the trace follows the PRIMARY choice (choice 0 shares the
            # request id); sibling choices' engine lifecycles are not
            # traced — their token counts still land in the histograms.
            obs.start_request(
                request_id,
                parse_traceparent(request.headers.get("traceparent")),
                model=model_name, path=request.path, stream=stream,
                n=n_choices,
            )

        def choice_params(i: int) -> SamplingParams:
            if params.seed is None or i == 0:
                return params if i == 0 else dataclasses.replace(params)
            return dataclasses.replace(params, seed=params.seed + i)

        choice_ids = [
            request_id if i == 0 else f"{request_id}-c{i}"
            for i in range(n_choices)
        ]
        gens = [
            engine.generate(
                prompt_token_ids=prompt_token_ids,
                sampling_params=choice_params(i),
                request_id=choice_ids[i],
                adapter=adapter,
            )
            for i in range(n_choices)
        ]
        checkers = [
            StopChecker(tokenizer, params.stop) for _ in range(n_choices)
        ]
        # Accumulated host detokenize time across all choices, reported to
        # the obs layer when the request ends (the per-step phase the
        # engine core cannot see — it happens here in the server).  With
        # tracing off the untimed push keeps the pre-tracing hot path:
        # zero perf_counter calls per token.
        detok_s = [0.0]
        if obs.enabled:
            def timed_push(checker: StopChecker, token_id: int):
                t0 = time.perf_counter()
                out = checker.push(token_id)
                detok_s[0] += time.perf_counter() - t0
                return out
        else:
            def timed_push(checker: StopChecker, token_id: int):
                return checker.push(token_id)

        # Running character offset per choice for the legacy completions
        # logprobs text_offset array (consumed by e.g. lm-evaluation-harness).
        stream_offsets = [0] * n_choices

        def _logprob_entry(event) -> dict:
            """One token's OpenAI chat-style logprobs entry."""
            return {
                "token": (
                    tokenizer.decode([event.token_id])
                    if event.token_id >= 0 else ""
                ),
                "logprob": event.logprob,
                "top_logprobs": [
                    {"token": tokenizer.decode([tid]), "logprob": lp}
                    for tid, lp in (event.top_logprobs or [])
                ],
            }

        def chunk_payload(delta_text: str, finish_reason, first: bool,
                          event=None, index: int = 0):
            if chat:
                delta = {}
                if first:
                    delta["role"] = "assistant"
                if delta_text:
                    delta["content"] = delta_text
                choice = {"index": index, "delta": delta,
                          "finish_reason": finish_reason}
                if params.logprobs and event is not None:
                    choice["logprobs"] = {"content": [_logprob_entry(event)]}
            else:
                choice = {"index": index, "text": delta_text,
                          "finish_reason": finish_reason}
                if params.logprobs and event is not None:
                    tok_text = (
                        tokenizer.decode([event.token_id])
                        if event.token_id >= 0 else ""
                    )
                    choice["logprobs"] = {
                        "tokens": [tok_text],
                        "token_logprobs": [event.logprob],
                        "top_logprobs": [
                            {
                                tokenizer.decode([tid]): lp
                                for tid, lp in (event.top_logprobs or [])
                            }
                        ],
                        "text_offset": [stream_offsets[index]],
                    }
                    stream_offsets[index] += len(tok_text)
            return {
                "id": request_id,
                "object": object_name,
                "created": created,
                "model": model_name,
                "choices": [choice],
            }

        if stream:
            stream_headers = {
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
                "X-Request-Id": request_id,
            }
            if disagg_prefix_outcome is not None:
                stream_headers["X-Disagg-Prefix"] = disagg_prefix_outcome
            response = web.StreamResponse(headers=stream_headers)
            await response.prepare(request)

            # Merge the n per-choice event streams through one queue so
            # chunks interleave as tokens arrive (each chunk carries its
            # choice index).
            queue: asyncio.Queue = asyncio.Queue()

            async def pump(i: int, g):
                try:
                    async for ev in g:
                        await queue.put((i, ev, None))
                    await queue.put((i, None, None))
                except Exception as e:  # surfaced on the write loop
                    await queue.put((i, None, e))

            pumps = [
                asyncio.create_task(pump(i, g)) for i, g in enumerate(gens)
            ]
            first = [True] * n_choices
            live = [True] * n_choices
            retired = [False] * n_choices  # manually removed from `remaining`
            total_out = 0
            shed_on_deadline = False
            # The compile taint rides the FIRST data chunk (headers are
            # already on the wire at prepare(), before TTFT is known):
            # the router proxy sniffs it to keep a compile-excluded TTFT
            # window without parsing every chunk.
            compile_stamped = False
            try:
                remaining = n_choices
                while remaining:
                    i, event, error = await queue.get()
                    if error is not None:
                        if isinstance(error, DeadlineExceeded):
                            # Expired while queued: the stream is already
                            # prepared, so surface a structured SSE error
                            # event (no [DONE] — the stream did not
                            # complete) instead of a truncated body.
                            shed_on_deadline = True
                            await response.write(
                                f"data: {json.dumps({'error': {'message': str(error), 'type': 'deadline_expired', 'code': 504}})}\n\n".encode()
                            )
                            break
                        raise error
                    if event is None:
                        # A choice retired on a stop match was already
                        # deducted; its pump may still deliver a stale
                        # sentinel (it can enqueue finished+sentinel before
                        # the writer handles the stop token) — counting it
                        # again would end the stream under live siblings.
                        if not retired[i]:
                            remaining -= 1
                        continue
                    if not live[i]:
                        continue  # post-stop events of an aborting choice
                    checker = checkers[i]
                    delta, stopped = timed_push(checker, event.token_id)
                    if event.finished and not stopped:
                        # Flush any partial-stop-suffix holdback so the
                        # client gets the full tail.
                        delta += checker.flush()
                    if delta or first[i] or params.logprobs:
                        # A stop-triggering token is trimmed from the text,
                        # so it must not contribute a logprobs entry either
                        # (OpenAI: logprobs.content aligns with content).
                        payload = chunk_payload(
                            delta, None, first[i],
                            # The -1 sentinel (stop_token_ids) is equally
                            # absent from content, so no entry for it.
                            event=(
                                None if stopped or event.token_id < 0
                                else event
                            ),
                            index=i,
                        )
                        if not compile_stamped:
                            compile_stamped = True
                            if obs.enabled and obs.compile_tainted(
                                request_id
                            ):
                                payload["compile"] = True
                        await response.write(
                            f"data: {json.dumps(payload)}\n\n".encode()
                        )
                        first[i] = False
                    if stopped or event.finished:
                        if stopped or event.finish_reason == FinishReason.STOP:
                            reason = "stop"
                        elif event.finish_reason == FinishReason.GUIDED_INVALID:
                            reason = "guided_invalid"
                        else:
                            reason = "length"
                        if stopped and not event.finished:
                            # Abort emits no further events, so this pump
                            # will never send its sentinel: retire the
                            # choice here (cancelling the pump runs the
                            # generator's finally, which aborts in-engine).
                            pumps[i].cancel()
                            retired[i] = True
                            remaining -= 1
                        live[i] = False
                        total_out += event.num_output_tokens
                        final = chunk_payload("", reason, first[i], index=i)
                        await response.write(
                            f"data: {json.dumps(final)}\n\n".encode()
                        )
                if include_usage and not shed_on_deadline:
                    # OpenAI stream_options.include_usage: one extra
                    # final chunk with empty choices carrying the usage
                    # (and no usage anywhere otherwise).
                    usage_chunk = {
                        "id": request_id,
                        "object": object_name,
                        "created": created,
                        "model": model_name,
                        "choices": [],
                        "usage": {
                            "prompt_tokens": len(prompt_token_ids),
                            "completion_tokens": total_out,
                            "total_tokens": len(prompt_token_ids) + total_out,
                        },
                    }
                    await response.write(
                        f"data: {json.dumps(usage_chunk)}\n\n".encode()
                    )
                if not shed_on_deadline:
                    await response.write(b"data: [DONE]\n\n")
                await response.write_eof()
            except ConnectionResetError:
                pass  # cleanup below aborts every live choice
            finally:
                # Cancelling a pump closes its generator, whose finally
                # aborts the engine request if it hasn't finished — so a
                # disconnect or a mid-stream error on one choice never
                # leaves sibling choices decoding for nobody.
                for task in pumps:
                    task.cancel()
                if obs.enabled:
                    obs.record_detokenize(request_id, detok_s[0])
            return response

        # Non-streaming: drain all choices CONCURRENTLY (async generators
        # are lazy — a sequential for-loop would only submit choice i+1's
        # engine request after choice i finished, serializing what the
        # engine would otherwise batch).
        async def drain(i: int, gen):
            checker = checkers[i]
            text_parts = []
            logprob_entries = []
            prompt_lp = None
            finish_reason = "length"
            out_tokens = 0
            async for event in gen:
                if event.prompt_logprobs is not None:
                    prompt_lp = event.prompt_logprobs
                delta, stopped = timed_push(checker, event.token_id)
                text_parts.append(delta)
                if params.logprobs and event.token_id >= 0:
                    # The stop_token_ids sentinel contributes no text, so
                    # it must not contribute a logprobs entry either.
                    logprob_entries.append(event)
                if stopped:
                    finish_reason = "stop"
                    out_tokens = event.num_output_tokens
                    if not event.finished:
                        await engine.abort(choice_ids[i])
                    break
                if event.finished:
                    text_parts.append(checker.flush())
                    out_tokens = event.num_output_tokens
                    if event.finish_reason == FinishReason.STOP:
                        finish_reason = "stop"
                    elif event.finish_reason == FinishReason.GUIDED_INVALID:
                        finish_reason = "guided_invalid"
                    else:
                        finish_reason = "length"
                    break
            return ("".join(text_parts), logprob_entries, finish_reason,
                    out_tokens, prompt_lp)

        drain_tasks = [
            asyncio.create_task(drain(i, g)) for i, g in enumerate(gens)
        ]
        try:
            drained = await asyncio.gather(*drain_tasks)
        except DeadlineExceeded as e:
            # One choice expired while queued (the engine already released
            # its state).  The deadline is a WHOLE-REQUEST contract: a
            # non-streaming response must carry all n choices together,
            # and past the deadline nobody is waiting for it — so cancel
            # the sibling drains too (each cancellation closes its
            # generator, whose finally aborts the choice in-engine, even
            # ones already running) and shed with a clean 504.  The
            # engine-side "running sequences are exempt" rule is about
            # the SWEEP not killing independent streaming requests;
            # sibling choices of a dead request are not independent.
            for t in drain_tasks:
                t.cancel()
            if obs.enabled:
                obs.on_abort(request_id)
            return web.json_response(
                {"error": {"message": str(e), "type": "deadline_expired",
                           "code": 504}},
                status=504,
            )
        if obs.enabled:
            obs.record_detokenize(request_id, detok_s[0])
        choices = []
        total_out = 0
        for i, (text, logprob_entries, finish_reason, out_tokens,
                prompt_lp) in enumerate(drained):
            checker = checkers[i]
            total_out += out_tokens
            if params.logprobs:
                # Align with the post-stop-trim content: tokens consumed by
                # a (possibly multi-token) stop string contribute no
                # entries.  (Streaming can't retract already-sent entries;
                # this exact alignment is the non-streaming guarantee.)
                logprob_entries = logprob_entries[
                    : checker.aligned_token_count()
                ]
            if chat:
                tool_args_ok = False
                if forced_tool is not None:
                    try:
                        json.loads(text)
                        tool_args_ok = True
                    except (json.JSONDecodeError, TypeError):
                        # Budget too small for the guided close: surface
                        # the truncation (finish_reason from drain, plain
                        # content) instead of claiming a tool call with
                        # unparseable arguments.
                        tool_args_ok = False
                if forced_tool is not None and tool_args_ok:
                    # OpenAI tool-calling shape: arguments carry the
                    # guided-JSON output verbatim.
                    message = {
                        "role": "assistant",
                        "content": None,
                        "tool_calls": [{
                            "id": f"call_{uuid.uuid4().hex[:20]}",
                            "type": "function",
                            "function": {
                                "name": forced_tool["function"]["name"],
                                "arguments": text,
                            },
                        }],
                    }
                    finish_reason = "tool_calls"
                else:
                    message = {"role": "assistant", "content": text}
                choice = {
                    "index": i,
                    "message": message,
                    "finish_reason": finish_reason,
                }
                if params.logprobs:
                    choice["logprobs"] = {
                        "content": [_logprob_entry(e) for e in logprob_entries]
                    }
            else:
                out_text = (prompt + text) if params.echo else text
                choice = {"index": i, "text": out_text,
                          "finish_reason": finish_reason}
                if params.logprobs:
                    token_texts = [
                        tokenizer.decode([e.token_id]) if e.token_id >= 0 else ""
                        for e in logprob_entries
                    ]
                    token_lps = [e.logprob for e in logprob_entries]
                    tops = [
                        {
                            tokenizer.decode([tid]): lp
                            for tid, lp in (e.top_logprobs or [])
                        }
                        for e in logprob_entries
                    ]
                    if params.echo and prompt_lp is not None:
                        # Prepend the prompt's per-position entries (echo
                        # + logprobs: the lm-eval loglikelihood surface;
                        # position 0 has null logprob per OpenAI).
                        p_texts = [
                            tokenizer.decode([tid])
                            for tid in prompt_token_ids[: len(prompt_lp)]
                        ]
                        p_lps = [entry[0] for entry in prompt_lp]
                        p_tops = [
                            {
                                tokenizer.decode([tid]): lp
                                for tid, lp in (entry[1] or [])
                            } if entry[1] is not None else None
                            for entry in prompt_lp
                        ]
                        token_texts = p_texts + token_texts
                        token_lps = p_lps + token_lps
                        tops = p_tops + tops
                    offsets, pos = [], 0
                    for t in token_texts:
                        offsets.append(pos)
                        pos += len(t)
                    choice["logprobs"] = {
                        "tokens": token_texts,
                        "token_logprobs": token_lps,
                        "top_logprobs": tops,
                        "text_offset": offsets,
                    }
            choices.append(choice)
        obj = "chat.completion" if chat else "text_completion"
        n_out = total_out
        final_headers = {"X-Request-Id": request_id}
        if disagg_prefix_outcome is not None:
            final_headers["X-Disagg-Prefix"] = disagg_prefix_outcome
        final_body = {
            "id": request_id,
            "object": obj,
            "created": created,
            "model": model_name,
            "choices": choices,
            "usage": {
                "prompt_tokens": len(prompt_token_ids),
                "completion_tokens": n_out,
                "total_tokens": len(prompt_token_ids) + n_out,
            },
        }
        if obs.enabled and obs.compile_tainted(request_id):
            # An XLA compile fired inside this request's dispatches: its
            # latency is cold-start, not steady state.  The router's
            # stats monitor reads this to keep a compile-excluded TTFT
            # window (same marker the streaming path puts in the first
            # SSE chunk).
            final_body["compile"] = True
        return web.json_response(final_body, headers=final_headers)

    async def embeddings(request: web.Request) -> web.Response:
        """OpenAI /v1/embeddings: normalized mean-pooled final hidden
        states (llama.encode).  The engine the router proxies this path to
        must actually serve it."""
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return web.json_response(
                {"error": {"message": "invalid JSON",
                           "type": "invalid_request_error"}},
                status=400,
            )
        raw_input = body.get("input")
        if isinstance(raw_input, str):
            inputs = [raw_input]
        elif isinstance(raw_input, list) and all(
            isinstance(x, str) for x in raw_input
        ):
            inputs = raw_input
        else:
            return web.json_response(
                {"error": {"message": "'input' must be a string or list of "
                           "strings", "type": "invalid_request_error"}},
                status=400,
            )
        if not 1 <= len(inputs) <= 128:
            # Each item is a full device forward; an unbounded list would
            # let one request starve completions traffic.
            return web.json_response(
                {"error": {"message": f"'input' must contain 1-128 items, "
                           f"got {len(inputs)}",
                           "type": "invalid_request_error"}},
                status=400,
            )
        err, token_lists, deadline = _encode_admission(request, body, inputs)
        if err is not None:
            return err
        try:
            vectors, token_counts = await _embed_texts(
                inputs, token_lists=token_lists, deadline=deadline
            )
            total_tokens = sum(token_counts)
        except DeadlineExceeded as e:
            return web.json_response(
                {"error": {"message": str(e), "type": "deadline_expired",
                           "code": 504}},
                status=504,
            )
        except ValueError as e:
            # Over-long input, or a model without an encode path.
            return web.json_response(
                {"error": {"message": str(e),
                           "type": "invalid_request_error"}},
                status=400,
            )
        data = [
            {
                "object": "embedding",
                "index": i,
                "embedding": [float(v) for v in vector],
            }
            for i, vector in enumerate(vectors)
        ]
        return web.json_response({
            "object": "list",
            "data": data,
            "model": body.get("model", served_model),
            "usage": {"prompt_tokens": total_tokens,
                      "total_tokens": total_tokens},
        })

    def _encode_admission(request, body, texts):
        """Shared PR-5 overload protection for the encode surface
        (embeddings / rerank / score), applied BEFORE any device work is
        queued: deadline parse (400 on malformed), bounded admission
        (structured 429 + Retry-After against the encode-queue caps),
        expired-deadline shed (504).  Returns (error_response,
        token_lists, deadline); the token lists are reused by the embed
        call so each text tokenizes once."""
        tokenizer = engine.engine.tokenizer
        token_lists = [tokenizer.encode(text) for text in texts]
        now = time.time()
        try:
            deadline = parse_deadline(request.headers, body, now)
        except ValueError as e:
            return (
                web.json_response(
                    {"error": {"message": str(e),
                               "type": "invalid_request_error"}},
                    status=400,
                ),
                None, None,
            )
        rejection = engine.check_encode_admission(
            len(token_lists), sum(len(ids) for ids in token_lists)
        )
        if rejection is not None:
            engine.engine.admission_rejected += 1
            return (
                web.json_response(
                    {
                        "error": {
                            "message": (
                                "engine overloaded: "
                                f"{rejection.queued_requests} texts "
                                f"({rejection.queued_tokens} prompt tokens) "
                                "already queued on the encode lane; retry "
                                f"after {rejection.retry_after_s}s"
                            ),
                            "type": "overloaded",
                            "code": 429,
                            "detail": dataclasses.asdict(rejection),
                        }
                    },
                    status=429,
                    headers={"Retry-After": str(rejection.retry_after_s)},
                ),
                None, None,
            )
        if deadline is not None and now >= deadline:
            # Event-loop-side counter (the step thread owns
            # deadline_expired), same split as the completions path.
            engine.engine.deadline_expired_admission += 1
            return (
                web.json_response(
                    {"error": {"message": (
                        "request deadline already expired at admission"
                    ), "type": "deadline_expired", "code": 504}},
                    status=504,
                ),
                None, None,
            )
        return None, token_lists, deadline

    async def _embed_texts(texts, token_lists=None, deadline=None):
        """Embed a list of strings via the batched encode lane: texts
        queue on the EncodeBatcher and the STEP THREAD runs them as
        [B, T]-bucketed encode batches at window boundaries
        (engine/server/encode_batcher.py) — this coroutine never touches
        the device.  --no-encode-lane restores the legacy serial
        per-text path.  Returns (unit vectors, per-text token counts).

        Raises ValueError for over-long inputs or models without an
        encode path — callers map that to a 400 — and DeadlineExceeded
        when a queued text's deadline expired before dispatch (504).
        """
        tokenizer = engine.engine.tokenizer
        if token_lists is None:
            token_lists = [tokenizer.encode(text) for text in texts]
        vectors = await engine.embed_batch(token_lists, deadline=deadline)
        return vectors, [len(ids) for ids in token_lists]

    def _dot(a, b) -> float:
        return float(np.dot(a, b))

    async def rerank(request: web.Request) -> web.Response:
        """Jina/Cohere-style rerank (the contract the reference router
        proxies at /v1/rerank and /rerank): cosine relevance of each
        document to the query via the encode path, sorted descending."""
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return web.json_response(
                {"error": {"message": "invalid JSON",
                           "type": "invalid_request_error"}},
                status=400,
            )
        query = body.get("query")
        documents = body.get("documents")
        if not isinstance(query, str) or not isinstance(documents, list) or not all(
            isinstance(d, str) for d in documents
        ):
            return web.json_response(
                {"error": {"message": "'query' must be a string and "
                           "'documents' a list of strings",
                           "type": "invalid_request_error"}},
                status=400,
            )
        if not 1 <= len(documents) <= 128:
            return web.json_response(
                {"error": {"message": f"'documents' must contain 1-128 items, "
                           f"got {len(documents)}",
                           "type": "invalid_request_error"}},
                status=400,
            )
        top_n = body.get("top_n")
        if top_n is not None and (
            not isinstance(top_n, int) or isinstance(top_n, bool) or top_n < 1
        ):
            # Validate BEFORE the device forwards below, like every other
            # parameter on this endpoint.
            return web.json_response(
                {"error": {"message": "'top_n' must be a positive integer",
                           "type": "invalid_request_error"}},
                status=400,
            )
        texts = [query] + documents
        err, token_lists, deadline = _encode_admission(request, body, texts)
        if err is not None:
            return err
        try:
            vectors, token_counts = await _embed_texts(
                texts, token_lists=token_lists, deadline=deadline
            )
            total_tokens = sum(token_counts)
        except DeadlineExceeded as e:
            return web.json_response(
                {"error": {"message": str(e), "type": "deadline_expired",
                           "code": 504}},
                status=504,
            )
        except ValueError as e:
            return web.json_response(
                {"error": {"message": str(e), "type": "invalid_request_error"}},
                status=400,
            )
        qvec, dvecs = vectors[0], vectors[1:]
        results = [
            {"index": i, "document": {"text": documents[i]},
             "relevance_score": _dot(qvec, dvec)}
            for i, dvec in enumerate(dvecs)
        ]
        results.sort(key=lambda r: r["relevance_score"], reverse=True)
        if top_n is not None:
            results = results[:top_n]
        if not body.get("return_documents", True):
            for r in results:
                r.pop("document")
        return web.json_response({
            "id": f"rerank-{uuid.uuid4().hex[:16]}",
            "model": body.get("model", served_model),
            "usage": {"prompt_tokens": total_tokens,
                      "total_tokens": total_tokens},
            "results": results,
        })

    async def score(request: web.Request) -> web.Response:
        """vLLM-style /score: similarity of text_1 x text_2 pairs.  A single
        text_1 broadcasts over the text_2 list; equal-length lists pair
        elementwise."""
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return web.json_response(
                {"error": {"message": "invalid JSON",
                           "type": "invalid_request_error"}},
                status=400,
            )

        def as_list(v):
            if isinstance(v, str):
                return [v]
            if isinstance(v, list) and all(isinstance(x, str) for x in v):
                return v
            return None

        t1, t2 = as_list(body.get("text_1")), as_list(body.get("text_2"))
        if t1 is None or t2 is None or not t1 or not t2:
            return web.json_response(
                {"error": {"message": "'text_1' and 'text_2' must be "
                           "non-empty strings or lists of strings",
                           "type": "invalid_request_error"}},
                status=400,
            )
        if len(t1) == 1:
            t1 = t1 * len(t2)
        if len(t1) != len(t2):
            return web.json_response(
                {"error": {"message": f"'text_1' ({len(t1)}) and 'text_2' "
                           f"({len(t2)}) must broadcast (1-to-N or equal "
                           "length)", "type": "invalid_request_error"}},
                status=400,
            )
        if len(t2) > 128:
            return web.json_response(
                {"error": {"message": f"at most 128 pairs, got {len(t2)}",
                           "type": "invalid_request_error"}},
                status=400,
            )
        # Embed each distinct text once: a broadcast text_1 would
        # otherwise re-run the device forward per pair.
        distinct = list(dict.fromkeys(t1 + t2))
        err, token_lists, deadline = _encode_admission(request, body, distinct)
        if err is not None:
            return err
        try:
            vectors, token_counts = await _embed_texts(
                distinct, token_lists=token_lists, deadline=deadline
            )
        except DeadlineExceeded as e:
            return web.json_response(
                {"error": {"message": str(e), "type": "deadline_expired",
                           "code": 504}},
                status=504,
            )
        except ValueError as e:
            return web.json_response(
                {"error": {"message": str(e), "type": "invalid_request_error"}},
                status=400,
            )
        by_text = dict(zip(distinct, vectors))
        tokens_by_text = dict(zip(distinct, token_counts))
        # Usage reflects the logical pairs (per-pair accounting), even
        # though broadcast texts are embedded once.
        total_tokens = sum(
            tokens_by_text[a] + tokens_by_text[b] for a, b in zip(t1, t2)
        )
        data = [
            {"object": "score", "index": i,
             "score": _dot(by_text[a], by_text[b])}
            for i, (a, b) in enumerate(zip(t1, t2))
        ]
        return web.json_response({
            "id": f"score-{uuid.uuid4().hex[:16]}",
            "object": "list",
            "model": body.get("model", served_model),
            "usage": {"prompt_tokens": total_tokens,
                      "total_tokens": total_tokens},
            "data": data,
        })

    # -- multi-LoRA admin (proposals/lora-tpu-support.md control plane) ----

    async def lora_list(_req: web.Request) -> web.Response:
        return web.json_response({"adapters": engine.engine.loaded_adapters()})

    async def lora_load(request: web.Request) -> web.Response:
        try:
            body = await request.json()
            name = body["name"]
            path = body["path"]
        except (json.JSONDecodeError, KeyError):
            return web.json_response(
                {"error": {"message": "need JSON body with 'name' and 'path'"}},
                status=400,
            )
        try:
            # Off-loop: file I/O + hundreds of host->device transfers would
            # otherwise stall every in-flight SSE stream.  Catch broadly:
            # a corrupt file raises safetensors' own error type.
            slot = await asyncio.to_thread(
                engine.engine.load_lora_from_path,
                name, path, float(body.get("alpha", 16.0)),
            )
        except Exception as e:
            return web.json_response(
                {"error": {"message": f"{type(e).__name__}: {e}"}}, status=400
            )
        return web.json_response({"name": name, "slot": slot})

    async def lora_unload(request: web.Request) -> web.Response:
        try:
            engine.engine.unload_lora(request.match_info["name"])
        except ValueError as e:
            return web.json_response({"error": {"message": str(e)}}, status=400)
        return web.json_response({"ok": True})

    app.router.add_get("/v1/models", models)
    app.router.add_get("/health", health)
    app.router.add_get("/ready", ready)
    app.router.add_post("/drain", drain_endpoint)
    app.router.add_get("/metrics", metrics)
    app.router.add_get("/debug/requests", debug_requests)
    app.router.add_get("/debug/requests/{request_id}", debug_request)
    app.router.add_get("/debug/windows", debug_windows)
    app.router.add_get("/debug/compiles", debug_compiles)
    app.router.add_post("/v1/chat/completions", chat_completions)
    app.router.add_post("/v1/completions", completions)
    app.router.add_post("/v1/embeddings", embeddings)
    app.router.add_post("/v1/rerank", rerank)
    app.router.add_post("/rerank", rerank)
    app.router.add_post("/v1/score", score)
    app.router.add_post("/score", score)
    app.router.add_get("/admin/lora", lora_list)
    app.router.add_post("/admin/lora", lora_load)
    app.router.add_delete("/admin/lora/{name}", lora_unload)

    # vLLM's /tokenize + /detokenize: clients budget long-context
    # requests against max_model_len without shipping the tokenizer.
    async def tokenize(request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return web.json_response(
                {"error": {"message": "invalid JSON",
                           "type": "invalid_request_error"}},
                status=400,
            )
        tokenizer = engine.engine.tokenizer
        prompt = body.get("prompt")
        messages = body.get("messages")
        if isinstance(messages, list):
            try:
                prompt = tokenizer.apply_chat_template(messages)
            except Exception as e:
                return web.json_response(
                    {"error": {"message": f"chat template failed: {e}",
                               "type": "invalid_request_error"}},
                    status=400,
                )
        if not isinstance(prompt, str):
            return web.json_response(
                {"error": {"message": "'prompt' (string) or 'messages' "
                           "(list) is required",
                           "type": "invalid_request_error"}},
                status=400,
            )
        ids = tokenizer.encode(prompt)
        return web.json_response({
            "tokens": ids,
            "count": len(ids),
            "max_model_len": engine.engine.config.scheduler.max_model_len,
        })

    async def detokenize(request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return web.json_response(
                {"error": {"message": "invalid JSON",
                           "type": "invalid_request_error"}},
                status=400,
            )
        tokens = body.get("tokens")
        if not isinstance(tokens, list) or not all(
            isinstance(t, int) for t in tokens
        ):
            return web.json_response(
                {"error": {"message": "'tokens' must be a list of ids",
                           "type": "invalid_request_error"}},
                status=400,
            )
        return web.json_response(
            {"prompt": engine.engine.tokenizer.decode(tokens)}
        )

    app.router.add_post("/tokenize", tokenize)
    app.router.add_post("/detokenize", detokenize)

    # On-demand device profiling (vLLM's /start_profile and /stop_profile,
    # TPU-native: jax.profiler traces, viewable in TensorBoard/XProf or
    # Perfetto).  Serving continues while the trace records, so a
    # production TTFT spike can be captured in situ.
    profile_state = {"dir": None}

    async def start_profile(request: web.Request) -> web.Response:
        if profile_state["dir"] is not None:
            return web.json_response(
                {"error": {"message": "profiling already running "
                           f"(writing {profile_state['dir']})"}},
                status=409,
            )
        import jax

        try:
            body = await request.json()
        except Exception:
            body = {}
        trace_dir = body.get("trace_dir") or os.environ.get(
            "PSTPU_PROFILE_DIR", "/tmp/pstpu_profile"
        )
        try:
            jax.profiler.start_trace(trace_dir)
        except Exception as e:
            return web.json_response(
                {"error": {"message": f"start_trace failed: {e}"}},
                status=500,
            )
        profile_state["dir"] = trace_dir
        logger.info("profiling started -> %s", trace_dir)
        return web.json_response({"ok": True, "trace_dir": trace_dir})

    async def stop_profile(_req: web.Request) -> web.Response:
        if profile_state["dir"] is None:
            return web.json_response(
                {"error": {"message": "profiling is not running"}},
                status=409,
            )
        import jax

        trace_dir, profile_state["dir"] = profile_state["dir"], None
        try:
            jax.profiler.stop_trace()
        except Exception as e:
            return web.json_response(
                {"error": {"message": f"stop_trace failed: {e}"}},
                status=500,
            )
        logger.info("profiling stopped; trace in %s", trace_dir)
        return web.json_response({"ok": True, "trace_dir": trace_dir})

    app.router.add_post("/start_profile", start_profile)
    app.router.add_post("/stop_profile", stop_profile)

    async def lifecycle(app):
        await engine.start()
        # Follower->leader drain relay (slice-wide drain): a follower's
        # SIGTERM/preStop never leaves the collectives — it relays to
        # the leader, and the LEADER runs the one drain the whole group
        # follows (in-flight streams finish, then the step loop's
        # shutdown publish releases every member to exit 0 in order).
        # The relay fires on the monitor thread; begin() needs the loop.
        if engine.slice_monitor is not None:
            loop = asyncio.get_running_loop()
            engine.slice_monitor.on_drain_relay = (
                lambda: loop.call_soon_threadsafe(drain.begin)
            )
        yield
        await engine.close()

    app.cleanup_ctx.append(lifecycle)
    return app


def _parse_buckets(args):
    """Validate --prefill-buckets at parse time: each bucket must be a
    positive multiple of --block-size (the prefill plan sizes new_block_ids
    as bucket//block_size), returned ascending (the scheduler chunks long
    prompts at prefill_buckets[-1])."""
    try:
        buckets = sorted(int(b) for b in args.prefill_buckets.split(","))
    except ValueError:
        raise SystemExit(f"--prefill-buckets must be integers: {args.prefill_buckets!r}")
    for b in buckets:
        if b <= 0 or b % args.block_size:
            raise SystemExit(
                f"--prefill-buckets entries must be positive multiples of "
                f"--block-size={args.block_size}; got {b}"
            )
    return tuple(buckets)


# stackcheck: thread=health-serve
def _serve_health(health_loop, health_app, host, port) -> None:
    """Follower health-probe server thread: own loop + AppRunner (not
    web.run_app) so _run_follower can stop this thread and join it on
    the way out — a bare run_app daemon thread would die with the
    process holding a half-written probe response."""
    asyncio.set_event_loop(health_loop)
    runner = web.AppRunner(
        health_app, handle_signals=False, access_log=None
    )
    try:
        # The drain path's stop() can land while we are still inside
        # a startup run_until_complete (follower_loop failing fast,
        # e.g. unreachable leader): that raises "Event loop stopped
        # before Future completed" — fall through to cleanup anyway
        # so the listener socket is always released.
        health_loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, host, port)
        health_loop.run_until_complete(site.start())
        health_loop.run_forever()
    except RuntimeError:
        pass
    finally:
        try:
            if not health_loop.is_closed():
                health_loop.run_until_complete(runner.cleanup())
        except RuntimeError:
            pass
        finally:
            if not health_loop.is_closed():
                health_loop.close()


# stackcheck: thread=slice-guard
def _slice_guard(channel, stop_event) -> None:
    """Follower-side group-fail watcher: the leader's monitor writes a
    group-fail marker on the control-plane side channel when a member
    dies, and THIS thread is how a live follower sees it — the main
    thread is blocked inside a collective the dead member will never
    join, so only an off-collective poll can release it.  fatal_exit
    (never sys.exit): the wedged collective would hang atexit teardown."""
    from production_stack_tpu.engine.parallel import distributed

    while not stop_event.wait(0.5):
        reason = channel.group_failed()
        if reason is not None:
            logger.error(
                "slice group marked failed (%s); exiting for a parallel "
                "group restart", reason,
            )
            distributed.fatal_exit(1)
            return  # unreachable except under monkeypatched exit


def _run_follower(config, denv, args) -> None:
    """Follower process of a multi-host slice group: tiny probe app for
    k8s (the StatefulSet has one pod template, so every ordinal must
    answer probes AND the preStop /drain hook) + the lockstep step loop.

    Drain contract (docs/robustness.md "Slice lifecycle contract"):
    SIGTERM or POST /drain on a follower RELAYS the drain intent to the
    leader through the control-plane side channel — the follower keeps
    stepping (it never unilaterally leaves the collectives, which would
    kill every in-flight stream on the slice) until the leader finishes
    the in-flight streams and announces shutdown, releasing the whole
    group to exit 0 in order."""
    import signal
    import threading

    from production_stack_tpu.engine.core.engine import LLMEngine
    from production_stack_tpu.engine.parallel import distributed

    health_app = web.Application()
    engine = LLMEngine(config)
    channel = distributed.LockstepChannel(
        denv, member_timeout_s=args.slice_member_timeout_s
    )

    async def health(_req: web.Request) -> web.Response:
        if channel.stale():
            # Leader heartbeats while idle; prolonged silence means it is
            # gone, and an SPMD group cannot heal a lost member in place:
            # fail liveness so k8s restarts this pod into a fresh group.
            return web.json_response(
                {"status": "unhealthy", "role": "follower",
                 "problem": "no leader event within the staleness window"},
                status=503,
            )
        return web.json_response(
            {"status": "ok", "role": "follower",
             "process_id": denv.process_id}
        )

    async def ready(_req: web.Request) -> web.Response:
        """Follower readiness: 503 once a drain was relayed (the pod is
        on its way out; the client Service only selects ordinal 0, but
        operators and preStop ordering read this) or when the leader
        went stale."""
        if channel.drain_relayed:
            return web.json_response(
                {"status": "draining", "role": "follower"}, status=503
            )
        if channel.stale():
            return web.json_response(
                {"status": "unhealthy", "role": "follower"}, status=503
            )
        return web.json_response({"status": "ready", "role": "follower"})

    def _relay_drain(source: str) -> bool:
        relayed = channel.relay_drain()
        if relayed:
            logger.info(
                "follower %d: %s -> drain relayed to the leader; stepping "
                "until the group shutdown", denv.process_id, source,
            )
        else:
            logger.warning(
                "follower %d: %s but no control-plane side channel; "
                "relying on the leader's own drain/staleness path",
                denv.process_id, source,
            )
        return relayed

    async def drain_endpoint(_req: web.Request) -> web.Response:
        """POST /drain (helm preStop — one pod template, every ordinal
        gets the hook): relay to the leader, never exit unilaterally."""
        relayed = _relay_drain("POST /drain")
        return web.json_response({
            "draining": True, "role": "follower", "relayed": relayed,
        })

    health_app.router.add_get("/health", health)
    health_app.router.add_get("/ready", ready)
    health_app.router.add_post("/drain", drain_endpoint)

    # SIGTERM (kubelet pod termination) converges on the same relay.
    # signal.signal works here: _run_follower runs on the main thread.
    try:
        signal.signal(
            signal.SIGTERM, lambda _sig, _frm: _relay_drain("SIGTERM")
        )
    except (ValueError, OSError):  # non-main thread (tests) / platform
        pass

    health_loop = asyncio.new_event_loop()

    health_thread = threading.Thread(
        target=_serve_health,
        args=(health_loop, health_app, args.host, args.port),
        name="health-serve", daemon=True,
    )
    health_thread.start()
    guard_stop = threading.Event()
    guard_thread = threading.Thread(
        target=_slice_guard, args=(channel, guard_stop),
        name="slice-guard", daemon=True,
    )
    guard_thread.start()
    logger.info(
        "tpu-engine follower %d/%d ready (leader owns the HTTP surface)",
        denv.process_id, denv.num_processes,
    )
    try:
        distributed.follower_loop(engine, channel)
    finally:
        # Drain path: stop the probe server and join it, then release
        # the engine's worker threads (deleter queue included) so a
        # follower restart never strands queued remote work.  The loop
        # may already be closed (_serve_health died on a bind error);
        # engine.close() must run regardless.
        guard_stop.set()
        guard_thread.join(5)
        try:
            health_loop.call_soon_threadsafe(health_loop.stop)
        except RuntimeError:
            pass
        health_thread.join(10)
        engine.close()


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description="TPU serving engine (OpenAI API)")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=8000)
    parser.add_argument("--model", default="tiny-llama", help="model preset name")
    parser.add_argument("--served-model-name", default=None)
    parser.add_argument("--weights-path", default=None)
    parser.add_argument("--tokenizer", default=None)
    parser.add_argument(
        "--chat-template",
        default=None,
        help="path to a Jinja chat-template file overriding the "
        "tokenizer's (the chart mounts modelSpec.chatTemplate here; "
        "reference deployment-vllm-multi.yaml:260-270)",
    )
    parser.add_argument("--max-num-seqs", type=int, default=8)
    parser.add_argument("--max-model-len", type=int, default=2048)
    parser.add_argument("--block-size", type=int, default=16)
    parser.add_argument("--num-blocks", type=int, default=None)
    parser.add_argument(
        "--prefill-buckets",
        default=None,
        help="comma-separated prefill bucket lengths (prompts beyond the "
        "largest bucket run as chunked prefill)",
    )
    parser.add_argument(
        "--speculative-ngram",
        type=int,
        default=0,
        help="n-gram (prompt-lookup) speculative decoding: draft K tokens "
        "from the sequence's own history and verify them alongside the "
        "committed token in one forward.  With the K-step decode window "
        "active (the default) the drafter runs INSIDE the window scan — "
        "drafts proposed on-device, acceptance folded into the carried "
        "state, a rejected draft costs a scan iteration, never a host "
        "round-trip.  Greedy-only; with --no-multi-step-window the "
        "legacy host-side speculative path runs instead",
    )
    parser.add_argument(
        "--speculative-model",
        default=None,
        help="draft-MODEL speculative decoding: a model preset name "
        "(e.g. a 2-layer llama sharing the target's tokenizer/vocab — "
        "a vocab mismatch refuses to boot) loaded as a second tiny "
        "model on the same mesh.  It proposes --speculative-draft-len "
        "tokens per scan iteration INSIDE the K-step window, "
        "autoregressively from its own small device-resident KV cache "
        "(dedicated draft pool; target KV capacity untouched), and the "
        "target verifies draft+1 rows in the same wide forward the "
        "n-gram drafter uses.  Mutually exclusive with "
        "--speculative-ngram; requires the window machinery (no legacy "
        "host path).  Unlike n-gram lookup, acceptance holds up on "
        "non-templated text",
    )
    parser.add_argument(
        "--speculative-draft-len",
        type=int,
        default=4,
        help="draft tokens the model drafter proposes per scan "
        "iteration (the D in the W = D+1 verify-row fan-out; only "
        "meaningful with --speculative-model)",
    )
    parser.add_argument(
        "--speculative-draft-pool-blocks",
        type=int,
        default=None,
        help="device blocks reserved for the draft model's dedicated KV "
        "pool (default: auto-sized for max_num_seqs rows).  Exhaustion "
        "never stalls — a window that cannot allocate draft blocks "
        "declines to a plain window, counted under "
        "tpu:multistep_fallback_total{reason=draft_pool}",
    )
    parser.add_argument(
        "--no-speculative-model",
        action="store_true",
        help="force the model drafter OFF even if --speculative-model "
        "is set (deploy-template escape hatch; restores ngram-only / "
        "non-speculative behavior exactly)",
    )
    parser.add_argument(
        "--num-scheduler-steps",
        type=int,
        default=1,
        help="legacy spelling of the K-step decode window (vLLM "
        "--num-scheduler-steps): a value > 1 forces window size K "
        "through the same device-resident machinery --decode-window "
        "sizes; 1 defers to --decode-window",
    )
    parser.add_argument(
        "--no-multi-step-window",
        action="store_true",
        help="disable K-step device-resident decode windows (the default "
        "decode fast path: K decode+sample iterations per device "
        "dispatch with on-device penalties, the min_tokens EOS floor "
        "and per-row stop masking) and restore single-token stepping "
        "exactly — A/B baseline / debugging.  With --speculative-ngram "
        "this is the compat escape hatch selecting the legacy host-side "
        "speculative path",
    )
    parser.add_argument(
        "--decode-window",
        type=int,
        default=8,
        help="window size K for the K-step decode fast path (iterations "
        "fused per pure-decode dispatch; the per-token host round-trip "
        "is amortized K-fold and the device stop-mask keeps stop "
        "conditions from wasting the tail of the window)",
    )
    parser.add_argument(
        "--no-pipeline-decode",
        action="store_true",
        help="disable the async lookahead decode pipeline (dispatch "
        "decode step or K-step window N+1 while N's tokens are in "
        "flight; greedy streams are identical, decode_host_gap_ms shows "
        "the recovered host serialization).  Auto-disabled only by the "
        "legacy host-side speculative path (--speculative-ngram with "
        "--no-multi-step-window)",
    )
    parser.add_argument(
        "--no-mixed-batch",
        action="store_true",
        help="disable fused mixed prefill+decode steps (arriving prompts "
        "then stall all decoders for a full prefill bucket per step — "
        "the pre-mixed alternating scheduler).  Auto-disabled by the "
        "legacy host-side speculative path (--speculative-ngram with "
        "--no-multi-step-window) and dp/sp meshes",
    )
    parser.add_argument(
        "--no-mixed-window",
        action="store_true",
        help="disable mixed K-step windows (a waiting prompt's prefill "
        "chunks riding the device-resident decode scan) and restore the "
        "K=1 mixed scheduling exactly: a waiting head forces "
        "single-token steps, counted under tpu:multistep_fallback_total"
        '{reason="waiting_head"} — A/B baseline / debugging',
    )
    parser.add_argument(
        "--no-multi-prompt-window",
        action="store_true",
        help="disable multi-prompt packing inside mixed K-step windows "
        "and restore the single-head window planner exactly (one "
        "waiting prompt's chunks per window, adaptive K-halving clamp "
        "under deep queues) — A/B baseline / debugging",
    )
    parser.add_argument(
        "--max-num-batched-tokens",
        type=int,
        default=None,
        help="token budget per fused mixed step (decode tokens count "
        "first, the prefill chunk gets the remainder; a mixed K-step "
        "window applies it per scan iteration, so the window total is "
        "K x the budget); default admits the largest chunk bucket "
        "beside a full decode batch",
    )
    parser.add_argument("--host-offload-gb", type=float, default=0.0)
    parser.add_argument("--remote-kv-url", default=None)
    parser.add_argument(
        "--disagg-role",
        default=None,
        choices=["prefill", "decode", "both", "encode"],
        help="cross-engine prefix sharing through the remote KV store: "
        "'prefill' exports prompt KV blocks after prefill, 'decode' "
        "imports matching blocks instead of recomputing, 'both' shares "
        "symmetrically (requires --remote-kv-url); 'encode' marks a "
        "dedicated embed/rerank/score pool member (no KV handoff, no "
        "--remote-kv-url needed) — the router's encode lane prefers it",
    )
    parser.add_argument(
        "--no-remote-prefetch",
        action="store_true",
        help="disable the asynchronous batched KV transfer plane "
        "(admission-time remote-prefix prefetch, off-step offload "
        "staging, async restore page-in) and restore the legacy "
        "synchronous in-schedule transfers — A/B baseline / debugging",
    )
    parser.add_argument(
        "--prefetch-threads", type=int, default=2,
        help="background fetcher threads for the KV prefetch plane",
    )
    parser.add_argument(
        "--disagg-handoff-wait-s", type=float, default=2.0,
        help="decode-phase handoff: bounded wait for the prefetched "
        "prefix chain to land in the cache before admitting anyway "
        "(caps the TTFT tax of a slow store; a store miss exits early; "
        "0 disables the wait)",
    )
    parser.add_argument("--no-prefix-caching", action="store_true")
    parser.add_argument(
        "--kv-cache-dtype",
        default=None,
        choices=["auto", "int8"],
        help="KV cache precision (vLLM --kv-cache-dtype analogue): int8 "
        "stores cached K/V as int8 with per-(token, head) scales — KV HBM "
        "bytes roughly halve, so the pool holds ~2x the tokens",
    )
    parser.add_argument(
        "--kv-wire-format",
        default=None,
        choices=["auto", "fp32", "int8"],
        help="offload/remote wire representation for quantized KV caches: "
        "auto (default) serializes an int8 cache's native (data, scale) "
        "tuples — ~4x resident tokens per host-DRAM byte, kvserver serde "
        "v2 with a probe-once dense-v1 fallback against legacy stores; "
        "fp32 pins the legacy dense wire (rollout escape hatch / A/B "
        "baseline); int8 is auto plus strictness (requires an int8 "
        "cache; a non-v2 store logs a loud downgrade warning)",
    )
    parser.add_argument("--dtype", default=None, help="override preset dtype")
    parser.add_argument(
        "--quantization",
        default=None,
        choices=["int8"],
        help="weight-only quantization of the projection matmuls "
        "(halves decode's HBM weight traffic)",
    )
    # Mesh axes (TPU-first: the reference chart only passes
    # --tensor-parallel-size through to vLLM, deployment-vllm-multi.yaml:84-87;
    # here dp/tp/sp are first-class — config.ParallelConfig).
    parser.add_argument("--data-parallel", type=int, default=1)
    parser.add_argument("--tensor-parallel", type=int, default=1)
    parser.add_argument("--sequence-parallel", type=int, default=1)
    parser.add_argument(
        "--sequence-parallel-mode", choices=["ring", "ulysses"], default="ring"
    )
    # Multi-LoRA slots (engine/lora.py); adapters load via POST /admin/lora.
    parser.add_argument("--max-loras", type=int, default=0)
    parser.add_argument("--max-lora-rank", type=int, default=16)
    # Overload protection + graceful lifecycle (docs/robustness.md).
    parser.add_argument(
        "--no-admission-control",
        action="store_true",
        help="disable bounded admission (the waiting queue then grows "
        "without bound, exactly the legacy behavior; overload times out "
        "in the middle instead of being shed with a 429 at the edge)",
    )
    parser.add_argument(
        "--max-queued-requests", type=int, default=None,
        help="waiting-queue request bound for bounded admission "
        "(default: 4 x --max-num-seqs)",
    )
    parser.add_argument(
        "--max-queued-tokens", type=int, default=None,
        help="waiting-queue prompt-token bound for bounded admission "
        "(default: 2 x --max-num-seqs x --max-model-len)",
    )
    parser.add_argument(
        "--no-encode-lane",
        action="store_true",
        help="disable the batched encode lane (embed/rerank/score then "
        "run the legacy serial per-text encode off the step thread, and "
        "encode admission falls back to the generation caps) — A/B "
        "baseline / debugging",
    )
    parser.add_argument(
        "--encode-batch-buckets", default=None,
        help="comma-separated B-axis bucket grid for encode batches "
        "(default 1,2,4,8); the T axis pads to the prefill buckets",
    )
    parser.add_argument(
        "--max-queued-encode-texts", type=int, default=None,
        help="encode-queue text bound for bounded admission "
        "(default: 32 x the largest encode batch bucket)",
    )
    parser.add_argument(
        "--step-watchdog-s", type=float, default=300.0,
        help="fail /health liveness when the engine step loop has not "
        "iterated in this many seconds (hung device dispatch); 0 disables",
    )
    parser.add_argument(
        "--slice-member-timeout-s", type=float, default=10.0,
        help="multi-host slice groups: fail the leader's /health (and "
        "fatal-exit the whole group into a parallel restart) when a "
        "member's lockstep acks stop advancing for this long — well "
        "under --step-watchdog-s, so a dead follower fails the slice in "
        "seconds instead of wedging collectives until the watchdog; "
        "0 disables group liveness (staleness-window behavior only)",
    )
    parser.add_argument(
        "--drain-grace-s", type=float, default=30.0,
        help="on SIGTERM or POST /drain: stop admitting (503 + "
        "Connection: close), flip /ready to 503, let in-flight streams "
        "finish up to this many seconds, then exit 0",
    )
    parser.add_argument(
        "--no-tracing",
        action="store_true",
        help="disable request tracing + step-phase histograms "
        "(obs.tracing=off: restores the untraced hot path; /debug/requests "
        "returns an empty ring and /metrics drops the histogram families' "
        "samples growth)",
    )
    parser.add_argument(
        "--trace-ring-size", type=int, default=256,
        help="completed request timelines kept for GET /debug/requests",
    )
    parser.add_argument(
        "--trace-ring-bytes", type=int, default=8 * 1024 * 1024,
        help="byte bound on the completed-trace ring (JSON-encoded size; "
        "evictions past it count in tpu:obs_trace_dropped_total; 0 = "
        "count bound only)",
    )
    parser.add_argument(
        "--window-ring-size", type=int, default=1024,
        help="window flight records kept for GET /debug/windows",
    )
    parser.add_argument("--log-level", default="info")
    args = parser.parse_args(argv)

    init_logger("production_stack_tpu", args.log_level)
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # TPU hosts ship a sitecustomize that pins the TPU plugin at
        # interpreter startup; honor an explicit CPU request anyway (same
        # dance as tests/conftest.py and bench.py).
        import jax

        jax.config.update("jax_platforms", "cpu")
    config = config_from_preset(
        args.model,
        **{
            "weights_path": args.weights_path,
            "tokenizer": args.tokenizer,
            "scheduler.max_num_seqs": args.max_num_seqs,
            "scheduler.max_model_len": args.max_model_len,
            **(
                {"scheduler.prefill_buckets": _parse_buckets(args)}
                if args.prefill_buckets
                else {}
            ),
            "scheduler.num_scheduler_steps": args.num_scheduler_steps,
            "scheduler.speculative_ngram": args.speculative_ngram,
            **(
                {
                    "scheduler.speculative_model": args.speculative_model,
                    "scheduler.speculative_draft_len":
                        args.speculative_draft_len,
                    **(
                        {
                            "scheduler.speculative_draft_pool_blocks":
                                args.speculative_draft_pool_blocks,
                        }
                        if args.speculative_draft_pool_blocks is not None
                        else {}
                    ),
                }
                if args.speculative_model is not None
                and not args.no_speculative_model
                else {}
            ),
            **(
                {"scheduler.multi_step_window": False}
                if args.no_multi_step_window else {}
            ),
            "scheduler.decode_window": args.decode_window,
            **(
                {"scheduler.pipeline_decode": False}
                if args.no_pipeline_decode else {}
            ),
            **(
                {"scheduler.mixed_batch": False}
                if args.no_mixed_batch else {}
            ),
            **(
                {"scheduler.mixed_window": False}
                if args.no_mixed_window else {}
            ),
            **(
                {"scheduler.multi_prompt_window": False}
                if args.no_multi_prompt_window else {}
            ),
            **(
                {"scheduler.max_num_batched_tokens": args.max_num_batched_tokens}
                if args.max_num_batched_tokens is not None else {}
            ),
            "cache.block_size": args.block_size,
            "cache.num_blocks": args.num_blocks,
            "cache.host_offload_gb": args.host_offload_gb,
            "cache.remote_kv_url": args.remote_kv_url,
            "cache.disagg_role": args.disagg_role,
            **(
                {"cache.remote_prefetch": False}
                if args.no_remote_prefetch else {}
            ),
            "cache.prefetch_threads": args.prefetch_threads,
            "cache.disagg_handoff_wait_s": args.disagg_handoff_wait_s,
            "cache.enable_prefix_caching": not args.no_prefix_caching,
            **(
                {"cache.kv_cache_dtype": args.kv_cache_dtype}
                if args.kv_cache_dtype else {}
            ),
            **(
                {"cache.kv_wire_format": args.kv_wire_format}
                if args.kv_wire_format else {}
            ),
            **({"model.dtype": args.dtype} if args.dtype else {}),
            **(
                {"model.quantization": args.quantization}
                if args.quantization else {}
            ),
            "parallel.data_parallel": args.data_parallel,
            "parallel.tensor_parallel": args.tensor_parallel,
            "parallel.sequence_parallel": args.sequence_parallel,
            "parallel.sequence_parallel_mode": args.sequence_parallel_mode,
            "lora.max_loras": args.max_loras,
            "lora.max_rank": args.max_lora_rank,
            **(
                {"scheduler.admission_control": False}
                if args.no_admission_control else {}
            ),
            **(
                {"scheduler.max_queued_requests": args.max_queued_requests}
                if args.max_queued_requests is not None else {}
            ),
            **(
                {"scheduler.max_queued_tokens": args.max_queued_tokens}
                if args.max_queued_tokens is not None else {}
            ),
            **(
                {"scheduler.encode_lane": False}
                if args.no_encode_lane else {}
            ),
            **(
                {"scheduler.encode_batch_buckets": tuple(
                    int(b) for b in args.encode_batch_buckets.split(",")
                )}
                if args.encode_batch_buckets else {}
            ),
            **(
                {"scheduler.max_queued_encode_texts":
                    args.max_queued_encode_texts}
                if args.max_queued_encode_texts is not None else {}
            ),
            "scheduler.step_watchdog_s": args.step_watchdog_s,
            "obs.tracing": not args.no_tracing,
            "obs.trace_ring_size": args.trace_ring_size,
            "obs.trace_ring_bytes": args.trace_ring_bytes,
            "obs.window_ring_size": args.window_ring_size,
        },
    )
    # Multi-host slice bootstrap (chart StatefulSet mode / GKE TPU pod
    # env): initialize jax.distributed so the mesh spans every worker's
    # chips.  Follower processes build the same engine, serve only
    # /health, and step in lockstep with the leader's event broadcasts.
    from production_stack_tpu.engine.parallel import distributed

    denv = distributed.maybe_initialize()
    if denv is not None and config.cache.remote_prefetch is None:
        # Async KV transfers are thread-timing-dependent (stager slot
        # busy-ness, restore page-in readiness); inside a lockstep
        # multi-host group a per-replica difference in offload/restore
        # outcomes desyncs the step plans.  Auto mode therefore resolves
        # to the deterministic synchronous path here; an EXPLICIT
        # remote_prefetch=True is honored (operator's call).
        logger.info(
            "multi-host lockstep group: disabling async KV transfer "
            "plane (cache.remote_prefetch auto -> False)"
        )
        config.cache.remote_prefetch = False
    if denv is not None and config.scheduler.encode_lane is None:
        # A leader-only encode forward would desync the SPMD followers'
        # jitted launch sequence (encode batches are not part of the
        # lockstep event broadcast).  Auto resolves to off here; an
        # EXPLICIT encode_lane=True is still cleared by the AsyncEngine
        # guard, which is the one that owns device dispatch.
        logger.info(
            "multi-host lockstep group: disabling the batched encode "
            "lane (scheduler.encode_lane auto -> False)"
        )
        config.scheduler.encode_lane = False
    if denv is not None and args.data_parallel > 1:
        # dp shards the decode batch; across PROCESSES the leader could
        # not read the non-addressable logit/token shards (and dp over
        # DCN wastes the slice's ICI anyway).  Replica-level dp belongs
        # to the chart (replicaCount = more slice groups); within a
        # multi-host group use tp/sp.
        raise SystemExit(
            "--data-parallel > 1 is not supported inside a multi-host "
            "slice group; scale replicas with the chart's replicaCount "
            "and use --tensor-parallel/--sequence-parallel across hosts"
        )
    if denv is not None and not denv.is_leader:
        _run_follower(config, denv, args)
        return
    lockstep = (
        distributed.LockstepChannel(
            denv, member_timeout_s=args.slice_member_timeout_s
        )
        if denv is not None else None
    )

    engine = AsyncEngine(config, lockstep=lockstep)
    if args.chat_template:
        with open(args.chat_template, "r", encoding="utf-8") as f:
            engine.engine.tokenizer.chat_template = f.read()
        try:
            # Fail at boot, not per-request: render a probe conversation so
            # template typos (undefined vars, syntax errors) surface now.
            engine.engine.tokenizer.apply_chat_template(
                [{"role": "system", "content": "probe"},
                 {"role": "user", "content": "probe"}]
            )
        except Exception as e:
            raise SystemExit(
                f"--chat-template {args.chat_template} failed to render: "
                f"{type(e).__name__}: {e}"
            )
        logger.info("Chat template override: %s", args.chat_template)
    served = args.served_model_name or args.model
    app = build_engine_app(engine, served, drain_grace_s=args.drain_grace_s)

    # Graceful SIGTERM (k8s pod termination): replace aiohttp's
    # raise-GracefulExit handler with a drain — readiness flips, admission
    # stops, in-flight streams finish within --drain-grace-s, and the
    # drain's exit_cb re-enters aiohttp's graceful-exit path via SIGINT so
    # cleanup_ctx (engine.close) still runs and the process exits 0.
    # app.on_startup runs AFTER AppRunner.setup registered aiohttp's
    # handlers, so add_signal_handler here wins.
    import signal

    async def _install_sigterm(app_: web.Application) -> None:
        drain = app_["drain"]
        drain.exit_cb = lambda: os.kill(os.getpid(), signal.SIGINT)
        loop = asyncio.get_running_loop()
        try:
            loop.add_signal_handler(
                signal.SIGTERM,
                lambda: (
                    logger.info("SIGTERM: beginning graceful drain"),
                    drain.begin(),
                ),
            )
        except (NotImplementedError, RuntimeError):  # non-main thread / win
            pass

    app.on_startup.append(_install_sigterm)
    logger.info("Starting tpu-engine (%s) on %s:%d", served, args.host, args.port)
    web.run_app(app, host=args.host, port=args.port, access_log=None)


if __name__ == "__main__":
    main()
