"""Batched encode lane: the queue between the event loop and the step
thread for embed/rerank/score inputs.

The event loop ``submit()``s validated token lists (one asyncio future
per text) and never touches the device; the STEP THREAD drains the queue
via ``run_pending()`` at window boundaries — each drain is one
[B, T]-bucketed ``LLMEngine.encode_batch`` dispatch, a prefill-chunk-
shaped pass with no KV bookkeeping.  While generation is live the loop
runs at most one batch per iteration (an embed burst adds at most one
encode pass between decode windows, so ITL stays bounded); with the
device idle it drains the queue completely.

Results cross back to the event loop the same way token events do:
``loop.call_soon_threadsafe`` future resolution — no polling, no shared
mutable results.
"""

from __future__ import annotations

import asyncio
import threading
import time
from typing import List, Optional, Tuple

from production_stack_tpu.engine.core.engine import LLMEngine


class _Item:
    __slots__ = ("token_ids", "future", "loop", "deadline")

    def __init__(
        self,
        token_ids: List[int],
        future: "asyncio.Future",
        loop: "asyncio.AbstractEventLoop",
        deadline: Optional[float],
    ):
        self.token_ids = token_ids
        self.future = future
        self.loop = loop
        self.deadline = deadline


class EncodeBatcher:
    """FIFO encode queue with two single-threaded sides: submissions on
    the event loop, batch execution on the engine step thread.  The
    shared list is the only crossing point and is lock-guarded; the
    engine's ``encode_queue_depth`` gauge is overwritten (never summed)
    from both sides, so the snapshot race is benign."""

    def __init__(self, engine: LLMEngine):
        self._engine = engine
        self._lock = threading.Lock()
        self._items: List[_Item] = []

    # -- event-loop side ---------------------------------------------------

    def snapshot(self) -> Tuple[int, int]:
        """(queued texts, queued tokens) — the encode-admission read.
        Advisory like the generation check: concurrent handlers may
        interleave between check and submit, but the overshoot is
        bounded by the handful of bodies being parsed at once."""
        with self._lock:
            return (
                len(self._items),
                sum(len(i.token_ids) for i in self._items),
            )

    def submit(
        self,
        batch_token_ids: List[List[int]],
        loop: "asyncio.AbstractEventLoop",
        deadline: Optional[float] = None,
    ) -> List["asyncio.Future"]:
        """Queue one future per text (already validated by the caller);
        the caller wakes the step loop."""
        items = [
            _Item(list(ids), loop.create_future(), loop, deadline)
            for ids in batch_token_ids
        ]
        with self._lock:
            self._items.extend(items)
            depth = len(self._items)
        self._engine.encode_queue_depth = depth
        return [i.future for i in items]

    def fail_all(self, exc: Exception) -> None:
        """Shutdown path: resolve every queued future with ``exc`` so no
        embed request hangs past the step thread's exit."""
        with self._lock:
            items, self._items = self._items, []
        self._engine.encode_queue_depth = 0
        for item in items:
            self._resolve(item, exc)

    # -- step-thread side --------------------------------------------------

    def has_pending(self) -> bool:
        with self._lock:
            return bool(self._items)

    # stackcheck: thread=engine-step-loop
    def run_pending(self, max_batches: int = 1) -> int:
        """Drain up to ``max_batches`` [B, T]-bucketed encode batches
        (0 = until the queue is empty).  Returns batches dispatched.
        STEP-THREAD-only: this is the single place encode work touches
        the device, and it shares the thread (and therefore the window
        boundary) with dispatch()/collect()."""
        from production_stack_tpu.engine.server.async_engine import (
            DeadlineExceeded,
        )

        ran = 0
        while max_batches <= 0 or ran < max_batches:
            batch = self._take_batch()
            if not batch:
                break
            # stackcheck: allow=SC201 reason=the batcher only exists single-host (AsyncEngine skips construction under multi-host lockstep, where the server auto-disables the encode lane) so no replica can diverge on this clock read — same contract as the deadline sweep in _run_loop
            now = time.time()
            live: List[_Item] = []
            for item in batch:
                # stackcheck: allow=SC201 reason=single-host only; see the clock-read annotation above
                if item.deadline is not None and now > item.deadline:
                    # Queued-expiry shed, encode flavor: the step thread
                    # owns deadline_expired (one writer per counter).
                    self._engine.deadline_expired += 1
                    self._resolve(item, DeadlineExceeded(
                        "embedding input missed its deadline while queued "
                        "for the encode lane; shed before dispatch"
                    ))
                else:
                    live.append(item)
            if not live:
                continue  # whole batch expired; no device work happened
            try:
                vectors = self._engine.encode_batch(
                    [i.token_ids for i in live]
                )
            except Exception as e:  # surface per-future, keep loop alive
                for item in live:
                    self._resolve(item, e)
            else:
                for item, vec in zip(live, vectors):
                    self._resolve(item, vec)
            ran += 1
        return ran

    def _take_batch(self) -> List[_Item]:
        cap = self._engine.config.scheduler.encode_batch_buckets[-1]
        with self._lock:
            batch, self._items = self._items[:cap], self._items[cap:]
            depth = len(self._items)
        self._engine.encode_queue_depth = depth
        return batch

    @staticmethod
    def _resolve(item: _Item, result) -> None:
        def _set() -> None:
            if item.future.done():
                return  # consumer gave up (cancelled) — nothing to do
            if isinstance(result, Exception):
                item.future.set_exception(result)
            else:
                item.future.set_result(result)

        item.loop.call_soon_threadsafe(_set)
