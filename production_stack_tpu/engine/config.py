"""Engine configuration.

Mirrors the configuration surface the reference exposes per modelSpec in
helm (helm/values.yaml:16-128: model, dtype, maxModelLen, prefix caching,
chunked prefill, tensorParallelSize) — expressed TPU-first: parallelism is a
mesh shape, memory is an HBM fraction for the paged-KV pool.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple


@dataclasses.dataclass
class ModelConfig:
    """Decoder-only transformer architecture (llama family + friends)."""

    name: str = "tiny-llama"
    vocab_size: int = 384  # covers the 260-entry byte-fallback tokenizer
    hidden_size: int = 64
    intermediate_size: int = 128
    num_layers: int = 2
    num_heads: int = 4
    num_kv_heads: int = 2
    head_dim: Optional[int] = None  # defaults to hidden_size // num_heads
    max_model_len: int = 2048
    rope_theta: float = 10000.0
    # Llama-3.1/3.2-style "llama3" RoPE scaling (HF rope_scaling dict:
    # factor / low_freq_factor / high_freq_factor /
    # original_max_position_embeddings) — stretches an 8k-trained RoPE to
    # 128k contexts.  None = classic RoPE.
    rope_scaling: Optional[dict] = None
    rms_norm_eps: float = 1e-5
    tie_word_embeddings: bool = False
    dtype: str = "bfloat16"
    # Architecture switches (cover llama/mistral/qwen-style variants).
    attention_bias: bool = False
    mlp_bias: bool = False
    sliding_window: Optional[int] = None  # mistral-style local attention
    # Sparse MoE (mixtral-style): 0 = dense MLP.  Experts shard over the
    # tp mesh axis (models/llama.py _moe_mlp).
    num_experts: int = 0
    num_experts_per_tok: int = 2
    # Gemma-family switches: zero-centered RMSNorm weights (output scaled
    # by 1+w), tanh-approx GeGLU activation, sqrt(h) embedding scaling.
    rms_norm_offset: float = 0.0
    hidden_act: str = "silu"  # silu | gelu_tanh
    scale_embeddings: bool = False
    # Weight-only quantization of the projection matmuls (decode is
    # HBM-bandwidth-bound: int8 weights halve the bytes streamed per step,
    # nearly doubling the decode roofline).  None | "int8" (per-out-channel
    # symmetric scales; embeddings/norms/biases stay in dtype).
    quantization: Optional[str] = None

    def __post_init__(self):
        if self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_heads
        assert self.num_heads % self.num_kv_heads == 0
        if self.quantization not in (None, "int8"):
            raise ValueError(
                f"Unknown quantization {self.quantization!r} (None | int8)"
            )
        if self.hidden_act not in ("silu", "gelu_tanh"):
            # A typo (or HF's own string, "gelu_pytorch_tanh") silently
            # falling back to silu would serve wrong logits forever.
            raise ValueError(
                f"Unknown hidden_act {self.hidden_act!r} (silu | gelu_tanh)"
            )

    @property
    def q_per_kv(self) -> int:
        return self.num_heads // self.num_kv_heads


# Preset architectures (shapes from the public HF configs of each family;
# weights are loaded from local checkpoints or randomly initialized).
PRESETS = {
    "tiny-llama": ModelConfig(),
    "debug-1l": ModelConfig(name="llama-debug-1l", num_layers=1),
    "llama-3.2-1b": ModelConfig(
        name="llama-3.2-1b",
        vocab_size=128256,
        hidden_size=2048,
        intermediate_size=8192,
        num_layers=16,
        num_heads=32,
        num_kv_heads=8,
        head_dim=64,
        max_model_len=8192,
        rope_theta=500000.0,
        tie_word_embeddings=True,
        # The 3.2 checkpoints ship llama3 rope scaling (128k-trained).
        rope_scaling={
            "rope_type": "llama3",
            "factor": 32.0,
            "low_freq_factor": 1.0,
            "high_freq_factor": 4.0,
            "original_max_position_embeddings": 8192,
        },
    ),
    "llama-3.2-3b": ModelConfig(
        name="llama-3.2-3b",
        vocab_size=128256,
        hidden_size=3072,
        intermediate_size=8192,
        num_layers=28,
        num_heads=24,
        num_kv_heads=8,
        head_dim=128,
        max_model_len=8192,
        rope_theta=500000.0,
        tie_word_embeddings=True,
        rope_scaling={
            "rope_type": "llama3",
            "factor": 32.0,
            "low_freq_factor": 1.0,
            "high_freq_factor": 4.0,
            "original_max_position_embeddings": 8192,
        },
    ),
    "llama-3-8b": ModelConfig(
        name="llama-3-8b",
        vocab_size=128256,
        hidden_size=4096,
        intermediate_size=14336,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        head_dim=128,
        max_model_len=8192,
        rope_theta=500000.0,
    ),
    # The reference's benchmark comparison model
    # (tutorials/07-benchmark-multi-round-qa-single-gpu.md:5 uses
    # Llama-3.1-8B-Instruct): llama-3-8b architecture + llama3 rope
    # scaling for long context.  HF max is 131072; capped to 32k here —
    # a v5e chip's HBM (16 GB) holds ~45k bf16 KV tokens beside the 16 GB
    # weights only with offload/int8-KV, so the default stays realistic.
    "llama-3.1-8b": ModelConfig(
        name="llama-3.1-8b",
        vocab_size=128256,
        hidden_size=4096,
        intermediate_size=14336,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        head_dim=128,
        max_model_len=32768,
        rope_theta=500000.0,
        rope_scaling={
            "rope_type": "llama3",
            "factor": 8.0,
            "low_freq_factor": 1.0,
            "high_freq_factor": 4.0,
            "original_max_position_embeddings": 8192,
        },
    ),
    "mistral-7b": ModelConfig(
        name="mistral-7b",
        vocab_size=32000,
        hidden_size=4096,
        intermediate_size=14336,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        head_dim=128,
        max_model_len=8192,
        rope_theta=10000.0,
        sliding_window=4096,
    ),
    # Gemma family: zero-centered norms (1+w), GeGLU, sqrt(h) embedding
    # scale, head_dim decoupled from hidden/heads, always-tied embeddings.
    "gemma-2b": ModelConfig(
        name="gemma-2b",
        vocab_size=256000,
        hidden_size=2048,
        intermediate_size=16384,
        num_layers=18,
        num_heads=8,
        num_kv_heads=1,
        head_dim=256,
        max_model_len=8192,
        rope_theta=10000.0,
        rms_norm_eps=1e-6,
        tie_word_embeddings=True,
        rms_norm_offset=1.0,
        hidden_act="gelu_tanh",
        scale_embeddings=True,
    ),
    "gemma-7b": ModelConfig(
        name="gemma-7b",
        vocab_size=256000,
        hidden_size=3072,
        intermediate_size=24576,
        num_layers=28,
        num_heads=16,
        num_kv_heads=16,
        head_dim=256,
        max_model_len=8192,
        rope_theta=10000.0,
        rms_norm_eps=1e-6,
        tie_word_embeddings=True,
        rms_norm_offset=1.0,
        hidden_act="gelu_tanh",
        scale_embeddings=True,
    ),
    "mixtral-8x7b": ModelConfig(
        name="mixtral-8x7b",
        vocab_size=32000,
        hidden_size=4096,
        intermediate_size=14336,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        head_dim=128,
        max_model_len=8192,
        rope_theta=1000000.0,
        num_experts=8,
        num_experts_per_tok=2,
    ),
    # Qwen2/2.5 family: QKV biases (attention_bias), high rope theta.
    "qwen2.5-0.5b": ModelConfig(
        name="qwen2.5-0.5b",
        vocab_size=151936,
        hidden_size=896,
        intermediate_size=4864,
        num_layers=24,
        num_heads=14,
        num_kv_heads=2,
        head_dim=64,
        max_model_len=8192,
        rope_theta=1000000.0,
        rms_norm_eps=1e-6,
        attention_bias=True,
        tie_word_embeddings=True,
    ),
    "qwen2.5-7b": ModelConfig(
        name="qwen2.5-7b",
        vocab_size=152064,
        hidden_size=3584,
        intermediate_size=18944,
        num_layers=28,
        num_heads=28,
        num_kv_heads=4,
        head_dim=128,
        max_model_len=8192,
        rope_theta=1000000.0,
        rms_norm_eps=1e-6,
        attention_bias=True,
    ),
}


@dataclasses.dataclass
class CacheConfig:
    """Paged KV cache (TPU HBM pool + host DRAM offload tier)."""

    block_size: int = 16  # tokens per block
    num_blocks: Optional[int] = None  # None -> sized from HBM fraction
    hbm_utilization: float = 0.90  # fraction of free HBM for weights+KV
    # stackcheck: allow=SC401 reason=prefix caching has been the default-on contract since the seed; the safe rollback is the explicit opt-out (--no-prefix-caching), and the KV-transfer plane auto-disables itself when this is off
    enable_prefix_caching: bool = True
    # Host-DRAM offload tier (the reference's LMCache CPU-offload analogue,
    # deployment-vllm-multi.yaml:161-166).
    host_offload_gb: float = 0.0
    # Remote shared KV store URL, e.g. "kv://host:port"
    # (reference lm://host:port, _helpers.tpl:164-166).
    remote_kv_url: Optional[str] = None
    # Cross-engine prefix sharing through the remote store, content-keyed
    # by the same hash chain as the local prefix cache.  "prefill": export
    # full prompt blocks after each prefill; "decode": import matching
    # blocks on admission instead of recomputing; "both": symmetric
    # sharing.  This is the disaggregated-prefill building block (the
    # reference lists disagg as roadmap-only, README.md:57) and the
    # TPU-native analogue of LMCache's shared-store prefill reuse.
    # Requires remote_kv_url.
    disagg_role: Optional[str] = None
    # Asynchronous batched KV transfer plane (kv/prefetch.py +
    # kv/offload.py OffloadStager): admission-time remote-prefix prefetch
    # on fetcher threads (one MGET round-trip per hash chain), off-step
    # preemption offload staging, and async restore page-in — no kvserver
    # RPC or host-DMA wait ever runs inside Scheduler.schedule() or the
    # step thread's critical section.  None = auto (ON whenever
    # remote_kv_url is set); False restores the legacy synchronous
    # in-schedule transfers (A/B baseline; debugging).
    remote_prefetch: Optional[bool] = None
    # Background fetcher threads for the prefetch plane (each issues
    # independent RPCs through the client connection pool).
    prefetch_threads: int = 2
    # Disaggregated decode-phase handoff: how long the API server lets a
    # handoff-tagged request wait (off the event loop, off the step
    # thread) for the prefetched chain to land in the prefix cache before
    # admitting anyway.  Bounds the TTFT tax of a slow store; an actual
    # store miss exits the wait early.  0 disables the wait (handoff
    # requests admit local-only like any other, and will recompute).
    disagg_handoff_wait_s: float = 2.0
    # KV cache precision (vLLM --kv-cache-dtype analogue).  "int8" stores
    # each cached K/V vector as int8 with a per-(token, head) fp32 scale:
    # KV HBM traffic and pool bytes roughly halve (decode is
    # KV-bandwidth-bound at long context, SURVEY §5 long-context story),
    # so num_blocks roughly doubles at equal memory.  Importers
    # cast/quantize, so engines with different kv dtypes still share
    # prefixes; the offload/remote representation is kv_wire_format's
    # call.
    kv_cache_dtype: str = "auto"
    # Offload/remote wire representation for quantized caches.  "auto"
    # (default): an int8 cache serializes its native (data, scale)
    # tuples — no dequant round-trip on the D2H path, ~4x the resident
    # tokens per host-DRAM byte vs the fp32 wire, and snapshot serde v2
    # on the kvserver (the client probes the store once and falls back
    # to v1 dense against a legacy deployment — kvserver/protocol.py).
    # "int8" is auto plus strictness: invalid without an int8 cache,
    # and a store that fails the serde-v2 probe logs a loud WARNING at
    # downgrade (auto downgrades silently — by design, it is the
    # rollout default).  "fp32" pins the legacy dense wire
    # (bit-preserving via exact requantization — the rollout escape
    # hatch and A/B baseline).  Dense (non-int8) caches always use the
    # dense wire.
    kv_wire_format: str = "auto"

    def __post_init__(self):
        if self.disagg_role not in (None, "prefill", "decode", "both",
                                    "encode"):
            raise ValueError(
                f"Unknown disagg_role {self.disagg_role!r} "
                "(None | prefill | decode | both | encode)"
            )
        if (
            self.disagg_role is not None
            and self.disagg_role != "encode"
            and not self.remote_kv_url
        ):
            # "encode" is a pool label, not a KV-sharing role: a
            # dedicated embed/rerank/score pool member does no prefix
            # handoff and needs no store.
            raise ValueError("disagg_role requires remote_kv_url")
        if self.kv_cache_dtype not in ("auto", "int8"):
            raise ValueError(
                f"Unknown kv_cache_dtype {self.kv_cache_dtype!r} "
                "(auto | int8)"
            )
        if self.kv_wire_format not in ("auto", "fp32", "int8"):
            raise ValueError(
                f"Unknown kv_wire_format {self.kv_wire_format!r} "
                "(auto | fp32 | int8)"
            )
        if self.kv_wire_format == "int8" and self.kv_cache_dtype != "int8":
            raise ValueError(
                "kv_wire_format=int8 serializes the int8 cache's native "
                "(data, scale) representation; it requires "
                "kv_cache_dtype=int8 (a dense cache has nothing "
                "quantized to put on the wire)"
            )
        if self.prefetch_threads < 1:
            raise ValueError("prefetch_threads must be >= 1")
        if self.disagg_handoff_wait_s < 0:
            raise ValueError("disagg_handoff_wait_s must be >= 0")

    @property
    def remote_prefetch_enabled(self) -> bool:
        """Resolved async-transfer gate: auto (None) turns on exactly
        when a remote store is configured."""
        if self.remote_prefetch is None:
            return self.remote_kv_url is not None
        return bool(self.remote_prefetch)

    @property
    def wire_quantized(self) -> bool:
        """Resolved wire representation: True when offload/remote
        snapshots carry the int8 cache's native (data, scale) tuples
        (kv_cache_dtype=int8 with kv_wire_format auto/int8); False is
        the dense wire — always for dense caches, and for int8 caches
        pinned to the legacy fp32 wire."""
        return self.kv_cache_dtype == "int8" and self.kv_wire_format != "fp32"


@dataclasses.dataclass
class ParallelConfig:
    """SPMD mesh layout: data/tensor/sequence/expert axes over ICI.

    The reference only passes --tensor-parallel-size through to vLLM
    (deployment-vllm-multi.yaml:84-87); here the mesh is first-class.
    """

    data_parallel: int = 1
    tensor_parallel: int = 1
    sequence_parallel: int = 1  # sequence-parallel axis for long context
    # "ring" (ppermute KV rotation, ring_attention.py) or "ulysses"
    # (all-to-all head redistribution, ulysses.py — needs
    # (num_kv_heads/tp) % sp == 0).
    sequence_parallel_mode: str = "ring"
    expert_parallel: int = 1  # reserved for MoE models

    @property
    def mesh_shape(self) -> Tuple[int, int, int]:
        return (self.data_parallel, self.tensor_parallel, self.sequence_parallel)

    @property
    def world_size(self) -> int:
        return (
            self.data_parallel
            * self.tensor_parallel
            * self.sequence_parallel
            * self.expert_parallel
        )


@dataclasses.dataclass
class SchedulerConfig:
    """Continuous batching (vLLM-style scheduler semantics, TPU twist:
    fixed shape buckets so every step hits a cached XLA executable)."""

    max_num_seqs: int = 8  # decode batch (padded, static shape)
    max_prefill_tokens: int = 2048  # prefill bucket ceiling
    prefill_buckets: Tuple[int, ...] = (128, 256, 512, 1024, 2048)
    max_model_len: int = 2048
    # Fused mixed prefill+decode steps (Sarathi-Serve / vLLM chunked-
    # prefill-integrated batching, TPU twist: static chunk buckets).  When
    # running sequences exist AND a prompt waits, one step packs every
    # running sequence's decode token plus a bounded prefill chunk of the
    # head waiting sequence into ONE model invocation, so arriving prompts
    # no longer stall all decoders for a full prefill bucket (the ITL
    # spike the tpu:itl_seconds histogram shows under load).  None = auto
    # (ON whenever the classic single-step path is active and the mesh has
    # no dp/sp axis); False restores the alternating one-plan-per-step
    # scheduler exactly.
    mixed_batch: Optional[bool] = None
    # Per-step token budget for mixed steps (vLLM --max-num-batched-tokens
    # analogue): decode tokens (== running batch size) count first, the
    # prefill chunk gets the remainder.  None = auto: always admits the
    # largest chunk bucket beside a full decode batch.
    max_num_batched_tokens: Optional[int] = None
    # Chunk-length buckets for the prefill segment of a mixed step.  Kept
    # deliberately small: the compiled-shape space for mixed executables
    # is |prefill_chunk_buckets| x |decode batch buckets|.
    prefill_chunk_buckets: Tuple[int, ...] = (128, 256, 512)
    # "recompute" (drop + re-prefill) or "offload" (page out to host DRAM)
    preemption_mode: str = "offload"
    # Legacy spelling of the K-step decode window (vLLM's
    # --num-scheduler-steps): a value > 1 forces window size K =
    # num_scheduler_steps through the same device-resident window
    # machinery multi_step_window gates.  1 = defer to multi_step_window.
    num_scheduler_steps: int = 1
    # K-step device-resident decode windows — THE default decode fast
    # path: the scheduler emits pure-decode plans with a decode_window-
    # iteration budget whenever no prompt is waiting, and the engine runs
    # the whole window as ONE device dispatch (lax.scan over decode +
    # on-device sampling with penalties, the min_tokens EOS floor and
    # per-row stop masking), so the per-token host round-trip is
    # amortized K-fold.  Batches using logprobs / logit_bias / guided
    # decoding (host-visible per-token state) fall back to single-step
    # per dispatch (tpu:multistep_fallback_total).  With
    # speculative_ngram set, the n-gram drafter runs INSIDE the window
    # scan (spec_window_enabled).  None = auto (ON); False
    # (--no-multi-step-window) restores single-token stepping exactly —
    # and, with speculative_ngram, the legacy host-side speculative path
    # (greedy parity asserted in tests/test_multistep_window.py and
    # tests/test_speculative.py).
    multi_step_window: Optional[bool] = None
    # Window size K for multi_step_window (compiled-shape inventory grows
    # by one scan executable per decode bucket; scan compile cost is
    # ~independent of K).
    decode_window: int = 8
    # N-gram (prompt-lookup) speculative decoding: draft up to this many
    # tokens by matching the sequence's trailing bigram against its own
    # recent history and verify them alongside the committed token in
    # ONE forward (the draft rows share the step's weight streaming, so
    # accepted drafts are nearly free on an HBM-bound decode).  With the
    # K-step decode window active (the default) the drafter runs INSIDE
    # the window scan: drafts are proposed on-device from the carried
    # history, verified in the same scan-iteration forward, and
    # acceptance folds into the carried state — a rejected draft costs a
    # scan iteration, never a host round-trip.  Greedy-only (acceptance
    # compares the model's own argmax); batches with sampled rows run
    # the plain window, and logprobs/logit_bias/guided rows fall back to
    # single-step like any other window batch.  With
    # multi_step_window=False the LEGACY host-side speculative path runs
    # instead (drafts built on the host, one wide verify dispatch per
    # step — the A/B baseline and the fallback the host-state rows use).
    # 0 = off.
    speculative_ngram: int = 0
    # Draft-MODEL speculative decoding: a second, tiny model (a PRESETS
    # name, e.g. "tiny-llama" — loaded through the same registry/weights
    # path as the target and sharded on the same mesh) proposes up to
    # speculative_draft_len tokens per scan iteration INSIDE the K-step
    # window, autoregressively from its own small device-resident KV
    # cache (carried through the scan like the n-gram history buffer;
    # blocks come from a dedicated draft pool so target KV capacity is
    # untouched).  The target verifies draft+1 rows in the SAME wide
    # forward the n-gram drafter uses — the two drafters are proposal
    # sources behind one in-scan drafting interface, so acceptance,
    # penalties, min_tokens, stop masks and the PRNG ordinal schedule
    # are shared and greedy streams stay byte-identical across
    # {none, ngram, model}.  Mutually exclusive with speculative_ngram
    # (one proposal source per engine); requires the window machinery
    # (no legacy host path exists for the model drafter).  Unlike the
    # n-gram drafter, proposals depend only on draft weights + carried
    # state, so acceptance holds up on non-templated text.  None = off.
    speculative_model: Optional[str] = None
    # Draft tokens proposed per scan iteration by the model drafter
    # (the D in the W = D+1 verify-row fan-out; the model-drafter
    # analogue of speculative_ngram's count).
    speculative_draft_len: int = 4
    # Device blocks reserved for the draft model's KV pool.  None = auto
    # (sized for max_num_seqs rows at the drafter's history window plus
    # chained-window growth).  Exhaustion never stalls: a window that
    # cannot allocate draft blocks declines to a plain (non-speculative)
    # window, counted under tpu:multistep_fallback_total{reason=draft_pool}.
    speculative_draft_pool_blocks: Optional[int] = None
    # Mixed K-step windows: a waiting prompt's prefill chunks ride the
    # device-resident decode scan instead of forcing K=1 steps — each
    # scan iteration runs the packed [decode + chunk] mixed forward
    # (decode rows advance one token from the carried state; the head
    # prompt's NEXT chunk rides the same forward with its chunk cursor
    # carried in-graph), so under sustained arrivals the fleet keeps the
    # K-fold host-round-trip amortization it used to forfeit whenever a
    # prompt waited.  The window length is min(decode_window, chunks
    # remaining for the head prompt, an adaptive clamp halving per
    # extra waiter) so the window ALWAYS ends at an admission boundary
    # — greedy streams stay byte-identical and seeded streams
    # bit-identical to the K=1 mixed path, and TTFT never regresses
    # more than one window's worth.  None = auto (ON whenever mixed
    # steps and K-step windows are both active); False
    # (--no-mixed-window) restores the K=1 mixed scheduling exactly
    # (waiting head -> K=1 steps, tpu:multistep_fallback_total
    # {reason="waiting_head"}).
    mixed_window: Optional[bool] = None
    # Multi-prompt packed mixed windows: each scan iteration of a mixed
    # K-step window may carry a chunk cursor from a DIFFERENT waiting
    # prompt (ragged per-iteration cursors over the same static
    # prefill_chunk_buckets shapes — steady-state serving never
    # recompiles), so deep queues fill the window instead of shrinking
    # it.  The packed path retires the adaptive K-halving clamp
    # (mixed_window_clamp) and runs full-K pure-decode windows when the
    # batch is slot-full (no admission is possible mid-window anyway),
    # driving {reason="waiting_head"} fallbacks to zero under surge.
    # Admission still happens only at window boundaries, so greedy
    # streams stay byte-identical and seeded streams bit-identical to
    # the single-head path.  None = auto (ON whenever
    # mixed_window_enabled); False (--no-multi-prompt-window) restores
    # the PR-15 single-head window + adaptive clamp exactly,
    # plan-by-plan.
    multi_prompt_window: Optional[bool] = None
    # Bounded admission (overload protection): once the waiting queue
    # holds this many requests (or prompt tokens), the API server rejects
    # new work with a structured 429 + Retry-After instead of queueing it
    # unboundedly (reject early and cheaply at the edge, not time out
    # expensively in the middle — docs/robustness.md).  None = auto:
    # max_queued_requests -> 4 x max_num_seqs,
    # max_queued_tokens   -> 2 x max_num_seqs x max_model_len.
    max_queued_requests: Optional[int] = None
    max_queued_tokens: Optional[int] = None
    # Master gate for bounded admission.  None = auto (ON);
    # False (--no-admission-control) restores the unbounded legacy
    # admission exactly (greedy parity asserted in tests/test_overload.py).
    admission_control: Optional[bool] = None
    # Batched encode lane: embed/rerank/score inputs queue on the event
    # loop and the STEP THREAD drains them as [B, T]-bucketed encode
    # batches at window boundaries — one prefill-chunk-shaped pass with
    # no KV bookkeeping, so decode windows are never preempted mid-scan
    # and the device is never touched off the step thread.  None = auto
    # (ON; the server auto-disables it under multi-host lockstep, where
    # a leader-only encode forward would desync the SPMD followers);
    # False (--no-encode-lane) restores the serial per-text embed path.
    encode_lane: Optional[bool] = None
    # B-axis bucket grid for encode batches: a batch of n texts pads to
    # the smallest bucket >= n (T pads to a prefill chunk bucket), so
    # the jitted executable count stays |encode_batch_buckets| x
    # |prefill_chunk_buckets| — the same grid discipline as mixed steps.
    encode_batch_buckets: Tuple[int, ...] = (1, 2, 4, 8)
    # Encode-queue admission bound (texts): once this many texts are
    # queued for the encode lane, new embed/rerank/score requests get a
    # structured 429 + Retry-After (PR-5 admission, encode flavor).
    # None = auto: 32 x encode_batch_buckets[-1].
    max_queued_encode_texts: Optional[int] = None
    # Step-loop watchdog: /health fails liveness when the engine step
    # thread has not completed an iteration within this many seconds (a
    # hung device dispatch otherwise serves a green probe forever).
    # Generous default: the first XLA compile of a large bucket set can
    # legitimately take minutes.  0 disables the check.
    step_watchdog_s: float = 300.0
    # Async lookahead decode pipeline: dispatch decode step (or K-step
    # window) N+1 — input tokens chained from N's still-in-flight
    # device-resident sample — before reading N's result back, so host
    # scheduling/detokenize overlaps device compute.  Greedy streams are
    # byte-identical to synchronous stepping; single-step batches using
    # host-state sampling features fall back per step, and K-step windows
    # chain through the device-resident window carry (done/penalty state
    # rides along, so stopped rows stay frozen in the successor).
    # None = auto (ON unless the LEGACY host-side speculative path is
    # active — speculative_ngram with the window disabled — whose wide
    # verify dispatch is synchronous); explicit True conflicts with that
    # legacy combination; False forces synchronous stepping.
    pipeline_decode: Optional[bool] = None

    def __post_init__(self):
        if self.speculative_ngram < 0:
            raise ValueError("speculative_ngram must be >= 0")
        if self.speculative_draft_len < 1:
            raise ValueError("speculative_draft_len must be >= 1")
        if (
            self.speculative_draft_pool_blocks is not None
            and self.speculative_draft_pool_blocks < 2
        ):
            # BlockPool reserves block 0 as the null block; a pool of
            # fewer than 2 blocks can never allocate anything.
            raise ValueError("speculative_draft_pool_blocks must be >= 2")
        if self.speculative_model is not None and self.speculative_ngram:
            raise ValueError(
                "speculative_model and speculative_ngram are mutually "
                "exclusive (one proposal source per engine); drop "
                "--speculative-ngram or pass --no-speculative-model"
            )
        if self.speculative_model is not None and self.multi_step_window is False:
            raise ValueError(
                "speculative_model runs INSIDE the K-step window scan and "
                "has no legacy host-side path; drop --no-multi-step-window "
                "or --speculative-model"
            )
        if self.decode_window < 1:
            raise ValueError("decode_window must be >= 1")
        if self.num_scheduler_steps > 1 and self.multi_step_window is False:
            raise ValueError(
                "num_scheduler_steps > 1 requests a K-step decode window "
                "but multi_step_window=False disables the window machinery "
                "that runs it; drop one of the two"
            )
        # speculative_ngram COMPOSES with multi_step_window /
        # num_scheduler_steps / pipeline_decode / mixed_batch: the
        # drafter runs inside the window scan (draft-and-verify per scan
        # iteration, acceptance folded into the carried state).  Only
        # the LEGACY host-side speculative path — speculative_ngram with
        # the window explicitly disabled — keeps the old conflicts: its
        # wide verify dispatch is synchronous and one-plan-shaped.
        legacy_spec = bool(self.speculative_ngram) and self.window_steps == 1
        if self.pipeline_decode and legacy_spec:
            raise ValueError(
                "pipeline_decode requires the fused speculative window; "
                "the legacy host-side speculative path (speculative_ngram "
                "with multi_step_window=False) dispatches synchronously — "
                "drop --no-multi-step-window or --no-pipeline-decode"
            )
        if self.mixed_batch and legacy_spec:
            raise ValueError(
                "mixed_batch requires the fused speculative window; the "
                "legacy host-side speculative path (speculative_ngram "
                "with multi_step_window=False) assumes one plan shape per "
                "dispatch — drop --no-multi-step-window or "
                "--no-mixed-batch"
            )
        if self.mixed_window and self.multi_step_window is False:
            raise ValueError(
                "mixed_window=True requests prefill chunks riding the "
                "K-step decode scan but multi_step_window=False disables "
                "the window machinery; drop one of the two"
            )
        if self.mixed_window and self.mixed_batch is False:
            raise ValueError(
                "mixed_window=True requires mixed_batch (the chunk "
                "machinery); drop --no-mixed-batch or --mixed-window"
            )
        if self.multi_prompt_window and self.mixed_window is False:
            raise ValueError(
                "multi_prompt_window=True packs prompts into mixed K-step "
                "windows but mixed_window=False disables those windows; "
                "drop --no-mixed-window or --multi-prompt-window"
            )
        if not self.prefill_chunk_buckets:
            raise ValueError("prefill_chunk_buckets must be non-empty")
        if tuple(sorted(self.prefill_chunk_buckets)) != tuple(
            self.prefill_chunk_buckets
        ):
            raise ValueError("prefill_chunk_buckets must be sorted ascending")
        if self.max_queued_requests is not None and self.max_queued_requests < 1:
            raise ValueError("max_queued_requests must be >= 1")
        if self.max_queued_tokens is not None and self.max_queued_tokens < 1:
            raise ValueError("max_queued_tokens must be >= 1")
        if not self.encode_batch_buckets:
            raise ValueError("encode_batch_buckets must be non-empty")
        if tuple(sorted(self.encode_batch_buckets)) != tuple(
            self.encode_batch_buckets
        ) or self.encode_batch_buckets[0] < 1:
            raise ValueError(
                "encode_batch_buckets must be positive and sorted ascending"
            )
        if (
            self.max_queued_encode_texts is not None
            and self.max_queued_encode_texts < 1
        ):
            raise ValueError("max_queued_encode_texts must be >= 1")
        if self.step_watchdog_s < 0:
            raise ValueError("step_watchdog_s must be >= 0 (0 disables)")
        if (
            self.max_num_batched_tokens is not None
            and self.max_num_batched_tokens
            < self.max_num_seqs + self.prefill_chunk_buckets[0]
        ):
            raise ValueError(
                f"max_num_batched_tokens={self.max_num_batched_tokens} can "
                "never admit a prefill chunk beside a full decode batch; "
                f"needs >= max_num_seqs + smallest chunk bucket "
                f"({self.max_num_seqs} + {self.prefill_chunk_buckets[0]})"
            )

    @property
    def window_steps(self) -> int:
        """Resolved K-step decode-window size: iterations a pure-decode
        plan may fuse into one device dispatch.  1 = single-token steps
        (window off); num_scheduler_steps > 1 keeps its legacy meaning
        as an explicit window size.  Speculation no longer resolves the
        window off — the drafter runs INSIDE the scan (spec_window_enabled);
        only the explicit multi_step_window=False escape hatch restores
        the legacy host-side speculative path."""
        if self.multi_step_window is False:
            return 1
        if self.num_scheduler_steps > 1:
            return self.num_scheduler_steps
        return max(1, self.decode_window)

    @property
    def spec_drafter(self) -> Optional[str]:
        """Configured in-scan proposal source: "ngram" (prompt-lookup
        from the carried history buffer), "model" (tiny draft model with
        its own device-resident KV), or None.  Selection only — gate on
        spec_window_enabled for whether the fused path actually runs."""
        if self.speculative_model is not None:
            return "model"
        if self.speculative_ngram:
            return "ngram"
        return None

    @property
    def spec_draft_len(self) -> int:
        """Draft tokens proposed per scan iteration by whichever drafter
        is configured (the D in the W = D+1 verify-row fan-out)."""
        if self.speculative_model is not None:
            return self.speculative_draft_len
        return self.speculative_ngram

    @property
    def spec_window_enabled(self) -> bool:
        """The fused draft-and-verify path: speculation (n-gram or draft
        model) proposed, verified, and folded INSIDE the K-step window
        scan.  False means either no speculation, or the legacy host-side
        speculative path (speculative_ngram with multi_step_window=False;
        the model drafter has no legacy path — it is simply inert at
        K=1)."""
        return self.spec_drafter is not None and self.window_steps > 1

    @property
    def window_max_tokens(self) -> int:
        """Per-pure-decode-window token ceiling a single row may emit:
        K iterations, each committing one token plus up to
        spec_draft_len accepted drafts under the fused path.  THE
        bound the scheduler budgets block allocation and max_model_len
        room against (max-acceptance growth), and the engine sizes the
        chained-window block-table delta from."""
        if self.spec_window_enabled:
            return self.window_steps * (self.spec_draft_len + 1)
        return self.window_steps

    @property
    def pipeline_enabled(self) -> bool:
        """Resolved pipeline gate: auto (None) turns on unless the
        LEGACY host-side speculative path owns the dispatch shape
        (fused speculative windows chain through the pipeline like any
        window: N+1 dispatched off window N's device-resident carry,
        draft history included)."""
        if self.pipeline_decode is None:
            return not (self.speculative_ngram and self.window_steps == 1)
        return self.pipeline_decode

    @property
    def mixed_enabled(self) -> bool:
        """Resolved mixed-step gate: auto (None) turns on unless the
        LEGACY host-side speculative path is active (mixed steps coexist
        with K-step windows — speculative or not: the scheduler picks
        K=1 mixed steps while a prompt waits and K>1 pure-decode windows
        otherwise).  The engine additionally clears ``mixed_batch`` when
        the mesh has a dp/sp axis (the packed mixed batch is not
        dp/sp-shardable)."""
        if self.mixed_batch is None:
            return not (self.speculative_ngram and self.window_steps == 1)
        return self.mixed_batch

    @property
    def mixed_window_enabled(self) -> bool:
        """Resolved mixed K-step window gate: auto (None) turns on
        whenever BOTH parents are active — mixed steps (the chunk
        machinery) and K>1 windows (the scan machinery).  An explicit
        True still requires both parents: the fused plan shape does not
        exist without them."""
        if self.mixed_window is False:
            return False
        return self.mixed_enabled and self.window_steps > 1

    @property
    def multi_prompt_window_enabled(self) -> bool:
        """Resolved packed-window gate: auto (None) rides
        mixed_window_enabled — packing is the default whenever mixed
        K-step windows exist.  False (--no-multi-prompt-window) keeps
        the windows but restores the PR-15 single-head planner and its
        adaptive clamp exactly."""
        if self.multi_prompt_window is False:
            return False
        return self.mixed_window_enabled

    def mixed_window_clamp(self, num_waiting: int) -> int:
        """Adaptive per-window iteration clamp keyed to waiting-queue
        depth: the head prompt gets the full window to itself, and each
        EXTRA waiter halves it (deep queue -> shorter windows -> more
        frequent admission re-evaluation), so no waiter's TTFT regresses
        more than one window's worth behind the head's chunks."""
        extra = max(0, num_waiting - 1)
        return max(1, self.window_steps >> min(extra, 8))

    @property
    def admission_enabled(self) -> bool:
        """Resolved bounded-admission gate: auto (None) means ON."""
        if self.admission_control is None:
            return True
        return bool(self.admission_control)

    @property
    def queued_requests_cap(self) -> int:
        """Resolved waiting-queue request bound."""
        if self.max_queued_requests is not None:
            return self.max_queued_requests
        return 4 * self.max_num_seqs

    @property
    def queued_tokens_cap(self) -> int:
        """Resolved waiting-queue prompt-token bound."""
        if self.max_queued_tokens is not None:
            return self.max_queued_tokens
        return 2 * self.max_num_seqs * self.max_model_len

    @property
    def encode_lane_enabled(self) -> bool:
        """Resolved encode-lane gate: auto (None) means ON.  The server
        additionally clears it under multi-host lockstep (leader-only
        encode forwards would desync SPMD followers)."""
        if self.encode_lane is None:
            return True
        return bool(self.encode_lane)

    @property
    def queued_encode_texts_cap(self) -> int:
        """Resolved encode-queue text bound (admission)."""
        if self.max_queued_encode_texts is not None:
            return self.max_queued_encode_texts
        return 32 * self.encode_batch_buckets[-1]

    @property
    def batched_tokens_budget(self) -> int:
        """Resolved per-step token budget for mixed steps."""
        if self.max_num_batched_tokens is not None:
            return self.max_num_batched_tokens
        return self.max_num_seqs + self.prefill_chunk_buckets[-1]


@dataclasses.dataclass
class LoraServingConfig:
    """Multi-LoRA slots (engine/lora.py); max_loras=0 disables the path."""

    max_loras: int = 0
    max_rank: int = 16

    @property
    def enabled(self) -> bool:
        return self.max_loras > 0

    @property
    def num_slots(self) -> int:
        # +1 for the identity slot 0 (base model).
        return self.max_loras + 1


@dataclasses.dataclass
class ObsConfig:
    """Observability layer (production_stack_tpu/obs): request tracing,
    /debug/requests ring buffers, and the per-step phase histograms.

    ``tracing=False`` is the fast-path gate: every obs hook in the engine
    core returns before touching any state (no histogram observes, no
    trace allocations per step) — the pre-tracing hot path, verified by
    tests/test_observability.py."""

    # stackcheck: allow=SC401 reason=tracing default-on is the PR-2 contract (--no-tracing restores the untraced fast path, verified by a zero-state + greedy-parity test)
    tracing: bool = True
    # Completed request timelines kept per component (bounds /debug memory).
    trace_ring_size: int = 256
    # Byte bound on the completed-trace ring: long-prompt records are
    # hundreds of times larger than short ones, so the count bound alone
    # does not bound resident memory.  Oldest records are evicted past
    # this and counted in tpu:obs_trace_dropped_total.  0 disables the
    # byte bound (count bound only).
    trace_ring_bytes: int = 8 * 1024 * 1024
    # Completed window flight-recorder records kept (obs/flight_recorder):
    # one per engine dispatch, served at GET /debug/windows and joined
    # into /debug/requests/{id}.
    window_ring_size: int = 1024

    def __post_init__(self):
        if self.trace_ring_size < 1:
            raise ValueError("trace_ring_size must be >= 1")
        if self.trace_ring_bytes < 0:
            raise ValueError("trace_ring_bytes must be >= 0")
        if self.window_ring_size < 1:
            raise ValueError("window_ring_size must be >= 1")


@dataclasses.dataclass
class EngineConfig:
    model: ModelConfig = dataclasses.field(default_factory=ModelConfig)
    cache: CacheConfig = dataclasses.field(default_factory=CacheConfig)
    parallel: ParallelConfig = dataclasses.field(default_factory=ParallelConfig)
    scheduler: SchedulerConfig = dataclasses.field(default_factory=SchedulerConfig)
    lora: LoraServingConfig = dataclasses.field(default_factory=LoraServingConfig)
    obs: ObsConfig = dataclasses.field(default_factory=ObsConfig)
    seed: int = 0
    tokenizer: Optional[str] = None  # HF tokenizer path; None -> byte fallback
    weights_path: Optional[str] = None  # safetensors dir; None -> random init
    # Draft-model checkpoint (scheduler.speculative_model); None -> the
    # same deterministic random init the target uses, seeded identically
    # on every replica (lockstep-safe by construction).
    draft_weights_path: Optional[str] = None

    def __post_init__(self):
        # The scheduler must not admit sequences the cache cannot hold.
        self.scheduler.max_model_len = min(
            self.scheduler.max_model_len, self.model.max_model_len
        )


def config_from_preset(name: str, **overrides) -> EngineConfig:
    if name not in PRESETS:
        raise ValueError(f"Unknown model preset {name!r}; available: {sorted(PRESETS)}")
    model = dataclasses.replace(PRESETS[name])
    cfg = EngineConfig(model=model)
    for key, value in overrides.items():
        obj = cfg
        *path, last = key.split(".")
        for part in path:
            obj = getattr(obj, part)
        setattr(obj, last, value)
    # setattr bypasses dataclass validation: re-run every sub-config's
    # __post_init__ so invalid override COMBINATIONS (e.g. speculative +
    # multi-step, disagg without a store URL) fail at construction, not
    # as undefined runtime behavior.
    for sub in (cfg.model, cfg.cache, cfg.scheduler, cfg.parallel, cfg.lora,
                cfg.obs):
        post = getattr(sub, "__post_init__", None)
        if post is not None:
            post()
    return cfg
