"""Tokenizer abstraction: HF tokenizer when available, byte-level fallback.

The byte fallback keeps the engine fully functional in zero-egress
environments (CI, clusterless smoke tests): deterministic, reversible,
vocab of 256 bytes + 4 specials.
"""

from __future__ import annotations

import logging
from typing import List, Optional

logger = logging.getLogger(__name__)


class ByteTokenizer:
    """Reversible byte-level tokenizer: token = byte value + 4 specials."""

    PAD, BOS, EOS, UNK = 0, 1, 2, 3
    OFFSET = 4

    def __init__(self):
        self.vocab_size = 256 + self.OFFSET
        self.bos_token_id = self.BOS
        self.eos_token_id = self.EOS
        self.pad_token_id = self.PAD

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        ids = [b + self.OFFSET for b in text.encode("utf-8")]
        return ([self.BOS] if add_bos else []) + ids

    def decode(self, ids: List[int]) -> str:
        # Ids beyond the byte range can appear when the model's vocab is
        # padded larger than the tokenizer's (random-init smoke models).
        data = bytes(
            i - self.OFFSET for i in ids if self.OFFSET <= i < self.OFFSET + 256
        )
        return data.decode("utf-8", errors="replace")

    def apply_chat_template(self, messages) -> str:
        parts = [f"<|{m.get('role', 'user')}|>{m.get('content', '')}" for m in messages]
        return "\n".join(parts) + "\n<|assistant|>"


class HFTokenizer:
    """Thin wrapper over transformers.AutoTokenizer (local files only)."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
        self.vocab_size = len(self._tok)
        self.bos_token_id = self._tok.bos_token_id
        self.eos_token_id = self._tok.eos_token_id
        self.pad_token_id = self._tok.pad_token_id or 0

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        return self._tok.encode(text, add_special_tokens=add_bos)

    def decode(self, ids: List[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)

    def apply_chat_template(self, messages) -> str:
        try:
            return self._tok.apply_chat_template(
                messages, tokenize=False, add_generation_prompt=True
            )
        except Exception:
            parts = [f"{m.get('role')}: {m.get('content', '')}" for m in messages]
            return "\n".join(parts) + "\nassistant:"


def get_tokenizer(path: Optional[str]):
    if path:
        try:
            return HFTokenizer(path)
        except Exception:
            logger.exception(
                "Could not load HF tokenizer from %s; using byte fallback", path
            )
    return ByteTokenizer()
