"""Tokenizer abstraction: HF tokenizer when available, byte-level fallback.

The byte fallback keeps the engine fully functional in zero-egress
environments (CI, clusterless smoke tests): deterministic, reversible,
vocab of 256 bytes + 4 specials.
"""

from __future__ import annotations

import logging
from functools import lru_cache
from typing import List, Optional

logger = logging.getLogger(__name__)


class ByteTokenizer:
    """Reversible byte-level tokenizer: token = byte value + 4 specials."""

    PAD, BOS, EOS, UNK = 0, 1, 2, 3
    OFFSET = 4

    def __init__(self):
        self.vocab_size = 256 + self.OFFSET
        self.bos_token_id = self.BOS
        self.eos_token_id = self.EOS
        self.pad_token_id = self.PAD
        self.chat_template: Optional[str] = None  # Jinja override

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        ids = [b + self.OFFSET for b in text.encode("utf-8")]
        return ([self.BOS] if add_bos else []) + ids

    def decode(self, ids: List[int]) -> str:
        # Ids beyond the byte range can appear when the model's vocab is
        # padded larger than the tokenizer's (random-init smoke models).
        data = bytes(
            i - self.OFFSET for i in ids if self.OFFSET <= i < self.OFFSET + 256
        )
        return data.decode("utf-8", errors="replace")

    def apply_chat_template(self, messages, tools=None) -> str:
        if self.chat_template is not None:
            return _render_jinja(
                self.chat_template, messages, bos="", eos="", tools=tools
            )
        parts = [f"<|{m.get('role', 'user')}|>{m.get('content', '')}" for m in messages]
        if tools:
            import json as _json

            parts.insert(0, "<|tools|>" + _json.dumps(tools))
        return "\n".join(parts) + "\n<|assistant|>"


class HFTokenizer:
    """Thin wrapper over transformers.AutoTokenizer (local files only)."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
        self.vocab_size = len(self._tok)
        self.bos_token_id = self._tok.bos_token_id
        self.eos_token_id = self._tok.eos_token_id
        self.pad_token_id = self._tok.pad_token_id or 0
        self.chat_template: Optional[str] = None  # Jinja override

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        return self._tok.encode(text, add_special_tokens=add_bos)

    def decode(self, ids: List[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)

    def apply_chat_template(self, messages, tools=None) -> str:
        tool_kwargs = {"tools": tools} if tools else {}
        if self.chat_template is not None:
            # An explicitly configured template must never be silently
            # replaced by the degenerate fallback: the server validates it
            # at startup, and any later failure should surface loudly.
            return self._tok.apply_chat_template(
                messages,
                tokenize=False,
                add_generation_prompt=True,
                chat_template=self.chat_template,
                **tool_kwargs,
            )
        try:
            return self._tok.apply_chat_template(
                messages, tokenize=False, add_generation_prompt=True,
                **tool_kwargs,
            )
        except Exception:
            parts = [f"{m.get('role')}: {m.get('content', '')}" for m in messages]
            return "\n".join(parts) + "\nassistant:"


@lru_cache(maxsize=8)
def _compile_jinja(template: str):
    """Compile once per template string: rendering sits on the request hot
    path.  StrictUndefined so typos fail the startup validation render
    instead of silently emitting empty strings."""
    import jinja2

    env = jinja2.Environment(undefined=jinja2.StrictUndefined, autoescape=False)
    return env.from_string(template)


def _render_jinja(template: str, messages, bos: str, eos: str, tools=None) -> str:
    """Render a custom chat template (the reference chart's chatTemplate
    ConfigMap, deployment-vllm-multi.yaml:260-270, passed to vllm serve as
    --chat-template).  jinja2 ships with transformers in this image.
    ``bos_token``/``eos_token`` are provided because standard HF templates
    reference them."""
    return _compile_jinja(template).render(
        messages=messages,
        add_generation_prompt=True,
        bos_token=bos,
        eos_token=eos,
        tools=tools,
    )


def get_tokenizer(path: Optional[str]):
    if path:
        try:
            return HFTokenizer(path)
        except Exception:
            logger.exception(
                "Could not load HF tokenizer from %s; using byte fallback", path
            )
    return ByteTokenizer()
