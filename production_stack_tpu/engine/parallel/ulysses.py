"""Ulysses-style sequence parallelism: all-to-all head redistribution.

The second sequence-parallel strategy next to ring attention
(ring_attention.py).  Where the ring rotates K/V shards around the mesh
(sp-1 ppermute hops, online-softmax merging), Ulysses performs ONE
all-to-all that re-shards the tensors from sequence-split to head-split —
each device then holds the FULL sequence for a subset of heads and runs
plain (or flash-kernel) attention locally, followed by the inverse
all-to-all.  Trade-offs on TPU:

* ring: O(sp) neighbor hops riding ICI, memory bounded by one KV shard —
  scales to contexts where even one head's full-sequence KV won't fit.
* ulysses: 2 collective phases total and the LOCAL attention is whole —
  so the single-device Pallas flash kernel applies per shard unchanged —
  but each device must hold full-sequence K/V for its head subset, and
  the kv-head count must divide: (num_kv_heads / tp) % sp == 0.

Same mask semantics as ops/attention.py::prefill_attention (causal over
cached prefix + new tokens, validity bounds); selected via
``ParallelConfig.sequence_parallel_mode = "ulysses"``.

The reference stack has no sequence parallelism at all (SURVEY.md section
2.7); both strategies here are TPU-native capability on top of parity.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax import lax

from production_stack_tpu.engine.ops.attention import prefill_attention


def _seq_to_heads(x: jax.Array, axis_name: str) -> jax.Array:
    """[Tl, h, D] sequence-sharded -> [T, h/sp, D] head-sharded.

    tiled all-to-all keeps chunk order, so row i*Tl+t is global position
    i*Tl+t — consecutive positions, which is what the dense attention's
    position math assumes."""
    return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=0, tiled=True)


def _heads_to_seq(x: jax.Array, axis_name: str) -> jax.Array:
    """Inverse: [T, h/sp, D] -> [Tl, h, D]."""
    return lax.all_to_all(x, axis_name, split_axis=0, concat_axis=1, tiled=True)


def ulysses_prefill_with_prefix(
    q: jax.Array,  # [Tl, H, D] local query shard (new tokens)
    k: jax.Array,  # [Tl, K, D] local key shard (new tokens)
    v: jax.Array,  # [Tl, K, D]
    k_prefix: jax.Array,  # [Cl, K, D] local shard of the cached prefix
    v_prefix: jax.Array,  # [Cl, K, D]
    cached_len: jax.Array,  # scalar int32: valid prefix tokens (global)
    valid_len: jax.Array,  # scalar int32: valid new tokens (global)
    *,
    axis_name: str,
    scale: float,
    sliding_window: Optional[int] = None,
) -> jax.Array:
    """Sequence-parallel prefill attention via head redistribution; the
    sp>1 Ulysses counterpart of prefill_attention, called inside
    ``shard_map`` by models/llama.py.

    GQA alignment: the head axis is split into sp contiguous chunks, so q
    chunk j covers query-head groups [j*K/sp, (j+1)*K/sp) — exactly the
    kv heads in kv chunk j — provided K % sp == 0 (validated at engine
    startup, parallel/shardings.py)."""
    q_full = _seq_to_heads(q, axis_name)  # [T, H/sp, D]
    k_full = _seq_to_heads(k, axis_name)  # [T, K/sp, D]
    v_full = _seq_to_heads(v, axis_name)
    kp_full = _seq_to_heads(k_prefix, axis_name)  # [C, K/sp, D]
    vp_full = _seq_to_heads(v_prefix, axis_name)

    # Full-sequence attention on the local head subset; single-device
    # dispatch applies (Pallas flash kernel on TPU, dense elsewhere).
    out_full = prefill_attention(
        q_full, k_full, v_full, kp_full, vp_full, cached_len, valid_len,
        scale=scale, sliding_window=sliding_window,
    )
    return _heads_to_seq(out_full, axis_name)  # [Tl, H, D]
