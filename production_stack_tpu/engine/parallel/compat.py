"""JAX version compatibility shims.

``shard_map`` graduated from ``jax.experimental.shard_map`` to the
top-level ``jax`` namespace (and renamed ``check_rep`` to ``check_vma``)
across jax releases; this repo must run on both sides of that move.
Import ``shard_map`` from here instead of from ``jax`` directly.
"""

from __future__ import annotations

try:  # jax >= 0.5: top-level export, check_vma keyword
    from jax import shard_map as _shard_map

    _LEGACY = False
except ImportError:  # jax 0.4.x: experimental module, check_rep keyword
    from jax.experimental.shard_map import shard_map as _shard_map

    _LEGACY = True


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True, **kw):
    if _LEGACY:
        kw["check_rep"] = check_vma
    else:
        kw["check_vma"] = check_vma
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
    )
