"""GSPMD sharding rules for params, KV caches and activations.

Megatron-style tensor parallelism expressed as PartitionSpecs — XLA inserts
the ICI collectives (one psum after o_proj, one after down_proj per layer):

  q/k/v_proj  [h, heads*hd]  -> shard output dim over tp (head-parallel)
  o_proj      [heads*hd, h]  -> shard input dim over tp (psum after)
  gate/up     [h, I]         -> shard I over tp
  down        [I, h]         -> shard I over tp (psum after)
  embed       [V, h]         -> shard V over tp (logits all-gathered)
  KV cache    [N, bs, K, D]  -> shard K (kv heads) over tp
  decode batch [S, ...]      -> shard S over dp

Requires num_heads % tp == 0 and num_kv_heads % tp == 0 (GQA: tp beyond
num_kv_heads would duplicate KV — rejected rather than silently replicated).
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from production_stack_tpu.engine.config import ModelConfig
from production_stack_tpu.engine.parallel.mesh import AXES

TP = AXES.TP
DP = AXES.DP


def validate_tp(cfg: ModelConfig, tp_size: int) -> None:
    if cfg.num_heads % tp_size:
        raise ValueError(f"num_heads={cfg.num_heads} not divisible by tp={tp_size}")
    if cfg.num_kv_heads % tp_size:
        raise ValueError(
            f"num_kv_heads={cfg.num_kv_heads} not divisible by tp={tp_size}"
        )
    if cfg.num_experts:
        if cfg.num_experts % tp_size:
            raise ValueError(
                f"num_experts={cfg.num_experts} not divisible by tp={tp_size} "
                "(MoE experts shard over the tp axis)"
            )
    elif cfg.intermediate_size % tp_size:
        raise ValueError(
            f"intermediate_size={cfg.intermediate_size} not divisible by tp={tp_size}"
        )


def validate_sp_mode(cfg: ModelConfig, par) -> None:
    """Ulysses redistributes heads across sp: every device's local kv-head
    count (after tp) must split evenly (parallel/ulysses.py)."""
    if par.sequence_parallel_mode not in ("ring", "ulysses"):
        raise ValueError(
            f"Unknown sequence_parallel_mode {par.sequence_parallel_mode!r} "
            "(ring|ulysses)"
        )
    sp, tp = par.sequence_parallel, par.tensor_parallel
    if par.sequence_parallel_mode == "ulysses" and sp > 1:
        local_kv = cfg.num_kv_heads // tp
        if local_kv % sp:
            raise ValueError(
                f"ulysses needs (num_kv_heads/tp)={local_kv} divisible by "
                f"sp={sp}; use sequence_parallel_mode='ring' instead"
            )
    if (
        par.sequence_parallel_mode == "ring"
        and sp > 1
        and cfg.sliding_window is not None
    ):
        # The ring rotation has no window support; silently computing full
        # attention would be wrong for windowed models (e.g. mistral).
        raise ValueError(
            f"sliding_window={cfg.sliding_window} is not supported with "
            "sequence_parallel_mode='ring'; use 'ulysses' (requires "
            "(num_kv_heads/tp) % sp == 0) or sp=1"
        )


def _maybe_quant(spec: P, cfg) -> object:
    """Quantized projections are {"q": int8 [in, out], "s": f32 [out]}
    (models/llama.py quantize_params): the int8 block keeps the weight's
    spec, the scale follows the OUT (last) axis partitioning."""
    if cfg.quantization is None:
        return spec
    return {"q": spec, "s": P(spec[1] if len(spec) >= 2 else None)}


def _layer_specs(cfg) -> Dict[str, P]:
    specs = {
        "input_layernorm": P(),
        "post_attention_layernorm": P(),
        "q_proj": _maybe_quant(P(None, TP), cfg),
        "k_proj": _maybe_quant(P(None, TP), cfg),
        "v_proj": _maybe_quant(P(None, TP), cfg),
        "o_proj": _maybe_quant(P(TP, None), cfg),
    }
    if cfg.num_experts:
        # MoE: experts shard over the tp axis (expert parallelism); the
        # router gate is replicated.  GSPMD reduces the weighted expert
        # sum across tp (models/llama.py _moe_mlp).
        specs["gate"] = P()
        specs["experts_gate"] = P(TP, None, None)
        specs["experts_up"] = P(TP, None, None)
        specs["experts_down"] = P(TP, None, None)
    else:
        specs["gate_proj"] = _maybe_quant(P(None, TP), cfg)
        specs["up_proj"] = _maybe_quant(P(None, TP), cfg)
        specs["down_proj"] = _maybe_quant(P(TP, None), cfg)
    if cfg.attention_bias:
        # Biases follow their projection's output (head) dim.
        specs["q_bias"] = P(TP)
        specs["k_bias"] = P(TP)
        specs["v_bias"] = P(TP)
    return specs


def param_specs(cfg: ModelConfig) -> Dict:
    """PartitionSpec tree matching the param tree from models/llama.py."""
    specs: Dict = {
        "embed_tokens": P(TP, None),
        "norm": P(),
        "layers": [_layer_specs(cfg) for _ in range(cfg.num_layers)],
    }
    if not cfg.tie_word_embeddings:
        specs["lm_head"] = _maybe_quant(P(None, TP), cfg)
    return specs


def param_shardings(cfg: ModelConfig, mesh: Mesh) -> Dict:
    import jax

    return jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec),
        param_specs(cfg),
        is_leaf=lambda x: isinstance(x, P),
    )


def kv_cache_spec() -> P:
    # [num_blocks, block_size, num_kv_heads, head_dim]: shard kv heads.
    return P(None, None, TP, None)


def kv_scale_sharding(mesh: Mesh) -> NamedSharding:
    # int8 KV scale planes [num_blocks, block_size, num_kv_heads]: the
    # head axis shards over tp exactly like the data (kv/quant.py).
    return NamedSharding(mesh, P(None, None, TP))


def kv_cache_shardings(cfg: ModelConfig, mesh: Mesh) -> List[Tuple]:
    sharding = NamedSharding(mesh, kv_cache_spec())
    return [(sharding, sharding) for _ in range(cfg.num_layers)]


def decode_batch_spec() -> P:
    return P(DP)  # shard sequences over data-parallel axis
