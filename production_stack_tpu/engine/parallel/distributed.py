"""Multi-host (multi-process) bootstrap + lockstep serving protocol.

The reference scales a single engine across accelerators with NCCL over
/dev/shm inside one pod (reference helm/templates/deployment-vllm-multi.yaml:198-228);
a multi-host TPU slice (e.g. v5e-16 = 4x4, four 4-chip workers) instead
runs ONE jax program across several worker pods: every process calls
``jax.distributed.initialize`` against worker 0, ``jax.devices()``
becomes the global chip list, and the engine's mesh/pjit shardings span
hosts with XLA emitting ICI/DCN collectives.

Serving on top of SPMD needs one more ingredient: every process must
launch the SAME jitted computations in the same order.  The engine is
deterministic given its request stream, so the leader (process 0, the
only one serving HTTP) broadcasts the per-iteration event batch —
(new requests, aborts, shutdown) — and every follower applies it to its
own engine replica and steps in lockstep.  Followers hold the model/KV
shards jax assigned them; outputs are read on the leader.

Environment contract (set by the Helm chart's multi-host StatefulSet
mode, templates/deployment-engine.yaml):

  PSTPU_NUM_PROCESSES       total worker pods in the slice group
  PSTPU_PROCESS_ID          this pod's ordinal (StatefulSet pod index)
  PSTPU_COORDINATOR_ADDRESS worker-0 DNS name:port (headless service)

GKE TPU pod environments (TPU_WORKER_ID / TPU_WORKER_HOSTNAMES, injected
by the TPU device plugin) are honored as a fallback, so a hand-rolled
JobSet works too.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import pickle
import threading
import time
from typing import Any, Callable, Dict, Optional

logger = logging.getLogger(__name__)

_COORD_PORT = 8476

# Monotonic epoch guard: two epochs minted in the same millisecond (or a
# clock step backwards across a fast restart) must still order strictly.
_last_epoch = 0
_epoch_lock = threading.Lock()


def new_epoch() -> int:
    """A leader boot nonce, strictly larger than any epoch this process
    minted before: wall-clock milliseconds, bumped past the previous
    value on collision.  Restarted groups therefore always carry a
    STRICTLY larger epoch — the split-brain guard's ordering."""
    global _last_epoch
    with _epoch_lock:
        _last_epoch = max(int(time.time() * 1000), _last_epoch + 1)
        return _last_epoch


def fatal_exit(code: int = 1) -> None:
    """Terminate the process immediately after flushing log handlers.

    Used when a lockstep member must die NOW: ``sys.exit`` would run
    atexit hooks (jax.distributed teardown blocks on collectives the
    dead/desynced group will never complete), turning a clean k8s
    restart into a hung pod.  Module-level indirection so tests can
    monkeypatch it."""
    logging.shutdown()
    os._exit(code)


@dataclasses.dataclass(frozen=True)
class DistributedEnv:
    coordinator_address: str
    num_processes: int
    process_id: int

    @property
    def is_leader(self) -> bool:
        return self.process_id == 0


def detect_env(environ=None) -> Optional[DistributedEnv]:
    """Multi-process topology from the environment, or None for the
    ordinary single-process case.

    Explicit PSTPU_* variables win; the GKE TPU pod contract
    (TPU_WORKER_ID + TPU_WORKER_HOSTNAMES) is the fallback.  A
    single-entry hostname list (the axon tunnel sets
    TPU_WORKER_HOSTNAMES=localhost) is single-process.
    """
    env = os.environ if environ is None else environ
    if "PSTPU_NUM_PROCESSES" in env:
        n = int(env["PSTPU_NUM_PROCESSES"])
        if n <= 1:
            return None
        return DistributedEnv(
            coordinator_address=env["PSTPU_COORDINATOR_ADDRESS"],
            num_processes=n,
            process_id=int(env["PSTPU_PROCESS_ID"]),
        )
    hostnames = [
        h for h in env.get("TPU_WORKER_HOSTNAMES", "").split(",") if h
    ]
    if len(hostnames) > 1:
        return DistributedEnv(
            coordinator_address=f"{hostnames[0]}:{_COORD_PORT}",
            num_processes=len(hostnames),
            process_id=int(env.get("TPU_WORKER_ID", "0")),
        )
    return None


def maybe_initialize(environ=None) -> Optional[DistributedEnv]:
    """Call ``jax.distributed.initialize`` when the environment declares a
    multi-process topology.  Must run before any jax computation; after
    it, ``jax.devices()`` is the GLOBAL device list.  Returns the
    detected topology (None = single process, nothing done)."""
    denv = detect_env(environ)
    if denv is None:
        return None
    import jax

    logger.info(
        "initializing jax.distributed: coordinator=%s process %d/%d",
        denv.coordinator_address, denv.process_id, denv.num_processes,
    )
    jax.distributed.initialize(
        coordinator_address=denv.coordinator_address,
        num_processes=denv.num_processes,
        process_id=denv.process_id,
    )
    return denv


# -- group control-plane side channel (acks, drain relay, group fail) ------
#
# The lockstep broadcast is a COLLECTIVE: it can only prove liveness of
# members that still participate, and it hangs — rather than reporting —
# when one is gone.  Group liveness therefore rides a tiny key/value side
# channel: followers write monotonic ack ordinals after every received
# event batch, the leader's monitor thread polls them, a follower relays
# drain intent the same way, and the leader's group-fail marker tells
# followers to restart even when the collective transport is wedged.
# Nothing on this channel ever feeds a step plan directly — every
# plan-affecting decision still flows through the leader's published
# event batches, so lockstep determinism holds by construction.


def _ack_key(epoch: int, process_id: int, ordinal: int) -> str:
    # Ordinal-suffixed keys: every write lands on a FRESH key, so the
    # channel works on write-once stores (older jaxlib coordinator KV
    # refuses overwrites) as well as overwriting ones.
    return f"pstpu/{epoch}/ack/{process_id}/{ordinal}"


def _drain_key(epoch: int, process_id: int) -> str:
    return f"pstpu/{epoch}/drain/{process_id}"


def _mismatch_key(epoch: int, process_id: int) -> str:
    # Written by a follower observing epoch ``epoch`` from a group it
    # does not belong to, read by THAT group's leader (it owns the
    # epoch) so the fleet can tell split-brain restarts from silence.
    return f"pstpu/{epoch}/mismatch/{process_id}"


def _fail_key(epoch: int) -> str:
    return f"pstpu/{epoch}/fail"


class LocalAckStore:
    """In-process ack store: the single-process stand-in (tests, fake
    slice groups) for the jax.distributed coordinator's KV service."""

    def __init__(self) -> None:
        self._data: Dict[str, str] = {}
        self._lock = threading.Lock()

    def set(self, key: str, value: str) -> None:
        with self._lock:
            self._data[key] = value

    def get(self, key: str) -> Optional[str]:
        with self._lock:
            return self._data.get(key)


class CoordinatorAckStore:
    """Ack store over the jax.distributed coordinator's key/value
    service — the side channel every slice member can already reach
    (it bootstrapped through it).  All failures degrade to None/no-op:
    a flaky KV read must never take down a healthy group; prolonged
    silence is what the monitor reacts to."""

    def __init__(self) -> None:
        from jax._src import distributed as jax_distributed

        client = jax_distributed.global_state.client
        if client is None:
            raise RuntimeError("jax.distributed is not initialized")
        if not hasattr(client, "key_value_try_get"):
            # No NON-BLOCKING read on this jaxlib: a blocking get's
            # per-absent-key wait would serialize the monitor sweep
            # (~100 ms x members), so group liveness degrades to OFF
            # (staleness-window behavior) rather than to a slow monitor
            # that mismeasures silence.
            raise RuntimeError(
                "coordinator KV client has no key_value_try_get"
            )
        self._client = client

    def set(self, key: str, value: str) -> None:
        try:
            self._client.key_value_set(key, value)
        except Exception:
            logger.debug("coordinator KV set failed for %s", key, exc_info=True)

    def get(self, key: str) -> Optional[str]:
        try:
            value = self._client.key_value_try_get(key)
        except Exception:
            return None
        return None if value is None else str(value)


def _maybe_coordinator_store() -> Optional[CoordinatorAckStore]:
    try:
        return CoordinatorAckStore()
    except Exception:
        return None


class GroupEpochMismatch(RuntimeError):
    """A follower observed an event batch from a different group
    incarnation (epoch change after adoption, or a mid-stream join): its
    engine state cannot be in lockstep with that group — the only safe
    move is fatal_exit into a fresh parallel group restart."""


# -- lockstep event channel ------------------------------------------------


def broadcast_pyobj(obj: Any, is_source: bool) -> Any:
    """Broadcast a picklable object from process 0 to all processes.

    Two fixed-shape collectives (broadcast_one_to_all requires identical
    shapes everywhere): first the payload length, then the padded payload
    bytes.  Cost is one small + one payload-sized collective — the
    lockstep payload is request metadata (token ids, sampling params),
    thousands of times smaller than one decode step's activations.
    """
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import multihost_utils

    payload = pickle.dumps(obj) if is_source else b""
    n = multihost_utils.broadcast_one_to_all(
        jnp.asarray(len(payload), jnp.int32)
    )
    n = int(n)
    buf = np.zeros((n,), np.uint8)
    if is_source:
        buf[:] = np.frombuffer(payload, np.uint8)
    buf = multihost_utils.broadcast_one_to_all(jnp.asarray(buf))
    return pickle.loads(np.asarray(buf).tobytes())


@dataclasses.dataclass
class StepEvents:
    """One lockstep iteration's inputs, leader -> followers."""

    requests: list = dataclasses.field(default_factory=list)
    # (request_id, prompt_token_ids, SamplingParams, adapter)
    aborts: list = dataclasses.field(default_factory=list)
    shutdown: bool = False
    # Group identity: the leader's boot nonce and a monotonic publish
    # ordinal, stamped by LockstepChannel.publish.  A follower adopts
    # (epoch, seq=1) from its first event and fatal-exits on any
    # mismatch thereafter — a restarted member can never replay into a
    # newer (or older) group incarnation.
    epoch: int = 0
    seq: int = 0


class LockstepChannel:
    """Leader/follower event exchange for multi-host serving.

    The leader calls :meth:`publish` with each iteration's event batch
    right before stepping its engine; followers call :meth:`receive` and
    apply the same batch to their replica, keeping every process's
    scheduler state — and therefore every jitted launch — identical.
    Idle iterations are not published beyond a periodic empty HEARTBEAT
    batch (liveness signal), so followers block in ``receive`` without
    spinning collectives.

    Group liveness (docs/robustness.md "Slice lifecycle contract"):
    every received batch is acknowledged back to the leader through the
    ``ack_store`` side channel (throttled to ``member_timeout_s/4``);
    the leader's :class:`GroupLivenessMonitor` fails the slice's
    ``/health`` when a member stays silent past ``member_timeout_s``.
    Every publish carries the group ``epoch`` (leader boot nonce) and a
    monotonic ``seq``; followers adopt the first and die loudly on any
    change (:class:`GroupEpochMismatch`).
    """

    def __init__(
        self,
        denv: DistributedEnv,
        heartbeat_seconds: float = 10.0,
        member_timeout_s: float = 10.0,
        ack_store=None,
    ):
        self.denv = denv
        self.member_timeout_s = float(member_timeout_s)
        # Leader publishes an empty batch at least this often while idle;
        # followers treat event staleness beyond a few heartbeats as a
        # dead leader (follower /health fails -> k8s restarts the pod;
        # SPMD groups cannot heal a lost member in place).  The idle
        # heartbeat must outpace the member-liveness window, or an idle
        # group would trip the monitor between heartbeats.
        if self.member_timeout_s > 0:
            heartbeat_seconds = min(
                heartbeat_seconds, self.member_timeout_s / 3.0
            )
        self.heartbeat_seconds = heartbeat_seconds
        self.last_event_time = time.time()
        # The control-plane side channel; None disables group liveness
        # (single-process tests, or a coordinator without a KV service).
        self.ack_store = (
            ack_store if ack_store is not None else _maybe_coordinator_store()
        )
        self.epoch = new_epoch() if denv.is_leader else 0
        self.seq = 0
        self._epoch_adopted = denv.is_leader
        # Follower ack throttle state.
        self._ack_ordinal = 0
        self._last_ack_time = 0.0
        self._drain_relayed = False

    def publish(self, events: StepEvents) -> None:
        assert self.denv.is_leader
        self.seq += 1
        events.epoch = self.epoch
        events.seq = self.seq
        broadcast_pyobj(events, is_source=True)
        self.last_event_time = time.time()

    def receive(self) -> StepEvents:
        assert not self.denv.is_leader
        events = broadcast_pyobj(None, is_source=False)
        self.last_event_time = time.time()
        self._check_epoch(events)
        self.seq = getattr(events, "seq", 0)
        self._maybe_ack()
        return events

    def _check_epoch(self, events: StepEvents) -> None:
        epoch = getattr(events, "epoch", 0)
        seq = getattr(events, "seq", 0)
        if not epoch:
            return  # pre-epoch peer (tests with hand-rolled events)
        if not self._epoch_adopted:
            if seq > 1:
                # First event this process ever saw is mid-stream: a
                # restarted member attaching to a RUNNING group.  Its
                # engine state is steps behind the group's — replaying
                # from here would silently desync the SPMD launches.
                self._report_epoch_mismatch(epoch)
                raise GroupEpochMismatch(
                    f"joined group epoch {epoch} at seq {seq}: a restarted "
                    "member cannot replay into a running group"
                )
            self.epoch = epoch
            self._epoch_adopted = True
            if self._drain_relayed and self.ack_store is not None:
                # A drain relayed BEFORE adoption (SIGTERM during the
                # leader's boot) was keyed under epoch 0, which no
                # monitor polls — re-relay under the adopted epoch so
                # the intent is never silently lost.
                self.ack_store.set(
                    _drain_key(self.epoch, self.denv.process_id),
                    str(time.time()),
                )
            return
        if epoch != self.epoch:
            self._report_epoch_mismatch(epoch)
            raise GroupEpochMismatch(
                f"group epoch changed {self.epoch} -> {epoch}: this member "
                "belongs to a dead incarnation and must restart"
            )

    def _report_epoch_mismatch(self, observed_epoch: int) -> None:
        """Tell the OBSERVED group's leader (it owns that epoch and its
        monitor polls it) that a member of another incarnation saw its
        events — tpu:lockstep_member_failures_total{reason="epoch_mismatch"}."""
        if self.ack_store is not None and observed_epoch:
            self.ack_store.set(
                _mismatch_key(observed_epoch, self.denv.process_id),
                str(self.epoch),
            )

    def _maybe_ack(self) -> None:
        """Write a liveness ack (monotonic ordinal -> latest seq seen),
        throttled so an idle-heartbeat cadence and a busy step cadence
        cost the same: at most ~4 KV writes per member timeout."""
        if self.ack_store is None or self.member_timeout_s <= 0:
            return
        now = time.time()
        interval = self.member_timeout_s / 4.0
        if self._ack_ordinal and now - self._last_ack_time < interval:
            return
        self._ack_ordinal += 1
        self._last_ack_time = now
        self.ack_store.set(
            _ack_key(self.epoch, self.denv.process_id, self._ack_ordinal),
            str(self.seq),
        )

    def relay_drain(self) -> bool:
        """Follower-side drain intent (SIGTERM / preStop POST /drain):
        RELAY to the leader through the side channel instead of leaving
        the collectives — the follower keeps stepping until the leader
        announces shutdown, so in-flight streams finish before any
        member exits.  Returns False when no side channel exists (the
        caller falls back to waiting out the staleness window)."""
        if self.ack_store is None:
            return False
        self._drain_relayed = True
        self.ack_store.set(
            _drain_key(self.epoch, self.denv.process_id), str(time.time())
        )
        return True

    @property
    def drain_relayed(self) -> bool:
        return self._drain_relayed

    def group_failed(self) -> Optional[str]:
        """The leader's group-fail marker, readable by any member even
        when the collective transport is wedged."""
        if self.ack_store is None or not self.epoch:
            return None
        return self.ack_store.get(_fail_key(self.epoch))

    def mark_group_failed(self, reason: str) -> None:
        if self.ack_store is not None and self.epoch:
            self.ack_store.set(_fail_key(self.epoch), reason)

    def stale(self, factor: float = 6.0) -> bool:
        """No event for ``factor`` heartbeats: the leader is gone."""
        return time.time() - self.last_event_time \
            > factor * self.heartbeat_seconds


class GroupLivenessMonitor:
    """Leader-side member-liveness watchdog for a lockstep slice group.

    A dedicated thread (never the step thread: ack reads are RPCs to the
    coordinator) polls every follower's ack ordinals.  A member whose
    acks stop advancing for ``member_timeout_s`` while events are being
    published fails the whole slice: :meth:`problem` turns non-None
    (the leader's ``/health`` conjoins it -> 503 within the timeout, so
    the router's breaker routes around the slice in seconds), the
    group-fail marker is written so live followers restart in parallel,
    and — with ``exit_on_failure`` — the leader ``fatal_exit``s so k8s
    restarts the whole pod group together.  The same poll carries the
    follower->leader drain relay (``on_drain_relay`` fires once).
    """

    FAILURE_REASONS = ("member_silent", "epoch_mismatch")

    def __init__(
        self,
        channel: LockstepChannel,
        *,
        on_drain_relay: Optional[Callable[[], None]] = None,
        exit_on_failure: bool = True,
        poll_interval_s: Optional[float] = None,
        clock: Callable[[], float] = time.time,
    ):
        self.channel = channel
        self.on_drain_relay = on_drain_relay
        self.exit_on_failure = exit_on_failure
        timeout = max(channel.member_timeout_s, 0.05)
        self.poll_interval_s = (
            poll_interval_s if poll_interval_s is not None
            else max(0.05, timeout / 8.0)
        )
        self._clock = clock
        self._lock = threading.Lock()
        now = clock()
        members = range(1, channel.denv.num_processes)
        self._next_ordinal = {pid: 1 for pid in members}
        self._last_progress = {pid: now for pid in members}
        self._last_seq = {pid: 0 for pid in members}
        self._armed = False  # becomes True once the leader published
        self._problem: Optional[str] = None
        self._drain_seen: set = set()
        self._mismatch_seen: set = set()
        self.member_failures: Dict[str, int] = {}
        self.drain_relays = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="slice-monitor", daemon=True
        )
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        with self._lock:
            thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout)

    # -- reads (health endpoint / metrics, asyncio loop) -------------------

    def problem(self) -> Optional[str]:
        with self._lock:
            return self._problem

    def member_ack_ages(self) -> Dict[int, float]:
        """Seconds since each member's acks last advanced (0.0 before the
        first publish arms the monitor) — tpu:lockstep_member_last_ack_seconds."""
        now = self._clock()
        with self._lock:
            if not self._armed:
                return {pid: 0.0 for pid in self._last_progress}
            return {
                pid: max(0.0, now - t)
                for pid, t in self._last_progress.items()
            }

    def record_failure(self, reason: str) -> None:
        with self._lock:
            self.member_failures[reason] = (
                self.member_failures.get(reason, 0) + 1
            )

    # -- the monitor thread ------------------------------------------------

    # stackcheck: thread=slice-monitor
    def _run(self) -> None:
        while not self._stop.is_set():
            self.poll_once()
            if self.problem() is not None:
                break
            self._stop.wait(self.poll_interval_s)
        problem = self.problem()
        if problem is None or self._stop.is_set():
            return
        # Bounded fail-and-restart: the marker restarts live followers
        # in parallel (they poll it off-collective), one short beat lets
        # in-flight health probes observe the 503, then the leader exits
        # nonzero so k8s restarts the whole group together.  No shutdown
        # broadcast: a publish is a collective and would wedge on the
        # very member whose death we just detected.
        self.channel.mark_group_failed(problem)
        if self.exit_on_failure:
            if self._stop.wait(min(1.0, 2 * self.poll_interval_s)):
                # stop() landed during the beat: the process is shutting
                # down cleanly — do not turn an exit-0 into a restart.
                return
            logger.error("slice group failed (%s); restarting group", problem)
            fatal_exit(1)

    def poll_once(self) -> None:
        """One ack/relay sweep (separable for deterministic tests)."""
        store = self.channel.ack_store
        if store is None:
            return
        now = self._clock()
        epoch = self.channel.epoch
        with self._lock:
            if not self._armed:
                if self.channel.seq == 0:
                    # Nothing published yet: members have nothing to ack.
                    for pid in self._last_progress:
                        self._last_progress[pid] = now
                    return
                self._armed = True
            members = list(self._next_ordinal)
        for pid in members:
            # Per-member clock read: a slow store must not let sweep
            # duration inflate another member's measured silence.
            now = self._clock()
            advanced = False
            # Bounded catch-up: followers write at most ~4 acks per
            # timeout, so a handful of probes always reaches the head.
            for _ in range(64):
                with self._lock:
                    ordinal = self._next_ordinal[pid]
                value = store.get(_ack_key(epoch, pid, ordinal))
                if value is None:
                    break
                advanced = True
                with self._lock:
                    self._next_ordinal[pid] = ordinal + 1
                    try:
                        self._last_seq[pid] = int(value)
                    except ValueError:
                        pass
            with self._lock:
                if advanced:
                    self._last_progress[pid] = now
                silent_s = now - self._last_progress[pid]
                timeout = self.channel.member_timeout_s
                if (
                    self._problem is None
                    and timeout > 0
                    and silent_s > timeout
                ):
                    self._problem = (
                        f"slice member {pid} silent for {silent_s:.1f}s "
                        f"(member timeout {timeout:.1f}s); the SPMD group "
                        "cannot heal a lost member in place"
                    )
                    self.member_failures["member_silent"] = (
                        self.member_failures.get("member_silent", 0) + 1
                    )
            if store.get(_drain_key(epoch, pid)) is not None:
                # Consume only when a callback is wired: a relay seen
                # during the start()->callback-assignment window (or one
                # already on the channel at leader boot) must survive
                # until someone can actually begin the drain.
                cb = self.on_drain_relay
                fire = False
                with self._lock:
                    if pid not in self._drain_seen and cb is not None:
                        self._drain_seen.add(pid)
                        self.drain_relays += 1
                        fire = True
                if fire and cb is not None:
                    logger.info(
                        "slice member %d relayed drain intent; draining "
                        "the whole group through the leader", pid,
                    )
                    cb()
            if store.get(_mismatch_key(epoch, pid)) is not None:
                count = False
                with self._lock:
                    if pid not in self._mismatch_seen:
                        self._mismatch_seen.add(pid)
                        count = True
                if count:
                    # A member of another incarnation observed this
                    # group's events (split-brain restart in flight);
                    # it fatal-exited itself — count the reason so the
                    # fleet can tell mismatches from plain silence.
                    self.record_failure("epoch_mismatch")


def follower_loop(engine, channel: LockstepChannel) -> None:
    """Run a follower replica: apply the leader's event batches and step
    in lockstep until shutdown.  Outputs are discarded — the leader owns
    the HTTP surface; this process only contributes its device shards to
    the collective computation.

    ``engine.step()`` here is the same dispatch/collect pipeline the
    leader's loop drives, so with pipeline_decode on every replica
    enqueues the identical lookahead launch sequence (collects are pure
    host reads of addressable shards — no collectives), keeping the SPMD
    group in sync."""
    logger.info("follower %d: entering lockstep loop", channel.denv.process_id)
    while True:
        try:
            events = channel.receive()
        except GroupEpochMismatch:
            # Split-brain guard: this member belongs to a different group
            # incarnation than the one publishing (leader restarted, or
            # this member restarted into a running group).  Its engine
            # state cannot be in lockstep — exit nonzero so k8s restarts
            # the whole slice group into one fresh epoch together.
            logger.exception(
                "follower: group epoch mismatch; exiting for a clean "
                "parallel group restart"
            )
            fatal_exit(1)
            return  # unreachable except under monkeypatched exit
        if events.shutdown:
            logger.info("follower: leader announced shutdown")
            return
        for request_id in events.aborts:
            engine.abort_request(request_id)
        for request_id, token_ids, params, adapter in events.requests:
            try:
                engine.add_request(
                    request_id,
                    prompt_token_ids=token_ids,
                    sampling_params=params,
                    adapter=adapter,
                )
            except Exception:
                # The leader hit the same validation error and already
                # answered the client; stay in lockstep.
                logger.exception("follower: add_request failed")
        if engine.has_unfinished():
            try:
                engine.step()
            except Exception:
                # An unguarded step error would kill this process while
                # the leader keeps publishing, wedging the group in
                # collectives until a partial restart that cannot rejoin
                # the running jax.distributed incarnation anyway.  Exit
                # nonzero promptly so k8s restarts the WHOLE slice group
                # together (an SPMD group cannot heal a lost member in
                # place).
                logger.exception(
                    "follower: engine.step failed; exiting nonzero so "
                    "the slice group restarts together"
                )
                fatal_exit(1)
                return  # unreachable except under monkeypatched exit
