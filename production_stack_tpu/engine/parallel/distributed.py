"""Multi-host (multi-process) bootstrap + lockstep serving protocol.

The reference scales a single engine across accelerators with NCCL over
/dev/shm inside one pod (reference helm/templates/deployment-vllm-multi.yaml:198-228);
a multi-host TPU slice (e.g. v5e-16 = 4x4, four 4-chip workers) instead
runs ONE jax program across several worker pods: every process calls
``jax.distributed.initialize`` against worker 0, ``jax.devices()``
becomes the global chip list, and the engine's mesh/pjit shardings span
hosts with XLA emitting ICI/DCN collectives.

Serving on top of SPMD needs one more ingredient: every process must
launch the SAME jitted computations in the same order.  The engine is
deterministic given its request stream, so the leader (process 0, the
only one serving HTTP) broadcasts the per-iteration event batch —
(new requests, aborts, shutdown) — and every follower applies it to its
own engine replica and steps in lockstep.  Followers hold the model/KV
shards jax assigned them; outputs are read on the leader.

Environment contract (set by the Helm chart's multi-host StatefulSet
mode, templates/deployment-engine.yaml):

  PSTPU_NUM_PROCESSES       total worker pods in the slice group
  PSTPU_PROCESS_ID          this pod's ordinal (StatefulSet pod index)
  PSTPU_COORDINATOR_ADDRESS worker-0 DNS name:port (headless service)

GKE TPU pod environments (TPU_WORKER_ID / TPU_WORKER_HOSTNAMES, injected
by the TPU device plugin) are honored as a fallback, so a hand-rolled
JobSet works too.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import pickle
import time
from typing import Any, Optional

logger = logging.getLogger(__name__)

_COORD_PORT = 8476


def fatal_exit(code: int = 1) -> None:
    """Terminate the process immediately after flushing log handlers.

    Used when a lockstep member must die NOW: ``sys.exit`` would run
    atexit hooks (jax.distributed teardown blocks on collectives the
    dead/desynced group will never complete), turning a clean k8s
    restart into a hung pod.  Module-level indirection so tests can
    monkeypatch it."""
    logging.shutdown()
    os._exit(code)


@dataclasses.dataclass(frozen=True)
class DistributedEnv:
    coordinator_address: str
    num_processes: int
    process_id: int

    @property
    def is_leader(self) -> bool:
        return self.process_id == 0


def detect_env(environ=None) -> Optional[DistributedEnv]:
    """Multi-process topology from the environment, or None for the
    ordinary single-process case.

    Explicit PSTPU_* variables win; the GKE TPU pod contract
    (TPU_WORKER_ID + TPU_WORKER_HOSTNAMES) is the fallback.  A
    single-entry hostname list (the axon tunnel sets
    TPU_WORKER_HOSTNAMES=localhost) is single-process.
    """
    env = os.environ if environ is None else environ
    if "PSTPU_NUM_PROCESSES" in env:
        n = int(env["PSTPU_NUM_PROCESSES"])
        if n <= 1:
            return None
        return DistributedEnv(
            coordinator_address=env["PSTPU_COORDINATOR_ADDRESS"],
            num_processes=n,
            process_id=int(env["PSTPU_PROCESS_ID"]),
        )
    hostnames = [
        h for h in env.get("TPU_WORKER_HOSTNAMES", "").split(",") if h
    ]
    if len(hostnames) > 1:
        return DistributedEnv(
            coordinator_address=f"{hostnames[0]}:{_COORD_PORT}",
            num_processes=len(hostnames),
            process_id=int(env.get("TPU_WORKER_ID", "0")),
        )
    return None


def maybe_initialize(environ=None) -> Optional[DistributedEnv]:
    """Call ``jax.distributed.initialize`` when the environment declares a
    multi-process topology.  Must run before any jax computation; after
    it, ``jax.devices()`` is the GLOBAL device list.  Returns the
    detected topology (None = single process, nothing done)."""
    denv = detect_env(environ)
    if denv is None:
        return None
    import jax

    logger.info(
        "initializing jax.distributed: coordinator=%s process %d/%d",
        denv.coordinator_address, denv.process_id, denv.num_processes,
    )
    jax.distributed.initialize(
        coordinator_address=denv.coordinator_address,
        num_processes=denv.num_processes,
        process_id=denv.process_id,
    )
    return denv


# -- lockstep event channel ------------------------------------------------


def broadcast_pyobj(obj: Any, is_source: bool) -> Any:
    """Broadcast a picklable object from process 0 to all processes.

    Two fixed-shape collectives (broadcast_one_to_all requires identical
    shapes everywhere): first the payload length, then the padded payload
    bytes.  Cost is one small + one payload-sized collective — the
    lockstep payload is request metadata (token ids, sampling params),
    thousands of times smaller than one decode step's activations.
    """
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import multihost_utils

    payload = pickle.dumps(obj) if is_source else b""
    n = multihost_utils.broadcast_one_to_all(
        jnp.asarray(len(payload), jnp.int32)
    )
    n = int(n)
    buf = np.zeros((n,), np.uint8)
    if is_source:
        buf[:] = np.frombuffer(payload, np.uint8)
    buf = multihost_utils.broadcast_one_to_all(jnp.asarray(buf))
    return pickle.loads(np.asarray(buf).tobytes())


@dataclasses.dataclass
class StepEvents:
    """One lockstep iteration's inputs, leader -> followers."""

    requests: list = dataclasses.field(default_factory=list)
    # (request_id, prompt_token_ids, SamplingParams, adapter)
    aborts: list = dataclasses.field(default_factory=list)
    shutdown: bool = False


class LockstepChannel:
    """Leader/follower event exchange for multi-host serving.

    The leader calls :meth:`publish` with each iteration's event batch
    right before stepping its engine; followers call :meth:`receive` and
    apply the same batch to their replica, keeping every process's
    scheduler state — and therefore every jitted launch — identical.
    Idle iterations are not published beyond a periodic empty HEARTBEAT
    batch (liveness signal), so followers block in ``receive`` without
    spinning collectives.
    """

    def __init__(self, denv: DistributedEnv, heartbeat_seconds: float = 10.0):
        self.denv = denv
        # Leader publishes an empty batch at least this often while idle;
        # followers treat event staleness beyond a few heartbeats as a
        # dead leader (follower /health fails -> k8s restarts the pod;
        # SPMD groups cannot heal a lost member in place).
        self.heartbeat_seconds = heartbeat_seconds
        self.last_event_time = time.time()

    def publish(self, events: StepEvents) -> None:
        assert self.denv.is_leader
        broadcast_pyobj(events, is_source=True)
        self.last_event_time = time.time()

    def receive(self) -> StepEvents:
        assert not self.denv.is_leader
        events = broadcast_pyobj(None, is_source=False)
        self.last_event_time = time.time()
        return events

    def stale(self, factor: float = 6.0) -> bool:
        """No event for ``factor`` heartbeats: the leader is gone."""
        return time.time() - self.last_event_time \
            > factor * self.heartbeat_seconds


def follower_loop(engine, channel: LockstepChannel) -> None:
    """Run a follower replica: apply the leader's event batches and step
    in lockstep until shutdown.  Outputs are discarded — the leader owns
    the HTTP surface; this process only contributes its device shards to
    the collective computation.

    ``engine.step()`` here is the same dispatch/collect pipeline the
    leader's loop drives, so with pipeline_decode on every replica
    enqueues the identical lookahead launch sequence (collects are pure
    host reads of addressable shards — no collectives), keeping the SPMD
    group in sync."""
    logger.info("follower %d: entering lockstep loop", channel.denv.process_id)
    while True:
        events = channel.receive()
        if events.shutdown:
            logger.info("follower: leader announced shutdown")
            return
        for request_id in events.aborts:
            engine.abort_request(request_id)
        for request_id, token_ids, params, adapter in events.requests:
            try:
                engine.add_request(
                    request_id,
                    prompt_token_ids=token_ids,
                    sampling_params=params,
                    adapter=adapter,
                )
            except Exception:
                # The leader hit the same validation error and already
                # answered the client; stay in lockstep.
                logger.exception("follower: add_request failed")
        if engine.has_unfinished():
            try:
                engine.step()
            except Exception:
                # An unguarded step error would kill this process while
                # the leader keeps publishing, wedging the group in
                # collectives until a partial restart that cannot rejoin
                # the running jax.distributed incarnation anyway.  Exit
                # nonzero promptly so k8s restarts the WHOLE slice group
                # together (an SPMD group cannot heal a lost member in
                # place).
                logger.exception(
                    "follower: engine.step failed; exiting nonzero so "
                    "the slice group restarts together"
                )
                fatal_exit(1)
                return  # unreachable except under monkeypatched exit
