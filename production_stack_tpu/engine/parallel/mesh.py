"""Device mesh construction.

Axes:
  dp — data parallel: replicates the model, shards the decode batch.
  tp — tensor parallel: shards attention heads / MLP channels; XLA emits
       psum over ICI after o_proj and down_proj.
  sp — sequence parallel: ring-attention axis for long-context prefill.

On GKE the axes map onto the physical slice topology (e.g. v5e ``2x4``);
``jax.experimental.mesh_utils`` picks an ICI-friendly device order.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Optional

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

from production_stack_tpu.engine.config import ParallelConfig

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class MeshAxes:
    DP: str = "dp"
    TP: str = "tp"
    SP: str = "sp"


AXES = MeshAxes()


def build_mesh(parallel: ParallelConfig, devices: Optional[list] = None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    shape = parallel.mesh_shape  # (dp, tp, sp)
    needed = int(np.prod(shape))
    if needed > len(devices):
        raise ValueError(
            f"Mesh {shape} needs {needed} devices; only {len(devices)} available"
        )
    devices = devices[:needed]
    try:
        device_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except Exception:
        # Fallback (CPU virtual devices have no topology info).
        device_array = np.asarray(devices).reshape(shape)
    return Mesh(device_array, (AXES.DP, AXES.TP, AXES.SP))


def single_device_mesh() -> Mesh:
    return Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1, 1), (AXES.DP, AXES.TP, AXES.SP))
