"""Ring attention: causal self-attention sharded over the sequence axis.

Long-context prefill that exceeds one chip's HBM runs with the sequence
split over the ``sp`` mesh axis: each device keeps its query shard resident
while K/V shards rotate around the ring via ``lax.ppermute`` (ICI
neighbor-to-neighbor), accumulating with an online-softmax (flash-style
log-sum-exp merge).  Compute on the current shard overlaps the transfer of
the next — XLA pipelines the ppermute with the einsum.

The reference stack has no sequence parallelism anywhere (SURVEY.md section
2.7: long context is handled purely by KV offload); this is a TPU-native
capability on top of parity.

Called inside ``shard_map`` over the mesh, e.g.:

    out = shard_map(
        lambda q, k, v: ring_self_attention(q, k, v, axis_name="sp", scale=s),
        mesh=mesh,
        in_specs=(P("sp", None, None),) * 3,
        out_specs=P("sp", None, None),
    )(q, k, v)
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _chunk_attention(
    q: jax.Array,  # [Tq, H, D]
    k: jax.Array,  # [Tk, K, D]
    v: jax.Array,  # [Tk, K, D]
    q_pos: jax.Array,  # [Tq] global positions
    k_pos: jax.Array,  # [Tk] global positions
    scale: float,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Partial attention of one KV chunk: returns (scores_max, exp_sum,
    weighted_values) for online-softmax merging.  Shapes:
    m [H, Tq], l [H, Tq], o [Tq, H, D]."""
    Tq, H, D = q.shape
    K = k.shape[1]
    G = H // K
    qg = q.reshape(Tq, K, G, D)
    scores = jnp.einsum("tkgd,skd->kgts", qg, k, preferred_element_type=jnp.float32)
    scores = scores * scale  # [K, G, Tq, Tk]
    mask = k_pos[None, None, None, :] <= q_pos[None, None, :, None]
    scores = jnp.where(mask, scores, NEG_INF)
    m = jnp.max(scores, axis=-1)  # [K, G, Tq]
    # Guard fully-masked rows: exp(NEG_INF - NEG_INF) would be 1.
    safe_m = jnp.maximum(m, -1e29)
    p = jnp.exp(scores - safe_m[..., None])  # [K, G, Tq, Tk]
    p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1)  # [K, G, Tq]
    o = jnp.einsum(
        "kgts,skd->tkgd", p.astype(v.dtype), v, preferred_element_type=jnp.float32
    )  # [Tq, K, G, D]
    return m, l, o.astype(jnp.float32)


def _merge_partials(m_acc, l_acc, o_acc, m_new, l_new, o_new):
    """Online-softmax merge of two partial-attention accumulators."""
    K, G, Tl = m_acc.shape
    _, H, D = o_acc.shape
    m_tot = jnp.maximum(m_acc, m_new)
    safe = jnp.maximum(m_tot, -1e29)
    alpha = jnp.exp(m_acc - safe)  # [K, G, Tq]
    beta = jnp.exp(m_new - safe)
    l_tot = l_acc * alpha + l_new * beta
    o_scale_old = alpha.transpose(2, 0, 1)[..., None]  # [Tq, K, G, 1]
    o_scale_new = beta.transpose(2, 0, 1)[..., None]
    o_tot = (
        o_acc.reshape(Tl, K, G, D) * o_scale_old
        + o_new.reshape(Tl, K, G, D) * o_scale_new
    ).reshape(Tl, H, D)
    return m_tot, l_tot, o_tot


def _ring_partials(
    q, k, v, q_pos, *, axis_name, scale, valid_len, key_pos_base, init
):
    """Run one ring: rotate K/V shards via ppermute, accumulating partial
    attention against ``q`` with online softmax.  ``key_pos_base`` is the
    global position of the ring's first key (shard s holds keys at
    key_pos_base + s*Tk + arange(Tk)); ``valid_len`` counts valid keys
    within the ring; ``init`` seeds the accumulator (e.g. with a previous
    ring's partials).  Returns unnormalized (m, l, o)."""
    Tl, H, D = q.shape
    Tk = k.shape[0]
    # lax.axis_size is jax>=0.5; psum of 1 over the axis is the portable
    # spelling (constant-folded at trace time).
    sp = getattr(lax, "axis_size", lambda a: lax.psum(1, a))(axis_name)
    my_idx = lax.axis_index(axis_name)

    def body(step, carry):
        m_acc, l_acc, o_acc, k_cur, v_cur = carry
        src_idx = (my_idx - step) % sp  # whose shard we currently hold
        local_idx = src_idx * Tk + jnp.arange(Tk)
        k_pos = key_pos_base + local_idx
        if valid_len is not None:
            k_pos = jnp.where(local_idx < valid_len, k_pos, jnp.int32(2**30))
        m_new, l_new, o_new = _chunk_attention(q, k_cur, v_cur, q_pos, k_pos, scale)
        m_tot, l_tot, o_tot = _merge_partials(
            m_acc, l_acc, o_acc, m_new, l_new, o_new.reshape(Tl, H, D)
        )
        # Rotate K/V to the next device; compute on the current shard
        # overlaps the transfer of the next.
        perm = [(i, (i + 1) % sp) for i in range(sp)]
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return m_tot, l_tot, o_tot, k_next, v_next

    m_f, l_f, o_f, _, _ = lax.fori_loop(0, sp, body, (*init, k, v))
    return m_f, l_f, o_f


def _normalize(q, l_f, o_f):
    Tl, H, D = q.shape
    K, G, _ = l_f.shape
    denom = jnp.maximum(l_f, 1e-20).transpose(2, 0, 1)[..., None]  # [Tq, K, G, 1]
    out = o_f.reshape(Tl, K, G, D) / denom
    return out.reshape(Tl, H, D).astype(q.dtype)


def ring_self_attention(
    q: jax.Array,  # [Tl, H, D] local query shard
    k: jax.Array,  # [Tl, K, D] local key shard
    v: jax.Array,  # [Tl, K, D] local value shard
    *,
    axis_name: str,
    scale: float,
    valid_len: Optional[jax.Array] = None,  # global valid token count
) -> jax.Array:
    """Causal self-attention with K/V rotating around the ring."""
    Tl, H, D = q.shape
    K = k.shape[1]
    G = H // K
    my_idx = lax.axis_index(axis_name)

    q_pos = my_idx * Tl + jnp.arange(Tl)
    if valid_len is not None:
        # Mask padded queries by pushing their positions before all keys.
        q_pos = jnp.where(q_pos < valid_len, q_pos, -1)

    init = (
        jnp.full((K, G, Tl), NEG_INF, jnp.float32),
        jnp.zeros((K, G, Tl), jnp.float32),
        jnp.zeros((Tl, H, D), jnp.float32),
    )
    _, l_f, o_f = _ring_partials(
        q, k, v, q_pos,
        axis_name=axis_name, scale=scale, valid_len=valid_len,
        key_pos_base=jnp.int32(0), init=init,
    )
    return _normalize(q, l_f, o_f)


def ring_prefill_with_prefix(
    q: jax.Array,  # [Tl, H, D] local query shard (new tokens)
    k: jax.Array,  # [Tl, K, D] local key shard (new tokens)
    v: jax.Array,  # [Tl, K, D] local value shard
    k_prefix: jax.Array,  # [Cl, K, D] local shard of the cached prefix
    v_prefix: jax.Array,  # [Cl, K, D]
    cached_len: jax.Array,  # scalar int32: valid prefix tokens (global)
    valid_len: jax.Array,  # scalar int32: valid new tokens (global)
    *,
    axis_name: str,
    scale: float,
) -> jax.Array:
    """Sequence-parallel paged prefill attention: queries attend to the
    cached prefix plus all causally-visible new tokens.  BOTH the prefix
    and the new tokens' K/V are sharded over the sp ring (no device holds
    the full prefix — at max_model_len-sized prefixes a replicated prefix
    would reintroduce exactly the memory wall the ring avoids), rotating
    via ppermute in two chained rings that share one online-softmax
    accumulator.  This is the sp>1 counterpart of
    ops/attention.py::prefill_attention (same mask semantics), called
    inside ``shard_map`` by models/llama.py when the engine mesh has an sp
    axis."""
    Tl, H, D = q.shape
    K = k.shape[1]
    G = H // K
    my_idx = lax.axis_index(axis_name)

    local_new_idx = my_idx * Tl + jnp.arange(Tl)  # index among new tokens
    q_pos = cached_len + local_new_idx
    # Padded queries (beyond valid_len) attend to nothing; their rows are
    # never read (engine samples from position valid_len-1).
    q_pos = jnp.where(local_new_idx < valid_len, q_pos, -1)

    # Ring 1: the cached prefix (global positions 0..cached_len; shard s
    # holds prefix tokens s*Cl..(s+1)*Cl).
    init = (
        jnp.full((K, G, Tl), NEG_INF, jnp.float32),
        jnp.zeros((K, G, Tl), jnp.float32),
        jnp.zeros((Tl, H, D), jnp.float32),
    )
    init = _ring_partials(
        q, k_prefix, v_prefix, q_pos,
        axis_name=axis_name, scale=scale, valid_len=cached_len,
        key_pos_base=jnp.int32(0), init=init,
    )

    # Ring 2: the new tokens' K/V shards (positions cached_len + i).
    _, l_f, o_f = _ring_partials(
        q, k, v, q_pos,
        axis_name=axis_name, scale=scale, valid_len=valid_len,
        key_pos_base=cached_len, init=init,
    )
    return _normalize(q, l_f, o_f)


def ring_prefill_attention(mesh, q, k, v, *, scale: float, valid_len=None):
    """Convenience wrapper: shard T over the sp axis and run the ring."""
    from production_stack_tpu.engine.parallel.compat import shard_map
    from jax.sharding import PartitionSpec as P

    from production_stack_tpu.engine.parallel.mesh import AXES

    fn = lambda q_, k_, v_: ring_self_attention(  # noqa: E731
        q_, k_, v_, axis_name=AXES.SP, scale=scale, valid_len=valid_len
    )
    return shard_map(
        fn,
        mesh=mesh,
        in_specs=(P(AXES.SP), P(AXES.SP), P(AXES.SP)),
        out_specs=P(AXES.SP),
        check_vma=False,
    )(q, k, v)
