"""Ring attention: causal self-attention sharded over the sequence axis.

Long-context prefill that exceeds one chip's HBM runs with the sequence
split over the ``sp`` mesh axis: each device keeps its query shard resident
while K/V shards rotate around the ring via ``lax.ppermute`` (ICI
neighbor-to-neighbor), accumulating with an online-softmax (flash-style
log-sum-exp merge).  Compute on the current shard overlaps the transfer of
the next — XLA pipelines the ppermute with the einsum.

The reference stack has no sequence parallelism anywhere (SURVEY.md section
2.7: long context is handled purely by KV offload); this is a TPU-native
capability on top of parity.

Called inside ``shard_map`` over the mesh, e.g.:

    out = shard_map(
        lambda q, k, v: ring_self_attention(q, k, v, axis_name="sp", scale=s),
        mesh=mesh,
        in_specs=(P("sp", None, None),) * 3,
        out_specs=P("sp", None, None),
    )(q, k, v)
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _chunk_attention(
    q: jax.Array,  # [Tq, H, D]
    k: jax.Array,  # [Tk, K, D]
    v: jax.Array,  # [Tk, K, D]
    q_pos: jax.Array,  # [Tq] global positions
    k_pos: jax.Array,  # [Tk] global positions
    scale: float,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Partial attention of one KV chunk: returns (scores_max, exp_sum,
    weighted_values) for online-softmax merging.  Shapes:
    m [H, Tq], l [H, Tq], o [Tq, H, D]."""
    Tq, H, D = q.shape
    K = k.shape[1]
    G = H // K
    qg = q.reshape(Tq, K, G, D)
    scores = jnp.einsum("tkgd,skd->kgts", qg, k, preferred_element_type=jnp.float32)
    scores = scores * scale  # [K, G, Tq, Tk]
    mask = k_pos[None, None, None, :] <= q_pos[None, None, :, None]
    scores = jnp.where(mask, scores, NEG_INF)
    m = jnp.max(scores, axis=-1)  # [K, G, Tq]
    # Guard fully-masked rows: exp(NEG_INF - NEG_INF) would be 1.
    safe_m = jnp.maximum(m, -1e29)
    p = jnp.exp(scores - safe_m[..., None])  # [K, G, Tq, Tk]
    p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1)  # [K, G, Tq]
    o = jnp.einsum(
        "kgts,skd->tkgd", p.astype(v.dtype), v, preferred_element_type=jnp.float32
    )  # [Tq, K, G, D]
    return m, l, o.astype(jnp.float32)


def ring_self_attention(
    q: jax.Array,  # [Tl, H, D] local query shard
    k: jax.Array,  # [Tl, K, D] local key shard
    v: jax.Array,  # [Tl, K, D] local value shard
    *,
    axis_name: str,
    scale: float,
    valid_len: Optional[jax.Array] = None,  # global valid token count
) -> jax.Array:
    """Causal self-attention with K/V rotating around the ring."""
    Tl, H, D = q.shape
    K = k.shape[1]
    G = H // K
    sp = lax.axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)

    q_pos = my_idx * Tl + jnp.arange(Tl)
    if valid_len is not None:
        # Mask padded queries by pushing their positions before all keys.
        q_pos = jnp.where(q_pos < valid_len, q_pos, -1)

    def body(step, carry):
        m_acc, l_acc, o_acc, k_cur, v_cur = carry
        src_idx = (my_idx - step) % sp  # whose shard we currently hold
        k_pos = src_idx * Tl + jnp.arange(Tl)
        if valid_len is not None:
            k_pos = jnp.where(k_pos < valid_len, k_pos, jnp.int32(2**30))
        m_new, l_new, o_new = _chunk_attention(q, k_cur, v_cur, q_pos, k_pos, scale)
        # Online-softmax merge.
        m_tot = jnp.maximum(m_acc, m_new)
        safe = jnp.maximum(m_tot, -1e29)
        alpha = jnp.exp(m_acc - safe)  # [K, G, Tq]
        beta = jnp.exp(m_new - safe)
        l_tot = l_acc * alpha + l_new * beta
        o_scale_old = alpha.transpose(2, 0, 1)[..., None]  # [Tq, K, G, 1]
        o_scale_new = beta.transpose(2, 0, 1)[..., None]
        o_tot = (
            o_acc.reshape(Tl, K, G, D) * o_scale_old
            + o_new.reshape(Tl, K, G, D) * o_scale_new
        ).reshape(Tl, H, D)
        # Rotate K/V to the next device (skip after the last chunk).
        perm = [(i, (i + 1) % sp) for i in range(sp)]
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return m_tot, l_tot, o_tot, k_next, v_next

    m0 = jnp.full((K, G, Tl), NEG_INF, jnp.float32)
    l0 = jnp.zeros((K, G, Tl), jnp.float32)
    o0 = jnp.zeros((Tl, H, D), jnp.float32)
    m_f, l_f, o_f, _, _ = lax.fori_loop(0, sp, body, (m0, l0, o0, k, v))

    denom = jnp.maximum(l_f, 1e-20).transpose(2, 0, 1)[..., None]  # [Tq, K, G, 1]
    out = o_f.reshape(Tl, K, G, D) / denom
    return out.reshape(Tl, H, D).astype(q.dtype)


def ring_prefill_attention(mesh, q, k, v, *, scale: float, valid_len=None):
    """Convenience wrapper: shard T over the sp axis and run the ring."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from production_stack_tpu.engine.parallel.mesh import AXES

    fn = lambda q_, k_, v_: ring_self_attention(  # noqa: E731
        q_, k_, v_, axis_name=AXES.SP, scale=scale, valid_len=valid_len
    )
    return shard_map(
        fn,
        mesh=mesh,
        in_specs=(P(AXES.SP), P(AXES.SP), P(AXES.SP)),
        out_specs=P(AXES.SP),
        check_rep=False,
    )(q, k, v)
