"""SPMD parallelism over the TPU device mesh.

The reference's entire intra-model parallelism story is passing
``--tensor-parallel-size`` to vLLM plus an NCCL shm volume
(SURVEY.md section 2.7).  Here it is first-class and TPU-native: a
``jax.sharding.Mesh`` with (dp, tp, sp) axes, GSPMD-partitioned params and
KV caches (XLA inserts the all-reduces over ICI), and ring attention over
the sp axis for sequences that exceed one chip's HBM.
"""

from production_stack_tpu.engine.parallel.mesh import build_mesh, MeshAxes
from production_stack_tpu.engine.parallel.shardings import (
    kv_cache_shardings,
    param_shardings,
)

__all__ = ["build_mesh", "MeshAxes", "param_shardings", "kv_cache_shardings"]
