"""Schema-constrained structured outputs (``response_format: json_schema``).

vLLM compiles JSON schemas to token-level grammars (outlines/xgrammar);
the same capability here rides the byte-level machinery of
``engine/guided.py``, schema-first: the schema compiles to a SCRIPT of
forced structural literals (braces, canonical ``"key":`` headers,
commas) interleaved with typed VALUE SLOTS the model fills — so output
conforms BY CONSTRUCTION, the model only ever chooses the values, and
the host-side candidate-validation loop (engine._guided_override) works
unchanged because :class:`SchemaGuide` duck-types ``JsonGuide``.

Output is canonical: keys in schema order, no insignificant whitespace.
Supported schema subset (everything the OpenAI structured-outputs strict
mode guarantees for flat-to-moderately-nested tool schemas):

* ``type: object`` with ``properties`` (all treated as required, emitted
  in declaration order; ``additionalProperties`` are never produced),
* scalar types ``string`` / ``number`` / ``integer`` / ``boolean`` /
  ``null``,
* ``enum`` of strings or numbers,
* ``type: array`` with ``items`` (+ ``minItems`` / ``maxItems``),
* nested objects/arrays of all of the above,
* absent/unknown ``type``: a free-form JSON value slot.

Unsupported constructs (``anyOf``, ``$ref``, patterns, numeric ranges)
raise :class:`SchemaCompileError` at request admission — a 400, never a
silently ignored constraint.
"""

from __future__ import annotations

import json
from typing import List, Optional, Tuple

from production_stack_tpu.engine.guided import (
    DONE,
    NUM,
    _N_TERMINAL,
    closure_cost as value_closure_cost,
    initial_state,
    step_byte,
)


class SchemaCompileError(ValueError):
    pass


# -- schema -> nodes -------------------------------------------------------
#
# Node forms (plain tuples, hashable):
#   ("lit", bytes)                       forced literal
#   ("seq", (node, ...))                 fixed sequence
#   ("val", restrict)                    one JSON value; restrict in
#                                        ("", "string", "number",
#                                         "integer", "boolean", "null")
#   ("enum", (bytes, ...))               one of fixed JSON literals
#   ("arr", node, min_items, max_items)  [-1 = unbounded]

_SCALARS = {"string", "number", "integer", "boolean", "null"}
_ANNOTATIONS = {
    "title", "description", "default", "examples", "$schema", "required",
    "additionalProperties",
}


def compile_schema(schema: dict):
    """Schema dict -> node tree.  Raises SchemaCompileError on anything
    outside the supported subset."""
    if not isinstance(schema, dict):
        raise SchemaCompileError("schema must be an object")
    unsupported = {
        k for k in schema
        if k not in _ANNOTATIONS
        and k not in ("type", "properties", "items", "enum", "minItems",
                      "maxItems")
    }
    if unsupported:
        raise SchemaCompileError(
            f"unsupported schema keywords: {sorted(unsupported)}"
        )
    if "enum" in schema:
        choices = []
        for v in schema["enum"]:
            if not isinstance(v, (str, int, float, bool)) and v is not None:
                raise SchemaCompileError("enum values must be scalars")
            choices.append(json.dumps(v).encode())
        if not choices:
            raise SchemaCompileError("enum must be non-empty")
        return ("enum", tuple(choices))
    stype = schema.get("type")
    if stype == "object" or (stype is None and "properties" in schema):
        props = schema.get("properties") or {}
        if not isinstance(props, dict):
            raise SchemaCompileError("'properties' must be an object")
        if not props:
            return ("lit", b"{}")
        parts: List = []
        for i, (key, sub) in enumerate(props.items()):
            header = ("," if i else "") + json.dumps(key) + ":"
            parts.append(("lit", header.encode()))
            parts.append(compile_schema(sub))
        return ("seq", (("lit", b"{"), *parts, ("lit", b"}")))
    if stype == "array":
        items = compile_schema(schema.get("items", {}))
        min_items = int(schema.get("minItems", 0))
        max_items = int(schema.get("maxItems", -1))
        if max_items != -1 and max_items < min_items:
            raise SchemaCompileError("maxItems < minItems")
        return ("arr", items, min_items, max_items)
    if stype in _SCALARS:
        return ("val", stype)
    if stype is None:
        return ("val", "")  # free-form JSON value
    raise SchemaCompileError(f"unsupported type {stype!r}")


def _node_min_len(node) -> int:
    kind = node[0]
    if kind == "lit":
        return len(node[1])
    if kind == "seq":
        return sum(_node_min_len(n) for n in node[1])
    if kind == "enum":
        return min(len(c) for c in node[1])
    if kind == "arr":
        _, items, min_items, _ = node
        if min_items == 0:
            return 2  # []
        return 2 + min_items * _node_min_len(items) + (min_items - 1)
    # val: shortest JSON values per restriction.
    restrict = node[1]
    return {"string": 2, "number": 1, "integer": 1, "boolean": 4,
            "null": 4, "": 1}[restrict]


# -- execution: a stack machine over frames --------------------------------
#
# Frame forms:
#   ("lit", bytes, off)
#   ("seq", nodes, idx)        children entered lazily via _enter
#   ("arr", item_node, count, phase)   phase: "open" | "after"
#   ("val", FSMState, restrict)
#   ("enum", choices, off)

_INT_FORBIDDEN = frozenset(b".eE")
_RESTRICT_FIRST = {
    "string": frozenset(b'"'),
    "number": frozenset(b"-0123456789"),
    "integer": frozenset(b"-0123456789"),
    "boolean": frozenset(b"tf"),
    "null": frozenset(b"n"),
}


def _frame_of(node):
    kind = node[0]
    if kind == "lit":
        return ("lit", node[1], 0)
    if kind == "seq":
        return ("seq", node[1], 0)
    if kind == "arr":
        return ("arr", node[1], node[2], node[3], 0, "open")
    if kind == "enum":
        return ("enum", node[1], 0)
    return ("val", initial_state(require_object=False), node[1])


def _enter(stack: Tuple) -> Tuple:
    """Push child frames until the top is a leaf (lit/val/enum/arr)."""
    while stack:
        top = stack[-1]
        if top[0] == "seq":
            nodes, idx = top[1], top[2]
            if idx >= len(nodes):
                # exhausted seq: pop, advance parent
                stack = _pop(stack[:-1])
                continue
            stack = stack + (_frame_of(nodes[idx]),)
            continue
        return stack
    return stack


def _pop(stack: Tuple) -> Tuple:
    """A child frame completed: advance the parent and re-enter."""
    if not stack:
        return stack
    top = stack[-1]
    if top[0] == "seq":
        advanced = ("seq", top[1], top[2] + 1)
        return _enter(stack[:-1] + (advanced,))
    if top[0] == "arr":
        _, item, mn, mx, count, _phase = top
        return stack[:-1] + (("arr", item, mn, mx, count + 1, "after"),)
    return stack


def _completable(frame) -> bool:
    kind = frame[0]
    if kind == "lit":
        return frame[2] >= len(frame[1])
    if kind == "enum":
        return any(frame[2] == len(c) for c in frame[1])
    if kind == "val":
        st = frame[1]
        return st.mode == DONE or (
            st.mode == NUM and st.aux in _N_TERMINAL and not st.stack
        )
    if kind == "arr":
        return False  # closes only via its ']' byte
    return False


def _frame_step(frame, b: int):
    """Byte into the top frame.  Returns a tuple of replacement frames
    (possibly with a pushed child), or None if the byte doesn't fit."""
    kind = frame[0]
    c = bytes([b])
    if kind == "lit":
        data, off = frame[1], frame[2]
        if off < len(data) and data[off] == b:
            return (("lit", data, off + 1),)
        return None
    if kind == "enum":
        choices, off = frame[1], frame[2]
        nxt = tuple(ch for ch in choices if len(ch) > off and ch[off] == b)
        if not nxt:
            return None
        return (("enum", nxt, off + 1),)
    if kind == "val":
        st, restrict = frame[1], frame[2]
        if st.mode == "value" and not st.stack:
            allowed = _RESTRICT_FIRST.get(restrict)
            if allowed is not None and b not in allowed:
                return None
        if restrict == "integer" and st.mode == NUM and b in _INT_FORBIDDEN:
            return None
        if c in b" \t\n\r" and st.mode != "str":
            # Canonical form: no insignificant whitespace in slots (string
            # CONTENTS may of course contain spaces).
            return None
        ns = step_byte(st, b)
        if ns is None:
            return None
        return (("val", ns, restrict),)
    if kind == "arr":
        _, item, mn, mx, count, phase = frame
        if phase == "open":
            if b != 0x5B:  # [
                return None
            if mn == 0:
                # Either close immediately or start the first element:
                # the ']' case is handled when it arrives (phase after
                # with count 0 allows ']').
                return (("arr", item, mn, mx, 0, "after_open"),)
            return (("arr", item, mn, mx, 0, "elems"), "PUSH")
        if phase == "after_open":
            if b == 0x5D:  # ] — empty array
                return "COMPLETE"
            if mx == 0:
                # maxItems 0: only [] conforms — reject starting an
                # element by construction instead of leaning on the
                # finish-time validate_instance re-check.
                return None
            # First element begins with this byte: push the item frame
            # and re-dispatch.
            return (("arr", item, mn, mx, 0, "elems"), "REPUSH", b)
        if phase == "after":
            if b == 0x2C:  # ,
                if mx != -1 and count >= mx:
                    return None
                return (("arr", item, mn, mx, count, "elems"), "PUSH")
            if b == 0x5D and count >= mn:  # ]
                return "COMPLETE"
            return None
        return None
    return None


def _exhausted(frame) -> bool:
    """Completable AND unable to consume any further byte — such frames
    pop eagerly so ``done`` reads true right after the final byte."""
    kind = frame[0]
    if kind == "lit":
        return frame[2] >= len(frame[1])
    if kind == "enum":
        return all(len(c) <= frame[2] for c in frame[1]) and _completable(
            frame
        )
    if kind == "val":
        # DONE consumes only whitespace, which slots reject.
        return frame[1].mode == DONE
    return False


def _normalize(stack: Tuple) -> Tuple:
    while stack and _exhausted(stack[-1]):
        stack = _pop(stack[:-1])
    return stack


def _machine_step(stack: Tuple, b: int) -> Optional[Tuple]:
    """One byte through the stack machine; None = invalid."""
    if not stack:
        return None  # script complete: nothing may follow
    top = stack[-1]
    result = _frame_step(top, b)
    if result == "COMPLETE":
        return _normalize(_pop(stack[:-1]))
    if result is not None:
        if len(result) >= 2 and result[1] == "PUSH":
            base = stack[:-1] + (result[0],)
            return _normalize(_enter(base + (_frame_of(result[0][1]),)))
        if len(result) >= 2 and result[1] == "REPUSH":
            base = stack[:-1] + (result[0],)
            entered = _enter(base + (_frame_of(result[0][1]),))
            return _machine_step(entered, result[2])
        return _normalize(stack[:-1] + result)
    # Top frame can't take the byte: if it is completable, pop and retry
    # (e.g. a number slot ends exactly when the next literal begins).
    if _completable(top):
        return _machine_step(_pop(stack[:-1]), b)
    return None


def _stack_closure_cost(stack: Tuple) -> int:
    total = 0
    for frame in stack:
        kind = frame[0]
        if kind == "lit":
            total += len(frame[1]) - frame[2]
        elif kind == "enum":
            matching = [len(c) - frame[2] for c in frame[1]
                        if len(c) >= frame[2]]
            total += min(matching) if matching else 0
        elif kind == "val":
            total += value_closure_cost(frame[1])
        elif kind == "seq":
            nodes, idx = frame[1], frame[2]
            # idx's child (if any) rides as its own frame above this one;
            # count only the elements AFTER it.
            total += sum(_node_min_len(n) for n in nodes[idx + 1:])
        elif kind == "arr":
            _, item, mn, mx, count, phase = frame
            if phase == "open":
                total += _node_min_len(("arr", item, mn, mx))
            else:
                remaining = max(mn - count, 0)
                # when an element is in flight (frames above), it is
                # counted by those frames; each remaining element costs
                # a ',' + its minimal bytes; plus the closing ']'.
                total += remaining * (1 + _node_min_len(item)) + 1
    return total


def _poppable_to_empty(stack: Tuple) -> bool:
    """Could the script complete HERE, with every in-flight frame at a
    valid end state?  Root-position scalars make this genuinely
    ambiguous (after "42" an integer may end or grow another digit), so
    completion is a CHOICE the engine expresses by picking EOS — see
    SchemaGuide.may_finish."""
    while stack:
        if not _completable(stack[-1]):
            return False
        stack = _pop(stack[:-1])
    return True


_COMPILE_CACHE: dict = {}


def compile_schema_cached(schema: dict):
    """compile_schema with a content-keyed cache: admission validates
    the schema and the per-sequence guides reuse the same node tree."""
    key = json.dumps(schema, sort_keys=True)
    node = _COMPILE_CACHE.get(key)
    if node is None:
        node = compile_schema(schema)
        if len(_COMPILE_CACHE) > 256:
            _COMPILE_CACHE.clear()
        _COMPILE_CACHE[key] = node
    return node


class SchemaGuide:
    """Duck-types :class:`engine.guided.JsonGuide` for the engine's
    host-side candidate-validation loop, but over a schema script."""

    def __init__(self, schema: dict):
        self.root = compile_schema_cached(schema)
        self.schema = schema
        self.stack: Tuple = _enter((_frame_of(("seq", (self.root,))),))
        self.closing = False

    @property
    def done(self) -> bool:
        return not self.stack

    def may_finish(self) -> bool:
        """True when EOS is a valid choice: every in-flight frame sits at
        a valid end state (root scalars: "42" may end OR grow; nested
        positions complete via their following structural byte instead)."""
        return _poppable_to_empty(self.stack)

    def finalize(self) -> None:
        """The engine chose EOS at a may_finish() point: collapse the
        remaining completable frames so ``done`` holds."""
        assert self.may_finish()
        self.stack = ()

    def closure_cost(self) -> int:
        return _stack_closure_cost(self.stack)

    def try_token(self, token_bytes: bytes):
        if not token_bytes:
            return None
        stack = self.stack
        for b in token_bytes:
            stack = _machine_step(stack, b)
            if stack is None:
                return None
        if self.closing and _stack_closure_cost(stack) >= self.closure_cost():
            return None
        return stack

    def accept(self, new_stack, token_bytes: bytes) -> None:
        self.stack = new_stack


# -- minimal instance validator (finish-time re-check + tests) -------------


def validate_instance(schema: dict, value) -> bool:
    """Does ``value`` conform?  Mirrors exactly the compile subset."""
    if "enum" in schema:
        return any(value == v for v in schema["enum"])
    stype = schema.get("type")
    if stype == "object" or (stype is None and "properties" in schema):
        if not isinstance(value, dict):
            return False
        props = schema.get("properties") or {}
        if set(value) != set(props):
            return False
        return all(validate_instance(sub, value[k])
                   for k, sub in props.items())
    if stype == "array":
        if not isinstance(value, list):
            return False
        mn = int(schema.get("minItems", 0))
        mx = int(schema.get("maxItems", -1))
        if len(value) < mn or (mx != -1 and len(value) > mx):
            return False
        items = schema.get("items", {})
        return all(validate_instance(items, v) for v in value)
    if stype == "string":
        return isinstance(value, str)
    if stype == "integer":
        return isinstance(value, int) and not isinstance(value, bool)
    if stype == "number":
        return isinstance(value, (int, float)) and not isinstance(value, bool)
    if stype == "boolean":
        return isinstance(value, bool)
    if stype == "null":
        return value is None
    return True  # free-form slot
