"""Multi-LoRA serving: static adapter slots, batched application.

Implements proposals/lora-tpu-support.md's engine half.  XLA compiles one
program, so adapter swaps must not change shapes: the engine reserves
``max_loras`` slots of rank-``max_rank`` A/B factors per target projection
at startup.  Loading an adapter is a device-array slice update (no
recompile); slot 0 is the identity (all-zero B) and is what base-model
requests run with, so a LoRA-enabled engine pays one small gather+matmul
pair per projection and nothing else.

Per-sequence selection: decode carries ``adapter_idx [S]`` (each row
gathers its own A/B — MXU-friendly batched einsum); prefill is
single-sequence and uses a scalar index.

HF/peft checkpoint mapping (load_peft_safetensors): peft stores
``lora_A.weight [r, in]`` and ``lora_B.weight [out, r]`` per target; we
store transposed ([in, r], [r, out]) so application is ``(x @ A) @ B``,
scaled by alpha/r.

Reference counterpart: the reference stack's LoRA story is a design doc
(proposals/lora-k8s-support.md); execution would happen inside vLLM's CUDA
LoRA machinery.  Here the TPU engine owns it.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

logger = logging.getLogger(__name__)

# Projections that can carry LoRA factors (HF peft target_modules names).
TARGETS = ("q_proj", "k_proj", "v_proj", "o_proj",
           "gate_proj", "up_proj", "down_proj")


# The slot-count/rank knobs live in config.LoraServingConfig (referenced
# here as ``lora_cfg``); this module owns the arrays and the math.


def _proj_dims(cfg) -> Dict[str, Tuple[int, int]]:
    h, hd = cfg.hidden_size, cfg.head_dim
    H, K, I = cfg.num_heads, cfg.num_kv_heads, cfg.intermediate_size
    dims = {
        "q_proj": (h, H * hd),
        "k_proj": (h, K * hd),
        "v_proj": (h, K * hd),
        "o_proj": (H * hd, h),
    }
    if not getattr(cfg, "num_experts", 0):
        # MoE models (mixtral) have stacked expert MLPs with no flat
        # gate/up/down projections: LoRA there is attention-only, and an
        # adapter shipping MLP factors must fail the load loudly (the
        # validation below rejects unknown projections) rather than load
        # "successfully" with its MLP deltas silently dropped.
        dims.update({
            "gate_proj": (h, I),
            "up_proj": (h, I),
            "down_proj": (I, h),
        })
    return dims


def init_lora_params(model_cfg, lora_cfg, dtype) -> Dict:
    """Zero-initialized slot arrays: {"layers": [{proj: (A, B)}...],
    "scale": [num_slots]}.  Zero B => identity for every slot until loaded."""
    L = lora_cfg.num_slots
    r = lora_cfg.max_rank
    layers = []
    dims = _proj_dims(model_cfg)
    for _ in range(model_cfg.num_layers):
        layer = {}
        for proj, (d_in, d_out) in dims.items():
            layer[proj] = (
                jnp.zeros((L, d_in, r), dtype),
                jnp.zeros((L, r, d_out), dtype),
            )
        layers.append(layer)
    return {"layers": layers, "scale": jnp.zeros((L,), jnp.float32)}


def lora_delta(
    x: jax.Array,  # [T, d_in] (prefill) or [S, d_in] (decode)
    A: jax.Array,  # [L, d_in, r]
    B: jax.Array,  # [L, r, d_out]
    idx: jax.Array,  # scalar (prefill) or [S] (decode, row-aligned)
    scale: jax.Array,  # [L] per-slot alpha/r
) -> jax.Array:
    """fp32 delta ``scale[idx] * (x @ A[idx]) @ B[idx]``."""
    xf = x.astype(jnp.float32)
    if idx.ndim == 0:
        a = A[idx].astype(jnp.float32)  # [d_in, r]
        b = B[idx].astype(jnp.float32)
        return (xf @ a) @ b * scale[idx]
    a = A[idx].astype(jnp.float32)  # [S, d_in, r] row gather
    b = B[idx].astype(jnp.float32)
    t = jnp.einsum("sd,sdr->sr", xf, a)
    return jnp.einsum("sr,sro->so", t, b) * scale[idx][:, None]


class AdapterRegistry:
    """Host-side name -> slot bookkeeping + device array updates.

    Concurrency contract: ``params`` is replaced by a SINGLE attribute
    assignment after the full new tree is built (build-then-swap), so the
    engine step thread — which reads ``registry.params`` once per step —
    always sees a complete old or complete new tree, never a torn mix.
    A failed load raises before the swap and leaves state untouched.
    """

    def __init__(self, model_cfg, lora_cfg, dtype):
        self.model_cfg = model_cfg
        self.lora_cfg = lora_cfg
        self.dtype = dtype
        self.params = init_lora_params(model_cfg, lora_cfg, dtype)
        self._slots: Dict[str, int] = {}  # name -> slot (1..max_loras)
        # Prefix-cache namespaces: a fresh id per LOAD event (not the slot
        # index) — reusing a freed slot, or reloading changed weights under
        # the same name, must never hit KV cached by the previous tenant.
        self._namespaces: Dict[str, int] = {}
        self._next_namespace = 1

    def slot_of(self, name: Optional[str]) -> int:
        if not name:
            return 0
        try:
            return self._slots[name]
        except KeyError:
            raise ValueError(
                f"Unknown LoRA adapter {name!r}; loaded: {sorted(self._slots)}"
            ) from None

    def namespace_of(self, name: Optional[str]) -> int:
        """Prefix-cache namespace for this adapter (0 = base model)."""
        if not name:
            return 0
        self.slot_of(name)  # raises for unknown
        return self._namespaces[name]

    def loaded(self) -> List[str]:
        return sorted(self._slots)

    def load(
        self,
        name: str,
        layer_factors: List[Dict[str, Tuple[np.ndarray, np.ndarray]]],
        rank: int,
        alpha: float,
    ) -> int:
        """Install adapter ``name``; factors are per-layer {proj: (A [in,r],
        B [r,out])} — missing projections stay zero (identity)."""
        if rank > self.lora_cfg.max_rank:
            raise ValueError(
                f"adapter rank {rank} exceeds max_rank {self.lora_cfg.max_rank}"
            )
        if len(layer_factors) != self.model_cfg.num_layers:
            raise ValueError(
                f"adapter has {len(layer_factors)} layers; model has "
                f"{self.model_cfg.num_layers}"
            )
        dims = _proj_dims(self.model_cfg)
        # Validate EVERY shape before the first device write: a mid-loop
        # failure must not leave a half-written adapter serving traffic.
        for li, factors in enumerate(layer_factors):
            for proj, (A_np, B_np) in factors.items():
                if proj not in dims:
                    raise ValueError(f"layer {li}: unknown projection {proj!r}")
                d_in, d_out = dims[proj]
                if A_np.shape != (d_in, rank) or B_np.shape != (rank, d_out):
                    raise ValueError(
                        f"layer {li} {proj}: got A{A_np.shape} B{B_np.shape}, "
                        f"want A({d_in},{rank}) B({rank},{d_out})"
                    )

        slot = self._slots.get(name)
        if slot is None:
            used = set(self._slots.values())
            free = [
                s for s in range(1, self.lora_cfg.num_slots) if s not in used
            ]
            if not free:
                raise ValueError(
                    f"all {self.lora_cfg.max_loras} LoRA slots in use; "
                    f"unload one of {sorted(self._slots)}"
                )
            slot = free[0]

        new_layers = []
        for li, factors in enumerate(layer_factors):
            old_layer = self.params["layers"][li]
            new_layer = {}
            for proj in dims:
                A_dev, B_dev = old_layer[proj]
                d_in, d_out = dims[proj]
                A_full = np.zeros((d_in, self.lora_cfg.max_rank), np.float32)
                B_full = np.zeros((self.lora_cfg.max_rank, d_out), np.float32)
                if proj in factors:
                    A_np, B_np = factors[proj]
                    A_full[:, :rank] = A_np
                    B_full[:rank, :] = B_np
                new_layer[proj] = (
                    A_dev.at[slot].set(jnp.asarray(A_full, self.dtype)),
                    B_dev.at[slot].set(jnp.asarray(B_full, self.dtype)),
                )
            new_layers.append(new_layer)
        new_scale = self.params["scale"].at[slot].set(alpha / rank)
        # Single-assignment swap (see class docstring).
        self.params = {"layers": new_layers, "scale": new_scale}
        self._slots[name] = slot
        self._namespaces[name] = self._next_namespace
        self._next_namespace += 1
        logger.info("LoRA adapter %r loaded into slot %d (rank %d)", name, slot, rank)
        return slot

    def unload(self, name: str) -> None:
        slot = self._slots.pop(name, None)
        if slot is None:
            return
        self._namespaces.pop(name, None)
        # Zeroing B alone makes the slot an identity again; A can stay.
        new_layers = [
            {
                proj: (A_dev, B_dev.at[slot].set(0.0))
                for proj, (A_dev, B_dev) in layer.items()
            }
            for layer in self.params["layers"]
        ]
        new_scale = self.params["scale"].at[slot].set(0.0)
        self.params = {"layers": new_layers, "scale": new_scale}
        logger.info("LoRA adapter %r unloaded from slot %d", name, slot)


def load_peft_safetensors(path: str, num_layers: int):
    """Read an HF/peft adapter_model.safetensors into per-layer factors.
    Returns (layer_factors, rank).  peft names:
    ``base_model.model.model.layers.{i}.self_attn.q_proj.lora_A.weight``."""
    from safetensors import safe_open

    with safe_open(path, framework="np") as f:
        tensors = {k: f.get_tensor(k) for k in f.keys()}
    layer_factors: List[Dict] = [{} for _ in range(num_layers)]
    rank = None
    for key, value in tensors.items():
        if ".layers." not in key or ".lora_" not in key:
            continue
        li = int(key.split(".layers.")[1].split(".")[0])
        proj = next((p for p in TARGETS if f".{p}." in key), None)
        if proj is None or li >= num_layers:
            continue
        a_part = ".lora_A." in key
        A, B = layer_factors[li].get(proj, (None, None))
        if a_part:
            A = value.T  # [r, in] -> [in, r]
            rank = value.shape[0]
        else:
            B = value.T  # [out, r] -> [r, out]
        layer_factors[li][proj] = (A, B)
    if rank is None:
        raise ValueError(f"no lora_A tensors found in {path}")
    for li, factors in enumerate(layer_factors):
        for proj, (A, B) in list(factors.items()):
            if A is None or B is None:
                raise ValueError(f"layer {li} {proj}: incomplete A/B pair")
    return layer_factors, rank
