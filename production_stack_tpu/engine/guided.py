"""Guided decoding: JSON-constrained generation (response_format).

The OpenAI ``response_format: {"type": "json_object"}`` contract — the
model's output must parse as a JSON object.  The reference stack proxies
whatever its engine supports; vLLM implements this with grammar FSMs
(outlines/xgrammar).  TPU twist: rather than shipping a [V]-wide allowed
mask to the device every step (a per-token host->HBM transfer that would
defeat fused decode), sampling for guided sequences moves host-side: the
logits row comes back once per token and candidates are validated in
probability order against a byte-level JSON pushdown automaton until one
fits.  Typically the first candidate is already valid, so the common cost
is one FSM simulation per token.

The automaton accepts exactly the JSON value grammar (RFC 8259: objects,
arrays, strings with escapes incl. \\uXXXX, numbers, true/false/null,
insignificant whitespace), tracks nesting with an explicit stack, and for
``json_object`` requires the top-level value to be an object.  When the
value completes, only whitespace may follow and EOS becomes the forced
choice.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

WS = b" \t\n\r"
DIGITS = b"0123456789"
HEX = b"0123456789abcdefABCDEF"

# Scalar modes (the stack holds container contexts).
V_START = "value"  # expecting a value
STR = "str"  # inside a string
STR_ESC = "esc"  # after backslash
STR_U = "u"  # inside \uXXXX (state carries remaining hex count)
NUM = "num"  # inside a number (sub-state tracks part)
LIT = "lit"  # inside true/false/null (state carries remainder)
AFTER = "after"  # a value just closed: , } ] or end
OBJ_KEY = "okey"  # expecting a key string (or })
OBJ_COLON = "colon"  # expecting :
DONE = "done"  # top-level value complete: whitespace only

_LITERALS = (b"true", b"false", b"null")

# Number sub-states: what the next byte may be.
N_SIGN = "sign"  # after leading '-'
N_INT = "int"  # in integer part
N_Z = "zero"  # leading zero consumed (no more int digits)
N_DOT = "dot"  # after '.' (need digit)
N_FRAC = "frac"  # in fraction digits
N_E = "e"  # after e/E (need sign or digit)
N_ESIGN = "esign"  # after exponent sign (need digit)
N_EXP = "exp"  # in exponent digits

# A number is "complete" (may be followed by , } ] ws) in these sub-states.
_N_TERMINAL = {N_INT, N_Z, N_FRAC, N_EXP}


@dataclasses.dataclass(frozen=True)
class FSMState:
    mode: str = V_START
    stack: Tuple[str, ...] = ()  # "{" and "[" container contexts
    aux: str = ""  # literal remainder / number sub-state / hex count


def initial_state(require_object: bool = True) -> FSMState:
    # require_object: json_object mode — the first non-ws byte must be '{'.
    return FSMState(mode=V_START, stack=(), aux="{" if require_object else "")


def _close_value(state: FSMState) -> FSMState:
    """A value finished: what comes next depends on the container."""
    if not state.stack:
        return FSMState(mode=DONE, stack=(), aux="")
    return FSMState(mode=AFTER, stack=state.stack, aux="")


def step_byte(state: FSMState, b: int) -> Optional[FSMState]:
    """One byte through the automaton; None = invalid."""
    c = bytes([b])
    mode = state.mode

    if mode == DONE:
        return state if c in WS else None

    if mode == STR:
        if b == 0x22:  # closing quote
            # A key string closes into the colon state; a value string
            # closes the value.
            if state.aux == "key":
                return FSMState(OBJ_COLON, state.stack, "")
            return _close_value(state)
        if b == 0x5C:  # backslash
            return FSMState(STR_ESC, state.stack, state.aux)
        if b < 0x20:  # control chars must be escaped
            return None
        return state

    if mode == STR_ESC:
        if c in b'"\\/bfnrt':
            return FSMState(STR, state.stack, state.aux)
        if b == 0x75:  # u
            return FSMState(STR_U, state.stack, state.aux + "|4")
        return None

    if mode == STR_U:
        if c not in HEX:
            return None
        aux, n = state.aux.rsplit("|", 1)
        n = int(n) - 1
        if n == 0:
            return FSMState(STR, state.stack, aux)
        return FSMState(STR_U, state.stack, f"{aux}|{n}")

    if mode == LIT:
        if state.aux and b == state.aux.encode()[0]:
            rest = state.aux[1:]
            if rest:
                return FSMState(LIT, state.stack, rest)
            return _close_value(state)
        return None

    if mode == NUM:
        sub = state.aux
        if c in DIGITS:
            if sub in (N_SIGN, N_INT):
                # "0" may not be followed by more int digits.
                if sub == N_SIGN and b == 0x30:
                    return FSMState(NUM, state.stack, N_Z)
                return FSMState(NUM, state.stack, N_INT)
            if sub == N_Z:
                return None
            if sub in (N_DOT, N_FRAC):
                return FSMState(NUM, state.stack, N_FRAC)
            if sub in (N_E, N_ESIGN, N_EXP):
                return FSMState(NUM, state.stack, N_EXP)
        if b == 0x2E and sub in (N_INT, N_Z):  # .
            return FSMState(NUM, state.stack, N_DOT)
        if c in b"eE" and sub in _N_TERMINAL - {N_EXP}:
            return FSMState(NUM, state.stack, N_E)
        if c in b"+-" and sub == N_E:
            return FSMState(NUM, state.stack, N_ESIGN)
        if sub in _N_TERMINAL:
            # The number ends; re-dispatch this byte in the closed state.
            return step_byte(_close_value(state), b)
        return None

    if mode == OBJ_KEY:
        if c in WS:
            return state
        if b == 0x22:
            return FSMState(STR, state.stack, "key")
        if b == 0x7D:
            if state.aux == "first":
                return None  # {..., } — trailing comma
            return step_close_container(state, "}")
        return None

    if mode == OBJ_COLON:
        if c in WS:
            return state
        if b == 0x3A:  # :
            return FSMState(V_START, state.stack, "")
        return None

    if mode == AFTER:
        if c in WS:
            return state
        top = state.stack[-1]
        if b == 0x2C:  # ,
            if top == "{":
                return FSMState(OBJ_KEY, state.stack, "first")
            return FSMState(V_START, state.stack, "")
        if b == 0x7D and top == "{":
            return step_close_container(state, "}")
        if b == 0x5D and top == "[":
            return step_close_container(state, "]")
        return None

    if mode == V_START:
        if c in WS:
            return state
        if state.aux == "{" and b != 0x7B:
            return None  # json_object: top level must be an object
        if b == 0x7B:  # {
            return FSMState(OBJ_KEY, state.stack + ("{",), "")
        if b == 0x5B:  # [
            # An array may immediately close.
            return FSMState(V_START, state.stack + ("[",), "maybe_empty")
        if b == 0x5D and state.stack and state.stack[-1] == "[" \
                and state.aux == "maybe_empty":
            return step_close_container(state, "]")
        if b == 0x22:
            return FSMState(STR, state.stack, "")
        if b == 0x2D:  # -
            return FSMState(NUM, state.stack, N_SIGN)
        if b == 0x30:
            return FSMState(NUM, state.stack, N_Z)
        if c in DIGITS:
            return FSMState(NUM, state.stack, N_INT)
        for lit in _LITERALS:
            if b == lit[0]:
                rest = lit[1:].decode()
                if rest:
                    return FSMState(LIT, state.stack, rest)
                return _close_value(state)
        return None

    return None


def step_close_container(state: FSMState, _which: str) -> FSMState:
    popped = FSMState(state.mode, state.stack[:-1], "")
    return _close_value(popped)


def advance_bytes(state: FSMState, data: bytes) -> Optional[FSMState]:
    for b in data:
        state = step_byte(state, b)
        if state is None:
            return None
    return state


def closure_cost(state: FSMState) -> int:
    """Lower bound on the bytes needed to complete the JSON value from
    ``state`` (each open container costs its closer; a string its quote;
    an object key its quote+colon+minimal value; ...).  Drives the
    budget-aware closing mode."""
    depth = len(state.stack)
    mode = state.mode
    if mode == DONE:
        return 0
    if mode == AFTER:
        return depth
    if mode == STR:
        # The leading 1 is the closing quote; a key then needs ':' plus a
        # minimal value (2 more).
        extra = 2 if state.aux == "key" else 0
        return 1 + extra + depth
    if mode == STR_ESC:
        return 2 + depth + (2 if state.aux == "key" else 0)
    if mode == STR_U:
        n = int(state.aux.rsplit("|", 1)[1])
        return 1 + n + depth + (2 if "key" in state.aux else 0)
    if mode == NUM:
        return depth if state.aux in _N_TERMINAL else 1 + depth
    if mode == LIT:
        return len(state.aux) + depth
    if mode == OBJ_KEY:
        if state.aux == "first":  # after comma: a key is mandatory
            return 4 + depth  # "" : v  then closers
        return depth  # '}' closes directly
    if mode == OBJ_COLON:
        return 2 + depth  # ':' + minimal value
    if mode == V_START:
        if state.aux == "{":
            return 2  # {}
        return 1 + depth  # minimal value then closers
    return depth


class JsonGuide:
    """Per-sequence guided-decoding state + token validation.

    Two anti-stall measures for models that wander inside the (infinite)
    JSON language: consecutive whitespace-only tokens are capped, and
    when the engine reports the remaining token budget is close to the
    closure cost, ``closing`` mode admits only tokens that strictly
    reduce it — the value completes instead of truncating mid-string."""

    MAX_WS_RUN = 2

    def __init__(self, require_object: bool = True):
        self.state = initial_state(require_object)
        self.ws_run = 0
        self.closing = False

    @property
    def done(self) -> bool:
        return self.state.mode == DONE

    def may_finish(self) -> bool:
        # A closed top-level JSON value is unambiguous: done == may end.
        return self.done

    def finalize(self) -> None:
        pass  # done already holds when may_finish() does

    def closure_cost(self) -> int:
        return closure_cost(self.state)

    @staticmethod
    def _is_ws(token_bytes: bytes) -> bool:
        return all(bytes([b]) in WS for b in token_bytes)

    def try_token(self, token_bytes: bytes) -> Optional[FSMState]:
        """State after consuming token_bytes, or None if any byte is
        invalid.  Empty-text tokens are invalid (no progress).  Pure:
        several candidates may be tried before one is accept()ed."""
        if not token_bytes:
            return None
        if self._is_ws(token_bytes) and self.ws_run >= self.MAX_WS_RUN:
            return None
        state = advance_bytes(self.state, token_bytes)
        if state is None:
            return None
        if self.closing and closure_cost(state) >= closure_cost(self.state):
            return None
        return state

    def accept(self, new_state: FSMState, token_bytes: bytes) -> None:
        self.state = new_state
        self.ws_run = self.ws_run + 1 if self._is_ws(token_bytes) else 0


class TokenTextCache:
    """token id -> decoded text, computed once per tokenizer (the guided
    sampler validates candidates in probability order)."""

    def __init__(self, tokenizer):
        self.tokenizer = tokenizer
        self._cache: dict = {}

    def text(self, token_id: int) -> str:
        got = self._cache.get(token_id)
        if got is None:
            got = self.tokenizer.decode([token_id])
            self._cache[token_id] = got
        return got
