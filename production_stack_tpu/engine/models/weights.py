"""Checkpoint loading: HF safetensors -> our functional param trees.

Zero-egress friendly: if no checkpoint directory is given (or it is
missing), models fall back to deterministic random init — throughput
benchmarking and scale testing need correct shapes, not trained weights.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from production_stack_tpu.engine.config import ModelConfig

logger = logging.getLogger(__name__)


def load_params(
    cfg: ModelConfig,
    weights_path: Optional[str],
    *,
    seed: int = 0,
):
    """Load HF-layout safetensors if available, else random init."""
    from production_stack_tpu.engine.models import llama

    if weights_path and os.path.isdir(weights_path):
        try:
            return load_hf_safetensors(cfg, weights_path)
        except Exception:
            logger.exception(
                "Failed to load weights from %s; falling back to random init",
                weights_path,
            )
    return llama.init_params(cfg, jax.random.PRNGKey(seed))


def _open_safetensors(weights_path: str) -> Dict[str, np.ndarray]:
    """Read all tensors from one or more *.safetensors shards."""
    from safetensors import safe_open  # ships with transformers

    tensors: Dict[str, np.ndarray] = {}
    index_file = os.path.join(weights_path, "model.safetensors.index.json")
    if os.path.exists(index_file):
        with open(index_file) as f:
            index = json.load(f)
        shards = sorted(set(index["weight_map"].values()))
    else:
        shards = sorted(
            f for f in os.listdir(weights_path) if f.endswith(".safetensors")
        )
    for shard in shards:
        with safe_open(os.path.join(weights_path, shard), framework="np") as f:
            for name in f.keys():
                tensors[name] = f.get_tensor(name)
    return tensors


def load_hf_safetensors(cfg: ModelConfig, weights_path: str):
    """Map HF LlamaForCausalLM tensor names into our layout.

    torch Linear stores [out, in]; we store [in, out], hence the transposes
    (see models/llama.py docstring).
    """
    sd = _open_safetensors(weights_path)
    dtype = jnp.dtype(cfg.dtype)

    def take(name: str, transpose: bool = False) -> jax.Array:
        arr = sd[name]
        if transpose:
            arr = arr.T
        return jnp.asarray(arr, dtype)

    params = {
        "embed_tokens": take("model.embed_tokens.weight"),
        "norm": take("model.norm.weight"),
        "layers": [],
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = take("lm_head.weight", transpose=True)
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}."
        layer = {
            "input_layernorm": take(p + "input_layernorm.weight"),
            "post_attention_layernorm": take(
                p + "post_attention_layernorm.weight"
            ),
            "q_proj": take(p + "self_attn.q_proj.weight", transpose=True),
            "k_proj": take(p + "self_attn.k_proj.weight", transpose=True),
            "v_proj": take(p + "self_attn.v_proj.weight", transpose=True),
            "o_proj": take(p + "self_attn.o_proj.weight", transpose=True),
        }
        if cfg.num_experts:
            # Mixtral: block_sparse_moe.gate + per-expert w1/w3/w2
            # (gate/up/down), stacked into [E, ...] arrays.
            moe = p + "block_sparse_moe."
            layer["gate"] = take(moe + "gate.weight", transpose=True)
            layer["experts_gate"] = jnp.stack([
                take(moe + f"experts.{e}.w1.weight", transpose=True)
                for e in range(cfg.num_experts)
            ])
            layer["experts_up"] = jnp.stack([
                take(moe + f"experts.{e}.w3.weight", transpose=True)
                for e in range(cfg.num_experts)
            ])
            layer["experts_down"] = jnp.stack([
                take(moe + f"experts.{e}.w2.weight", transpose=True)
                for e in range(cfg.num_experts)
            ])
        else:
            layer["gate_proj"] = take(p + "mlp.gate_proj.weight", transpose=True)
            layer["up_proj"] = take(p + "mlp.up_proj.weight", transpose=True)
            layer["down_proj"] = take(p + "mlp.down_proj.weight", transpose=True)
        if cfg.attention_bias:
            # Qwen2-style QKV biases (HF Qwen2Attention has bias=True on
            # q/k/v projections only).
            layer["q_bias"] = take(p + "self_attn.q_proj.bias")
            layer["k_bias"] = take(p + "self_attn.k_proj.bias")
            layer["v_bias"] = take(p + "self_attn.v_proj.bias")
        params["layers"].append(layer)
    logger.info("Loaded %d tensors from %s", len(sd), weights_path)
    return params
