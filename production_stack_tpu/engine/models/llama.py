"""Llama-family model (functional JAX, paged-KV attention).

Weight layout matches HF ``LlamaForCausalLM`` modulo transposition (we store
[in, out] so the forward is ``x @ W``); loaders in weights.py map HF
safetensors names directly.  Correctness is pinned against the HF torch
implementation in tests/test_llama_vs_hf.py.

Covers the whole RMSNorm+RoPE+gated-MLP decoder family via ModelConfig
switches: Llama 3.x (GQA, rope_theta, tied embeddings), Mistral
(sliding_window), Qwen2 (QKV biases), Mixtral (sparse MoE, _moe_mlp), and
Gemma (zero-centered norms, tanh GeGLU, sqrt(h) embedding scale, decoupled
head_dim/MQA) — each pinned against its HF torch implementation in
tests/test_llama_vs_hf.py.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from production_stack_tpu.engine.parallel.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from production_stack_tpu.engine.config import ModelConfig
from production_stack_tpu.engine.ops import attention as attn_ops
from production_stack_tpu.engine.ops.layers import (
    apply_rope,
    rms_norm,
    rope_cos_sin,
    swiglu,
)
from production_stack_tpu.engine.parallel.mesh import AXES

Params = Dict
KVCaches = List[Tuple[jax.Array, jax.Array]]


def _sp_size(mesh: Optional[Mesh]) -> int:
    return mesh.shape[AXES.SP] if mesh is not None else 1


def _constrain(x: jax.Array, mesh: Optional[Mesh], spec: P) -> jax.Array:
    """Pin an activation's sharding (no-op off-mesh)."""
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def param_dtype(cfg: ModelConfig):
    return jnp.dtype(cfg.dtype)


def init_params(cfg: ModelConfig, key: jax.Array) -> Params:
    """Random init with HF-compatible tree structure."""
    dtype = param_dtype(cfg)
    h, hd = cfg.hidden_size, cfg.head_dim
    H, K, I = cfg.num_heads, cfg.num_kv_heads, cfg.intermediate_size

    def dense(key, shape, scale=0.02):
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(dtype)

    keys = jax.random.split(key, cfg.num_layers + 3)
    params: Params = {
        "embed_tokens": dense(keys[0], (cfg.vocab_size, h)),
        "norm": jnp.ones((h,), dtype),
        "layers": [],
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = dense(keys[1], (h, cfg.vocab_size))
    for i in range(cfg.num_layers):
        lk = jax.random.split(keys[i + 3], 8)
        layer = {
            "input_layernorm": jnp.ones((h,), dtype),
            "post_attention_layernorm": jnp.ones((h,), dtype),
            "q_proj": dense(lk[0], (h, H * hd)),
            "k_proj": dense(lk[1], (h, K * hd)),
            "v_proj": dense(lk[2], (h, K * hd)),
            "o_proj": dense(lk[3], (H * hd, h)),
        }
        if cfg.num_experts:
            E = cfg.num_experts
            layer["gate"] = dense(lk[7], (h, E))
            layer["experts_gate"] = dense(lk[4], (E, h, I))
            layer["experts_up"] = dense(lk[5], (E, h, I))
            layer["experts_down"] = dense(lk[6], (E, I, h))
        else:
            layer["gate_proj"] = dense(lk[4], (h, I))
            layer["up_proj"] = dense(lk[5], (h, I))
            layer["down_proj"] = dense(lk[6], (I, h))
        if cfg.attention_bias:
            # Qwen2-style QKV biases (o_proj stays bias-free there).
            layer["q_bias"] = jnp.zeros((H * hd,), dtype)
            layer["k_bias"] = jnp.zeros((K * hd,), dtype)
            layer["v_bias"] = jnp.zeros((K * hd,), dtype)
        params["layers"].append(layer)
    return params


def _norm(x: jax.Array, weight: jax.Array, cfg: ModelConfig) -> jax.Array:
    return rms_norm(x, weight, cfg.rms_norm_eps, cfg.rms_norm_offset)


def _act(x: jax.Array, cfg: ModelConfig) -> jax.Array:
    if cfg.hidden_act == "gelu_tanh":  # gemma
        return jax.nn.gelu(x, approximate=True)
    return jax.nn.silu(x)


def _embed(params: Params, cfg: ModelConfig, tokens: jax.Array) -> jax.Array:
    x = params["embed_tokens"][tokens]
    if cfg.scale_embeddings:  # gemma: sqrt(h) in the input dtype
        x = x * jnp.asarray(cfg.hidden_size**0.5, x.dtype)
    return x


def _dot(x: jax.Array, w) -> jax.Array:
    """Projection matmul, fp32 accumulation.  ``w`` is either a plain
    [in, out] array or an int8 weight-only pair {"q": int8 [in, out],
    "s": f32 [out]} (quantize_params).  For the quantized form the convert
    fuses into the MXU operand read — int8 is what streams from HBM — and
    the per-out-channel scale applies to the small output:
    x @ (q * s) == (x @ q) * s."""
    if isinstance(w, dict):
        y = jnp.dot(x, w["q"].astype(x.dtype), preferred_element_type=jnp.float32)
        return y * w["s"]
    return jnp.dot(x, w, preferred_element_type=jnp.float32)


_QUANT_TARGETS = (
    "q_proj", "k_proj", "v_proj", "o_proj",
    "gate_proj", "up_proj", "down_proj",
)


def quantize_params(params: Params, cfg: ModelConfig) -> Params:
    """Per-out-channel symmetric int8 quantization of the projection
    weights (and lm_head).  Embeddings, norms, biases, and MoE expert
    stacks keep the model dtype — the dense projections are where decode's
    weight traffic is."""
    if cfg.quantization is None:
        return params

    def qw(w):
        w32 = w.astype(jnp.float32)
        amax = jnp.max(jnp.abs(w32), axis=0)
        s = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
        q = jnp.clip(jnp.round(w32 / s), -127, 127).astype(jnp.int8)
        return {"q": q, "s": s}

    out = dict(params)
    out["layers"] = []
    for layer in params["layers"]:
        new = dict(layer)
        for name in _QUANT_TARGETS:
            if name in layer:
                new[name] = qw(layer[name])
        out["layers"].append(new)
    if "lm_head" in params:
        out["lm_head"] = qw(params["lm_head"])
    return out


def _maybe_lora(y, x, lora_layer, proj, adapter_idx, lora_scale):
    """Add the LoRA delta for ``proj`` when adapters are live (lora.py)."""
    if lora_layer is None:
        return y
    from production_stack_tpu.engine.lora import lora_delta

    A, B = lora_layer[proj]
    return y + lora_delta(x, A, B, adapter_idx, lora_scale)


def _project_qkv(layer: Params, x: jax.Array, cfg: ModelConfig,
                 lora_layer=None, adapter_idx=None, lora_scale=None):
    """x: [T, h] -> q [T, H, D], k/v [T, K, D]."""
    T = x.shape[0]
    q = _dot(x, layer["q_proj"])
    k = _dot(x, layer["k_proj"])
    v = _dot(x, layer["v_proj"])
    q = _maybe_lora(q, x, lora_layer, "q_proj", adapter_idx, lora_scale)
    k = _maybe_lora(k, x, lora_layer, "k_proj", adapter_idx, lora_scale)
    v = _maybe_lora(v, x, lora_layer, "v_proj", adapter_idx, lora_scale)
    if cfg.attention_bias:
        q = q + layer["q_bias"].astype(jnp.float32)
        k = k + layer["k_bias"].astype(jnp.float32)
        v = v + layer["v_bias"].astype(jnp.float32)
    q = q.astype(x.dtype).reshape(T, cfg.num_heads, cfg.head_dim)
    k = k.astype(x.dtype).reshape(T, cfg.num_kv_heads, cfg.head_dim)
    v = v.astype(x.dtype).reshape(T, cfg.num_kv_heads, cfg.head_dim)
    return q, k, v


def _o_proj(layer: Params, out: jax.Array, lora_layer, adapter_idx, lora_scale):
    y = _dot(out, layer["o_proj"])
    return _maybe_lora(y, out, lora_layer, "o_proj", adapter_idx, lora_scale)


def _moe_mlp(layer: Params, x: jax.Array, cfg: ModelConfig) -> jax.Array:
    """Mixtral-style sparse MoE block: full-softmax router, top-k
    renormalized weights, SwiGLU experts.

    TPU-first layout: expert weights are STACKED ``[E, ...]`` arrays
    sharded over the tp mesh axis (parallel/shardings.py) — each device
    runs its E/tp experts over all tokens and GSPMD reduces the weighted
    sum.  Every token mathematically visits every (local) expert with its
    routing weight (zero outside the top-k): static shapes, no
    capacity-overflow token dropping, no host-side sorting.  The
    megablocks-style block-sparse dispatch kernel is the optimization
    path once profiling justifies it; this formulation is the correctness
    and sharding reference.
    """
    E, k = cfg.num_experts, cfg.num_experts_per_tok
    router_logits = jnp.dot(
        x, layer["gate"], preferred_element_type=jnp.float32
    )  # [T, E]
    probs = jax.nn.softmax(router_logits, axis=-1)
    top_vals, top_idx = jax.lax.top_k(probs, k)  # [T, k]
    top_vals = top_vals / jnp.sum(top_vals, axis=-1, keepdims=True)
    # Dense routing-weight matrix [T, E]: top-k weights, zero elsewhere.
    weights = jnp.sum(
        jax.nn.one_hot(top_idx, E, dtype=jnp.float32) * top_vals[..., None],
        axis=1,
    )

    gate = jnp.einsum(
        "th,ehi->tei", x, layer["experts_gate"],
        preferred_element_type=jnp.float32,
    )
    up = jnp.einsum(
        "th,ehi->tei", x, layer["experts_up"],
        preferred_element_type=jnp.float32,
    )
    activated = (_act(gate, cfg) * up).astype(x.dtype)
    down = jnp.einsum(
        "tei,eih->teh", activated, layer["experts_down"],
        preferred_element_type=jnp.float32,
    )  # [T, E, h]
    out = jnp.einsum("te,teh->th", weights, down)
    return out.astype(x.dtype)


def _mlp(layer: Params, x: jax.Array, lora_layer, adapter_idx, lora_scale,
         cfg: ModelConfig):
    """Gated MLP with optional LoRA on gate/up/down (matches ops/layers.py
    swiglu exactly when lora_layer is None); dispatches to the sparse MoE
    block for mixtral-style configs (LoRA then applies to attention only)."""
    if cfg.num_experts:
        return _moe_mlp(layer, x, cfg)
    if lora_layer is None and not isinstance(layer["gate_proj"], dict):
        return swiglu(
            x, layer["gate_proj"], layer["up_proj"], layer["down_proj"],
            act=cfg.hidden_act,
        )
    gate = _dot(x, layer["gate_proj"])
    up = _dot(x, layer["up_proj"])
    gate = _maybe_lora(gate, x, lora_layer, "gate_proj", adapter_idx, lora_scale)
    up = _maybe_lora(up, x, lora_layer, "up_proj", adapter_idx, lora_scale)
    activated = (_act(gate, cfg) * up).astype(x.dtype)
    down = _dot(activated, layer["down_proj"])
    down = _maybe_lora(
        down, activated, lora_layer, "down_proj", adapter_idx, lora_scale
    )
    return down.astype(x.dtype)


def _lm_head(params: Params, cfg: ModelConfig, hidden: jax.Array) -> jax.Array:
    """hidden [..., h] -> logits [..., V] in fp32."""
    if cfg.tie_word_embeddings:
        return jnp.dot(
            hidden, params["embed_tokens"].T, preferred_element_type=jnp.float32
        )
    return _dot(hidden, params["lm_head"])


def prefill(
    params: Params,
    cfg: ModelConfig,
    tokens: jax.Array,  # [T] int32 (padded to a bucket)
    cached_len: jax.Array,  # scalar int32: prefix tokens already in cache
    prefix_block_ids: jax.Array,  # [P] int32 (0-padded)
    new_block_ids: jax.Array,  # [T // block_size] int32 (null-padded)
    valid_len: jax.Array,  # scalar int32: true number of new tokens
    kv_caches: KVCaches,
    mesh: Optional[Mesh] = None,  # SPMD mesh; sp>1 -> ring/ulysses attention
    lora: Optional[Dict] = None,  # LoRA slot arrays (lora.py); None = off
    adapter_idx: Optional[jax.Array] = None,  # scalar slot for this seq
    sp_mode: str = "ring",  # sequence-parallel strategy when sp>1
    prompt_targets: Optional[jax.Array] = None,  # [T] int32 next-token ids
    prompt_topk: int = 0,  # static: top-k alternatives per prompt position
) -> Tuple[jax.Array, KVCaches]:
    """One sequence's prefill.  Returns (last-token logits [V], new caches);
    with ``prompt_targets`` set, returns (logits, caches, (target_logprob
    [T], top_ids [T, k], top_logps [T, k])) — the per-position
    next-token logprobs the OpenAI ``echo`` + ``logprobs`` surface needs
    (lm-eval-harness loglikelihood scoring).  The lm_head sweep runs in
    row chunks so the full [T, V] logits are never materialized.

    Under a mesh, the token axis is sharded over ``sp`` (every projection /
    MLP matmul computes on T/sp rows per device) and attention runs the
    ring (parallel/ring_attention.py) so no device ever materializes the
    full [T, T] score matrix; head/channel dims are sharded over ``tp``
    (GSPMD inserts the psum after o_proj / down_proj)."""
    T = tokens.shape[0]
    scale = cfg.head_dim**-0.5
    use_ring = _sp_size(mesh) > 1
    positions = cached_len + jnp.arange(T)
    cos, sin = rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta,
                            cfg.rope_scaling)

    x = _embed(params, cfg, tokens)  # [T, h]
    x = _constrain(x, mesh, P(AXES.SP, None))
    lora_scale = lora["scale"] if lora is not None else None
    new_caches: KVCaches = []
    for li, (layer, (k_cache, v_cache)) in enumerate(
        zip(params["layers"], kv_caches)
    ):
        lora_layer = lora["layers"][li] if lora is not None else None
        residual = x
        x_n = _norm(x, layer["input_layernorm"], cfg)
        q, k, v = _project_qkv(
            layer, x_n, cfg, lora_layer, adapter_idx, lora_scale
        )
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        k_prefix, v_prefix = attn_ops.gather_prefix_kv(
            k_cache, v_cache, prefix_block_ids, dtype=k.dtype
        )
        if use_ring:
            if sp_mode == "ulysses":
                from production_stack_tpu.engine.parallel.ulysses import (
                    ulysses_prefill_with_prefix,
                )

                sp_attention = partial(
                    ulysses_prefill_with_prefix,
                    sliding_window=cfg.sliding_window,
                )
            else:
                # The ring does not implement sliding windows;
                # validate_sp_mode rejects windowed models under ring sp>1
                # rather than silently widening the receptive field.
                from production_stack_tpu.engine.parallel.ring_attention import (
                    ring_prefill_with_prefix as sp_attention,
                )

            out = shard_map(
                partial(
                    sp_attention, axis_name=AXES.SP, scale=scale
                ),
                mesh=mesh,
                in_specs=(
                    P(AXES.SP, AXES.TP, None),  # q [T, H, D]
                    P(AXES.SP, AXES.TP, None),  # k [T, K, D]
                    P(AXES.SP, AXES.TP, None),  # v
                    P(AXES.SP, AXES.TP, None),  # k_prefix (ring-sharded too)
                    P(AXES.SP, AXES.TP, None),  # v_prefix
                    P(),  # cached_len
                    P(),  # valid_len
                ),
                out_specs=P(AXES.SP, AXES.TP, None),
                check_vma=False,
            )(q, k, v, k_prefix, v_prefix, cached_len, valid_len)
        else:
            out = attn_ops.prefill_attention(
                q, k, v, k_prefix, v_prefix, cached_len, valid_len,
                scale=scale, sliding_window=cfg.sliding_window, mesh=mesh,
            )
        k_cache, v_cache = attn_ops.write_prefill_kv(
            k_cache, v_cache, k, v, new_block_ids
        )
        new_caches.append((k_cache, v_cache))
        out = out.reshape(T, cfg.num_heads * cfg.head_dim)
        x = residual + _o_proj(
            layer, out, lora_layer, adapter_idx, lora_scale
        ).astype(x.dtype)
        residual = x
        x_n = _norm(x, layer["post_attention_layernorm"], cfg)
        x = residual + _mlp(layer, x_n, lora_layer, adapter_idx, lora_scale, cfg)

    x = _norm(x, params["norm"], cfg)
    last = x[jnp.maximum(valid_len - 1, 0)]  # [h]
    logits = _lm_head(params, cfg, last)
    if prompt_targets is None:
        return logits, new_caches

    # Chunked lm_head sweep: [C, V] at a time (T=2048, V=128k fp32 would
    # be ~1 GB if materialized whole).  C must divide T (buckets are
    # free-form CLI ints, e.g. 192).
    C = math.gcd(T, 128)
    k = max(prompt_topk, 1)
    rows = x.reshape(T // C, C, cfg.hidden_size)
    tgts = prompt_targets.reshape(T // C, C)

    def head_chunk(args):
        r, t = args
        lg = _lm_head(params, cfg, r)  # [C, V] fp32
        lsm = jax.nn.log_softmax(lg, axis=-1)
        tlp = jnp.take_along_axis(lsm, t[:, None], axis=-1)[:, 0]
        top_lp, top_id = jax.lax.top_k(lsm, k)
        return tlp, top_id.astype(jnp.int32), top_lp

    tlp, top_ids, top_lps = jax.lax.map(head_chunk, (rows, tgts))
    plp = (
        tlp.reshape(T),
        top_ids.reshape(T, k),
        top_lps.reshape(T, k),
    )
    return logits, new_caches, plp


def encode(
    params: Params,
    cfg: ModelConfig,
    tokens: jax.Array,  # [T] int32 (padded to a bucket)
    valid_len: jax.Array,  # scalar int32
    mesh: Optional[Mesh] = None,  # routes attention off Pallas under tp/sp
) -> jax.Array:
    """Embedding forward: causal self-attention over the prompt, returning
    the mean of the final-layer hidden states over valid tokens,
    L2-normalized — the /v1/embeddings path.  No KV bookkeeping: the
    sequence is processed once and discarded, so attention runs with an
    empty prefix and the per-layer K/V stay in registers/VMEM."""
    T = tokens.shape[0]
    scale = cfg.head_dim**-0.5
    positions = jnp.arange(T)
    cos, sin = rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta,
                            cfg.rope_scaling)
    empty_k = jnp.zeros((0, cfg.num_kv_heads, cfg.head_dim), cfg.dtype)
    empty_v = empty_k

    x = _embed(params, cfg, tokens)  # [T, h]
    for layer in params["layers"]:
        residual = x
        x_n = _norm(x, layer["input_layernorm"], cfg)
        q, k, v = _project_qkv(layer, x_n, cfg)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        out = attn_ops.prefill_attention(
            q, k, v, empty_k, empty_v, jnp.int32(0), valid_len,
            scale=scale, sliding_window=cfg.sliding_window, mesh=mesh,
        )
        out = out.reshape(T, cfg.num_heads * cfg.head_dim)
        x = residual + _o_proj(layer, out, None, None, None).astype(x.dtype)
        residual = x
        x_n = _norm(x, layer["post_attention_layernorm"], cfg)
        x = residual + _mlp(layer, x_n, None, None, None, cfg)

    x = _norm(x, params["norm"], cfg).astype(jnp.float32)
    mask = (jnp.arange(T) < valid_len)[:, None]
    pooled = jnp.sum(x * mask, axis=0) / jnp.maximum(valid_len, 1)
    return pooled / jnp.maximum(jnp.linalg.norm(pooled), 1e-9)


def encode_batch(
    params: Params,
    cfg: ModelConfig,
    tokens: jax.Array,  # [B, T] int32 (B and T both padded to buckets)
    valid_lens: jax.Array,  # [B] int32 (0 for padding rows)
    mesh: Optional[Mesh] = None,
) -> jax.Array:
    """Batched embedding forward: B independent ``encode`` passes fused
    into one dispatch — the encode lane's [B, T]-bucketed executable.
    Unsharded we vmap the single-text encode (one wide kernel); under a
    tp/sp mesh the shard_map'd attention inside ``encode`` is not
    vmappable, so rows run under ``jax.lax.map`` instead (still one
    dispatch, B sequential shard_map bodies).  Returns [B, hidden]
    L2-normalized float32 vectors; padding rows (valid_len 0) produce
    garbage vectors the caller drops."""
    if mesh is None:
        return jax.vmap(
            lambda t, v: encode(params, cfg, t, v, mesh=None)
        )(tokens, valid_lens)
    return jax.lax.map(
        lambda tv: encode(params, cfg, tv[0], tv[1], mesh=mesh),
        (tokens, valid_lens),
    )


def mixed_step(
    params: Params,
    cfg: ModelConfig,
    dec_tokens: jax.Array,  # [S] int32, one token per decoding sequence
    dec_positions: jax.Array,  # [S] int32 (=ctx_len-1)
    dec_block_tables: jax.Array,  # [S, Bmax] int32
    dec_ctx_lens: jax.Array,  # [S] int32 incl. the new token
    dec_slot_block_ids: jax.Array,  # [S] int32 block receiving the token
    dec_slot_offsets: jax.Array,  # [S] int32 offset within that block
    pf_tokens: jax.Array,  # [T] int32 prefill chunk (padded to a bucket)
    pf_cached_len: jax.Array,  # scalar int32: prefix tokens already cached
    pf_prefix_block_ids: jax.Array,  # [P] int32 (0-padded)
    pf_new_block_ids: jax.Array,  # [T // block_size] int32 (null-padded)
    pf_valid_len: jax.Array,  # scalar int32: true number of chunk tokens
    kv_caches: KVCaches,
    mesh: Optional[Mesh] = None,  # tp-only mesh (engine gates dp/sp to 1)
    lora: Optional[Dict] = None,
    adapter_idx: Optional[jax.Array] = None,  # [S+T] row-aligned slots
) -> Tuple[jax.Array, KVCaches]:
    """Fused mixed step: S decoding sequences' next tokens AND one
    sequence's prefill chunk in a single forward over the packed
    ``[S + T]`` token batch.  Returns (logits [S+1, V], new caches):
    rows 0..S-1 are the decode batch, row S is the chunk's last valid
    token (only meaningful on a final chunk).

    The win is shared weight streaming: every projection/MLP matmul runs
    once over S+T rows, so the decode batch — which would otherwise sit
    idle for a whole prefill bucket when a prompt arrives — pays zero
    extra HBM weight traffic for riding along.  Attention splits by
    segment: decode rows use paged attention over their block tables
    exactly like :func:`decode`; the chunk runs flash/dense prefill
    attention against its accumulated prefix blocks exactly like
    :func:`prefill`.  The two segments touch disjoint KV slots (decode
    appends land in each sequence's own tail block; the chunk writes its
    freshly allocated blocks and reads its ref-counted prefix), so the
    within-layer update order is immaterial.

    lm_head runs on S+1 rows only — the full [T, V] chunk logits are
    never materialized (mid-prompt rows have no consumer)."""
    S = dec_tokens.shape[0]
    T = pf_tokens.shape[0]
    scale = cfg.head_dim**-0.5
    positions = jnp.concatenate(
        [dec_positions, pf_cached_len + jnp.arange(T, dtype=jnp.int32)]
    )
    cos, sin = rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta,
                            cfg.rope_scaling)

    x = _embed(params, cfg, jnp.concatenate([dec_tokens, pf_tokens]))
    lora_scale = lora["scale"] if lora is not None else None
    new_caches: KVCaches = []
    for li, (layer, (k_cache, v_cache)) in enumerate(
        zip(params["layers"], kv_caches)
    ):
        lora_layer = lora["layers"][li] if lora is not None else None
        residual = x
        x_n = _norm(x, layer["input_layernorm"], cfg)
        q, k, v = _project_qkv(
            layer, x_n, cfg, lora_layer, adapter_idx, lora_scale
        )
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        # Decode segment: write-then-attend, like decode().
        k_cache, v_cache = attn_ops.append_decode_kv(
            k_cache, v_cache, k[:S], v[:S],
            dec_slot_block_ids, dec_slot_offsets,
        )
        out_dec = attn_ops.decode_attention(
            q[:S], k_cache, v_cache, dec_block_tables, dec_ctx_lens,
            scale=scale, sliding_window=cfg.sliding_window, mesh=mesh,
        )
        # Prefill segment: attend over prefix + chunk, then scatter the
        # chunk's KV into its new blocks.
        k_prefix, v_prefix = attn_ops.gather_prefix_kv(
            k_cache, v_cache, pf_prefix_block_ids, dtype=k.dtype
        )
        out_pf = attn_ops.prefill_attention(
            q[S:], k[S:], v[S:], k_prefix, v_prefix,
            pf_cached_len, pf_valid_len,
            scale=scale, sliding_window=cfg.sliding_window, mesh=mesh,
        )
        k_cache, v_cache = attn_ops.write_prefill_kv(
            k_cache, v_cache, k[S:], v[S:], pf_new_block_ids
        )
        new_caches.append((k_cache, v_cache))
        out = jnp.concatenate([out_dec, out_pf]).reshape(
            S + T, cfg.num_heads * cfg.head_dim
        )
        x = residual + _o_proj(
            layer, out, lora_layer, adapter_idx, lora_scale
        ).astype(x.dtype)
        residual = x
        x_n = _norm(x, layer["post_attention_layernorm"], cfg)
        x = residual + _mlp(layer, x_n, lora_layer, adapter_idx, lora_scale, cfg)

    x = _norm(x, params["norm"], cfg)
    tail = x[S + jnp.maximum(pf_valid_len - 1, 0)]  # chunk's last valid row
    head_rows = jnp.concatenate([x[:S], tail[None, :]], axis=0)  # [S+1, h]
    return _lm_head(params, cfg, head_rows), new_caches


def decode(
    params: Params,
    cfg: ModelConfig,
    tokens: jax.Array,  # [S] int32, one token per sequence (padded batch)
    positions: jax.Array,  # [S] int32 position of each token (=ctx_len-1)
    block_tables: jax.Array,  # [S, Bmax] int32
    ctx_lens: jax.Array,  # [S] int32 context length incl. the new token
    slot_block_ids: jax.Array,  # [S] int32 block receiving the new token
    slot_offsets: jax.Array,  # [S] int32 offset within that block
    kv_caches: KVCaches,
    mesh: Optional[Mesh] = None,  # SPMD mesh; batch sharded over dp
    lora: Optional[Dict] = None,  # LoRA slot arrays (lora.py); None = off
    adapter_idx: Optional[jax.Array] = None,  # [S] slot per sequence
) -> Tuple[jax.Array, KVCaches]:
    """Batched single-token decode.  Returns (logits [S, V], new caches).

    Under a mesh the batch axis is sharded over ``dp`` (each dp group
    decodes S/dp sequences) and heads over ``tp``; the paged KV pool is
    replicated across dp so any sequence can land on any dp group."""
    S = tokens.shape[0]
    scale = cfg.head_dim**-0.5
    cos, sin = rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta,
                            cfg.rope_scaling)

    x = _embed(params, cfg, tokens)  # [S, h]
    x = _constrain(x, mesh, P(AXES.DP, None))
    lora_scale = lora["scale"] if lora is not None else None
    new_caches: KVCaches = []
    for li, (layer, (k_cache, v_cache)) in enumerate(
        zip(params["layers"], kv_caches)
    ):
        lora_layer = lora["layers"][li] if lora is not None else None
        residual = x
        x_n = _norm(x, layer["input_layernorm"], cfg)
        q, k, v = _project_qkv(
            layer, x_n, cfg, lora_layer, adapter_idx, lora_scale
        )
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        # The new token's KV must be visible to its own attention: write
        # first, then attend (ctx_lens already includes the new token).
        k_cache, v_cache = attn_ops.append_decode_kv(
            k_cache, v_cache, k, v, slot_block_ids, slot_offsets
        )
        out = attn_ops.decode_attention(
            q, k_cache, v_cache, block_tables, ctx_lens,
            scale=scale, sliding_window=cfg.sliding_window, mesh=mesh,
        )
        new_caches.append((k_cache, v_cache))
        out = out.reshape(S, cfg.num_heads * cfg.head_dim)
        x = residual + _o_proj(
            layer, out, lora_layer, adapter_idx, lora_scale
        ).astype(x.dtype)
        residual = x
        x_n = _norm(x, layer["post_attention_layernorm"], cfg)
        x = residual + _mlp(layer, x_n, lora_layer, adapter_idx, lora_scale, cfg)

    x = _norm(x, params["norm"], cfg)
    return _lm_head(params, cfg, x), new_caches
