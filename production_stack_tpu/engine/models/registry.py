"""Model registry: architecture name -> functional model module.

Each module exposes ``init_params(cfg, key)``, ``prefill(...)``,
``decode(...)`` with the signatures defined in llama.py.
"""

from __future__ import annotations

from types import ModuleType

from production_stack_tpu.engine.models import llama

MODEL_REGISTRY = {
    # llama.py covers every RMSNorm+RoPE+GQA+gated-MLP family member; the
    # config (not the code) differentiates them — including QKV biases
    # (qwen2), sparse MoE (mixtral), and gemma's norm-offset/GeGLU/
    # embedding-scale switches.
    "llama": llama,
    "mistral": llama,
    "mixtral": llama,
    "qwen2": llama,
    "gemma": llama,
}


def get_model(architecture: str) -> ModuleType:
    arch = architecture.lower()
    for key, module in MODEL_REGISTRY.items():
        if key in arch:
            return module
    raise ValueError(
        f"Unsupported architecture {architecture!r}; known: {sorted(MODEL_REGISTRY)}"
    )
