"""Model registry: architecture name -> functional model module.

Each module exposes ``init_params(cfg, key)``, ``prefill(...)``,
``decode(...)`` with the signatures defined in llama.py.
"""

from __future__ import annotations

from types import ModuleType

from production_stack_tpu.engine.models import llama

MODEL_REGISTRY = {
    # llama.py covers every RMSNorm+RoPE+GQA+SwiGLU family member; the
    # config (not the code) differentiates them.
    "llama": llama,
    "mistral": llama,
    "qwen2": llama,
}


def get_model(architecture: str) -> ModuleType:
    arch = architecture.lower()
    for key, module in MODEL_REGISTRY.items():
        if key in arch:
            return module
    raise ValueError(
        f"Unsupported architecture {architecture!r}; known: {sorted(MODEL_REGISTRY)}"
    )
