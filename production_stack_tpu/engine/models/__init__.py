"""Model zoo: decoder-only transformer families, functional JAX style.

``llama.py`` covers the Llama 2/3(.x) and Mistral/Qwen-style architectures
(RMSNorm + rotate-half RoPE + GQA + SwiGLU, optional sliding window).
``opt.py`` covers OPT (learned positions + ReLU MLP + pre-LN) for tiny CPU
smoke deployments (the reference's facebook/opt-125m minimal install,
tutorials/assets/values-01-minimal-example.yaml).
"""

from production_stack_tpu.engine.models.registry import get_model, MODEL_REGISTRY

__all__ = ["get_model", "MODEL_REGISTRY"]
