"""Core numeric ops for the TPU engine.

Pure-JAX reference implementations live here; Pallas TPU kernels for the hot
paths live in ``pallas/`` and are selected at runtime on TPU backends.
"""
