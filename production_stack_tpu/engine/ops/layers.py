"""Norm / rotary-embedding primitives.

Conventions match the HF llama family exactly (rotate-half RoPE, RMSNorm in
fp32) so real checkpoints load without weight surgery; verified against
transformers' torch implementation in tests/test_llama_vs_hf.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(
    x: jax.Array, weight: jax.Array, eps: float, offset: float = 0.0
) -> jax.Array:
    """RMSNorm computed in fp32, cast back to input dtype.

    ``offset=1.0`` gives the gemma convention (zero-centered weights,
    output scaled by ``1 + w``)."""
    orig_dtype = x.dtype
    x32 = x.astype(jnp.float32)
    variance = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    normed = x32 * jax.lax.rsqrt(variance + eps)
    return (normed * (offset + weight.astype(jnp.float32))).astype(orig_dtype)


def rope_cos_sin(positions: jax.Array, head_dim: int, theta: float,
                 rope_scaling=None):
    """cos/sin tables for rotate-half RoPE at the given positions.

    positions: int array [...]; returns cos/sin of shape [..., head_dim]
    (frequencies duplicated across both halves, HF convention).

    ``rope_scaling`` supports the llama-3.1 "llama3" scheme (HF
    modeling_rope_utils._compute_llama3_parameters): low-frequency bands
    (long wavelengths) are divided by ``factor``, high-frequency bands
    kept, and the middle band smoothly interpolated — how the 3.1 family
    stretches an 8k-trained RoPE to 128k contexts.
    """
    half = head_dim // 2
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, half, dtype=jnp.float32) / half)
    )
    if rope_scaling is not None:
        factor = float(rope_scaling["factor"])
        low = float(rope_scaling.get("low_freq_factor", 1.0))
        high = float(rope_scaling.get("high_freq_factor", 4.0))
        orig = float(
            rope_scaling.get("original_max_position_embeddings", 8192)
        )
        low_freq_wavelen = orig / low
        high_freq_wavelen = orig / high
        wavelen = 2.0 * jnp.pi / inv_freq
        scaled = inv_freq / factor
        smooth = (orig / wavelen - low) / (high - low)
        interp = (1.0 - smooth) * scaled + smooth * inv_freq
        inv_freq = jnp.where(
            wavelen < high_freq_wavelen,
            inv_freq,
            jnp.where(wavelen > low_freq_wavelen, scaled, interp),
        )
    freqs = positions.astype(jnp.float32)[..., None] * inv_freq  # [..., half]
    emb = jnp.concatenate([freqs, freqs], axis=-1)  # [..., head_dim]
    return jnp.cos(emb), jnp.sin(emb)


def _rotate_half(x: jax.Array) -> jax.Array:
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([-x2, x1], axis=-1)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x: [..., num_heads, head_dim]; cos/sin: [..., head_dim] (no head axis)."""
    cos = cos[..., None, :]
    sin = sin[..., None, :]
    x32 = x.astype(jnp.float32)
    out = x32 * cos + _rotate_half(x32) * sin
    return out.astype(x.dtype)


def swiglu(
    x: jax.Array,
    gate_w: jax.Array,
    up_w: jax.Array,
    down_w: jax.Array,
    act: str = "silu",
) -> jax.Array:
    """Gated MLP: down( act(x @ gate) * (x @ up) ), bf16 matmuls on MXU.
    ``act``: "silu" (llama/mistral/qwen) or "gelu_tanh" (gemma GeGLU)."""
    gate = jnp.dot(x, gate_w, preferred_element_type=jnp.float32)
    up = jnp.dot(x, up_w, preferred_element_type=jnp.float32)
    if act == "gelu_tanh":
        gated = jax.nn.gelu(gate, approximate=True)
    else:
        gated = jax.nn.silu(gate)
    activated = (gated * up).astype(x.dtype)
    return jnp.dot(activated, down_w, preferred_element_type=jnp.float32).astype(x.dtype)
