"""Pallas TPU decode-attention kernel over the paged KV cache.

The decode step is HBM-bandwidth bound: each new token must read every live
KV block of its sequence once.  The pure-JAX gather path
(ops/attention.py:paged_decode_attention) pays that read **three times**
(gather-read, materialize-write, attention-read) and always over the full
``Bmax``-padded block table.  This kernel streams each sequence's actual
blocks HBM->VMEM exactly once with double-buffered async DMA and an online
softmax, and its per-sequence loop bound is the *real* context length, so a
256-token sequence in an 8k-token pool touches 16 blocks, not 512.

Blocks are fetched in chunks of ``chunk_blocks`` per pipeline stage: one
16-token block is too small to amortize DMA issue latency or fill the MXU,
so each stage issues ``chunk_blocks`` parallel block DMAs (their latencies
overlap in the DMA engine) and runs one online-softmax update over the
whole ``chunk_blocks * block_size``-token tile.

Grid: one program per sequence.  The block table and context lengths ride
in SMEM via scalar prefetch so DMA source indices are computable before the
body runs.  Accumulation is fp32 (softmax on the VPU, score/value matmuls
on the MXU).

Replaces the role CUDA PagedAttention kernels play inside the reference's
external vLLM engine (the reference itself ships no kernels — SURVEY.md
preamble; its engine containers do, helm/templates/deployment-vllm-multi.yaml:57-64).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _decode_kernel(
    # scalar prefetch (SMEM)
    block_tables_ref,  # [S, Bmax] int32
    ctx_lens_ref,  # [S] int32
    # inputs: q_ref, k_hbm, v_hbm[, ks_hbm, vs_hbm] (int8 cache scales)
    # outputs: o_ref
    # scratch: k_buf, v_buf[, ks_buf, vs_buf], sems
    *refs,
    bs: int,
    chunk_blocks: int,
    num_kv_heads: int,
    q_per_kv: int,
    head_dim: int,
    scale: float,
    sliding_window: Optional[int],
    quantized: bool,
):
    if quantized:
        (q_ref, k_hbm, v_hbm, ks_hbm, vs_hbm, o_ref,
         k_buf, v_buf, ks_buf, vs_buf, sems) = refs
    else:
        q_ref, k_hbm, v_hbm, o_ref, k_buf, v_buf, sems = refs
        ks_hbm = vs_hbm = ks_buf = vs_buf = None
    s = pl.program_id(0)
    ctx = ctx_lens_ref[s]
    nb = (ctx + bs - 1) // bs  # live KV blocks for this sequence
    C = chunk_blocks
    nc = (nb + C - 1) // C  # dynamic trip count: only live chunks
    K, G, D = num_kv_heads, q_per_kv, head_dim
    T = C * bs  # tokens per pipeline stage

    # fp32 query, pre-scaled; head h = k*G + g attends kv head k (GQA).
    q = (q_ref[0].reshape(K, G, D).astype(jnp.float32)) * scale

    def block_id(j):
        # Chunk-tail blocks past nb read table slot 0 (the null block) —
        # a valid, masked-out DMA source (tables are 0-padded).
        return block_tables_ref[s, jnp.minimum(j, nb - 1) * (j < nb)]

    def dma(cache, buf, kv, slot, c, j):
        return pltpu.make_async_copy(
            cache.at[block_id(j)], buf.at[slot, c], sems.at[kv, slot, c]
        )

    streams = [(k_hbm, k_buf, 0), (v_hbm, v_buf, 1)]
    if quantized:
        # Scale planes ride the same pipeline (tiny: [bs, K] fp32/block).
        streams += [(ks_hbm, ks_buf, 2), (vs_hbm, vs_buf, 3)]

    def start_chunk(slot, chunk):
        for c in range(C):  # static unroll: C parallel DMA issues
            for cache, buf, kv in streams:
                dma(cache, buf, kv, slot, c, chunk * C + c).start()

    def wait_chunk(slot, chunk):
        for c in range(C):
            for cache, buf, kv in streams:
                dma(cache, buf, kv, slot, c, chunk * C + c).wait()

    # Padded batch slots (ctx == 0) must not start DMAs: an un-waited DMA
    # leaves its semaphore signaled and poisons the next grid step's waits.
    @pl.when(nc > 0)
    def _():
        start_chunk(0, 0)

    def body(i, carry):
        m, l, acc = carry
        slot = jax.lax.rem(i, 2)
        nxt = jax.lax.rem(i + 1, 2)

        @pl.when(i + 1 < nc)
        def _():
            start_chunk(nxt, i + 1)

        wait_chunk(slot, i)
        # [C, bs, K, D] -> [K, T, D] (Mosaic needs lhs/rhs batch dims in
        # matching positions, so the kv-head axis moves to the front;
        # merging the leading dims is layout-free, D stays the lane dim).
        k = k_buf[slot].astype(jnp.float32).reshape(T, K, D).swapaxes(0, 1)
        v = v_buf[slot].astype(jnp.float32).reshape(T, K, D).swapaxes(0, 1)
        if quantized:
            # Per-(token, head) scales: [C, bs, K] -> [K, T, 1].
            ks = ks_buf[slot].astype(jnp.float32).reshape(T, K) \
                .swapaxes(0, 1)[..., None]
            vs = vs_buf[slot].astype(jnp.float32).reshape(T, K) \
                .swapaxes(0, 1)[..., None]
            k = k * ks
            v = v * vs

        # [K, G, D] x [K, T, D] -> [K, G, T]  (batch over kv heads)
        scores = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        pos = i * T + jax.lax.broadcasted_iota(jnp.int32, (1, 1, T), 2)
        mask = pos < ctx
        if sliding_window is not None:
            mask &= pos > ctx - 1 - sliding_window
        scores = jnp.where(mask, scores, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(scores, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        # [K, G, T] x [K, T, D] -> [K, G, D]
        pv = jax.lax.dot_general(
            p, v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc * alpha + pv

    m0 = jnp.full((K, G, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((K, G, 1), jnp.float32)
    acc0 = jnp.zeros((K, G, D), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, nc, body, (m0, l0, acc0))

    # Padded batch slots have ctx==0 -> l==0; emit zeros, not NaNs (their
    # logits are sliced off on the host, but NaN-free keeps debugging sane).
    l = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / l).reshape(K * G, D).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "sliding_window", "chunk_blocks", "interpret"),
)
def paged_decode_attention_pallas(
    q: jax.Array,  # [S, H, D]
    k_cache: jax.Array,  # [N, bs, K, D]
    v_cache: jax.Array,  # [N, bs, K, D]
    block_tables: jax.Array,  # [S, Bmax] int32 (0 = null block)
    ctx_lens: jax.Array,  # [S] int32 (0 for padded slots)
    *,
    scale: float,
    sliding_window: Optional[int] = None,
    chunk_blocks: int = 8,
    interpret: bool = False,
) -> jax.Array:
    """Decode attention over paged KV, streaming blocks HBM->VMEM.

    ``k_cache``/``v_cache`` may be int8 (data, scale) tuples
    (kv/quant.py): the scale planes stream through the same
    double-buffered pipeline and the dequantize (one VPU multiply per
    element) happens in VMEM — HBM traffic is the int8 bytes plus ~3%
    scales, the whole point of the mode.
    """
    from production_stack_tpu.engine.kv import quant as kv_quant

    quantized = kv_quant.is_quantized(k_cache)
    S, H, D = q.shape
    N, bs, K, _ = kv_quant.cache_shape(k_cache)
    G = H // K
    C = min(chunk_blocks, block_tables.shape[1])
    if D % 128 and not interpret:
        # The DMA slice needs a 128-lane-aligned head_dim on real TPU;
        # dispatch (ops/attention.py) keeps such models on the gather
        # path.  Interpret mode (CPU tests) has no tiling constraint.
        raise ValueError(f"pallas decode kernel requires head_dim%128==0, got {D}")

    kernel = functools.partial(
        _decode_kernel,
        bs=bs,
        chunk_blocks=C,
        num_kv_heads=K,
        q_per_kv=G,
        head_dim=D,
        scale=scale,
        sliding_window=sliding_window,
        quantized=quantized,
    )
    cache_in_specs = [pl.BlockSpec(memory_space=pl.ANY)] * (
        4 if quantized else 2
    )
    scratch = [
        pltpu.VMEM((2, C, bs, K, D),
                   jnp.int8 if quantized else k_cache.dtype),
        pltpu.VMEM((2, C, bs, K, D),
                   jnp.int8 if quantized else v_cache.dtype),
    ]
    if quantized:
        scratch += [
            pltpu.VMEM((2, C, bs, K), k_cache[1].dtype),
            pltpu.VMEM((2, C, bs, K), v_cache[1].dtype),
        ]
    scratch.append(pltpu.SemaphoreType.DMA((4 if quantized else 2, 2, C)))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S,),
        in_specs=[
            pl.BlockSpec((1, H, D), lambda s, *_: (s, 0, 0)),
            *cache_in_specs,  # caches (+ scale planes) stay in HBM
        ],
        out_specs=pl.BlockSpec((1, H, D), lambda s, *_: (s, 0, 0)),
        scratch_shapes=scratch,
    )
    inputs = (
        (q, k_cache[0], v_cache[0], k_cache[1], v_cache[1])
        if quantized else (q, k_cache, v_cache)
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, H, D), q.dtype),
        interpret=interpret,
    )(block_tables, ctx_lens, *inputs)
