"""Pallas TPU flash-attention kernel for prefill (causal + cached prefix).

Why the dense path stalls at ~0.44 MFU: ops/attention.py:prefill_attention
materializes the full fp32 score/prob tensors — [K, G, T, C+T] is ~430 MB
for a 2k-token llama-3.2-3b prefill, far beyond VMEM, so XLA spills them
to HBM and the MXU waits on bandwidth.  This kernel never materializes
scores in HBM: each program owns one Tq-row query tile (all heads), keeps
the full key/value rows resident in VMEM (a few MB at serving lengths),
streams them in Tk-column slices with an online softmax, and stops at the
causal frontier so upper-triangle waste is bounded by one Tk slice per
tile.

Layout notes (Mosaic): blocks keep the (head, lane) dims whole — q tiles
are [Tq, H, D], keys [S_k, K, D] — because Mosaic requires the last two
block dims divisible by (8, 128) or equal to the array's.  GQA regrouping
happens in-register via the same swapaxes/reshape moves the decode kernel
uses (paged_attention.py:114-115); both matmuls are K-batched dot_generals
contracting the lane dim, so no transposes are materialized.

Position/validity semantics match the dense path exactly
(ops/attention.py:128-143): key j < C is prefix slot j (valid while
j < cached_len), key j >= C is new token j-C at position cached_len+(j-C)
(valid while j-C < valid_len); query row t sits at cached_len + t.

Replaces the role of FlashAttention prefill kernels inside the reference's
external vLLM engine (the reference ships no kernels — SURVEY.md preamble).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _flash_prefill_kernel(
    # scalar prefetch (SMEM)
    cached_len_ref,  # [1] int32
    valid_len_ref,  # [1] int32
    # inputs (VMEM blocks)
    q_ref,  # [Tq, H, D] this tile's queries, all heads
    k_ref,  # [S_k, K, D] the full (padded) key row
    v_ref,  # [S_k, K, D]
    # outputs
    o_ref,  # [Tq, H, D]
    *,
    Tq: int,
    Tk: int,
    C: int,
    S_k: int,
    K: int,
    G: int,
    D: int,
    scale: float,
    sliding_window: Optional[int],
):
    i = pl.program_id(0)
    cached = cached_len_ref[0]
    valid = valid_len_ref[0]
    R = Tq * G  # query rows per kv head after GQA regrouping

    # [Tq, H, D] -> [K, Tq*G, D]: head h = k*G + g attends kv head k.
    q = q_ref[...].astype(jnp.float32) * scale
    q = q.reshape(Tq, K, G, D).swapaxes(0, 1).reshape(K, R, D)

    # Query positions per GQA-regrouped row r = t*G + g: row r's query
    # token is t = r // G.  Masks are built 2-D [R, Tk] and broadcast into
    # the 3-D scores ([K, R, Tk] where mask[None] — the exact pattern the
    # decode kernel lowers with); 4-D mask ops and bool-valued selects both
    # stall Mosaic.
    row_t = jax.lax.broadcasted_iota(jnp.int32, (R, 1), 0) // G
    q_pos = cached + i * Tq + row_t  # [R, 1]

    # Causal frontier: the tile's last query sits at cached + (i+1)*Tq - 1
    # and can see prefix keys (flat index < C) plus new keys with flat
    # index < C + (i+1)*Tq.  Slices wholly past that are skipped.
    frontier = C + (i + 1) * Tq
    nk = jax.lax.min((frontier + Tk - 1) // Tk, S_k // Tk)

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[pl.dslice(j * Tk, Tk)].astype(jnp.float32)  # [Tk, K, D]
        v = v_ref[pl.dslice(j * Tk, Tk)].astype(jnp.float32)
        k = k.swapaxes(0, 1)  # [K, Tk, D]
        v = v.swapaxes(0, 1)

        # [K, R, D] x [K, Tk, D] -> [K, R, Tk] (batch over kv heads).
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )

        flat = j * Tk + jax.lax.broadcasted_iota(jnp.int32, (1, Tk), 1)
        is_prefix = flat < C
        key_pos = jnp.where(is_prefix, flat, cached + flat - C)  # int select
        key_valid = (is_prefix & (flat < cached)) | (
            ~is_prefix & (flat - C < valid)
        )
        mask = key_valid & (key_pos <= q_pos)  # [R, Tk]
        if sliding_window is not None:
            mask &= key_pos > q_pos - sliding_window
        s = jnp.where(mask[None], s, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        # [K, R, Tk] x [K, Tk, D] -> [K, R, D]
        pv = jax.lax.dot_general(
            p, v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc * alpha + pv

    m0 = jnp.full((K, R, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((K, R, 1), jnp.float32)
    acc0 = jnp.zeros((K, R, D), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, nk, body, (m0, l0, acc0))

    # Rows past valid_len (padding) have every key masked -> l == 0; emit
    # zeros, not NaNs (the caller slices them off).
    l = jnp.where(l == 0.0, 1.0, l)
    out = (acc / l).reshape(K, Tq, G, D).swapaxes(0, 1)  # [Tq, K, G, D]
    o_ref[...] = out.reshape(Tq, K * G, D).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "sliding_window", "q_tile", "kv_tile", "interpret"),
)
def flash_prefill_attention(
    q: jax.Array,  # [T, H, D]
    k_new: jax.Array,  # [T, K, D]
    v_new: jax.Array,  # [T, K, D]
    k_prefix: jax.Array,  # [C, K, D] gathered cached prefix (may be C=0)
    v_prefix: jax.Array,  # [C, K, D]
    cached_len: jax.Array,  # scalar int32
    valid_len: jax.Array,  # scalar int32
    *,
    scale: float,
    sliding_window: Optional[int] = None,
    q_tile: int = 256,
    kv_tile: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """Flash causal prefill attention with prefix (Pallas TPU)."""
    T, H, D = q.shape
    K = k_new.shape[1]
    C = k_prefix.shape[0]
    if H % K:
        raise ValueError(f"H={H} not divisible by num_kv_heads={K}")
    G = H // K
    if D % 128 and not interpret:
        raise ValueError(f"flash prefill requires head_dim%128==0, got {D}")

    Tq = min(q_tile, T)
    if T % Tq:
        raise ValueError(f"T={T} not a multiple of q_tile={Tq}")

    keys = jnp.concatenate([k_prefix, k_new], axis=0)  # [C+T, K, D]
    values = jnp.concatenate([v_prefix, v_new], axis=0)
    S_raw = C + T
    Tk = min(kv_tile, S_raw)
    S_k = -(-S_raw // Tk) * Tk
    if S_k != S_raw:
        pad = [(0, S_k - S_raw), (0, 0), (0, 0)]
        keys = jnp.pad(keys, pad)  # padded keys are masked (j-C >= valid)
        values = jnp.pad(values, pad)

    kernel = functools.partial(
        _flash_prefill_kernel,
        Tq=Tq, Tk=Tk, C=C, S_k=S_k, K=K, G=G, D=D,
        scale=scale, sliding_window=sliding_window,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(T // Tq,),
        in_specs=[
            pl.BlockSpec((Tq, H, D), lambda i, *_: (i, 0, 0)),
            pl.BlockSpec((S_k, K, D), lambda i, *_: (0, 0, 0)),
            pl.BlockSpec((S_k, K, D), lambda i, *_: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((Tq, H, D), lambda i, *_: (i, 0, 0)),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((T, H, D), q.dtype),
        interpret=interpret,
    )(
        jnp.asarray(cached_len, jnp.int32).reshape(1),
        jnp.asarray(valid_len, jnp.int32).reshape(1),
        q, keys, values,
    )
