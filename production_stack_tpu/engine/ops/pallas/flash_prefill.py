"""Pallas TPU flash-attention kernel for prefill (causal + cached prefix).

Why the dense path stalls at ~0.44 MFU: ops/attention.py:prefill_attention
materializes the full fp32 score/prob tensors — [K, G, T, C+T] is ~430 MB
for a 2k-token llama-3.2-3b prefill, far beyond VMEM, so XLA spills them
to HBM and the MXU waits on bandwidth.  This kernel never materializes
scores in HBM: a 2-D grid (query tile x kv tile) streams keys/values
through VMEM in [Tk, K, D] slices while the online-softmax state
(running max, normalizer, and fp32 accumulator) lives in VMEM scratch
that persists across the kv dimension of the grid.  Nothing resident
scales with sequence length, so VMEM stays ~12 MB at any context
(a previous revision kept the whole [S_k, K, D] KV row resident, which
blew the 16 MB scoped-VMEM limit at 2k context on a 3B model).

Causal skipping: kv tiles wholly above a query tile's frontier are
skipped two ways — compute is fenced with ``pl.when``, and the kv
index map clamps to the last visible tile so Mosaic's revisit-elision
skips the DMA too (the block index doesn't change, so nothing is
re-fetched).

Layout notes (Mosaic): blocks keep the (head, lane) dims whole — q tiles
are [Tq, H, D], kv tiles [Tk, K, D] — because Mosaic requires the last
two block dims divisible by (8, 128) or equal to the array's.  GQA
regrouping happens in-register via the same swapaxes/reshape moves the
decode kernel uses (paged_attention.py:114-115); both matmuls are
K-batched dot_generals contracting the lane dim, so no transposes are
materialized.  The softmax running max/normalizer are stored broadcast
across the 128-lane dim (scratch must be lane-tiled anyway) and read
back with a lane-reduce.

Position/validity semantics match the dense path exactly
(ops/attention.py:128-143): key j < C is prefix slot j (valid while
j < cached_len), key j >= C is new token j-C at position cached_len+(j-C)
(valid while j-C < valid_len); query row t sits at cached_len + t.

Replaces the role of FlashAttention prefill kernels inside the reference's
external vLLM engine (the reference ships no kernels — SURVEY.md preamble).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
LANES = 128  # scratch lane width: fp32 scratch must tile to (8, 128)


def _flash_prefill_kernel(
    # scalar prefetch (SMEM)
    cached_len_ref,  # [1] int32
    valid_len_ref,  # [1] int32
    # inputs (VMEM blocks)
    q_ref,  # [Tq, H, D] this query tile, all heads
    k_ref,  # [Tk, K, D] this kv tile
    v_ref,  # [Tk, K, D]
    # outputs
    o_ref,  # [Tq, H, D]
    # scratch (VMEM, persists across the kv grid dim)
    m_ref,  # [K, R, LANES] fp32 running max (lane-broadcast)
    l_ref,  # [K, R, LANES] fp32 running normalizer (lane-broadcast)
    acc_ref,  # [K, R, D] fp32 output accumulator
    *,
    Tq: int,
    Tk: int,
    C: int,
    NKV: int,
    K: int,
    G: int,
    D: int,
    scale: float,
    sliding_window: Optional[int],
):
    i = pl.program_id(0)
    j = pl.program_id(1)
    cached = cached_len_ref[0]
    valid = valid_len_ref[0]
    R = Tq * G  # query rows per kv head after GQA regrouping

    # Last kv tile any query in this tile can see: the tile's last query
    # sits at cached + (i+1)*Tq - 1 and sees prefix keys (flat < C) plus
    # new keys with flat index < C + (i+1)*Tq.
    last = (C + (i + 1) * Tq - 1) // Tk

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full((K, R, LANES), NEG_INF, jnp.float32)
        l_ref[...] = jnp.zeros((K, R, LANES), jnp.float32)
        acc_ref[...] = jnp.zeros((K, R, D), jnp.float32)

    @pl.when(j <= last)
    def _compute():
        # [Tq, H, D] -> [K, Tq*G, D]: head h = k*G + g attends kv head k.
        q = q_ref[...].astype(jnp.float32) * scale
        q = q.reshape(Tq, K, G, D).swapaxes(0, 1).reshape(K, R, D)
        k = k_ref[...].astype(jnp.float32).swapaxes(0, 1)  # [K, Tk, D]
        v = v_ref[...].astype(jnp.float32).swapaxes(0, 1)

        # [K, R, D] x [K, Tk, D] -> [K, R, Tk] (batch over kv heads).
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )

        # Masks are built 2-D [R, Tk] and broadcast into the 3-D scores
        # (mask[None] — the exact pattern the decode kernel lowers with);
        # 4-D mask ops and bool-valued selects both stall Mosaic.  Query
        # row r = t*G + g is query token t = r // G.
        row_t = jax.lax.broadcasted_iota(jnp.int32, (R, 1), 0) // G
        q_pos = cached + i * Tq + row_t  # [R, 1]
        flat = j * Tk + jax.lax.broadcasted_iota(jnp.int32, (1, Tk), 1)
        is_prefix = flat < C
        key_pos = jnp.where(is_prefix, flat, cached + flat - C)  # int select
        key_valid = (is_prefix & (flat < cached)) | (
            ~is_prefix & (flat - C < valid)
        )
        mask = key_valid & (key_pos <= q_pos)  # [R, Tk]
        if sliding_window is not None:
            mask &= key_pos > q_pos - sliding_window
        s = jnp.where(mask[None], s, NEG_INF)

        m_prev = jnp.max(m_ref[...], axis=-1, keepdims=True)  # [K, R, 1]
        l_prev = jnp.max(l_ref[...], axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        # [K, R, Tk] x [K, Tk, D] -> [K, R, D]
        pv = jax.lax.dot_general(
            p, v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        acc_ref[...] = acc_ref[...] * alpha + pv
        m_ref[...] = jnp.broadcast_to(m_new, (K, R, LANES))
        l_ref[...] = jnp.broadcast_to(l_new, (K, R, LANES))

    @pl.when(j == NKV - 1)
    def _final():
        # Rows past valid_len (padding) have every key masked -> l == 0;
        # emit zeros, not NaNs (the caller slices them off).
        l = jnp.max(l_ref[...], axis=-1, keepdims=True)
        l = jnp.where(l == 0.0, 1.0, l)
        out = (acc_ref[...] / l).reshape(K, Tq, G, D).swapaxes(0, 1)
        o_ref[...] = out.reshape(Tq, K * G, D).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "sliding_window", "q_tile", "kv_tile", "interpret"),
)
def flash_prefill_attention(
    q: jax.Array,  # [T, H, D]
    k_new: jax.Array,  # [T, K, D]
    v_new: jax.Array,  # [T, K, D]
    k_prefix: jax.Array,  # [C, K, D] gathered cached prefix (may be C=0)
    v_prefix: jax.Array,  # [C, K, D]
    cached_len: jax.Array,  # scalar int32
    valid_len: jax.Array,  # scalar int32
    *,
    scale: float,
    sliding_window: Optional[int] = None,
    q_tile: int = 128,
    kv_tile: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """Flash causal prefill attention with prefix (Pallas TPU)."""
    T, H, D = q.shape
    K = k_new.shape[1]
    C = k_prefix.shape[0]
    if H % K:
        raise ValueError(f"H={H} not divisible by num_kv_heads={K}")
    G = H // K
    if D % 128 and not interpret:
        raise ValueError(f"flash prefill requires head_dim%128==0, got {D}")

    Tq = min(q_tile, T)
    if T % Tq:
        raise ValueError(f"T={T} not a multiple of q_tile={Tq}")

    keys = jnp.concatenate([k_prefix, k_new], axis=0)  # [C+T, K, D]
    values = jnp.concatenate([v_prefix, v_new], axis=0)
    S_raw = C + T
    Tk = min(kv_tile, S_raw)
    S_k = -(-S_raw // Tk) * Tk
    if S_k != S_raw:
        pad = [(0, S_k - S_raw), (0, 0), (0, 0)]
        keys = jnp.pad(keys, pad)  # padded keys are masked (j-C >= valid)
        values = jnp.pad(values, pad)
    NKV = S_k // Tk

    kernel = functools.partial(
        _flash_prefill_kernel,
        Tq=Tq, Tk=Tk, C=C, NKV=NKV, K=K, G=G, D=D,
        scale=scale, sliding_window=sliding_window,
    )

    def kv_index(i, j, *_):
        # Clamp to the tile's causal frontier: for skipped steps the block
        # index repeats, so Mosaic's revisit-elision skips the DMA.
        last = (C + (i + 1) * Tq - 1) // Tk
        return (jnp.minimum(j, last), 0, 0)

    R = Tq * G
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(T // Tq, NKV),
        in_specs=[
            pl.BlockSpec((Tq, H, D), lambda i, j, *_: (i, 0, 0)),
            pl.BlockSpec((Tk, K, D), kv_index),
            pl.BlockSpec((Tk, K, D), kv_index),
        ],
        out_specs=pl.BlockSpec((Tq, H, D), lambda i, j, *_: (i, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((K, R, LANES), jnp.float32),
            pltpu.VMEM((K, R, LANES), jnp.float32),
            pltpu.VMEM((K, R, D), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((T, H, D), q.dtype),
        # The fp32 score/prob intermediates ([K, R, Tk] each) plus the
        # online-softmax scratch exceed the compiler's default 16 MB scoped
        # VMEM at serving tile sizes; v5e/v6e have 128 MB, so raise the cap
        # rather than shrink tiles below MXU-efficient shapes.
        # CompilerParams is the jax>=0.5 name; 0.4.x calls it
        # TPUCompilerParams.
        compiler_params=getattr(
            pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
        )(vmem_limit_bytes=96 * 1024 * 1024),
        interpret=interpret,
    )(
        jnp.asarray(cached_len, jnp.int32).reshape(1),
        jnp.asarray(valid_len, jnp.int32).reshape(1),
        q, keys, values,
    )
