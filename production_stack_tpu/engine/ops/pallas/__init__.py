"""Pallas TPU kernels for the hot serving ops.

paged_attention: decode-phase attention streaming paged KV blocks
HBM->VMEM with double-buffered DMA (selected on TPU backends by
ops/attention.py; the pure-JAX gather path stays as the reference
implementation and the CPU/test path).
"""
