"""Paged attention (pure-JAX reference path).

Design notes (TPU-first):

* All shapes are static.  Prefill lengths are bucketed, decode batch is
  padded to the scheduler's ``max_num_seqs``; invalid slots are masked, and
  their KV writes land in the reserved *null block* 0 (never read).
* Softmax runs in fp32 (MXU accumulates fp32, VPU exponentiates fp32);
  inputs/outputs are bf16.
* The gather-based decode path below materializes [S, max_ctx, K, D] in HBM
  — correct everywhere (CPU tests, interpret mode) and fast enough for
  moderate contexts.  The Pallas kernel in pallas/paged_attention.py streams
  KV blocks HBM->VMEM instead and is selected on TPU backends.

KV cache layout per layer: ``[num_blocks, block_size, num_kv_heads, head_dim]``
— block-major so one block is a contiguous DMA unit for both the decode
kernel and host offload (kv/offload.py).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

NEG_INF = -1e30  # large-but-finite: keeps masked softmax rows NaN-free


def prefill_attention(
    q: jax.Array,  # [T, H, D]
    k_new: jax.Array,  # [T, K, D]
    v_new: jax.Array,  # [T, K, D]
    k_prefix: jax.Array,  # [C_max, K, D] gathered cached prefix (may be empty)
    v_prefix: jax.Array,  # [C_max, K, D]
    cached_len: jax.Array,  # scalar int: valid prefix tokens (< C_max)
    valid_len: jax.Array,  # scalar int: valid new tokens (<= T)
    *,
    scale: float,
    sliding_window: Optional[int] = None,
) -> jax.Array:
    """Causal attention for one sequence's prefill, attending to an optional
    cached prefix (prefix-cache hit) plus the new tokens themselves."""
    T, H, D = q.shape
    C_max = k_prefix.shape[0]
    K = k_new.shape[1]
    G = H // K

    keys = jnp.concatenate([k_prefix, k_new], axis=0)  # [C_max+T, K, D]
    values = jnp.concatenate([v_prefix, v_new], axis=0)

    # Positions: query i sits at cached_len + i; prefix key j at j; new key
    # j' at cached_len + j'.  Build key-position array of shape [C_max+T].
    prefix_pos = jnp.arange(C_max)
    new_pos = cached_len + jnp.arange(T)
    key_pos = jnp.concatenate([prefix_pos, new_pos])  # [C_max+T]
    q_pos = cached_len + jnp.arange(T)  # [T]

    # Valid keys: prefix slots < cached_len, new slots < valid_len.
    key_valid = jnp.concatenate(
        [prefix_pos < cached_len, jnp.arange(T) < valid_len]
    )

    mask = key_pos[None, :] <= q_pos[:, None]  # causal
    mask &= key_valid[None, :]
    if sliding_window is not None:
        mask &= key_pos[None, :] > (q_pos[:, None] - sliding_window)

    qg = q.reshape(T, K, G, D)
    # [T, K, G, D] x [S_k, K, D] -> [K, G, T, S_k]
    scores = jnp.einsum(
        "tkgd,skd->kgts", qg, keys, preferred_element_type=jnp.float32
    )
    scores = scores * scale
    scores = jnp.where(mask[None, None, :, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "kgts,skd->tkgd", probs.astype(values.dtype), values,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(T, H, D).astype(q.dtype)


def paged_decode_attention(
    q: jax.Array,  # [S, H, D] one new token per sequence
    k_cache: jax.Array,  # [N, bs, K, D]
    v_cache: jax.Array,  # [N, bs, K, D]
    block_tables: jax.Array,  # [S, Bmax] int32 (0 = null block)
    ctx_lens: jax.Array,  # [S] int32: tokens in context incl. current
    *,
    scale: float,
    sliding_window: Optional[int] = None,
) -> jax.Array:
    """Decode attention over paged KV via gather (reference path)."""
    S, H, D = q.shape
    N, bs, K, _ = k_cache.shape
    Bmax = block_tables.shape[1]
    G = H // K

    k = k_cache[block_tables].reshape(S, Bmax * bs, K, D)
    v = v_cache[block_tables].reshape(S, Bmax * bs, K, D)

    key_pos = jnp.arange(Bmax * bs)[None, :]  # [1, max_ctx]
    mask = key_pos < ctx_lens[:, None]  # [S, max_ctx]
    if sliding_window is not None:
        mask &= key_pos > (ctx_lens[:, None] - 1 - sliding_window)

    qg = q.reshape(S, K, G, D)
    scores = jnp.einsum(
        "skgd,stkd->skgt", qg, k, preferred_element_type=jnp.float32
    )
    scores = scores * scale
    scores = jnp.where(mask[:, None, None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "skgt,stkd->skgd", probs.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(S, H, D).astype(q.dtype)


def write_prefill_kv(
    k_cache: jax.Array,  # [N, bs, K, D]
    v_cache: jax.Array,
    k_new: jax.Array,  # [T, K, D], T = num_new_blocks * bs
    v_new: jax.Array,
    new_block_ids: jax.Array,  # [T // bs] int32; padding slots -> 0 (null)
) -> Tuple[jax.Array, jax.Array]:
    """Scatter freshly computed prefill KV into the paged cache."""
    N, bs, K, D = k_cache.shape
    nb = new_block_ids.shape[0]
    k_blocks = k_new.reshape(nb, bs, K, D).astype(k_cache.dtype)
    v_blocks = v_new.reshape(nb, bs, K, D).astype(v_cache.dtype)
    k_cache = k_cache.at[new_block_ids].set(k_blocks)
    v_cache = v_cache.at[new_block_ids].set(v_blocks)
    return k_cache, v_cache


def append_decode_kv(
    k_cache: jax.Array,  # [N, bs, K, D]
    v_cache: jax.Array,
    k: jax.Array,  # [S, K, D] one token per sequence
    v: jax.Array,
    slot_block_ids: jax.Array,  # [S] int32 block holding this token (0=null)
    slot_offsets: jax.Array,  # [S] int32 offset within the block
) -> Tuple[jax.Array, jax.Array]:
    """Scatter one new token's KV per sequence into the paged cache."""
    k_cache = k_cache.at[slot_block_ids, slot_offsets].set(k.astype(k_cache.dtype))
    v_cache = v_cache.at[slot_block_ids, slot_offsets].set(v.astype(v_cache.dtype))
    return k_cache, v_cache


def gather_prefix_kv(
    k_cache: jax.Array,  # [N, bs, K, D]
    v_cache: jax.Array,
    prefix_block_ids: jax.Array,  # [P] int32 (0-padded)
) -> Tuple[jax.Array, jax.Array]:
    """Gather a cached prefix as [P*bs, K, D] for prefill attention."""
    N, bs, K, D = k_cache.shape
    P = prefix_block_ids.shape[0]
    k = k_cache[prefix_block_ids].reshape(P * bs, K, D)
    v = v_cache[prefix_block_ids].reshape(P * bs, K, D)
    return k, v
