"""Paged attention (pure-JAX reference path).

Design notes (TPU-first):

* All shapes are static.  Prefill lengths are bucketed, decode batch is
  padded to the scheduler's ``max_num_seqs``; invalid slots are masked, and
  their KV writes land in the reserved *null block* 0 (never read).
* Softmax runs in fp32 (MXU accumulates fp32, VPU exponentiates fp32);
  inputs/outputs are bf16.
* The gather-based decode path below materializes [S, max_ctx, K, D] in HBM
  — correct everywhere (CPU tests, interpret mode) and fast enough for
  moderate contexts.  ``decode_attention`` dispatches to the Pallas kernel
  in pallas/paged_attention.py on TPU backends (set
  ``PSTPU_DISABLE_PALLAS=1`` to force the gather path, e.g. for A/B
  benchmarking); under a multi-device mesh the kernel runs per-shard via
  shard_map (batch over dp, heads over tp).

KV cache layout per layer: ``[num_blocks, block_size, num_kv_heads, head_dim]``
— block-major so one block is a contiguous DMA unit for both the decode
kernel and host offload (kv/offload.py).
"""

from __future__ import annotations

import os
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from production_stack_tpu.engine.parallel.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30  # large-but-finite: keeps masked softmax rows NaN-free

# Quantized (int8) cache sides are (data, scale) tuples — kv/quant.py.
from production_stack_tpu.engine.kv import quant as kv_quant


def use_pallas_decode(num_kv_heads: int = 128, head_dim: int = 128) -> bool:
    """Trace-time dispatch check for the streaming decode kernel.

    Needs a real TPU and a 128-lane-aligned head_dim: the kernel splits
    the DMA'd KV row back into heads in VMEM, and Mosaic only lowers that
    shape cast when head_dim is a multiple of the 128-lane tile.  Covers
    llama-3-8b / llama-3.2-3b / mistral-7b (D=128); head_dim-64 models
    (llama-3.2-1b) and the tiny test models use the gather path."""
    if os.environ.get("PSTPU_DISABLE_PALLAS"):
        return False
    if num_kv_heads < 1 or head_dim % 128:
        return False
    return jax.default_backend() == "tpu"


def decode_attention(
    q: jax.Array,  # [S, H, D]
    k_cache: jax.Array,  # [N, bs, K, D]
    v_cache: jax.Array,
    block_tables: jax.Array,  # [S, Bmax]
    ctx_lens: jax.Array,  # [S]
    *,
    scale: float,
    sliding_window: Optional[int] = None,
    mesh: Optional[Mesh] = None,
) -> jax.Array:
    """Decode attention with backend dispatch (Pallas on TPU, gather else).

    Under a multi-device mesh the Pallas kernel runs per-shard inside
    shard_map: the decode batch (and its block table / context rows) is
    sharded over dp, heads over tp; the KV pool's block axis is replicated
    so per-shard block ids stay valid.
    """
    from production_stack_tpu.engine.parallel.mesh import AXES

    quantized = kv_quant.is_quantized(k_cache)
    K, D = kv_quant.cache_shape(k_cache)[2:4]
    # Under tp the kernel sees K/tp heads per shard; alignment must hold
    # for the per-shard KV row.
    tp = mesh.shape[AXES.TP] if mesh is not None and mesh.size > 1 else 1
    if not use_pallas_decode(K // tp, D):
        return paged_decode_attention(
            q, k_cache, v_cache, block_tables, ctx_lens,
            scale=scale, sliding_window=sliding_window,
        )
    from production_stack_tpu.engine.ops.pallas.paged_attention import (
        paged_decode_attention_pallas,
    )

    kernel = partial(
        paged_decode_attention_pallas, scale=scale, sliding_window=sliding_window
    )
    if mesh is None or mesh.size == 1:
        return kernel(q, k_cache, v_cache, block_tables, ctx_lens)

    cache_spec = (
        (P(None, None, AXES.TP, None), P(None, None, AXES.TP))
        if quantized else P(None, None, AXES.TP, None)
    )
    return shard_map(
        kernel,
        mesh=mesh,
        in_specs=(
            P(AXES.DP, AXES.TP, None),  # q: batch over dp, heads over tp
            cache_spec,  # k_cache: kv heads over tp (scales follow)
            cache_spec,  # v_cache
            P(AXES.DP, None),  # block_tables rows follow the batch
            P(AXES.DP),  # ctx_lens
        ),
        out_specs=P(AXES.DP, AXES.TP, None),
        check_vma=False,
    )(q, k_cache, v_cache, block_tables, ctx_lens)


def use_pallas_prefill(num_heads: int, num_kv_heads: int, head_dim: int,
                       num_tokens: int) -> bool:
    """Trace-time dispatch check for the flash prefill kernel: real TPU,
    128-lane-aligned head_dim, GQA-divisible heads, and a power-of-two-ish
    token bucket the q tiling divides (engine buckets are powers of two)."""
    if os.environ.get("PSTPU_DISABLE_PALLAS") or os.environ.get(
        "PSTPU_DISABLE_FLASH_PREFILL"
    ):
        # The second gate exists so bench.py's stage watchdog can re-exec
        # with only the prefill kernel disabled if it ever stalls a chip.
        return False
    if head_dim % 128 or num_heads % max(num_kv_heads, 1):
        return False
    if num_tokens % min(256, num_tokens):
        return False
    return jax.default_backend() == "tpu"


def prefill_attention(
    q: jax.Array,  # [T, H, D]
    k_new: jax.Array,  # [T, K, D]
    v_new: jax.Array,  # [T, K, D]
    k_prefix: jax.Array,  # [C_max, K, D] gathered cached prefix (may be empty)
    v_prefix: jax.Array,  # [C_max, K, D]
    cached_len: jax.Array,  # scalar int: valid prefix tokens (< C_max)
    valid_len: jax.Array,  # scalar int: valid new tokens (<= T)
    *,
    scale: float,
    sliding_window: Optional[int] = None,
    mesh: Optional[Mesh] = None,
) -> jax.Array:
    """Causal attention for one sequence's prefill, attending to an optional
    cached prefix (prefix-cache hit) plus the new tokens themselves.

    Dispatches to the Pallas flash kernel on single-device TPU (the dense
    path below materializes [K, G, T, C+T] fp32 scores, which spills to
    HBM for long prompts — see pallas/flash_prefill.py).  Under a
    multi-device mesh the dense path stays: GSPMD partitions its einsums
    across tp automatically, while a bare pallas_call cannot be
    auto-partitioned (the sp>1 case never reaches here — llama.prefill
    routes it to ring attention)."""
    T, H, D = q.shape
    single_device = mesh is None or mesh.size == 1
    if single_device and use_pallas_prefill(H, k_new.shape[1], D, T):
        from production_stack_tpu.engine.ops.pallas.flash_prefill import (
            flash_prefill_attention,
        )

        return flash_prefill_attention(
            q, k_new, v_new, k_prefix, v_prefix, cached_len, valid_len,
            scale=scale, sliding_window=sliding_window,
        )
    C_max = k_prefix.shape[0]
    K = k_new.shape[1]
    G = H // K

    keys = jnp.concatenate([k_prefix, k_new], axis=0)  # [C_max+T, K, D]
    values = jnp.concatenate([v_prefix, v_new], axis=0)

    # Positions: query i sits at cached_len + i; prefix key j at j; new key
    # j' at cached_len + j'.  Build key-position array of shape [C_max+T].
    prefix_pos = jnp.arange(C_max)
    new_pos = cached_len + jnp.arange(T)
    key_pos = jnp.concatenate([prefix_pos, new_pos])  # [C_max+T]
    q_pos = cached_len + jnp.arange(T)  # [T]

    # Valid keys: prefix slots < cached_len, new slots < valid_len.
    key_valid = jnp.concatenate(
        [prefix_pos < cached_len, jnp.arange(T) < valid_len]
    )

    mask = key_pos[None, :] <= q_pos[:, None]  # causal
    mask &= key_valid[None, :]
    if sliding_window is not None:
        mask &= key_pos[None, :] > (q_pos[:, None] - sliding_window)

    qg = q.reshape(T, K, G, D)
    # [T, K, G, D] x [S_k, K, D] -> [K, G, T, S_k]
    scores = jnp.einsum(
        "tkgd,skd->kgts", qg, keys, preferred_element_type=jnp.float32
    )
    scores = scores * scale
    scores = jnp.where(mask[None, None, :, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "kgts,skd->tkgd", probs.astype(values.dtype), values,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(T, H, D).astype(q.dtype)


def paged_decode_attention(
    q: jax.Array,  # [S, H, D] one new token per sequence
    k_cache: jax.Array,  # [N, bs, K, D]
    v_cache: jax.Array,  # [N, bs, K, D]
    block_tables: jax.Array,  # [S, Bmax] int32 (0 = null block)
    ctx_lens: jax.Array,  # [S] int32: tokens in context incl. current
    *,
    scale: float,
    sliding_window: Optional[int] = None,
) -> jax.Array:
    """Decode attention over paged KV via gather (reference path)."""
    S, H, D = q.shape
    N, bs, K, _ = kv_quant.cache_shape(k_cache)
    Bmax = block_tables.shape[1]
    G = H // K

    if kv_quant.is_quantized(k_cache):
        k = kv_quant.dequantize(
            k_cache[0][block_tables], k_cache[1][block_tables]
        ).reshape(S, Bmax * bs, K, D)
        v = kv_quant.dequantize(
            v_cache[0][block_tables], v_cache[1][block_tables]
        ).reshape(S, Bmax * bs, K, D)
    else:
        k = k_cache[block_tables].reshape(S, Bmax * bs, K, D)
        v = v_cache[block_tables].reshape(S, Bmax * bs, K, D)

    key_pos = jnp.arange(Bmax * bs)[None, :]  # [1, max_ctx]
    mask = key_pos < ctx_lens[:, None]  # [S, max_ctx]
    if sliding_window is not None:
        mask &= key_pos > (ctx_lens[:, None] - 1 - sliding_window)

    qg = q.reshape(S, K, G, D)
    scores = jnp.einsum(
        "skgd,stkd->skgt", qg, k, preferred_element_type=jnp.float32
    )
    scores = scores * scale
    scores = jnp.where(mask[:, None, None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "skgt,stkd->skgd", probs.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(S, H, D).astype(q.dtype)


def write_prefill_kv(
    k_cache: jax.Array,  # [N, bs, K, D]
    v_cache: jax.Array,
    k_new: jax.Array,  # [T, K, D], T = num_new_blocks * bs
    v_new: jax.Array,
    new_block_ids: jax.Array,  # [T // bs] int32; padding slots -> 0 (null)
) -> Tuple[jax.Array, jax.Array]:
    """Scatter freshly computed prefill KV into the paged cache."""
    N, bs, K, D = kv_quant.cache_shape(k_cache)
    nb = new_block_ids.shape[0]
    if kv_quant.is_quantized(k_cache):
        kd, ks = kv_quant.quantize_vectors(k_new.reshape(nb, bs, K, D))
        vd, vs = kv_quant.quantize_vectors(v_new.reshape(nb, bs, K, D))
        k_cache = (
            k_cache[0].at[new_block_ids].set(kd),
            k_cache[1].at[new_block_ids].set(ks),
        )
        v_cache = (
            v_cache[0].at[new_block_ids].set(vd),
            v_cache[1].at[new_block_ids].set(vs),
        )
        return k_cache, v_cache
    k_blocks = k_new.reshape(nb, bs, K, D).astype(k_cache.dtype)
    v_blocks = v_new.reshape(nb, bs, K, D).astype(v_cache.dtype)
    k_cache = k_cache.at[new_block_ids].set(k_blocks)
    v_cache = v_cache.at[new_block_ids].set(v_blocks)
    return k_cache, v_cache


def append_decode_kv(
    k_cache: jax.Array,  # [N, bs, K, D]
    v_cache: jax.Array,
    k: jax.Array,  # [S, K, D] one token per sequence
    v: jax.Array,
    slot_block_ids: jax.Array,  # [S] int32 block holding this token (0=null)
    slot_offsets: jax.Array,  # [S] int32 offset within the block
) -> Tuple[jax.Array, jax.Array]:
    """Scatter one new token's KV per sequence into the paged cache."""
    if kv_quant.is_quantized(k_cache):
        kd, ks = kv_quant.quantize_vectors(k)  # [S, K, D] -> + [S, K]
        vd, vs = kv_quant.quantize_vectors(v)
        k_cache = (
            k_cache[0].at[slot_block_ids, slot_offsets].set(kd),
            k_cache[1].at[slot_block_ids, slot_offsets].set(ks),
        )
        v_cache = (
            v_cache[0].at[slot_block_ids, slot_offsets].set(vd),
            v_cache[1].at[slot_block_ids, slot_offsets].set(vs),
        )
        return k_cache, v_cache
    k_cache = k_cache.at[slot_block_ids, slot_offsets].set(k.astype(k_cache.dtype))
    v_cache = v_cache.at[slot_block_ids, slot_offsets].set(v.astype(v_cache.dtype))
    return k_cache, v_cache


def gather_prefix_kv(
    k_cache: jax.Array,  # [N, bs, K, D] (or (data, scale) when int8)
    v_cache: jax.Array,
    prefix_block_ids: jax.Array,  # [P] int32 (0-padded)
    dtype=None,  # dequantization target for quantized caches (fp32 default)
) -> Tuple[jax.Array, jax.Array]:
    """Gather a cached prefix as [P*bs, K, D] for prefill attention.

    Quantized caches dequantize here — downstream prefill attention
    (dense, flash kernel, ring, ulysses) is precision-agnostic.
    """
    N, bs, K, D = kv_quant.cache_shape(k_cache)
    P = prefix_block_ids.shape[0]
    if kv_quant.is_quantized(k_cache):
        k = kv_quant.dequantize(
            k_cache[0][prefix_block_ids], k_cache[1][prefix_block_ids],
            dtype=dtype,
        ).reshape(P * bs, K, D)
        v = kv_quant.dequantize(
            v_cache[0][prefix_block_ids], v_cache[1][prefix_block_ids],
            dtype=dtype,
        ).reshape(P * bs, K, D)
        return k, v
    k = k_cache[prefix_block_ids].reshape(P * bs, K, D)
    v = v_cache[prefix_block_ids].reshape(P * bs, K, D)
    return k, v
