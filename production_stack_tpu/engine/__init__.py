"""TPU-native serving engine (JAX/XLA/Pallas).

The reference production stack delegates all compute to external vLLM CUDA
engine images (SURVEY.md: helm/templates/deployment-vllm-multi.yaml:57-64
runs ``vllm serve``).  There is no such off-the-shelf image contract for
TPU, so this package makes the stack standalone: an OpenAI-compatible
serving engine with paged KV-cache attention, continuous batching, prefix
caching, KV offload to host DRAM, and SPMD parallelism over a
``jax.sharding.Mesh`` — designed for the MXU/HBM/ICI cost model rather than
translated from CUDA.
"""
